"""Vmapped ensemble core: a batch of independent solves as one program.

A batch (an "ensemble") shares the compiled-program identity - (N, Lx/y/z,
T, timesteps, scheme, kernel path, k, dtype, batch size) - while each LANE
differs in

 * the initial time phase of the analytic solution (`LaneSpec.phase`;
   u(0) = Sx*Sy*Sz * cos(phase), which solves the PDE for any phase, so
   the per-lane error oracle stays exact),
 * the number of layers marched (`LaneSpec.stop_step`: the batch marches
   to the max and earlier-stopping lanes are FROZEN by `where` masking,
   which preserves their state bit-for-bit), and
 * optionally a per-lane tau^2 c^2(x,y,z) field (no analytic oracle, so
   field batches require compute_errors=False).

Wired paths: "roll" (the jnp stencil), "pallas" (the fused 1-step slab
kernel), "kfused" (the k-step onion, k >= 2) - each on BOTH schemes:
"standard" mirrors leapfrog.make_solver / kfused.make_kfused_solver, and
"compensated" (the flagship Kahan velocity form) mirrors
leapfrog.make_compensated_solver / kfused_comp.make_kfused_comp_solver
(the `fused_kstep_comp` onion for k >= 2).  Each lane's op sequence
inside the vmapped program mirrors the corresponding solo solver's op
for op - the BITWISE lane-parity contract is pinned by
tests/test_ensemble.py, and any change here or there must keep that
suite green.  Compensated batches are constant-speed only (the solo
velocity-form field path exists, but per-lane fields are not wired
through the compensated vmapped core).

Not every (scheme, path) vmaps on every backend (Mosaic's batching
support for the onion kernels differs from interpret mode's).
`vmap_capability` probes a tiny batched solve per (scheme, path,
backend) once and caches the verdict; a failed probe drops to the
LANE-LOOP fallback (sequential solo solves behind the same
EnsembleResult interface) with the reason RECORDED in
`EnsembleResult.fallback_reason`, and `probe_results()` exposes every
cached verdict for GET /metrics.  Nothing falls back silently.

Per-lane timestep masking on the "kfused" path freezes whole k-blocks, so
a lane's stop_step must sit on the block grid ((stop-1) % k == 0) or be
the full march; the 1-step paths mask per layer.
"""

from __future__ import annotations

import dataclasses
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from wavetpu.core.problem import Problem
from wavetpu.verify import oracle

PATHS = ("roll", "pallas", "kfused")
SCHEMES = ("standard", "compensated")


@dataclasses.dataclass(frozen=True)
class LaneSpec:
    """One lane of an ensemble batch.

    `phase`: initial time phase of the analytic solution (reference: 2*pi).
    `stop_step`: layers to march (None = the problem's timesteps; the lane
    freezes there while the batch marches on).  `c2tau2_field`: optional
    host (N,N,N) tau^2 c^2 array (stencil_ref.make_c2tau2_field).
    """

    phase: float = oracle.TWO_PI
    stop_step: Optional[int] = None
    c2tau2_field: Optional[object] = None

    def stop(self, problem: Problem) -> int:
        return (
            problem.timesteps if self.stop_step is None else self.stop_step
        )


def padding_lane() -> LaneSpec:
    """The masked filler lane the serve layer pads batches with: frozen
    after layer 1 (stop=1 sits on every k-block grid), default phase.
    Padding lanes ride the batch axis only - elementwise across lanes -
    so real lanes are bitwise unchanged (tests/test_ensemble.py pins it).
    """
    return LaneSpec(stop_step=1)


@dataclasses.dataclass
class EnsembleResult:
    """A batched solve's outcome: per-lane SolveResults + how it ran.

    `batched` False means the lane-loop fallback executed (reason in
    `fallback_reason` - never None in that case); `batch_size` counts the
    compiled program's lanes including padding, `n_lanes` the real ones.
    `solve_seconds` is the whole batch's wall time (each lane's
    SolveResult carries the same number: lanes finish together).
    """

    problem: Problem
    results: List["SolveResult"]  # noqa: F821 - from solver.leapfrog
    path: str
    batched: bool
    fallback_reason: Optional[str]
    batch_size: int
    n_lanes: int
    init_seconds: float
    solve_seconds: float
    # The raw (B, N, N, N) batched state (padding lanes included; None
    # on the lane-loop fallback).  The serve engine's per-lane watchdog
    # reduces over these directly - re-stacking the per-lane views
    # would copy the whole batch state per request batch.
    u_prev_batch: Optional[object] = None
    u_cur_batch: Optional[object] = None

    @property
    def aggregate_gcells_per_second(self) -> float:
        """Sum of per-lane cell-updates over the batch wall time - the
        serving throughput number (arXiv:2108.11076's batching win)."""
        if not self.solve_seconds:
            return 0.0
        total = sum(
            self.problem.cells_per_step * (r.steps_computed or 0)
            for r in self.results
        )
        return total / self.solve_seconds / 1e9


def _validate(problem: Problem, lanes: Sequence[LaneSpec], path: str,
              k: int, compute_errors: bool,
              scheme: str = "standard") -> bool:
    """Shared lane validation; returns with_field (all-or-none normalized
    by the caller via `fill_fields`)."""
    if path not in PATHS:
        raise ValueError(f"path must be one of {PATHS}, got {path!r}")
    if scheme not in SCHEMES:
        raise ValueError(
            f"scheme must be one of {SCHEMES}, got {scheme!r}"
        )
    if not lanes:
        raise ValueError("an ensemble needs at least one lane")
    if scheme == "compensated" and any(
        lane.c2tau2_field is not None for lane in lanes
    ):
        raise ValueError(
            "per-lane c2tau2 fields are not wired through the compensated "
            "vmapped core; use scheme='standard' for field batches"
        )
    if path == "kfused":
        if k < 2:
            raise ValueError(f"kfused path needs k >= 2, got {k}")
        if problem.N % k:
            raise ValueError(f"k={k} must divide N={problem.N}")
    with_field = any(lane.c2tau2_field is not None for lane in lanes)
    if with_field and compute_errors:
        raise ValueError(
            "per-lane c2tau2 fields have no analytic oracle; pass "
            "compute_errors=False"
        )
    for i, lane in enumerate(lanes):
        s = lane.stop(problem)
        if not 1 <= s <= problem.timesteps:
            raise ValueError(
                f"lane {i}: stop_step must be in [1, {problem.timesteps}],"
                f" got {s}"
            )
        if path == "kfused" and s != problem.timesteps and (s - 1) % k:
            raise ValueError(
                f"lane {i}: on the kfused path a lane freezes at whole "
                f"k-blocks - stop_step must satisfy (stop-1) % {k} == 0 "
                f"or equal timesteps={problem.timesteps}, got {s}"
            )
        if lane.c2tau2_field is not None and np.shape(
            lane.c2tau2_field
        ) != (problem.N,) * 3:
            raise ValueError(
                f"lane {i}: c2tau2_field shape "
                f"{np.shape(lane.c2tau2_field)} != {(problem.N,) * 3}"
            )
        if with_field and lane.phase != oracle.TWO_PI:
            # A shifted phase bootstraps layer 1 from the ANALYTIC
            # solution, which only exists for constant speed - and in a
            # field batch EVERY lane runs the variable-c kernel
            # (fill_fields), so the whole batch must keep the reference
            # phase.  (The serve scheduler never mixes these anyway:
            # field presence is part of the bucket key.)
            raise ValueError(
                f"lane {i}: a shifted phase has no analytic layer-1 "
                f"bootstrap in a variable-c field batch; use the "
                f"reference phase with c2tau2_field"
            )
    return with_field


def fill_fields(problem: Problem, lanes: Sequence[LaneSpec]) -> list:
    """In a field batch every lane runs the variable-c kernel, so lanes
    without a field get the CONSTANT tau^2 a^2 field (numerically the
    constant-speed problem; bitwise it matches the solo variable-c solve
    with that constant field, not the constant-c kernel - documented in
    docs/serving.md)."""
    const = None
    out = []
    for lane in lanes:
        if lane.c2tau2_field is None:
            if const is None:
                const = np.full(
                    (problem.N,) * 3, problem.a2tau2, dtype=np.float64
                )
            lane = dataclasses.replace(lane, c2tau2_field=const)
        out.append(lane)
    return out


def _lane_error_fn(problem: Problem, dtype):
    """(u, n, ct_table) -> (abs_e, rel_e): leapfrog._error_fn with the
    time-factor table as a runtime argument instead of a closed-over
    constant (per-lane tables ride the batch axis).  Must stay op-for-op
    identical to leapfrog._error_fn for the bitwise parity contract."""
    import jax.numpy as jnp

    from wavetpu.kernels import stencil_ref

    f_dtype = stencil_ref.compute_dtype(dtype)
    sx, sy, sz = oracle.spatial_factors(problem, f_dtype)
    mask = jnp.asarray(oracle.interior_masks_1d(problem.N))

    def errors(u, n, ct_table):
        fld = oracle.analytic_field(sx, sy, sz, ct_table[n])
        return oracle.layer_errors(u.astype(f_dtype), fld, mask, mask, mask)

    return errors


def _lane_error_fn_guarded(problem: Problem, dtype):
    """`_lane_error_fn` with the representation-zero sx planes excluded
    from the REL metric - the runtime-ct-table twin of
    kfused_comp._error_fn_guarded (the velocity-form onion's bootstrap-
    layer metric).  Must stay op-for-op identical to it."""
    import jax.numpy as jnp

    from wavetpu.kernels import stencil_ref
    from wavetpu.solver import kfused_comp

    f_dtype = stencil_ref.compute_dtype(dtype)
    sx, sy, sz = oracle.spatial_factors(problem, f_dtype)
    mask = jnp.asarray(oracle.interior_masks_1d(problem.N))
    mask_x = mask & (jnp.abs(sx) > kfused_comp._rel_guard_tol(f_dtype))

    def errors(u, n, ct_table):
        fld = oracle.analytic_field(sx, sy, sz, ct_table[n])
        return oracle.layer_errors(
            u.astype(f_dtype), fld, mask_x, mask, mask
        )

    return errors


def _comp_bootstrap(problem: Problem, dtype, v_dtype, carry_dtype, sx, sy,
                    sz, ct_table, taylor, comp_step):
    """Compensated layers 0/1 from a runtime ct table.

    The per-lane `taylor` selector mirrors the solo compensated solvers'
    STATIC phase decision: True = the compensated half-step bootstrap
    (v = carry = 0, coeff = C/2 - leapfrog.make_compensated_solver /
    kfused_comp._bootstrap), False = the exact analytic two-level
    initialization shifted phases take (u0/u1 analytic, v1 the exact
    analytic increment Sx Sy Sz (ct1 - ct0) - a pure product, matching
    leapfrog.analytic_increment_layer1; the u1 - u0 form FMA-contracts
    differently between program shapes).  Both branches mirror the
    corresponding solo program op for op; `where` selects bitwise.
    """
    import jax.numpy as jnp

    from wavetpu.kernels import stencil_ref

    u0 = stencil_ref.apply_dirichlet(
        oracle.analytic_field(sx, sy, sz, ct_table[0])
    ).astype(dtype)
    zero = jnp.zeros_like(u0)
    u1_s, v1_s, c1_s = comp_step(
        u0, zero, zero, problem, 0.5 * problem.a2tau2
    )
    v1_s = v1_s.astype(v_dtype)
    c1_s = c1_s.astype(carry_dtype)
    u1_a = stencil_ref.apply_dirichlet(
        oracle.analytic_field(sx, sy, sz, ct_table[1])
    ).astype(dtype)
    v1_a = stencil_ref.apply_dirichlet(
        oracle.analytic_field(sx, sy, sz, ct_table[1] - ct_table[0])
    ).astype(v_dtype)
    c1_a = jnp.zeros(u0.shape, carry_dtype)
    return (
        jnp.where(taylor, u1_s, u1_a),
        jnp.where(taylor, v1_s, v1_a),
        jnp.where(taylor, c1_s, c1_a),
    )


def _comp_step1(path: str, block_x, interpret):
    """The batch's 1-step compensated kernel
    `(u, v, carry, problem, coeff) -> (u', v', carry')`: the jnp-roll
    reference on the "roll" path, the fused Pallas kernel elsewhere
    (the "kfused" lane bootstraps through the same Pallas 1-step kernel
    the solo velocity-form onion does)."""
    from wavetpu.kernels import stencil_pallas, stencil_ref

    if path == "roll":
        return stencil_ref.compensated_step
    if path == "pallas":
        return stencil_pallas.make_compensated_step_fn(
            block_x=block_x, interpret=interpret
        )

    def step(u, v, carry, problem, coeff):
        return stencil_pallas.compensated_step(
            u, v, carry, problem, coeff, interpret=interpret
        )

    return step


def _bootstrap(problem: Problem, dtype, sx, sy, sz, ct_table, taylor,
               step, params):
    """Layers 0/1 from a runtime ct table.

    `taylor` is the lane's per-lane bootstrap selector: True = the
    reference's step-derived Taylor half-step (valid only at the
    reference phase, where u_t(0) = 0), False = the exact analytic
    layer-1 initialization shifted phases need (see
    leapfrog.make_solver).  The `where` reproduces the solo solver's
    STATIC phase decision at runtime, selecting bitwise between two
    branches that each mirror the corresponding solo program op for op.
    """
    import jax.numpy as jnp

    from wavetpu.kernels import stencil_ref

    f = stencil_ref.compute_dtype(dtype)
    u0 = stencil_ref.apply_dirichlet(
        oracle.analytic_field(sx, sy, sz, ct_table[0])
    ).astype(dtype)
    u1_step = (
        0.5 * (u0.astype(f) + step(u0, u0, problem, params).astype(f))
    ).astype(dtype)
    u1_analytic = stencil_ref.apply_dirichlet(
        oracle.analytic_field(sx, sy, sz, ct_table[1])
    ).astype(dtype)
    return u0, jnp.where(taylor, u1_step, u1_analytic)


def _step1_pair(problem: Problem, path: str, block_x, interpret,
                with_field):
    """(fn4, default_params) for the batch's 1-step kernel: the roll or
    pallas step in leapfrog's 4-arg ParamStep form.  For field batches the
    fn takes the per-lane field as its params argument (the throwaway
    ParamStep built here only donates its .fn; its dummy params are never
    used)."""
    from wavetpu.kernels import stencil_ref
    from wavetpu.solver import leapfrog

    if path == "roll":
        if with_field:
            return stencil_ref.make_variable_c_step(
                np.zeros((1, 1, 1))
            ).fn, ()
        return leapfrog._as_param_step(None)
    from wavetpu.kernels import stencil_pallas

    if with_field:
        return stencil_pallas.make_step_fn(
            block_x=block_x, interpret=interpret,
            c2tau2_field=np.zeros((1, 1, 1)),
        ).fn, ()
    return leapfrog._as_param_step(
        stencil_pallas.make_step_fn(block_x=block_x, interpret=interpret)
    )


class EnsembleSolver:
    """The compiled batched program for one (problem, path, batch) key.

    Built once, reused across batches - this is the object the serve
    layer's program cache holds.  `compile()` ahead-of-time lowers the
    vmapped march (warm-up without executing a solve); `run(lanes)`
    executes it on a packed batch and returns per-lane SolveResults.

    The lane program vmapped here mirrors the solo solver's op sequence
    exactly; tests/test_ensemble.py pins bitwise lane parity.
    """

    def __init__(
        self,
        problem: Problem,
        n_lanes: int,
        dtype=None,
        path: str = "roll",
        k: int = 4,
        compute_errors: bool = True,
        interpret: Optional[bool] = None,
        block_x: Optional[int] = None,
        with_field: bool = False,
        scheme: str = "standard",
    ):
        import jax
        import jax.numpy as jnp

        from wavetpu.kernels import stencil_ref

        if n_lanes < 1:
            raise ValueError(f"n_lanes must be >= 1, got {n_lanes}")
        if path not in PATHS:
            raise ValueError(f"path must be one of {PATHS}, got {path!r}")
        if scheme not in SCHEMES:
            raise ValueError(
                f"scheme must be one of {SCHEMES}, got {scheme!r}"
            )
        if path == "kfused":
            if k < 2:
                raise ValueError(f"kfused path needs k >= 2, got {k}")
            if problem.N % k:
                raise ValueError(f"k={k} must divide N={problem.N}")
        if with_field and compute_errors:
            raise ValueError(
                "field batches have no analytic oracle; pass "
                "compute_errors=False"
            )
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        self.problem = problem
        self.n_lanes = n_lanes
        self.dtype = jnp.float32 if dtype is None else dtype
        self.path = path
        self.k = k if path == "kfused" else 1
        self.compute_errors = compute_errors
        self.with_field = with_field
        self.scheme = scheme
        if scheme == "compensated":
            if with_field:
                raise ValueError(
                    "per-lane c2tau2 fields are not wired through the "
                    "compensated vmapped core"
                )
            if jnp.dtype(self.dtype) == jnp.bfloat16:
                raise ValueError(
                    "compensated scheme requires f32/f64 state"
                )
        self._f = stencil_ref.compute_dtype(self.dtype)
        self._exec = None
        self.compile_seconds: Optional[float] = None
        if scheme == "compensated":
            lane_run = (
                self._comp_kfused_lane(interpret, block_x)
                if path == "kfused"
                else self._comp_onestep_lane(interpret, block_x)
            )
        else:
            lane_run = (
                self._kfused_lane(interpret, block_x)
                if path == "kfused"
                else self._onestep_lane(interpret, block_x)
            )
        in_axes = (0, 0, 0, 0) if with_field else (0, 0, 0)
        self._runner = jax.jit(jax.vmap(lane_run, in_axes=in_axes))

    # ---- lane programs (solo op sequences with runtime ct tables) ----

    def _onestep_lane(self, interpret, block_x):
        import jax.numpy as jnp
        from jax import lax

        problem, dtype, f = self.problem, self.dtype, self._f
        compute_errors = self.compute_errors
        sx, sy, sz = oracle.spatial_factors(problem, f)
        errors = _lane_error_fn(problem, dtype)
        step, params0 = _step1_pair(
            problem, self.path, block_x, interpret, self.with_field
        )

        def lane_run(ct_table, stop, taylor, *field):
            params = field[0] if self.with_field else params0
            u0, u1 = _bootstrap(
                problem, dtype, sx, sy, sz, ct_table, taylor, step, params
            )
            a0 = r0 = jnp.zeros((), f)
            if compute_errors:
                a1, r1 = errors(u1, 1, ct_table)
            else:
                a1 = r1 = jnp.zeros((), f)

            def body(carry, n):
                u_prev, u = carry
                u_next = step(u_prev, u, problem, params)
                live = n <= stop
                if compute_errors:
                    ae, re = errors(u_next, n, ct_table)
                    ae = jnp.where(live, ae, jnp.zeros((), f))
                    re = jnp.where(live, re, jnp.zeros((), f))
                else:
                    ae = re = jnp.zeros((), f)
                return (
                    jnp.where(live, u, u_prev),
                    jnp.where(live, u_next, u),
                ), (ae, re)

            (u_prev, u_cur), (abs_t, rel_t) = lax.scan(
                body, (u0, u1), jnp.arange(2, problem.timesteps + 1)
            )
            return (
                u_prev,
                u_cur,
                jnp.concatenate([jnp.stack([a0, a1]), abs_t]),
                jnp.concatenate([jnp.stack([r0, r1]), rel_t]),
            )

        return lane_run

    def _kfused_lane(self, interpret, block_x):
        import jax.numpy as jnp
        from jax import lax

        from wavetpu.kernels import stencil_pallas
        from wavetpu.solver import kfused, leapfrog

        problem, dtype, f = self.problem, self.dtype, self._f
        k, compute_errors = self.k, self.compute_errors
        sx, _ct, syz, rsyz, xmask, inv_absx = kfused._oracle_parts(
            problem, f
        )
        _, sy, sz = oracle.spatial_factors(problem, f)
        errors = _lane_error_fn(problem, dtype)
        step1, params0 = _step1_pair(
            problem, "pallas", block_x, interpret, self.with_field
        )
        nsteps = problem.timesteps
        nblocks = (nsteps - 1) // k
        rem = (nsteps - 1) - nblocks * k

        def lane_run(ct_table, stop, taylor, *field):
            params = field[0] if self.with_field else params0
            u0, u1 = _bootstrap(
                problem, dtype, sx, sy, sz, ct_table, taylor, step1, params
            )
            a0 = r0 = jnp.zeros((), f)
            if compute_errors:
                a1, r1 = errors(u1, 1, ct_table)
            else:
                a1 = r1 = jnp.zeros((), f)

            def kblock(carry, nstart):
                u_prev, u = carry
                ctk = lax.dynamic_slice(ct_table, (nstart + 1,), (k,))
                sxct = ctk[:, None] * sx[None, :]
                up, uc, dmax, rmax = stencil_pallas.fused_kstep(
                    u_prev, u, syz, rsyz, sxct,
                    k=k, coeff=problem.a2tau2, inv_h2=problem.inv_h2,
                    c2tau2_field=field[0] if self.with_field else None,
                    block_x=block_x, interpret=interpret,
                    with_errors=compute_errors,
                )
                if compute_errors:
                    abs_e, rel_e = kfused._block_errors(
                        dmax, rmax, ctk, xmask, inv_absx
                    )
                else:
                    abs_e = rel_e = jnp.zeros((k,), f)
                # A lane freezes at whole blocks: live iff the block's
                # last layer is within the lane's march.
                live = nstart + k <= stop
                return (
                    jnp.where(live, up, u_prev),
                    jnp.where(live, uc, u),
                ), (
                    jnp.where(live, abs_e, jnp.zeros((k,), f)),
                    jnp.where(live, rel_e, jnp.zeros((k,), f)),
                )

            starts = 1 + k * jnp.arange(nblocks)
            (u_prev, u_cur), (abs_b, rel_b) = lax.scan(
                kblock, (u0, u1), starts
            )
            abs_parts = [abs_b.reshape(-1)]
            rel_parts = [rel_b.reshape(-1)]
            if rem:
                # The uniform remainder tail marches the 1-step kernel,
                # masked per layer (as the solo kfused march's tail would,
                # for lanes stopping before it).
                def body(carry, n):
                    u_prev, u = carry
                    u_next = step1(u_prev, u, problem, params)
                    live = n <= stop
                    if compute_errors:
                        ae, re = errors(u_next, n, ct_table)
                        ae = jnp.where(live, ae, jnp.zeros((), f))
                        re = jnp.where(live, re, jnp.zeros((), f))
                    else:
                        ae = re = jnp.zeros((), f)
                    return (
                        jnp.where(live, u, u_prev),
                        jnp.where(live, u_next, u),
                    ), (ae, re)

                (u_prev, u_cur), (ra, rr) = lax.scan(
                    body, (u_prev, u_cur),
                    nsteps - rem + 1 + jnp.arange(rem, dtype=jnp.int32),
                )
                abs_parts.append(ra)
                rel_parts.append(rr)
            return (
                u_prev,
                u_cur,
                jnp.concatenate(
                    [jnp.stack([a0, a1])] + abs_parts
                ),
                jnp.concatenate(
                    [jnp.stack([r0, r1])] + rel_parts
                ),
            )

        return lane_run

    def _comp_onestep_lane(self, interpret, block_x):
        """Compensated (Kahan) 1-step lane: mirrors
        leapfrog.make_compensated_solver op for op with a runtime ct
        table (roll = stencil_ref.compensated_step, pallas = the fused
        Pallas compensated kernel)."""
        import jax.numpy as jnp
        from jax import lax

        problem, dtype, f = self.problem, self.dtype, self._f
        compute_errors = self.compute_errors
        sx, sy, sz = oracle.spatial_factors(problem, f)
        errors = _lane_error_fn(problem, dtype)
        step = _comp_step1(self.path, block_x, interpret)

        def lane_run(ct_table, stop, taylor):
            u1, v1, c1 = _comp_bootstrap(
                problem, dtype, dtype, dtype, sx, sy, sz, ct_table,
                taylor, step,
            )
            a0 = r0 = jnp.zeros((), f)
            if compute_errors:
                a1, r1 = errors(u1, 1, ct_table)
            else:
                a1 = r1 = jnp.zeros((), f)

            def body(carry, n):
                u, v, c = carry
                u2, v2, c2 = step(u, v, c, problem, None)
                live = n <= stop
                if compute_errors:
                    ae, re = errors(u2, n, ct_table)
                    ae = jnp.where(live, ae, jnp.zeros((), f))
                    re = jnp.where(live, re, jnp.zeros((), f))
                else:
                    ae = re = jnp.zeros((), f)
                return (
                    jnp.where(live, u2, u),
                    jnp.where(live, v2, v),
                    jnp.where(live, c2, c),
                ), (ae, re)

            (u, v, c), (abs_t, rel_t) = lax.scan(
                body, (u1, v1, c1), jnp.arange(2, problem.timesteps + 1)
            )
            # u_prev reconstructed from the increment, as the solo
            # compensated solver returns it.
            return (
                u - v,
                u,
                jnp.concatenate([jnp.stack([a0, a1]), abs_t]),
                jnp.concatenate([jnp.stack([r0, r1]), rel_t]),
            )

        return lane_run

    def _comp_kfused_lane(self, interpret, block_x):
        """Velocity-form compensated onion lane: mirrors
        kfused_comp._make_march (k-fused blocks + a k=1 tail through the
        SAME kernel) with a runtime ct table, per-lane k-block stop
        masking on (u, v, carry), and the guarded rel metric."""
        import jax.numpy as jnp
        from jax import lax

        from wavetpu.kernels import stencil_pallas
        from wavetpu.solver import kfused, kfused_comp

        problem, dtype, f = self.problem, self.dtype, self._f
        k, compute_errors = self.k, self.compute_errors
        v_dtype = dtype
        carry_dtype = kfused_comp._default_carry_dtype(dtype)
        sx, _ct, syz, rsyz, xmask, inv_absx = kfused._oracle_parts(
            problem, f
        )
        inv_absx = jnp.where(
            jnp.abs(sx) > kfused_comp._rel_guard_tol(f), inv_absx,
            jnp.asarray(0.0, f),
        )
        _, sy, sz = oracle.spatial_factors(problem, f)
        errors1 = _lane_error_fn_guarded(problem, dtype)
        step1 = _comp_step1("kfused", block_x, interpret)
        nsteps = problem.timesteps
        nblocks = (nsteps - 1) // k
        rem = (nsteps - 1) - nblocks * k

        def kblock(u, v, c, ct_table, nstart, kk, bxo):
            ctk = lax.dynamic_slice(ct_table, (nstart + 1,), (kk,))
            sxct = ctk[:, None] * sx[None, :]
            u2, v2, c2, dmax, rmax = stencil_pallas.fused_kstep_comp(
                u, v, c, syz, rsyz, sxct,
                k=kk, coeff=problem.a2tau2, inv_h2=problem.inv_h2,
                block_x=bxo, interpret=interpret,
                with_errors=compute_errors,
            )
            if compute_errors:
                abs_e, rel_e = kfused._block_errors(
                    dmax, rmax, ctk, xmask, inv_absx
                )
            else:
                abs_e = rel_e = jnp.zeros((kk,), f)
            return u2, v2, c2, abs_e, rel_e

        def lane_run(ct_table, stop, taylor):
            u1, v1, c1 = _comp_bootstrap(
                problem, dtype, v_dtype, carry_dtype, sx, sy, sz,
                ct_table, taylor, step1,
            )
            a0 = r0 = jnp.zeros((), f)
            if compute_errors:
                a1, r1 = errors1(u1, 1, ct_table)
            else:
                a1 = r1 = jnp.zeros((), f)

            def body(state, nstart):
                u, v, c = state
                u2, v2, c2, abs_e, rel_e = kblock(
                    u, v, c, ct_table, nstart, k, block_x
                )
                live = nstart + k <= stop
                return (
                    jnp.where(live, u2, u),
                    jnp.where(live, v2, v),
                    jnp.where(live, c2, c),
                ), (
                    jnp.where(live, abs_e, jnp.zeros((k,), f)),
                    jnp.where(live, rel_e, jnp.zeros((k,), f)),
                )

            starts = 1 + k * jnp.arange(nblocks)
            (u, v, c), (abs_b, rel_b) = lax.scan(
                body, (u1, v1, c1), starts
            )
            abs_parts = [abs_b.reshape(-1)]
            rel_parts = [rel_b.reshape(-1)]
            for t in range(rem):
                # The solo march's remainder: the same kernel at k=1
                # (kfused_comp._make_march), masked per layer here.
                u2, v2, c2, a_1, r_1 = kblock(
                    u, v, c, ct_table, nsteps - rem + t, 1, None
                )
                live = nsteps - rem + t + 1 <= stop
                u = jnp.where(live, u2, u)
                v = jnp.where(live, v2, v)
                c = jnp.where(live, c2, c)
                abs_parts.append(
                    jnp.where(live, a_1, jnp.zeros((1,), f))
                )
                rel_parts.append(
                    jnp.where(live, r_1, jnp.zeros((1,), f))
                )
            # u_prev as kfused_comp._as_result reconstructs it.
            return (
                (u.astype(f) - v.astype(f)).astype(dtype),
                u,
                jnp.concatenate([jnp.stack([a0, a1])] + abs_parts),
                jnp.concatenate([jnp.stack([r0, r1])] + rel_parts),
            )

        return lane_run

    # ---- packing / compiling / running ----

    def pack(self, lanes: Sequence[LaneSpec]) -> Tuple:
        """Device arguments for a padded batch: (B, T+1) ct tables, (B,)
        stop layers, and (B, N, N, N) fields when the batch carries them
        (caller has already run `fill_fields`)."""
        import jax.numpy as jnp

        if len(lanes) != self.n_lanes:
            raise ValueError(
                f"batch has {len(lanes)} lanes; this program wants "
                f"{self.n_lanes} (pad with padding_lane())"
            )
        cts = np.stack(
            [
                oracle.time_factor_table_np(self.problem, lane.phase)
                for lane in lanes
            ]
        )
        stops = np.asarray(
            [lane.stop(self.problem) for lane in lanes], np.int32
        )
        # Per-lane bootstrap selector: the solo solvers' STATIC
        # phase-equality decision, evaluated at pack time (see
        # _bootstrap).
        taylor = np.asarray(
            [lane.phase == oracle.TWO_PI for lane in lanes], bool
        )
        args = (
            jnp.asarray(cts, self._f),
            jnp.asarray(stops),
            jnp.asarray(taylor),
        )
        if self.with_field:
            fields = np.stack(
                [np.asarray(lane.c2tau2_field) for lane in lanes]
            )
            args = args + (jnp.asarray(fields, self._f),)
        return args

    def _example_args(self) -> Tuple:
        import jax.numpy as jnp

        b, t = self.n_lanes, self.problem.timesteps
        args = (
            jnp.zeros((b, t + 1), self._f),
            jnp.ones((b,), jnp.int32),
            jnp.ones((b,), bool),
        )
        if self.with_field:
            args = args + (jnp.zeros((b,) + (self.problem.N,) * 3, self._f),)
        return args

    def compile(self) -> float:
        """AOT lower + compile (the serve engine's warm-up); idempotent.
        Returns the compile wall seconds (0.0 on a warm hit)."""
        if self._exec is not None:
            return 0.0
        t0 = time.perf_counter()
        self._exec = self._runner.lower(*self._example_args()).compile()
        self.compile_seconds = time.perf_counter() - t0
        return self.compile_seconds

    def executable_payload(self):
        """The serialized compiled executable - (payload_bytes,
        in_tree, out_tree) for the persistent program cache
        (serve/progcache.py) - or None when not yet compiled.  Raises
        where the jaxlib cannot serialize; callers probe
        `progcache.aot_capability()` first."""
        if self._exec is None:
            return None
        from jax.experimental import serialize_executable as se

        return se.serialize(self._exec)

    def adopt_executable(self, payload) -> float:
        """Install a deserialized executable (the disk tier's warm
        path - skips lower+compile entirely); returns the deserialize
        wall seconds.  Raises on an incompatible payload - the caller
        counts it a cache miss and compiles fresh."""
        from jax.experimental import serialize_executable as se

        t0 = time.perf_counter()
        self._exec = se.deserialize_and_load(*payload)
        self.compile_seconds = time.perf_counter() - t0
        return self.compile_seconds

    def run(self, lanes: Sequence[LaneSpec]):
        """Execute the batch; returns (outputs, init_seconds,
        solve_seconds) with outputs = (u_prev_b, u_cur_b, abs_b, rel_b).
        init_seconds is the compile time this call paid (0 when warm)."""
        import jax

        init_s = self.compile()
        args = self.pack(lanes)
        t0 = time.perf_counter()
        out = self._exec(*args)
        jax.block_until_ready(out)
        # Readback proves execution on remote backends (the same reasoning
        # as leapfrog._timed_compile_run's sync): the (B, T+1) error
        # block is the smallest always-present output.
        np.asarray(out[2])
        solve_s = time.perf_counter() - t0
        return out, init_s, solve_s


def _lane_results(problem, outputs, lanes, init_s, solve_s):
    """Per-lane SolveResults from batched outputs (padding already
    dropped by the caller passing only real lanes and their indices)."""
    from wavetpu.solver.leapfrog import SolveResult

    upb, ucb, ab, rb = outputs
    results = []
    for i, lane in enumerate(lanes):
        s = lane.stop(problem)
        results.append(
            SolveResult(
                problem=problem,
                u_prev=upb[i],
                u_cur=ucb[i],
                abs_errors=np.asarray(ab[i], np.float64)[: s + 1],
                rel_errors=np.asarray(rb[i], np.float64)[: s + 1],
                init_seconds=init_s,
                solve_seconds=solve_s,
                steps_computed=s,
                final_step=s,
            )
        )
    return results


# ---- capability probe ----

_PROBE_CACHE = {}


def vmap_capability(
    path: str,
    k: int = 2,
    interpret: Optional[bool] = None,
    with_field: bool = False,
    scheme: str = "standard",
) -> Tuple[bool, Optional[str]]:
    """Does jax.vmap compose with this (scheme, path) on this backend?

    Runs a tiny batched solve (N=8, 2 lanes) end to end once per
    (scheme, path, with_field, backend) and caches the verdict.  Returns
    (ok, reason): reason is the exception summary on failure - the string
    `solve_ensemble` records in `EnsembleResult.fallback_reason` so a
    fallback is never silent.
    """
    import jax

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    key = (scheme, path, bool(with_field), bool(interpret),
           jax.default_backend())
    if key in _PROBE_CACHE:
        return _PROBE_CACHE[key]
    try:
        tiny = Problem(N=8, timesteps=2 * max(2, k) + 1)
        lanes = [LaneSpec(), LaneSpec(phase=1.0)]
        if with_field:
            lanes = fill_fields(tiny, lanes)
        solver = EnsembleSolver(
            tiny, len(lanes), path=path, k=min(k, 2) if path == "kfused"
            else k, compute_errors=not with_field, interpret=interpret,
            with_field=with_field, scheme=scheme,
        )
        out, _, _ = solver.run(lanes)
        np.asarray(out[1])
        verdict = (True, None)
    except Exception as e:  # recorded, never raised: probe = capability
        verdict = (False, f"{type(e).__name__}: {e}")
    _PROBE_CACHE[key] = verdict
    return verdict


def probe_results() -> list:
    """Every cached vmap-capability verdict, as dicts - the /metrics
    surface that makes a chip silently serving lane-loop visible from
    the outside (GET /metrics -> program_cache.vmap_probes)."""
    return [
        {
            "scheme": k[0], "path": k[1], "with_field": k[2],
            "interpret": k[3], "backend": k[4],
            "ok": v[0], "reason": v[1],
        }
        for k, v in sorted(_PROBE_CACHE.items(), key=lambda kv: kv[0])
    ]


# ---- lane-loop fallback ----

def _solve_lane_loop(
    problem, lanes, dtype, scheme, path, k, compute_errors, interpret,
    block_x, reason,
):
    """Sequential solo solves behind the EnsembleResult interface - the
    recorded fallback when vmap does not compose on this backend."""
    from wavetpu.kernels import stencil_pallas, stencil_ref
    from wavetpu.solver import kfused, leapfrog

    results = []
    init_total = solve_total = 0.0
    for lane in lanes:
        s = lane.stop(problem)
        if scheme == "compensated" and path == "kfused":
            # The flagship velocity-form onion, lane by lane.
            from wavetpu.solver import kfused_comp

            res = kfused_comp.solve_kfused_comp(
                problem, dtype=dtype, k=k,
                compute_errors=compute_errors, stop_step=s,
                interpret=interpret, phase=lane.phase,
            )
        elif scheme == "compensated":
            comp_step = None
            if path == "pallas":
                comp_step = stencil_pallas.make_compensated_step_fn(
                    interpret=interpret
                )
            res = leapfrog.solve_compensated(
                problem, dtype=dtype, comp_step_fn=comp_step,
                compute_errors=compute_errors, stop_step=s,
                phase=lane.phase,
            )
        elif path == "kfused":
            res = kfused.solve_kfused(
                problem, dtype=dtype, k=k, compute_errors=compute_errors,
                stop_step=s, block_x=block_x, interpret=interpret,
                c2tau2_field=lane.c2tau2_field, phase=lane.phase,
            )
        else:
            if lane.c2tau2_field is not None:
                step_fn = (
                    stencil_pallas.make_step_fn(
                        block_x=block_x, interpret=interpret,
                        c2tau2_field=lane.c2tau2_field,
                    )
                    if path == "pallas"
                    else stencil_ref.make_variable_c_step(lane.c2tau2_field)
                )
            else:
                step_fn = (
                    stencil_pallas.make_step_fn(
                        block_x=block_x, interpret=interpret
                    )
                    if path == "pallas"
                    else None
                )
            res = leapfrog.solve(
                problem, dtype=dtype, step_fn=step_fn,
                compute_errors=compute_errors, stop_step=s,
                phase=lane.phase,
            )
        init_total += res.init_seconds
        solve_total += res.solve_seconds
        results.append(res)
    return EnsembleResult(
        problem=problem,
        results=results,
        path=path,
        batched=False,
        fallback_reason=reason,
        batch_size=len(lanes),
        n_lanes=len(lanes),
        init_seconds=init_total,
        solve_seconds=solve_total,
    )


def solve_ensemble(
    problem: Problem,
    lanes: Sequence[LaneSpec],
    dtype=None,
    scheme: str = "standard",
    path: str = "roll",
    k: int = 4,
    compute_errors: bool = True,
    interpret: Optional[bool] = None,
    block_x: Optional[int] = None,
    pad_to: Optional[int] = None,
    solver: Optional[EnsembleSolver] = None,
) -> EnsembleResult:
    """Solve a batch of lanes as one vmapped program (or the recorded
    lane-loop fallback).

    `pad_to` rounds the batch up to a program-cache bucket with masked
    `padding_lane()`s (dropped from `results`).  Pass a pre-built
    `solver` (the serve engine's cached program) to skip rebuilding; its
    geometry must match.
    """
    import jax
    import jax.numpy as jnp

    dtype = jnp.float32 if dtype is None else dtype
    lanes = list(lanes)
    with_field = _validate(problem, lanes, path, k, compute_errors,
                           scheme)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    ok, why = vmap_capability(
        path, k=k, interpret=interpret, with_field=with_field,
        scheme=scheme,
    )
    if not ok:
        return _solve_lane_loop(
            problem, lanes, dtype, scheme, path, k, compute_errors,
            interpret, block_x,
            f"vmap capability probe failed on scheme {scheme!r} path "
            f"{path!r}: {why}",
        )
    if with_field:
        lanes = fill_fields(problem, lanes)
    batch = lanes
    if pad_to is not None:
        if pad_to < len(lanes):
            raise ValueError(
                f"pad_to={pad_to} < {len(lanes)} real lanes"
            )
        pad = [padding_lane()] * (pad_to - len(lanes))
        batch = lanes + (fill_fields(problem, pad) if with_field else pad)
    if solver is None:
        solver = EnsembleSolver(
            problem, len(batch), dtype=dtype, path=path, k=k,
            compute_errors=compute_errors, interpret=interpret,
            block_x=block_x, with_field=with_field, scheme=scheme,
        )
    outputs, init_s, solve_s = solver.run(batch)
    return EnsembleResult(
        problem=problem,
        results=_lane_results(problem, outputs, lanes, init_s, solve_s),
        path=path,
        batched=True,
        fallback_reason=None,
        batch_size=len(batch),
        n_lanes=len(lanes),
        init_seconds=init_s,
        solve_seconds=solve_s,
        u_prev_batch=outputs[0],
        u_cur_batch=outputs[1],
    )
