"""Sharded x batched: an ensemble axis composed with the device mesh.

PR 3's vmapped core batches SINGLE-DEVICE solves; this module composes
the lane axis with the (MX, MY, MZ) mesh axes so a multi-chip host can
serve a batch of SHARDED solves as one program - the pod-scale
throughput composition of arXiv:2108.11076 (batch axis x device mesh).

Mechanism: shard_map-of-vmap.  The state rides as (B,) + topo.padded
sharded P(None, "x", "y", "z") - lane-major over the batch axis, spatial
axes on the mesh exactly as solver/sharded.py lays them out - and inside
shard_map the per-lane local march (the SAME op sequence
`sharded._local_solve_fns` runs: halo ppermutes, boundary masking,
pmax'd error reductions) is vmapped over the lane axis.  Collectives
batch under vmap (ppermute/pmax have batching rules), so every lane's
per-shard ops mirror the solo sharded solve op for op - the BITWISE
lane-parity contract of tests/test_ensemble_sharded.py, the sharded twin
of ensemble/batched.py's.

Lane identity is (phase, stop_step) - per-lane runtime (B, T+1) ct
tables, the per-lane taylor/analytic bootstrap selector, and per-layer
`where` stop masking (no k-block constraint: the sharded lane marches
the 1-step kernel).  Per-lane c2tau2 fields are not wired (constant
speed only); scheme is "standard" (the distributed velocity-form
flagship still serves solo via solver/kfused_comp.py).

`vmap_capability(mesh_shape, ...)` probes a tiny batched sharded solve
once per (mesh, kernel, backend) and caches the verdict; a failed probe
drops to the recorded lane-loop fallback (sequential solo sharded
solves), reason in `EnsembleResult.fallback_reason` and visible in
GET /metrics.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence, Tuple

import numpy as np

from wavetpu.core.problem import Problem
from wavetpu.ensemble.batched import (
    EnsembleResult,
    LaneSpec,
    _lane_results,
    padding_lane,
)
from wavetpu.verify import oracle

KERNELS = ("roll", "pallas")


def _validate(problem: Problem, lanes: Sequence[LaneSpec], kernel: str,
              compute_errors: bool) -> None:
    if kernel not in KERNELS:
        raise ValueError(
            f"kernel must be one of {KERNELS}, got {kernel!r}"
        )
    if not lanes:
        raise ValueError("an ensemble needs at least one lane")
    for i, lane in enumerate(lanes):
        if lane.c2tau2_field is not None:
            raise ValueError(
                f"lane {i}: per-lane c2tau2 fields are not wired through "
                f"the sharded ensemble (constant speed only)"
            )
        s = lane.stop(problem)
        if not 1 <= s <= problem.timesteps:
            raise ValueError(
                f"lane {i}: stop_step must be in [1, {problem.timesteps}],"
                f" got {s}"
            )


class ShardedEnsembleSolver:
    """One compiled shard_map-of-vmap program for (problem, mesh, batch).

    The sharded twin of `batched.EnsembleSolver` - same
    compile()/pack()/run() contract, so the serve engine's program cache
    holds either interchangeably.  Lane programs mirror
    `sharded.make_sharded_solver`'s local op sequence (kernel="roll" or
    "pallas", serial exchange, standard scheme).
    """

    def __init__(
        self,
        problem: Problem,
        n_lanes: int,
        mesh_shape: Tuple[int, int, int],
        dtype=None,
        kernel: str = "roll",
        compute_errors: bool = True,
        interpret: Optional[bool] = None,
        devices=None,
    ):
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from wavetpu import compat
        from wavetpu.core.grid import AXIS_NAMES
        from wavetpu.kernels import stencil_ref
        from wavetpu.solver import sharded

        if n_lanes < 1:
            raise ValueError(f"n_lanes must be >= 1, got {n_lanes}")
        if kernel not in KERNELS:
            raise ValueError(
                f"kernel must be one of {KERNELS}, got {kernel!r}"
            )
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        self.problem = problem
        self.n_lanes = n_lanes
        self.mesh_shape = tuple(int(m) for m in mesh_shape)
        self.dtype = jnp.float32 if dtype is None else dtype
        self.kernel = kernel
        self.compute_errors = compute_errors
        self._f = stencil_ref.compute_dtype(self.dtype)
        self._exec = None
        self.compile_seconds: Optional[float] = None
        topo, mesh = sharded._resolve_mesh(
            problem, self.mesh_shape, devices
        )
        self.topo = topo
        f = self._f
        dtype_s = self.dtype
        nsteps = problem.timesteps
        (sx, sy, sz), bcs, mes, _ct = sharded._replicated_inputs(
            problem, topo, dtype_s
        )
        step = sharded._make_local_step(
            problem, topo, dtype_s, kernel, False, interpret
        )
        compute = compute_errors

        def lane_body(ct, stop, taylor, sx, sy, sz, bcx, bcy, bcz,
                      mex, mey, mez):
            # Per-lane local march: the op sequence of
            # sharded._local_solve_fns (errors_fn/bootstrap/scan_layers)
            # with the ct table a runtime argument, both bootstrap
            # branches computed and `where`-selected per lane, and
            # per-layer stop masking.
            def errors(u, layer):
                if not compute:
                    z = jnp.zeros((), f)
                    return z, z
                field = oracle.analytic_field(sx, sy, sz, ct[layer])
                ae, re = oracle.layer_errors(
                    u.astype(f), field, mex, mey, mez
                )
                return (
                    lax.pmax(ae, AXIS_NAMES),
                    lax.pmax(re, AXIS_NAMES),
                )

            bc = (
                bcx[:, None, None] * bcy[None, :, None]
                * bcz[None, None, :]
            )
            u0 = (
                oracle.analytic_field(sx, sy, sz, ct[0]) * bc
            ).astype(dtype_s)
            s = step(u0, u0, bc, None)
            u1_step = (0.5 * (u0.astype(f) + s.astype(f))).astype(dtype_s)
            u1_an = (
                oracle.analytic_field(sx, sy, sz, ct[1]) * bc
            ).astype(dtype_s)
            u1 = jnp.where(taylor, u1_step, u1_an)
            a0 = r0 = jnp.zeros((), f)
            a1, r1 = errors(u1, 1)

            def body(carry, n):
                u_prev, u = carry
                u_next = step(u_prev, u, bc, None)
                live = n <= stop
                ae, re = errors(u_next, n)
                ae = jnp.where(live, ae, jnp.zeros((), f))
                re = jnp.where(live, re, jnp.zeros((), f))
                return (
                    jnp.where(live, u, u_prev),
                    jnp.where(live, u_next, u),
                ), (ae, re)

            (u_prev, u_cur), (abs_t, rel_t) = lax.scan(
                body, (u0, u1), jnp.arange(2, nsteps + 1)
            )
            return (
                u_prev,
                u_cur,
                jnp.concatenate([jnp.stack([a0, a1]), abs_t]),
                jnp.concatenate([jnp.stack([r0, r1]), rel_t]),
            )

        def local_batch(cts, stops, taylors, sx, sy, sz, bcx, bcy, bcz,
                        mex, mey, mez):
            return jax.vmap(
                lane_body, in_axes=(0, 0, 0) + (None,) * 9
            )(cts, stops, taylors, sx, sy, sz, bcx, bcy, bcz,
              mex, mey, mez)

        state_spec = P(None, *AXIS_NAMES)
        sharded_fn = compat.shard_map(
            local_batch,
            mesh=mesh,
            in_specs=(
                P(), P(), P(),
                P("x"), P("y"), P("z"),
                P("x"), P("y"), P("z"),
                P("x"), P("y"), P("z"),
            ),
            out_specs=(state_spec, state_spec, P(), P()),
            check_vma=False,
        )

        def run(cts, stops, taylors):
            return sharded_fn(cts, stops, taylors, sx, sy, sz, *bcs, *mes)

        self._runner = jax.jit(run)

    # ---- packing / compiling / running (EnsembleSolver contract) ----

    def pack(self, lanes: Sequence[LaneSpec]) -> Tuple:
        import jax.numpy as jnp

        if len(lanes) != self.n_lanes:
            raise ValueError(
                f"batch has {len(lanes)} lanes; this program wants "
                f"{self.n_lanes} (pad with padding_lane())"
            )
        cts = np.stack(
            [
                oracle.time_factor_table_np(self.problem, lane.phase)
                for lane in lanes
            ]
        )
        stops = np.asarray(
            [lane.stop(self.problem) for lane in lanes], np.int32
        )
        taylor = np.asarray(
            [lane.phase == oracle.TWO_PI for lane in lanes], bool
        )
        return (
            jnp.asarray(cts, self._f),
            jnp.asarray(stops),
            jnp.asarray(taylor),
        )

    def _example_args(self) -> Tuple:
        import jax.numpy as jnp

        b, t = self.n_lanes, self.problem.timesteps
        return (
            jnp.zeros((b, t + 1), self._f),
            jnp.ones((b,), jnp.int32),
            jnp.ones((b,), bool),
        )

    def compile(self) -> float:
        if self._exec is not None:
            return 0.0
        t0 = time.perf_counter()
        self._exec = self._runner.lower(*self._example_args()).compile()
        self.compile_seconds = time.perf_counter() - t0
        return self.compile_seconds

    def executable_payload(self):
        """Serialized executable for the persistent program cache -
        same contract as `batched.EnsembleSolver.executable_payload`
        (the two program types share the disk tier)."""
        if self._exec is None:
            return None
        from jax.experimental import serialize_executable as se

        return se.serialize(self._exec)

    def adopt_executable(self, payload) -> float:
        """Install a deserialized executable; see
        `batched.EnsembleSolver.adopt_executable`."""
        from jax.experimental import serialize_executable as se

        t0 = time.perf_counter()
        self._exec = se.deserialize_and_load(*payload)
        self.compile_seconds = time.perf_counter() - t0
        return self.compile_seconds

    def run(self, lanes: Sequence[LaneSpec]):
        import jax

        init_s = self.compile()
        args = self.pack(lanes)
        t0 = time.perf_counter()
        out = self._exec(*args)
        jax.block_until_ready(out)
        np.asarray(out[2])  # readback proves execution (leapfrog sync)
        solve_s = time.perf_counter() - t0
        return out, init_s, solve_s


# ---- capability probe ----

_PROBE_CACHE = {}


def vmap_capability(
    mesh_shape: Tuple[int, int, int],
    kernel: str = "roll",
    interpret: Optional[bool] = None,
) -> Tuple[bool, Optional[str]]:
    """Does shard_map-of-vmap compose on this (mesh, kernel, backend)?

    Runs a tiny batched sharded solve once per key and caches the
    verdict; `probe_results()` surfaces every cached entry for
    GET /metrics alongside the single-device probes."""
    import jax

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    key = (tuple(mesh_shape), kernel, bool(interpret),
           jax.default_backend())
    if key in _PROBE_CACHE:
        return _PROBE_CACHE[key]
    try:
        tiny = Problem(N=8, timesteps=4)
        lanes = [LaneSpec(), LaneSpec(phase=1.0)]
        solver = ShardedEnsembleSolver(
            tiny, len(lanes), mesh_shape, kernel=kernel,
            interpret=interpret,
        )
        out, _, _ = solver.run(lanes)
        np.asarray(out[1])
        verdict = (True, None)
    except Exception as e:  # recorded, never raised
        verdict = (False, f"{type(e).__name__}: {e}")
    _PROBE_CACHE[key] = verdict
    return verdict


def probe_results() -> list:
    """Cached sharded vmap-capability verdicts as dicts (for /metrics)."""
    return [
        {
            "mesh": list(k[0]), "kernel": k[1], "interpret": k[2],
            "backend": k[3], "ok": v[0], "reason": v[1],
        }
        for k, v in sorted(_PROBE_CACHE.items(), key=lambda kv: str(kv[0]))
    ]


# ---- lane-loop fallback + entry point ----

def _solve_lane_loop(problem, lanes, mesh_shape, dtype, kernel,
                     compute_errors, interpret, devices, reason):
    """Sequential solo sharded solves behind the EnsembleResult
    interface - the recorded fallback when the composition does not
    vmap on this backend."""
    from wavetpu.solver import sharded

    results = []
    init_total = solve_total = 0.0
    for lane in lanes:
        res = sharded.solve_sharded(
            problem, mesh_shape=mesh_shape, devices=devices, dtype=dtype,
            compute_errors=compute_errors, kernel=kernel,
            interpret=interpret, stop_step=lane.stop(problem),
            phase=lane.phase,
        )
        init_total += res.init_seconds
        solve_total += res.solve_seconds
        results.append(res)
    return EnsembleResult(
        problem=problem,
        results=results,
        path=f"sharded{tuple(mesh_shape)}:{kernel}",
        batched=False,
        fallback_reason=reason,
        batch_size=len(lanes),
        n_lanes=len(lanes),
        init_seconds=init_total,
        solve_seconds=solve_total,
    )


def solve_ensemble_sharded(
    problem: Problem,
    lanes: Sequence[LaneSpec],
    mesh_shape: Tuple[int, int, int],
    dtype=None,
    kernel: str = "roll",
    compute_errors: bool = True,
    interpret: Optional[bool] = None,
    devices=None,
    pad_to: Optional[int] = None,
    solver: Optional[ShardedEnsembleSolver] = None,
) -> EnsembleResult:
    """Solve a batch of lanes as ONE sharded batched program over
    `mesh_shape` (or the recorded lane-loop fallback).  Same padding /
    pre-built-solver contract as `batched.solve_ensemble`; every lane is
    bitwise equal to its solo `sharded.solve_sharded` on the same mesh
    (u_prev/u_cur are the PADDED topo arrays, as the solo solver
    returns them)."""
    import jax
    import jax.numpy as jnp

    dtype = jnp.float32 if dtype is None else dtype
    lanes = list(lanes)
    _validate(problem, lanes, kernel, compute_errors)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    ok, why = vmap_capability(mesh_shape, kernel=kernel,
                              interpret=interpret)
    if not ok:
        return _solve_lane_loop(
            problem, lanes, mesh_shape, dtype, kernel, compute_errors,
            interpret, devices,
            f"sharded vmap capability probe failed on mesh "
            f"{tuple(mesh_shape)} kernel {kernel!r}: {why}",
        )
    batch = lanes
    if pad_to is not None:
        if pad_to < len(lanes):
            raise ValueError(f"pad_to={pad_to} < {len(lanes)} real lanes")
        batch = lanes + [padding_lane()] * (pad_to - len(lanes))
    if solver is None:
        solver = ShardedEnsembleSolver(
            problem, len(batch), mesh_shape, dtype=dtype, kernel=kernel,
            compute_errors=compute_errors, interpret=interpret,
            devices=devices,
        )
    outputs, init_s, solve_s = solver.run(batch)
    return EnsembleResult(
        problem=problem,
        results=_lane_results(problem, outputs, lanes, init_s, solve_s),
        path=f"sharded{tuple(mesh_shape)}:{kernel}",
        batched=True,
        fallback_reason=None,
        batch_size=len(batch),
        n_lanes=len(lanes),
        init_seconds=init_s,
        solve_seconds=solve_s,
        u_prev_batch=outputs[0],
        u_cur_batch=outputs[1],
    )
