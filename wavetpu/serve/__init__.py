"""Inference-style serving layer over the ensemble engine.

`engine.py` caches compiled batched programs (LRU, keyed by the full
program identity incl. the batch-size bucket) and applies the per-lane
numerical-health watchdog; `scheduler.py` coalesces concurrent requests
into batches (shape bucketing + max-batch/max-wait dynamic batching);
`api.py` is the stdlib-HTTP JSON front end (`wavetpu serve` /
`wavetpu-serve`).  See docs/serving.md for the endpoint contract.
"""

from wavetpu.serve.engine import ProgramKey, ServeEngine
from wavetpu.serve.scheduler import (
    DynamicBatcher,
    QueueFullError,
    ServeMetrics,
    SolveRequest,
)

__all__ = [
    "DynamicBatcher",
    "ProgramKey",
    "QueueFullError",
    "ServeEngine",
    "ServeMetrics",
    "SolveRequest",
]
