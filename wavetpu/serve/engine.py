"""Compiled-program cache + batched execution for the serve layer.

One compiled batched program serves every request that matches its
identity: `ProgramKey` = the full problem geometry (N, Lx/y/z, T,
timesteps), scheme, kernel path, k, dtype, whether lanes carry c2 fields,
whether errors are computed, and the BATCH-SIZE BUCKET.  Requests are
padded up to the nearest bucket with masked `padding_lane()`s (which
provably leave real lanes bitwise unchanged - tests/test_ensemble.py), so
a handful of buckets (default 1/2/4/8) covers every occupancy without
per-batch recompilation.

The cache is a plain LRU: `max_programs` compiled executables, eviction
of the least-recently-used on overflow, hits/misses/evictions counted for
/metrics.  `warmup()` AOT-compiles ahead of traffic so the first request
of a bucket does not pay the XLA compile.

With `--program-cache-dir` set, a DISK tier (serve/progcache.py) sits
between the memory LRU and a fresh compile: memory miss -> try
adopting a persisted serialized executable (counted `disk_hit`, the
saved seconds credited in the registry and the compile ledger as
`source: disk`) -> else a fresh XLA compile (counted `miss`, recorded
`source: fresh`, and persisted for the next process).  `miss` therefore
still counts exactly the fresh compiles - the loadgen gate's
"second replica compiled nothing" assertion reads it unchanged.  Disk
problems (corrupt entries, stale fingerprints, full disk) are counted
misses that fall through to a fresh compile - never a request failure,
never a circuit-breaker feed.

Every batch passes the per-lane numerical-health watchdog (the same
guarded-amax reduction as run/health.py): a poisoned lane - NaN, Inf, or
amplitude blowup from e.g. a Courant-unstable request - yields a per-lane
error string while its batchmates' results stand.  One bad request can
not sink the batch.

Since the serving-resilience round the engine also carries a per-
ProgramKey CIRCUIT BREAKER (serve/resilience.py): K consecutive
compile/execute failures quarantine the key (batch bucket excluded - a
tier is one breaker however it batches), so a poisoned tier sheds fast
`QuarantinedError`s (HTTP 503 + Retry-After) instead of re-paying the
failing compile on every request and stalling the single scheduler
worker for everyone else.  After the cooldown one request probes
half-open; success closes the breaker.  `run/faults.py`'s serve plan
injects `compile-fail` (before the build) and `execute-nan` (after the
solve, proving the watchdog catches it) at this layer.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import List, Optional, Sequence, Tuple

from wavetpu.core.problem import Problem
from wavetpu.ensemble import batched as ensemble
from wavetpu.ensemble import sharded as ens_sharded
from wavetpu.obs import accuracy
from wavetpu.obs import ledger as compile_ledger
from wavetpu.obs import perf, tracing
from wavetpu.obs.registry import MetricsRegistry
from wavetpu.progkey import ProgramKey
from wavetpu.run import faults, health
from wavetpu.serve.resilience import CircuitBreaker, QuarantinedError


# ProgramKey moved to `wavetpu.progkey` (the fleet router derives the
# same identity without importing jax); imported above and still
# exported from this module - `from wavetpu.serve.engine import
# ProgramKey` keeps working everywhere.


class ServeEngine:
    """LRU-cached batched programs + watchdogged batch execution.

    Thread-safe for the single-scheduler-worker design (a lock guards the
    cache anyway so warmup from another thread is safe).  `interpret`
    defaults to auto (interpret-mode pallas off-TPU, native on TPU).
    """

    def __init__(
        self,
        bucket_sizes: Sequence[int] = (1, 2, 4, 8),
        max_programs: int = 8,
        compute_errors: bool = True,
        interpret: Optional[bool] = None,
        watchdog: bool = True,
        max_amp: Optional[float] = None,
        block_x: Optional[int] = None,
        registry: Optional[MetricsRegistry] = None,
        breaker_threshold: Optional[int] = 3,
        breaker_cooldown_s: float = 30.0,
        fault_plan: Optional[faults.ServeFaultPlan] = None,
        program_cache_dir: Optional[str] = None,
        program_cache_max_bytes: Optional[int] = None,
    ):
        if not bucket_sizes or any(b < 1 for b in bucket_sizes):
            raise ValueError(f"bad bucket_sizes {bucket_sizes}")
        if max_programs < 1:
            raise ValueError(f"max_programs must be >= 1, got {max_programs}")
        self.bucket_sizes = tuple(sorted(set(int(b) for b in bucket_sizes)))
        self.max_programs = max_programs
        self.compute_errors = compute_errors
        self.interpret = interpret
        self.watchdog = watchdog
        self.max_amp = max_amp
        self.block_x = block_x
        # `build_server` passes the server's registry so cache and
        # compile/execute metrics land in the same /metrics exposition
        # as the scheduler's; a standalone engine gets its own.
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self._c_cache = self.registry.counter(
            "wavetpu_program_cache_events_total",
            "compiled-program cache events", ("event",),
        )
        self._h_compile = self.registry.histogram(
            "wavetpu_serve_compile_seconds",
            "batched-program build+compile time on cache miss",
            buckets=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
                     120.0, 300.0),
        )
        self._h_execute = self.registry.histogram(
            "wavetpu_serve_execute_seconds",
            "batch solve wall time (warm=false includes this key's "
            "first compile in the same request)", ("warm",),
            buckets=(0.005, 0.025, 0.1, 0.25, 1.0, 2.5, 5.0, 10.0,
                     30.0, 60.0, 120.0, 300.0),
        )
        self._lock = threading.Lock()
        self._programs: "OrderedDict[ProgramKey, ensemble.EnsembleSolver]" = (
            OrderedDict()
        )
        # path -> recorded fallback reason (never silent; surfaced in
        # /metrics so an operator sees WHICH path refused to vmap).
        self.fallbacks: dict = {}
        # Per-ProgramKey circuit breaker (None = disabled): K
        # consecutive compile/execute failures quarantine the key
        # bucket-wide; state rides both /metrics views.
        self.breaker: Optional[CircuitBreaker] = (
            None if breaker_threshold is None else CircuitBreaker(
                threshold=breaker_threshold,
                cooldown_s=breaker_cooldown_s, registry=self.registry,
            )
        )
        # Chaos harness: the serve-path injection plan (shared server-
        # wide by build_server; a standalone engine reads WAVETPU_FAULT
        # itself).  None on the happy path - every seam is a None check.
        self.fault_plan = (
            fault_plan if fault_plan is not None
            else faults.serve_plan_from_env()
        )
        if self.fault_plan is not None:
            self.fault_plan.bind_registry(self.registry)
        # Persistent disk tier (serve/progcache.py): None without
        # --program-cache-dir - every use is a None check, so the
        # historical cacheless path is untouched.  A bad directory
        # raises HERE (operator config error at startup), not
        # per-request.
        self.progcache = None
        if program_cache_dir:
            from wavetpu.serve import progcache as progcache_mod

            self.progcache = progcache_mod.ProgramCache(
                program_cache_dir,
                max_bytes=program_cache_max_bytes,
                registry=self.registry, fault_plan=self.fault_plan,
            )

    # Cache hit/miss/eviction counts live in the registry counter - the
    # single source of truth for the JSON and Prometheus /metrics views;
    # these properties keep the historical attribute API readable.

    @property
    def hits(self) -> int:
        return int(self._c_cache.value(event="hit"))

    @property
    def misses(self) -> int:
        return int(self._c_cache.value(event="miss"))

    @property
    def evictions(self) -> int:
        return int(self._c_cache.value(event="eviction"))

    @property
    def disk_hits(self) -> int:
        return int(self._c_cache.value(event="disk_hit"))

    @property
    def max_batch(self) -> int:
        return self.bucket_sizes[-1]

    def bucket_for(self, n_lanes: int) -> int:
        """Smallest bucket >= n_lanes (the scheduler never exceeds
        max_batch, so there is always one)."""
        for b in self.bucket_sizes:
            if b >= n_lanes:
                return b
        raise ValueError(
            f"{n_lanes} lanes exceed the largest bucket "
            f"{self.bucket_sizes[-1]}"
        )

    def _dtype(self, dtype_name: str):
        import jax.numpy as jnp

        table = {"f32": jnp.float32, "f64": jnp.float64,
                 "bf16": jnp.bfloat16}
        if dtype_name not in table:
            raise ValueError(
                f"dtype must be one of {sorted(table)}, got {dtype_name!r}"
            )
        return table[dtype_name]

    def program(
        self, problem: Problem, scheme: str, path: str, k: int,
        dtype_name: str, with_field: bool, batch: int,
        mesh: Optional[Tuple[int, int, int]] = None,
    ):
        """The cached compiled program for this key, building (and
        compiling) on miss - or None when the vmapped core cannot serve
        the key (failed capability probe): the caller then runs the
        recorded lane-loop fallback.  `mesh` selects the sharded x
        batched composition (ensemble/sharded.py); a (mesh, bucket) pair
        is its own cached executable."""
        return self._program(
            problem, scheme, path, k, dtype_name, with_field, batch, mesh
        )[0]

    def _program(
        self, problem: Problem, scheme: str, path: str, k: int,
        dtype_name: str, with_field: bool, batch: int,
        mesh: Optional[Tuple[int, int, int]] = None,
    ):
        """`program()` plus THIS call's program-source attribution -
        (prog, source, compile_seconds) with source one of "memory"
        (LRU hit), "disk" (persistent-cache adoption), "fresh" (paid
        the XLA compile), or "fallback" (prog is None - capability-
        refused, the caller runs the lane loop).  Per-call state, not a
        counter diff - diffing the shared `misses` counter would race
        with a concurrent warmup taking a miss on a different key.
        `compile_seconds` is 0.0 on a memory hit or fallback, the
        deserialize wall on a disk hit, and the measured build+compile
        wall on a fresh compile - the `compile` component of the
        response's Server-Timing header."""
        compute_errors = self.compute_errors and not with_field
        if mesh is not None:
            if scheme != "standard":
                # Refuse loudly: silently serving a compensated request
                # with the standard scheme would be a wrong-result bug,
                # not a fallback.  (The HTTP layer 400s this at parse;
                # this guards direct ServeEngine users.)
                raise ValueError(
                    "sharded x batched serves the standard scheme only; "
                    f"got scheme={scheme!r} with mesh {tuple(mesh)}"
                )
            ok, why = ens_sharded.vmap_capability(
                mesh, kernel=path, interpret=self.interpret
            )
            if not ok:
                self.fallbacks.setdefault(
                    f"mesh:{tuple(mesh)}:{path}", why
                )
                return None, "fallback", 0.0
        else:
            ok, why = ensemble.vmap_capability(
                path, k=k, interpret=self.interpret,
                with_field=with_field, scheme=scheme,
            )
            if not ok:
                self.fallbacks.setdefault(f"{scheme}:{path}", why)
                return None, "fallback", 0.0
        key = ProgramKey.for_batch(
            problem, scheme, path, k, dtype_name, with_field,
            compute_errors, batch, mesh,
        )
        with self._lock:
            prog = self._programs.get(key)
            if prog is not None:
                self._programs.move_to_end(key)
                self._c_cache.inc(event="hit")
                return prog, "memory", 0.0

        def _build():
            if mesh is not None:
                return ens_sharded.ShardedEnsembleSolver(
                    problem, batch, mesh, dtype=self._dtype(dtype_name),
                    kernel=path, compute_errors=compute_errors,
                    interpret=self.interpret,
                )
            return ensemble.EnsembleSolver(
                problem, batch, dtype=self._dtype(dtype_name),
                path=path, k=k, compute_errors=compute_errors,
                interpret=self.interpret, block_x=self.block_x,
                with_field=with_field, scheme=scheme,
            )

        # Disk tier: adopt a persisted serialized executable before
        # paying a fresh compile.  A valid entry counts `disk_hit` ONLY
        # (not `miss` - `miss` stays exactly the fresh-compile count);
        # ANY disk problem falls through to the fresh path as a normal
        # miss.  The ledger gets a `source: disk` line whose compile_s
        # is the deserialize wall and whose fresh_compile_s is the
        # compile the entry replaced - the measured-savings record.
        key_dict = None
        if self.progcache is not None and self.progcache.usable:
            key_dict = compile_ledger.key_from_program_key(key)
            entry = self.progcache.load(key_dict)
            if entry is not None:
                payload, header = entry
                t0 = time.perf_counter()
                try:
                    prog = _build()
                    prog.adopt_executable(payload)
                except Exception:
                    # A checksum-valid entry whose payload this runtime
                    # refuses (the fingerprint net has a hole): counted,
                    # then the fresh path below pays the compile.
                    self.progcache.count("corrupt")
                    prog = None
                if prog is not None:
                    load_s = time.perf_counter() - t0
                    self._c_cache.inc(event="disk_hit")
                    fresh_s = header.get("compile_s")
                    if isinstance(fresh_s, (int, float)):
                        self.progcache.credit_saved(fresh_s, load_s)
                    compile_ledger.record_compile(
                        key_dict, load_s, source="disk",
                        fresh_compile_s=(
                            fresh_s
                            if isinstance(fresh_s, (int, float))
                            else None
                        ),
                    )
                    self._cache_insert(key, prog)
                    return prog, "disk", load_s
        self._c_cache.inc(event="miss")
        # Chaos seam: an injected compile failure lands exactly where a
        # real Mosaic/XLA build error would - after the miss is counted,
        # before any build work.
        if self.fault_plan is not None and self.fault_plan.fire(
            "compile-fail", n=problem.N, timesteps=problem.timesteps,
            scheme=scheme, path=path, k=key.k, dtype=dtype_name,
        ):
            raise faults.InjectedFault(
                f"injected compile failure ({scheme}:{path} "
                f"N={problem.N}/{problem.timesteps})"
            )
        # Build + compile OUTSIDE the lock (XLA compiles can take
        # seconds; warmup from another thread must not serialize on it).
        t0 = time.perf_counter()
        with tracing.span(
            "serve.compile", scheme=scheme, path=path, batch=batch,
            n=problem.N, mesh=None if mesh is None else list(mesh),
        ):
            prog = _build()
            if (
                self.progcache is not None
                and self.progcache.xla_hits is not None
            ):
                # XLA-fallback mode: the persistent compilation cache
                # serves transparently inside compile(); sample its hit
                # counter around the compile so the ledger still says
                # where the time (didn't) go.
                pre_hits = self.progcache.xla_hits.hits
            else:
                pre_hits = None
            prog.compile()
        compile_seconds = time.perf_counter() - t0
        self._h_compile.observe(compile_seconds)
        # Compile-cost ledger (obs/ledger.py): one appended line per
        # compile, keyed by the full ProgramKey, surviving process
        # restarts - the raw material for `wavetpu ledger-report`'s
        # cross-restart accounting and warmup manifest.  A None-check
        # no-op (zero file I/O) when no --telemetry-dir configured it.
        source = "fresh"
        xla_served = (
            pre_hits is not None
            and self.progcache.xla_hits.hits > pre_hits
        )
        if xla_served:
            # The XLA persistent cache served this compile.  In
            # fallback mode that IS the disk tier, so the ledger says
            # so; in AOT mode the request still paid a (fast) compile
            # call, no program was adopted, and the label stays fresh -
            # `warm: disk` must always mean an adoption.
            self.progcache.count("xla_hit")
            if self.progcache.xla_fallback:
                source = "disk"
        compile_ledger.record_compile(
            key_dict if key_dict is not None
            else compile_ledger.key_from_program_key(key),
            compile_seconds, source=source,
        )
        # Persist for the next process (AOT mode only; guarded - a full
        # disk must never fail the request that just compiled).  Never
        # from an xla-served compile: serializing a cache-served
        # executable yields a payload that cannot deserialize.
        if (
            not xla_served
            and self.progcache is not None and self.progcache.usable
        ):
            try:
                payload = prog.executable_payload()
                if payload is not None:
                    self.progcache.put(
                        key_dict, payload, compile_seconds
                    )
            except Exception:
                self.progcache.count("store_error")
        self._cache_insert(key, prog)
        return prog, "fresh", compile_seconds

    def _cache_insert(self, key: ProgramKey, prog) -> None:
        with self._lock:
            self._programs[key] = prog
            self._programs.move_to_end(key)
            while len(self._programs) > self.max_programs:
                self._programs.popitem(last=False)
                self._c_cache.inc(event="eviction")

    def warmup(
        self, problem: Problem, scheme: str = "standard",
        path: str = "roll", k: int = 4, dtype_name: str = "f32",
        with_field: bool = False, batches: Optional[Sequence[int]] = None,
        mesh: Optional[Tuple[int, int, int]] = None,
    ) -> List[int]:
        """AOT-compile the key for each requested bucket (default: all);
        returns the bucket sizes actually warmed (empty when the path
        falls back - recorded, not raised).  `mesh` warms the sharded x
        batched (mesh, bucket) programs."""
        warmed = []
        for b in (self.bucket_sizes if batches is None else batches):
            if self.program(
                problem, scheme, path, k, dtype_name, with_field, b, mesh
            ) is not None:
                warmed.append(b)
        return warmed

    # ---- chunked long solves (serve/preempt.py) ----

    @staticmethod
    def chunk_program_key(problem: Problem, scheme: str, path: str,
                          k: int, dtype_name: str, compute_errors: bool,
                          chunk_len: int) -> ProgramKey:
        """The chunk-program identity: the full-march ProgramKey at
        batch=1 with the chunk geometry folded into the path string
        (`roll@chunk64`).  `timesteps` stays the TOTAL march length -
        the chunk program's error oracle and tau depend on it - and the
        suffix keeps chunked and monolithic executables from colliding
        in the LRU, the ledger, and the progcache.  Router affinity
        tables carry the suffixed path transparently (progkey's
        warm-key plumbing treats path as an opaque string)."""
        base = ProgramKey.for_batch(
            problem, scheme, path, k, dtype_name, False,
            compute_errors, 1, None,
        )
        # for_batch normalizes k to 1 off the kfused path, so the
        # suffix rides in AFTER derivation.
        return base._replace(path=f"{path}@chunk{chunk_len}")

    def chunk_runner(
        self, problem: Problem, scheme: str, path: str, k: int,
        dtype_name: str, chunk_steps: int,
    ):
        """The cached ChunkRunner (bootstrap + fixed-length chunk
        programs) for a long solve's tier - (runner, source,
        compile_seconds) with the same memory -> disk -> fresh
        three-tier discipline and attribution as `_program`.  Lives in
        the same LRU as the ensemble programs (one `max_programs`
        budget, one hit/miss/eviction account, one warm-keys view).
        The circuit breaker is NOT consulted here: the chunked path
        has its own failure handling (per-chunk watchdog 422s, crash
        re-enqueue, checkpoint-and-preempt), none of which may
        quarantine the tier."""
        from wavetpu.run import supervisor
        from wavetpu.serve import preempt

        fuse = int(k) if path == "kfused" else 1
        chunk_len = supervisor.chunk_length(int(chunk_steps), fuse)
        compute_errors = self.compute_errors
        key = self.chunk_program_key(
            problem, scheme, path, k, dtype_name, compute_errors,
            chunk_len,
        )
        with self._lock:
            prog = self._programs.get(key)
            if prog is not None:
                self._programs.move_to_end(key)
                self._c_cache.inc(event="hit")
                return prog, "memory", 0.0

        def _build():
            return preempt.ChunkRunner(
                problem, scheme, path, fuse,
                self._dtype(dtype_name), dtype_name, compute_errors,
                chunk_steps=chunk_len, interpret=self.interpret,
                block_x=self.block_x,
            )

        key_dict = None
        if self.progcache is not None and self.progcache.usable:
            key_dict = compile_ledger.key_from_program_key(key)
            entry = self.progcache.load(key_dict)
            if entry is not None:
                payload, header = entry
                t0 = time.perf_counter()
                try:
                    prog = _build()
                    prog.adopt_executable(payload)
                except Exception:
                    self.progcache.count("corrupt")
                    prog = None
                if prog is not None:
                    load_s = time.perf_counter() - t0
                    self._c_cache.inc(event="disk_hit")
                    fresh_s = header.get("compile_s")
                    if isinstance(fresh_s, (int, float)):
                        self.progcache.credit_saved(fresh_s, load_s)
                    compile_ledger.record_compile(
                        key_dict, load_s, source="disk",
                        fresh_compile_s=(
                            fresh_s
                            if isinstance(fresh_s, (int, float))
                            else None
                        ),
                    )
                    self._cache_insert(key, prog)
                    return prog, "disk", load_s
        self._c_cache.inc(event="miss")
        # Same chaos seam placement as `_program`: after the miss is
        # counted, before any build work.
        if self.fault_plan is not None and self.fault_plan.fire(
            "compile-fail", n=problem.N, timesteps=problem.timesteps,
            scheme=scheme, path=path, k=key.k, dtype=dtype_name,
        ):
            raise faults.InjectedFault(
                f"injected compile failure ({scheme}:{path}@chunk"
                f"{chunk_len} N={problem.N}/{problem.timesteps})"
            )
        t0 = time.perf_counter()
        with tracing.span(
            "serve.compile", scheme=scheme,
            path=f"{path}@chunk{chunk_len}", batch=1, n=problem.N,
            mesh=None,
        ):
            prog = _build()
            prog.prime()
        compile_seconds = time.perf_counter() - t0
        self._h_compile.observe(compile_seconds)
        compile_ledger.record_compile(
            key_dict if key_dict is not None
            else compile_ledger.key_from_program_key(key),
            compile_seconds, source="fresh",
        )
        if self.progcache is not None and self.progcache.usable:
            try:
                payload = prog.executable_payload()
                if payload is not None:
                    self.progcache.put(key_dict, payload,
                                       compile_seconds)
            except Exception:
                self.progcache.count("store_error")
        self._cache_insert(key, prog)
        return prog, "fresh", compile_seconds

    def breaker_key(self, problem: Problem, scheme: str, path: str,
                    k: int, dtype_name: str, with_field: bool,
                    mesh: Optional[Tuple[int, int, int]] = None
                    ) -> ProgramKey:
        """The circuit-breaker identity: the ProgramKey with batch=0, so
        every bucket of a tier shares one breaker (a poisoned compile
        poisons the tier, not one bucket of it)."""
        return ProgramKey.for_batch(
            problem, scheme, path, k, dtype_name, with_field,
            self.compute_errors and not with_field, 0, mesh,
        )

    def breaker_stats(self) -> dict:
        """The JSON /metrics `breaker` block."""
        if self.breaker is None:
            return {"enabled": False}
        return {"enabled": True, **self.breaker.snapshot()}

    def cache_stats(self) -> dict:
        with self._lock:
            return {
                "programs": len(self._programs),
                "max_programs": self.max_programs,
                "hits": self.hits,
                "misses": self.misses,
                "disk_hits": self.disk_hits,
                "evictions": self.evictions,
                "keys": [list(k) for k in self._programs],
                # ProgramKey dicts the fleet router's affinity table
                # bootstraps from on a cold poll: programs compiled in
                # THIS process (memory LRU) plus .wtpc entries this
                # replica could adopt without a fresh compile (disk,
                # own-fingerprint only).
                "warm_keys": {
                    "memory": [
                        compile_ledger.key_from_program_key(k)
                        for k in self._programs
                    ],
                    "disk": (
                        self.progcache.entry_keys()
                        if self.progcache is not None else []
                    ),
                },
                "fallbacks": dict(self.fallbacks),
                # Disk tier (serve/progcache.py): entry count/bytes,
                # event counts, and the once-per-process AOT
                # serialization probe verdict.
                "progcache": (
                    self.progcache.stats()
                    if self.progcache is not None
                    else {"enabled": False}
                ),
                # Every cached vmap-capability verdict (single-device +
                # sharded): a chip silently serving lane-loop is visible
                # from the outside via these.
                "vmap_probes": (
                    ensemble.probe_results()
                    + ens_sharded.probe_results()
                ),
            }

    # ---- execution ----

    def lane_health(
        self, result: ensemble.EnsembleResult
    ) -> List[Optional[str]]:
        """Per-lane watchdog verdicts: None = healthy, else the error
        string for that lane's response.  The guarded-amax reduction maps
        NaN/Inf to +inf (run/health.py), so a poisoned lane trips without
        touching its batchmates."""
        if not self.watchdog:
            return [None] * len(result.results)
        # The context manager (not begin/end) so a raising reduction
        # still closes the span: a leaked span id would become every
        # later batch span's parent on this worker thread.
        with tracing.span(
            "serve.watchdog", lanes=len(result.results)
        ) as sp:
            # One fused pass per state array over the whole batch (B
            # scalars to host), not B separate reductions.  The vmapped
            # path hands us its raw batched outputs (no copy); the
            # lane-loop fallback has separate per-lane arrays and pays
            # one stack each.
            if result.u_prev_batch is not None:
                amaxes = [
                    health.guarded_amax_per_lane(
                        batch
                    )[: len(result.results)]
                    for batch in (result.u_prev_batch, result.u_cur_batch)
                ]
            else:
                import jax.numpy as jnp

                amaxes = [
                    health.guarded_amax_per_lane(
                        jnp.stack([getattr(r, name)
                                   for r in result.results])
                    )
                    for name in ("u_prev", "u_cur")
                ]
            out = []
            for amax in map(max, zip(*amaxes)):
                amax = float(amax)
                if health.healthy(amax, self.max_amp):
                    out.append(None)
                else:
                    bound = (
                        health.DEFAULT_AMP_BOUND
                        if self.max_amp is None else self.max_amp
                    )
                    out.append(
                        f"numerical-health trip: guarded amax {amax:g} "
                        f"exceeds bound {bound:g} (NaN/Inf count as inf)"
                    )
            sp["tripped"] = sum(1 for o in out if o is not None)
        return out

    def solve(
        self, problem: Problem, lanes: Sequence[ensemble.LaneSpec],
        scheme: str = "standard", path: str = "roll", k: int = 4,
        dtype_name: str = "f32",
        mesh: Optional[Tuple[int, int, int]] = None,
        timing: Optional[dict] = None,
        feed_breaker: bool = True,
    ) -> Tuple[ensemble.EnsembleResult, List[Optional[str]]]:
        """Pad to the bucket, run the cached program (or the recorded
        fallback), watchdog each lane; returns (EnsembleResult,
        per-lane health).  `mesh` routes the batch through the sharded x
        batched composition.  `timing`, when a dict is passed, is filled
        in place with `compile_seconds` (this call's cache-miss compile,
        0.0 warm) and `warm` ("true"/"false"/"fallback") - the
        scheduler threads it into each response's Server-Timing header
        without changing this method's return contract.
        `feed_breaker=False` (a batch of only shadow-solve lanes,
        serve/shadow.py) skips the circuit breaker entirely - neither
        admitted against an open key nor recorded on failure, so the
        off-hot-path accuracy sampler can never quarantine a program
        production traffic depends on."""
        lanes = list(lanes)
        with_field = any(lane.c2tau2_field is not None for lane in lanes)
        compute_errors = self.compute_errors and not with_field
        bucket = self.bucket_for(len(lanes))
        # Circuit breaker: an open key sheds HERE (fast QuarantinedError
        # the HTTP layer maps to 503 + Retry-After) before any compile
        # or device work; everything from program lookup through the
        # batched execute counts as one admit/record cycle.  Per-lane
        # watchdog trips are CLIENT errors (a Courant-unstable request)
        # and never feed the breaker.
        bkey = None
        if self.breaker is not None and feed_breaker:
            bkey = self.breaker_key(
                problem, scheme, path, k, dtype_name, with_field, mesh
            )
            self.breaker.admit(bkey)
        try:
            # Warm-vs-cold attribution: a solve whose program lookup had
            # to compile is this key's first-request latency, not its
            # steady state; the histogram label keeps the two
            # populations apart.  A capability-refused key runs the
            # lane-loop fallback, whose per-lane compile behavior is
            # jax-cache-dependent - its own label value, so fallback
            # outliers never pollute either the warm or the cold
            # batched population.
            prog, source, compile_seconds = self._program(
                problem, scheme, path, k, dtype_name, with_field, bucket,
                mesh
            )
            warm = prog is not None and source == "memory"
            # "disk" is its own label: a persistent-cache adoption pays
            # deserialize (ms) where a cold compile pays XLA (s) - the
            # two populations must not share a histogram bucket.
            warm_label = (
                "fallback" if prog is None
                else "true" if warm
                else "disk" if source == "disk" else "false"
            )
            if timing is not None:
                timing["compile_seconds"] = compile_seconds
                timing["warm"] = warm_label
            with tracing.span(
                "serve.execute", scheme=scheme, path=path,
                occupancy=len(lanes), bucket=bucket, warm=warm,
            ) as sp:
                if mesh is not None:
                    result = ens_sharded.solve_ensemble_sharded(
                        problem, lanes, mesh_shape=mesh,
                        dtype=self._dtype(dtype_name), kernel=path,
                        compute_errors=compute_errors,
                        interpret=self.interpret,
                        pad_to=bucket if prog is not None else None,
                        solver=prog,
                    )
                else:
                    result = ensemble.solve_ensemble(
                        problem, lanes, dtype=self._dtype(dtype_name),
                        scheme=scheme, path=path, k=k,
                        compute_errors=compute_errors,
                        interpret=self.interpret, block_x=self.block_x,
                        pad_to=bucket if prog is not None else None,
                        solver=prog,
                    )
                sp["batched"] = result.batched
                # Roofline attribution for the batch program: the
                # vmapped march moves batch_size x the per-lane traffic
                # (padding lanes stream bytes too), so the program-level
                # Gcell/s - not just the real-lane aggregate - is what
                # sits on the roofline.  Same attrs as the solo solve
                # gauges, stamped on this serve.execute span and the
                # server registry.
                # Guarded: an X-ray bug must never fail the batch (an
                # exception here would even feed the circuit breaker).
                try:
                    steps = max(
                        (r.steps_computed or problem.timesteps
                         for r in result.results),
                        default=problem.timesteps,
                    )
                    prog_gcells = (
                        problem.cells_per_step * result.batch_size
                        * steps / result.solve_seconds / 1e9
                        if result.solve_seconds else 0.0
                    )
                    rf = perf.record_roofline(
                        self.registry, result.path, perf.solve_perf(
                            prog_gcells, result.path, scheme=scheme,
                            k=k, n=problem.N,
                            itemsize=perf.DTYPE_ITEMSIZE.get(
                                dtype_name, 4
                            ),
                            with_field=with_field,
                        ),
                    )
                    if rf is not None:
                        sp["model_bytes_per_cell"] = (
                            rf["model_bytes_per_cell"]
                        )
                        sp["model_gbps"] = rf["model_gbps"]
                        sp["roofline_fraction"] = rf["roofline_fraction"]
                    perf.record_memory(self.registry, context="serve")
                except Exception:
                    pass
        except QuarantinedError:
            raise
        except Exception as e:
            if self.breaker is not None and bkey is not None:
                self.breaker.record_failure(bkey, e)
            raise
        if self.breaker is not None and bkey is not None:
            self.breaker.record_success(bkey)
        self._h_execute.observe(result.solve_seconds, warm=warm_label)
        if not result.batched and result.fallback_reason:
            self.fallbacks.setdefault(
                f"{scheme}:{result.path}", result.fallback_reason
            )
        # Chaos seam: execute-NaN poisons the batch's final state AFTER
        # the solve - the per-lane watchdog below must catch it (422s),
        # exactly as it would a real device fault.
        if self.fault_plan is not None and self.fault_plan.fire(
            "execute-nan", n=problem.N, timesteps=problem.timesteps,
            scheme=scheme, path=path, k=k, dtype=dtype_name,
        ):
            import numpy as np

            if result.u_cur_batch is not None:
                result.u_cur_batch = np.full(
                    np.shape(result.u_cur_batch), np.nan, np.float32
                )
            else:
                for r in result.results:
                    r.u_cur = np.full(
                        np.shape(r.u_cur), np.nan, np.float32
                    )
        verdicts = self.lane_health(result)
        # Accuracy observatory: every HEALTHY lane that computed oracle
        # errors stamps its measured max_abs_err and appends one
        # accuracy-ledger line (obs/accuracy.py) - rides the watchdog
        # reduction so the per-lane error arrays are read exactly once.
        # Guarded: the X-ray must never fail the batch it measures.
        if compute_errors:
            try:
                accuracy.observe_serve_batch(
                    result, verdicts, scheme=scheme, k=k,
                    dtype=dtype_name, registry=self.registry,
                )
            except Exception:
                pass
        return result, verdicts
