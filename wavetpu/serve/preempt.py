"""Preemptible long solves: serve-side chunked march + resumable state
tokens.

PR 2's supervisor proved the chunked-march machinery for CLI runs
(fixed-length chunk programs, bitwise-identical trajectories, resumable
checkpoints, watchdog-per-chunk).  This module brings it inside the
serve path:

 * `ChunkRunner` wraps `run/supervisor._Path` for the single-backend
   standard-scheme serve tiers (roll / pallas / kfused) and adds the
   one piece the supervisor rebuilds per call: a cached, AOT-compiled
   BOOTSTRAP program (`stop_step=1`) that produces layers 0..1 exactly
   as the uninterrupted solve would.  tau stays `T / timesteps`
   regardless of where the march stops, so bootstrap-to-1 followed by
   fixed-length chunks from start=1 replays the monolithic program's
   op sequence bitwise (the invariant tests/test_supervisor.py pins).
   One ChunkRunner per chunk ProgramKey lives in the engine's program
   LRU under the same ledger/progcache discipline as ensemble programs.

 * `SolveStateStore` is the cross-replica handoff surface: mid-flight
   state checkpoints under `--solve-state-dir`, CONTENT-ADDRESSED (the
   token is the sha256 of the file bytes) and REPLICA-VERIFIED on load
   (hash re-check + solve-identity match against the resuming request),
   so a forged or corrupt token gets a clean 422
   (`InvalidStateTokenError`), never a traceback.  Entries expire after
   `--solve-state-ttl-s` (GC piggybacks on `put`).

Chunk boundaries land on the k-fusion block grid (`chunk_length`), and
resume steps are validated against that grid, so a resumed kfused march
reproduces the uninterrupted op sequence exactly.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
import time
from typing import Optional, Sequence, Tuple

import numpy as np

from wavetpu.serve.resilience import InvalidStateTokenError

STATE_FORMAT_VERSION = 1

_TOKEN_PREFIX = "st-"
_TOKEN_SUFFIX = ".npz"
_TOKEN_HEX = frozenset("0123456789abcdef")

# Identity fields a resume token must match on the resuming request -
# everything that changes the trajectory or the chunk-program shape.
_IDENTITY_FIELDS = (
    "N", "Np", "Lx", "Ly", "Lz", "T", "timesteps",
    "scheme", "path", "k", "dtype", "compute_errors", "chunk_len",
)


def solve_identity(problem, scheme: str, path: str, k: int,
                   dtype_name: str, compute_errors: bool,
                   chunk_len: int) -> dict:
    """The JSON-stable identity a state token is bound to."""
    return {
        "format": STATE_FORMAT_VERSION,
        "N": int(problem.N),
        "Np": int(problem.Np),
        "Lx": float(problem.Lx),
        "Ly": float(problem.Ly),
        "Lz": float(problem.Lz),
        "T": float(problem.T),
        "timesteps": int(problem.timesteps),
        "scheme": str(scheme),
        "path": str(path),
        "k": int(k),
        "dtype": str(dtype_name),
        "compute_errors": bool(compute_errors),
        "chunk_len": int(chunk_len),
    }


def _file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


class SolveStateStore:
    """Content-addressed mid-flight solve checkpoints.

    `put` writes one .npz (state fields via io/checkpoint's bf16-safe
    codec + a JSON meta blob + error prefixes) to a temp file, names it
    by its own sha256, and atomically renames it in - so a half-written
    file is never loadable and identical states dedupe to one entry.
    `load` re-hashes the file and refuses on ANY mismatch or parse
    problem with `InvalidStateTokenError` (the 422 contract)."""

    def __init__(self, directory: str, ttl_s: float = 3600.0):
        self.directory = directory
        self.ttl_s = float(ttl_s)
        os.makedirs(directory, exist_ok=True)

    def path_for(self, token: str) -> str:
        return os.path.join(
            self.directory, _TOKEN_PREFIX + token + _TOKEN_SUFFIX
        )

    @staticmethod
    def valid_token(token) -> bool:
        return (
            isinstance(token, str)
            and len(token) == 64
            and all(c in _TOKEN_HEX for c in token)
        )

    def put(self, identity: dict, state: Sequence, step: int,
            abs_errors: np.ndarray, rel_errors: np.ndarray,
            origin_trace: Optional[Sequence[str]] = None,
            priority: Optional[str] = None) -> str:
        """Checkpoint `state` (layers up to `step` marched) -> token.

        `origin_trace` is the originating request's (trace id, span id)
        pair; it rides in the meta blob so a resuming replica can link
        its chunk spans back to the trace where the march began.
        `priority` is the march's QoS class: a resume adopts it, so a
        best_effort march stays best_effort however the resume request
        is labeled (the class was clamped at original admission).  Load
        identity verification only reads `_IDENTITY_FIELDS`, so the
        extra keys never affect token acceptance."""
        from wavetpu.io.checkpoint import _encode_field

        arrays = {}
        tags = []
        for i, field in enumerate(state):
            enc, tag = _encode_field(np.asarray(field))
            arrays[f"state{i}"] = enc
            tags.append(tag)
        meta = dict(identity)
        meta["step"] = int(step)
        meta["nstate"] = len(tags)
        meta["state_tags"] = tags
        if origin_trace is not None:
            meta["origin_trace"] = [str(x) for x in origin_trace]
        if priority is not None:
            meta["priority"] = str(priority)
        arrays["meta"] = np.frombuffer(
            json.dumps(meta, sort_keys=True).encode("utf-8"),
            dtype=np.uint8,
        )
        # Error prefixes ride along so the final result reports the full
        # per-layer history even across a handoff.
        arrays["abs_errors"] = np.asarray(
            abs_errors[: step + 1], dtype=np.float64
        )
        arrays["rel_errors"] = np.asarray(
            rel_errors[: step + 1], dtype=np.float64
        )
        fd, tmp = tempfile.mkstemp(dir=self.directory, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez(f, **arrays)
            token = _file_sha256(tmp)
            os.replace(tmp, self.path_for(token))
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self.gc()
        return token

    def load(self, token: str, expect_identity: Optional[dict] = None
             ) -> Tuple[dict, int, Tuple[np.ndarray, ...],
                        np.ndarray, np.ndarray]:
        """Verify + decode a token -> (identity, step, state, abs, rel).

        Every failure mode - malformed token, missing file, content
        hash mismatch (truncation/corruption/forgery of the name),
        unparseable npz, or identity mismatch against
        `expect_identity` - raises `InvalidStateTokenError` with a
        one-line reason."""
        if not self.valid_token(token):
            raise InvalidStateTokenError(
                "resume_token must be 64 lowercase hex characters"
            )
        path = self.path_for(token)
        if not os.path.exists(path):
            raise InvalidStateTokenError(
                "resume_token not found (expired, GCed, or from a "
                "replica not sharing this --solve-state-dir)"
            )
        try:
            if _file_sha256(path) != token:
                raise InvalidStateTokenError(
                    "resume_token failed content verification "
                    "(checkpoint bytes do not hash to the token)"
                )
            with np.load(path) as z:
                meta = json.loads(bytes(z["meta"]).decode("utf-8"))
                from wavetpu.io.checkpoint import _decode_field

                tags = meta["state_tags"]
                state = tuple(
                    _decode_field(z[f"state{i}"], tags[i])
                    for i in range(int(meta["nstate"]))
                )
                abs_e = np.asarray(z["abs_errors"], dtype=np.float64)
                rel_e = np.asarray(z["rel_errors"], dtype=np.float64)
        except InvalidStateTokenError:
            raise
        except Exception as exc:
            raise InvalidStateTokenError(
                f"resume_token checkpoint is unreadable: "
                f"{type(exc).__name__}"
            ) from None
        step = int(meta.get("step", -1))
        if expect_identity is not None:
            for field in _IDENTITY_FIELDS:
                if meta.get(field) != expect_identity.get(field):
                    raise InvalidStateTokenError(
                        f"resume_token does not match this request "
                        f"({field}: token has {meta.get(field)!r}, "
                        f"request needs {expect_identity.get(field)!r})"
                    )
            chunk_len = int(expect_identity["chunk_len"])
            timesteps = int(expect_identity["timesteps"])
            # Resume steps must land on the chunk grid (checkpoints are
            # only ever written there); off-grid steps would de-align a
            # kfused march from the uninterrupted op sequence.
            if (step < 1 or step >= timesteps
                    or (step - 1) % chunk_len != 0):
                raise InvalidStateTokenError(
                    f"resume_token step {step} is off the chunk grid "
                    f"(1 + j*{chunk_len}, below {timesteps})"
                )
            if len(abs_e) != step + 1 or len(rel_e) != step + 1:
                raise InvalidStateTokenError(
                    "resume_token error history is inconsistent with "
                    "its step"
                )
        return meta, step, state, abs_e, rel_e

    def gc(self) -> int:
        """Drop entries older than ttl_s (by mtime); returns the count.
        Racing replicas double-unlinking is harmless (missing_ok)."""
        removed = 0
        cutoff = time.time() - self.ttl_s
        try:
            names = os.listdir(self.directory)
        except OSError:
            return 0
        for name in names:
            if not (name.startswith(_TOKEN_PREFIX)
                    and name.endswith(_TOKEN_SUFFIX)):
                continue
            full = os.path.join(self.directory, name)
            try:
                if os.path.getmtime(full) < cutoff:
                    os.unlink(full)
                    removed += 1
            except OSError:
                continue
        return removed


class ChunkRunner:
    """A cacheable chunked-march program set for ONE serve tier.

    Holds a `_Path` (the supervisor's PathSpec->solver adapter) plus an
    AOT-compiled bootstrap; the engine caches one per chunk ProgramKey
    so bootstrap + chunk programs compile once per process per config
    (the supervisor's `first()` re-jits per call - unacceptable on the
    request path)."""

    def __init__(self, problem, scheme: str, path: str, k: int,
                 dtype, dtype_name: str, compute_errors: bool,
                 chunk_steps: int, interpret: Optional[bool] = None,
                 block_x: Optional[int] = None):
        from wavetpu.run import supervisor

        if scheme != "standard":
            raise ValueError(
                "chunked serving supports scheme='standard' only "
                "(ensemble bootstrap results carry no compensation "
                "state); compensated tiers run monolithic"
            )
        if path not in ("roll", "pallas", "kfused"):
            raise ValueError(f"chunked serving does not cover path "
                             f"{path!r}")
        fuse = int(k) if path == "kfused" else 1
        spec = supervisor.PathSpec(
            backend="single",
            scheme=scheme,
            fuse_steps=fuse,
            kernel="pallas" if path == "pallas" else "roll",
            dtype=dtype,
            compute_errors=compute_errors,
            interpret=interpret,
            block_x=block_x,
        )
        self._path = supervisor._Path(problem, spec)
        if path == "kfused" and self._path.kind != "kfused":
            raise ValueError(
                f"kfused chunked serving needs N % k == 0 "
                f"(N={problem.N}, k={fuse})"
            )
        self.problem = problem
        self.scheme = scheme
        self.path_name = path
        self.k = fuse
        self.dtype_name = dtype_name
        self.compute_errors = compute_errors
        self.chunk_len = supervisor.chunk_length(int(chunk_steps), fuse)
        self.identity = solve_identity(
            problem, scheme, path, fuse, dtype_name, compute_errors,
            self.chunk_len,
        )
        self.compile_seconds = 0.0   # cumulative, for the LRU/ledger
        self._boot = None            # (jitted runner, call args)
        self._boot_exec = None       # AOT-compiled bootstrap

    # -- geometry ------------------------------------------------------

    def march_lengths(self) -> Tuple[int, ...]:
        """The distinct chunk lengths a full march uses: the main
        length, plus the tail remainder when T-1 is not a multiple."""
        total = self.problem.timesteps - 1
        lens = []
        if total // self.chunk_len:
            lens.append(self.chunk_len)
        if total % self.chunk_len:
            lens.append(total % self.chunk_len)
        return tuple(lens)

    def next_length(self, step: int) -> int:
        """The next chunk's length when `step` layers are done."""
        return min(self.chunk_len, self.problem.timesteps - step)

    def total_chunks(self) -> int:
        total = self.problem.timesteps - 1
        return -(-total // self.chunk_len)

    # -- bootstrap (layers 0..1) ---------------------------------------

    def _boot_builders(self):
        if self._boot is None:
            p = self._path
            if p.kind == "kfused":
                from wavetpu.solver import kfused

                runner, run_params = kfused.make_kfused_solver(
                    self.problem, dtype=p.dtype, k=p.k,
                    compute_errors=self.compute_errors, stop_step=1,
                    block_x=p.spec.block_x, interpret=p.interpret,
                )
                self._boot = (runner, tuple(run_params))
            else:
                from wavetpu.solver import leapfrog

                runner, step_params = leapfrog.make_solver(
                    self.problem, dtype=p.dtype,
                    step_fn=p._step_fn(),
                    compute_errors=self.compute_errors, stop_step=1,
                )
                self._boot = (runner, (step_params,))
        return self._boot

    def _compile_boot(self) -> float:
        runner, args = self._boot_builders()
        if self._boot_exec is not None:
            return 0.0
        t0 = time.perf_counter()
        self._boot_exec = runner.lower(*args).compile()
        spent = time.perf_counter() - t0
        self.compile_seconds += spent
        return spent

    def bootstrap(self):
        """Run layers 0..1 exactly as the uninterrupted solve would;
        returns (state, abs2, rel2, compile_s, solve_s)."""
        import jax

        compile_s = self._compile_boot()
        _, args = self._boot
        t0 = time.perf_counter()
        out = self._boot_exec(*args)
        jax.block_until_ready(out)
        u_prev, u_cur, abs_all, rel_all = out
        abs_np = np.asarray(abs_all, dtype=np.float64)
        solve_s = time.perf_counter() - t0
        rel_np = np.asarray(rel_all, dtype=np.float64)
        return (u_prev, u_cur), abs_np, rel_np, compile_s, solve_s

    # -- chunks --------------------------------------------------------

    def chunk(self, state, start: int, length: int):
        """(state', abs_chunk, rel_chunk, solve_s, compile_s) - the
        supervisor's cached fixed-length chunk program."""
        return self._path.chunk(state, start, length)

    def prime(self) -> float:
        """Compile the bootstrap and EVERY chunk length this march will
        use, without marching (beyond the two bootstrap layers needed
        as example args); returns the compile wall seconds.  This is
        the warmup/cold-start surface: a primed runner serves its first
        long solve with zero fresh compiles."""
        import jax.numpy as jnp

        spent = self._compile_boot()
        out = self._boot_exec(*self._boot[1])
        state = (out[0], out[1])
        for length in self.march_lengths():
            if length in self._path._compiled:
                continue
            if length not in self._path._jit:
                self._path._jit[length] = self._path._build_runner(
                    length, state
                )
            runner, extra = self._path._jit[length]
            args = tuple(state) + (jnp.int32(1),) + extra
            t0 = time.perf_counter()
            self._path._compiled[length] = (
                runner.lower(*args).compile()
            )
            chunk_s = time.perf_counter() - t0
            self.compile_seconds += chunk_s
            spent += chunk_s
        return spent

    # -- state plumbing ------------------------------------------------

    def health_arrays(self, state):
        return self._path.health_arrays(state)

    def prepare(self, state):
        return self._path.prepare(state)

    def to_result(self, state, abs_full, rel_full, final_step: int,
                  init_s: float, solve_s: float, marched: int):
        return self._path.to_result(
            state, abs_full, rel_full, final_step, init_s, solve_s,
            marched,
        )

    @staticmethod
    def state_to_numpy(state):
        return tuple(np.asarray(a) for a in state)

    # -- persistent-cache hooks (serve/progcache.py) -------------------

    def executable_payload(self):
        """Serialized (boot + per-length chunk) executables for the
        disk tier, or None before `prime`/first use.  Raises where the
        jaxlib cannot serialize; callers probe
        `progcache.aot_capability()` first (same contract as
        EnsembleSolver.executable_payload)."""
        if self._boot_exec is None or not self._path._compiled:
            return None
        from jax.experimental import serialize_executable as se

        return {
            "format": 1,
            "boot": se.serialize(self._boot_exec),
            "chunks": {
                int(length): se.serialize(compiled)
                for length, compiled in self._path._compiled.items()
            },
        }

    def adopt_executable(self, payload) -> float:
        """Install deserialized executables (disk-tier warm path);
        returns the deserialize wall seconds.  Raises on an
        incompatible payload - the caller counts a miss and compiles
        fresh."""
        from jax.experimental import serialize_executable as se

        t0 = time.perf_counter()
        self._boot_builders()
        boot_exec = se.deserialize_and_load(*payload["boot"])
        chunk_execs = {}
        for length, blob in payload["chunks"].items():
            length = int(length)
            # The traced runner structure is needed alongside the
            # executable (chunk() reads its extra-args tuple); building
            # it is pure tracing setup, no compile.
            if length not in self._path._jit:
                self._path._jit[length] = self._path._build_runner(
                    length, None
                )
            chunk_execs[length] = se.deserialize_and_load(*blob)
        self._boot_exec = boot_exec
        self._path._compiled.update(chunk_execs)
        spent = time.perf_counter() - t0
        self.compile_seconds += spent
        return spent
