"""Request-path resilience primitives: typed failures + circuit breaker.

PR 2 made *solves* survive faults (supervisor, checkpoint rotation,
watchdog); this module is the serve-stack half of that contract.  The
scheduler, engine, and HTTP layer share a small failure taxonomy so a
client can tell "retry me" from "your fault" from "too late":

 * `DeadlineExceededError`  -> HTTP 504.  The request's `deadline_ms`
   budget expired (in queue, or while the batch was in flight).  Carries
   `queue_s` when the scheduler dropped it before execution, so the 504
   attributes WHERE the budget went.
 * `WorkerCrashError`       -> HTTP 503 + `Retry-After`.  The scheduler
   worker died mid-batch and was restarted by its supervisor; the
   request itself is fine - retry it.
 * `QuarantinedError`       -> HTTP 503 + `Retry-After`.  The request's
   ProgramKey is circuit-broken (K consecutive compile/execute
   failures); `retry_after_s` is the remaining cooldown.
 * `PreemptedError`         -> HTTP 503 + `Retry-After` + resume_token.
   A chunked long solve was checkpointed mid-march (drain/roll); the
   token resumes it on any replica sharing `--solve-state-dir`.
 * `InvalidStateTokenError` -> HTTP 422.  A `resume_token` failed
   verification (bad format, missing/corrupt/expired file, or identity
   mismatch with the request) - the client's fault, never retriable.
 * `ShedError`              -> HTTP 503 + `Retry-After`.  The brownout
   ladder (serve/scheduler.py BrownoutController) refused the request's
   priority class while queue-wait p95 is over threshold;
   `retry_after_s` is the measured queue-drain estimate, not a
   constant.

`CircuitBreaker` quarantines per program identity (the ProgramKey minus
its batch bucket - one poisoned tier is ONE breaker however it
batches).  Classic three-state machine:

    closed --K consecutive failures--> open --cooldown--> half_open
    half_open --probe success--> closed;  --probe failure--> open

While open, `admit()` sheds every request for the key with a fast
`QuarantinedError` instead of letting each one re-pay the failing
compile (and stall the single scheduler worker for everyone else's
batches).  After `cooldown_s` the next request through is the half-open
PROBE: its success closes the breaker, its failure re-opens the clock.
State is visible in both /metrics views (JSON `breaker` block;
Prometheus `wavetpu_serve_breaker_*`).

Imports neither jax nor numpy (same before-the-backend discipline as
obs/registry.py).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple


class DeadlineExceededError(RuntimeError):
    """The request's deadline budget expired before a result existed.
    `queue_s` (when set) is the time the request spent queued - the
    scheduler dropped it before batching rather than marching work
    nobody is waiting for."""

    def __init__(self, message: str, queue_s: Optional[float] = None,
                 resume_token: Optional[str] = None):
        super().__init__(message)
        self.queue_s = queue_s
        # Chunked long solves checkpoint on deadline expiry; the 504
        # carries this token so the client can resume instead of
        # restarting from layer 0 (serve/preempt.py).
        self.resume_token = resume_token


class WorkerCrashError(RuntimeError):
    """The scheduler worker crashed while this request was in flight.
    The supervisor restarted the worker; the request is RETRIABLE -
    mapped to 503 + Retry-After, never a hang."""


class PreemptedError(RuntimeError):
    """A chunked long solve was checkpointed and preempted before
    completion (replica drain / rolling deploy).  RETRIABLE: mapped to
    503 + Retry-After with `resume_token` in the body, so the retry -
    on this replica or any other sharing `--solve-state-dir` - resumes
    from the last completed chunk instead of layer 0."""

    def __init__(self, message: str, resume_token: Optional[str] = None,
                 retry_after_s: float = 1.0):
        super().__init__(message)
        self.resume_token = resume_token
        self.retry_after_s = retry_after_s


class InvalidStateTokenError(ValueError):
    """A `resume_token` failed verification: malformed token, missing or
    corrupt checkpoint file (content hash mismatch), expired entry, or
    an identity that does not match the request.  Client error (422),
    never a traceback and never retriable."""


class ShedError(RuntimeError):
    """The brownout ladder shed this request at admission: queue-wait
    p95 is over a rung threshold and the request's priority class is at
    or below the rung being shed.  RETRIABLE (503 + Retry-After) - the
    replica is overloaded, not broken.  `retry_after_s` is the MEASURED
    queue-drain estimate (`ServeMetrics.retry_after_s`), so the client
    backs off exactly as long as the backlog says, and `rung` names the
    ladder step that fired (docs/robustness.md "Brownout ladder")."""

    def __init__(self, message: str, retry_after_s: float = 1.0,
                 rung: str = ""):
        super().__init__(message)
        self.retry_after_s = retry_after_s
        self.rung = rung


class QuarantinedError(RuntimeError):
    """The request's program key is circuit-broken.  `retry_after_s` is
    the remaining cooldown before the half-open probe - the value the
    503's Retry-After header carries."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class CircuitBreaker:
    """Per-key three-state breaker (closed/open/half_open).

    Thread-safe; the serve layer calls `admit(key)` before touching the
    program cache, then exactly one of `record_failure` /
    `record_success` per admitted solve.  Keys are hashable tuples (the
    engine uses ProgramKey with batch=0 so every bucket of a tier
    shares one breaker).  Failure counting is CONSECUTIVE: any success
    resets the count, so a tier that fails intermittently under load
    never quarantines - only a key that fails `threshold` times in a
    row with no success between them.
    """

    def __init__(self, threshold: int = 3, cooldown_s: float = 30.0,
                 registry=None):
        if threshold < 1:
            raise ValueError(f"threshold must be >= 1, got {threshold}")
        if cooldown_s <= 0:
            raise ValueError(f"cooldown_s must be > 0, got {cooldown_s}")
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self._lock = threading.Lock()
        # key -> {state, consecutive_failures, opened_at, opens,
        #         last_error}
        self._keys: Dict[Tuple, dict] = {}
        self._c_events = None
        self._g_open = None
        if registry is not None:
            self._c_events = registry.counter(
                "wavetpu_serve_breaker_events_total",
                "circuit-breaker transitions and sheds", ("event",),
            )
            self._g_open = registry.gauge(
                "wavetpu_serve_breaker_open",
                "program keys currently quarantined (open or half-open)",
            )

    def _event(self, name: str) -> None:
        if self._c_events is not None:
            self._c_events.inc(event=name)

    def _set_open_gauge(self) -> None:
        if self._g_open is not None:
            self._g_open.set(sum(
                1 for st in self._keys.values()
                if st["state"] != "closed"
            ))

    def admit(self, key: Tuple) -> None:
        """Raise `QuarantinedError` when `key` is open and still cooling
        down; transition open -> half_open (admitting THIS call as the
        probe) once the cooldown has elapsed.  Closed keys pass free."""
        with self._lock:
            st = self._keys.get(key)
            if st is None or st["state"] == "closed":
                return
            if st["state"] == "open":
                elapsed = time.monotonic() - st["opened_at"]
                remaining = self.cooldown_s - elapsed
                if remaining > 0:
                    self._event("shed")
                    raise QuarantinedError(
                        f"program {self.describe(key)} is quarantined "
                        f"({st['consecutive_failures']} consecutive "
                        f"failures; last: {st['last_error']}); half-open "
                        f"probe in {remaining:.1f}s",
                        retry_after_s=remaining,
                    )
                st["state"] = "half_open"
                self._event("half_open")
            # half_open: this call is the probe (single scheduler
            # worker, so concurrent probes are a warmup-thread edge we
            # accept - both report into record_*).

    def record_failure(self, key: Tuple, error: BaseException) -> None:
        with self._lock:
            st = self._keys.setdefault(key, {
                "state": "closed", "consecutive_failures": 0,
                "opened_at": 0.0, "opens": 0, "last_error": "",
            })
            st["consecutive_failures"] += 1
            st["last_error"] = str(error)[:200]
            trip = (
                st["state"] == "half_open"  # failed probe re-opens
                or st["consecutive_failures"] >= self.threshold
            )
            if trip:
                if st["state"] != "open":
                    st["opens"] += 1
                    self._event("open")
                st["state"] = "open"
                st["opened_at"] = time.monotonic()
                self._set_open_gauge()

    def record_success(self, key: Tuple) -> None:
        with self._lock:
            st = self._keys.get(key)
            if st is None:
                return
            if st["state"] != "closed":
                self._event("close")
            st["state"] = "closed"
            st["consecutive_failures"] = 0
            st["last_error"] = ""
            self._set_open_gauge()

    @staticmethod
    def describe(key: Tuple) -> str:
        """A short human-readable key label for error strings and the
        JSON /metrics view (works for ProgramKey and plain tuples)."""
        fields = getattr(key, "_asdict", None)
        if fields is not None:
            d = fields()
            parts = [f"N={d.get('N')}", f"steps={d.get('timesteps')}",
                     f"{d.get('scheme')}:{d.get('path')}"]
            if d.get("k", 1) and d.get("k", 1) > 1:
                parts.append(f"k={d['k']}")
            if d.get("mesh"):
                parts.append(f"mesh={d['mesh']}")
            return "/".join(str(p) for p in parts)
        return repr(key)

    def snapshot(self) -> dict:
        """The JSON /metrics `breaker` block: config + every non-closed
        (or previously-tripped) key's state."""
        with self._lock:
            keys: List[dict] = []
            n_open = 0
            now = time.monotonic()
            for key, st in self._keys.items():
                if st["state"] == "closed" and st["opens"] == 0:
                    continue  # never tripped: noise, not signal
                if st["state"] != "closed":
                    n_open += 1
                row = {
                    "key": self.describe(key),
                    "state": st["state"],
                    "consecutive_failures": st["consecutive_failures"],
                    "opens": st["opens"],
                    "last_error": st["last_error"] or None,
                }
                if st["state"] == "open":
                    row["retry_after_s"] = round(max(
                        0.0, self.cooldown_s - (now - st["opened_at"])
                    ), 3)
                keys.append(row)
            return {
                "threshold": self.threshold,
                "cooldown_s": self.cooldown_s,
                "open": n_open,
                "keys": keys,
            }
