"""Persistent AOT program cache: compiled programs survive restarts.

BENCH_r04/r05 and the compile ledger put XLA compiles at 30-62 s
against 2-7 s solves - for a serving fleet, compilation is the dominant
cold-start and autoscaling cost, and every process restart pays it
again.  This module is the disk tier under the serve engine's in-memory
LRU (`--program-cache-dir`):

    memory LRU  ->  disk (this module)  ->  fresh XLA compile

An entry is one file per (ProgramKey, environment fingerprint):

    DIR/<sha256(key)[:20]>-<sha256(fingerprint)[:8]>.wtpc

    MAGIC | u32 header_len | header JSON | pickled AOT payload

The header carries the full key, the fingerprint (wavetpu/jax/jaxlib
version, backend, device kind - an executable deserialized into the
wrong runtime is a crash or, worse, silent garbage), the FRESH compile
seconds it replaced (the measured savings credit), and a sha256 of the
payload.  Writes are atomic (tmp + os.replace); loads validate magic,
fingerprint, length, and checksum - a truncated, stale-fingerprint, or
cross-version entry is a COUNTED miss that falls through to a fresh
compile, never a crash and never a circuit-breaker feed.

The payload is `jax.experimental.serialize_executable.serialize` of the
lowered-and-compiled ensemble program; `aot_capability()` probes once
per process whether this jaxlib round-trips it (serialize ->
deserialize -> execute a tiny program) and the verdict rides /metrics
next to the vmap probes.  Where the probe fails, the cache falls back
to JAX's persistent compilation cache (`jax_compilation_cache_dir`)
scoped to DIR/xla - compiles are then transparently fast but not
adoptable, so they still count as engine misses; the mode is visible in
the same probe surface.  In AOT mode the DIR/xla cache rides along
anyway: the incidental jits around the ensemble program (watchdog
reductions, padding helpers) are real cold-start cost with no
executable object to adopt, and the XLA cache is exactly their shape.

Size is bounded by `--program-cache-max-bytes`: LRU by access time
(entry mtime, refreshed via os.utime on every hit), oldest evicted
first, the newest entry never evicted (a budget smaller than one
program must not make the cache a no-op).

`wavetpu warmup --manifest MANIFEST.json [--program-cache-dir DIR]`
(main below) consumes `wavetpu ledger-report --emit-warmup-manifest`'s
output verbatim - each key round-trips through `program_key_from_dict`
- and pre-populates a fresh replica's disk cache, printing per-key
timings.  `wavetpu serve --warmup-manifest` runs the same keys through
the engine on the background-warmup thread, so /healthz readiness
flips only once the manifest is warm.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import struct
import sys
import threading
import time
from typing import List, Optional, Sequence, Tuple

from wavetpu.obs import ledger as compile_ledger

MAGIC = b"WTPC0001"
ENTRY_SUFFIX = ".wtpc"

FINGERPRINT_FIELDS = ("wavetpu", "jax", "jaxlib", "backend",
                      "device_kind")


def env_fingerprint() -> dict:
    """The environment identity a serialized executable is only valid
    under.  Any field drifting (jaxlib upgrade, different chip
    generation, CPU vs TPU) invalidates every entry written under the
    old value - by filename, so stale entries are simply never read."""
    import jax

    from wavetpu import __version__

    try:
        import jaxlib

        jaxlib_version = jaxlib.__version__
    except Exception:
        jaxlib_version = "unknown"
    try:
        devices = jax.devices()
        device_kind = devices[0].device_kind if devices else "none"
    except Exception:
        device_kind = "unknown"
    return {
        "wavetpu": __version__,
        "jax": jax.__version__,
        "jaxlib": jaxlib_version,
        "backend": jax.default_backend(),
        "device_kind": device_kind,
    }


# ------------------------------------------------ AOT capability probe

_AOT_PROBE: Optional[Tuple[bool, Optional[str]]] = None
_probe_lock = threading.Lock()


def aot_capability() -> Tuple[bool, Optional[str]]:
    """Can this jaxlib serialize, deserialize, AND execute a compiled
    executable?  Probed once per process with a tiny jit (the
    `vmap_capability` discipline: record the verdict, never raise), and
    surfaced in /metrics via `probe_results()` - a replica silently
    running the XLA-cache fallback must be visible from the outside."""
    global _AOT_PROBE
    with _probe_lock:
        if _AOT_PROBE is not None:
            return _AOT_PROBE
        restore = None
        try:
            import jax
            import jax.numpy as jnp
            from jax.experimental import serialize_executable as se

            # The probe must compile OUTSIDE the persistent compilation
            # cache: an XLA-cache-served executable serializes but
            # fails deserialize_and_load ("Symbols not found"), which
            # would flip every restarted replica into fallback mode -
            # exactly the processes the AOT tier exists for.
            try:
                restore = jax.config.jax_enable_compilation_cache
                jax.config.update("jax_enable_compilation_cache", False)
            except Exception:
                restore = None
            f = jax.jit(lambda x: x * 2.0 + 1.0)
            compiled = f.lower(jnp.zeros((4,), jnp.float32)).compile()
            triple = se.serialize(compiled)
            # Round-trip through pickle exactly as an entry file does -
            # a PyTreeDef that serializes but does not pickle would
            # pass a weaker probe and still corrupt every store.
            payload, in_tree, out_tree = pickle.loads(
                pickle.dumps(triple)
            )
            again = se.deserialize_and_load(payload, in_tree, out_tree)
            out = again(jnp.ones((4,), jnp.float32))
            if float(out[0]) != 3.0:
                raise RuntimeError(
                    f"deserialized program computed {float(out[0])}, "
                    f"want 3.0"
                )
            verdict = (True, None)
        except Exception as e:  # recorded, never raised
            verdict = (False, f"{type(e).__name__}: {e}")
        if restore is not None:
            try:
                import jax

                jax.config.update(
                    "jax_enable_compilation_cache", restore
                )
            except Exception:
                pass
        _AOT_PROBE = verdict
        return verdict


def probe_results() -> list:
    """The cached AOT-serialization verdict as a /metrics row (empty
    until something touched the cache - the probe is lazy)."""
    if _AOT_PROBE is None:
        return []
    return [{
        "probe": "aot_serialize_executable",
        "ok": _AOT_PROBE[0],
        "reason": _AOT_PROBE[1],
    }]


# --------------------------------------- XLA persistent-cache fallback


def enable_xla_cache(directory: str) -> bool:
    """Scope JAX's persistent compilation cache to `directory` (the
    fallback tier where AOT serialization is unavailable, and the solo
    CLI's mechanism - solo solvers jit internally, so there is no
    executable object to adopt).  Thresholds are zeroed so CI-scale
    compiles cache too.  Returns False (recorded, not raised) on any
    config the installed jax does not know."""
    try:
        import jax

        os.makedirs(directory, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", directory)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 0.0
        )
        jax.config.update(
            "jax_persistent_cache_min_entry_size_bytes", -1
        )
        try:
            # If ANY compile ran before this config landed (the AOT
            # probe, a warmup jit), jax initialized its cache as
            # disabled and silently ignores the new dir; a reset makes
            # the next compile re-read the config.  Private API,
            # best-effort: without it the cache still works when
            # configured before first compile.
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:
            pass
        return True
    except Exception:
        return False


class XlaCacheHitCounter:
    """Counts `/jax/compilation_cache/cache_hits` monitoring events -
    the only signal the in-process XLA cache exposes.  Lets the solo
    CLI (and the fallback serve tier) mark its ledger entry
    `source: disk` when the persistent cache actually served the
    compile.  Best-effort: an older jax without the monitoring hook
    just never counts."""

    def __init__(self):
        self.hits = 0
        self.installed = False
        try:
            from jax._src import monitoring

            def _cb(name, **kw):
                if "compilation_cache/cache_hits" in name:
                    self.hits += 1

            monitoring.register_event_listener(_cb)
            self._cb = _cb
            self.installed = True
        except Exception:
            pass


_XLA_HITS: Optional[XlaCacheHitCounter] = None


def shared_xla_hit_counter() -> XlaCacheHitCounter:
    """One process-wide counter (the monitoring listener cannot be
    unregistered, so per-instance counters would pile up a callback per
    ProgramCache a test suite creates)."""
    global _XLA_HITS
    with _probe_lock:
        if _XLA_HITS is None:
            _XLA_HITS = XlaCacheHitCounter()
        return _XLA_HITS


# ------------------------------------------------------ the disk tier


class ProgramCache:
    """Disk-backed serialized-executable store for one directory.

    Thread-safe; every failure mode (corrupt entry, stale fingerprint,
    full disk, unpicklable payload) is a counted event in
    `wavetpu_progcache_events_total{event=}` and a None/False return -
    the serve path must treat disk problems as cache misses, never as
    request failures."""

    def __init__(self, directory: str,
                 max_bytes: Optional[int] = None,
                 registry=None, fault_plan=None):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.max_bytes = max_bytes
        self.fault_plan = fault_plan
        self._lock = threading.Lock()
        # Private counts always; mirrored into the registry when the
        # engine hands us its /metrics registry.
        self.counts: dict = {}
        self._counter = None
        self._saved = None
        if registry is not None:
            self._counter = registry.counter(
                "wavetpu_progcache_events_total",
                "persistent program-cache events", ("event",),
            )
            self._saved = registry.counter(
                "wavetpu_progcache_saved_seconds_total",
                "compile seconds served from disk instead of XLA "
                "(fresh compile seconds minus deserialize seconds)",
            )
        # The XLA persistent cache rides along in BOTH modes: in AOT
        # mode it catches the incidental jits around the ensemble
        # program (watchdog reductions, padding helpers - real
        # cold-start cost with no adoptable executable); where the AOT
        # probe fails it IS the persistence mechanism (and gets the hit
        # counter, so fallback-mode compiles can be attributed).
        # Configured BEFORE the probe compiles anything - see
        # enable_xla_cache on why ordering matters.
        ok, _why = aot_capability()
        self.aot_ok = ok
        self.xla_cache = enable_xla_cache(
            os.path.join(directory, "xla")
        )
        self.xla_fallback = bool(self.xla_cache and not ok)
        # The hit counter serves two masters: fallback-mode ledger
        # attribution (`source: disk` when the XLA cache served a
        # compile), and - in AOT mode - the store guard: a payload
        # serialized from a cache-served executable fails to
        # deserialize, so such compiles must never be put().
        self.xla_hits: Optional[XlaCacheHitCounter] = (
            shared_xla_hit_counter() if self.xla_cache else None
        )
        self.fingerprint = env_fingerprint()
        self._fp_hash = hashlib.sha256(
            json.dumps(self.fingerprint, sort_keys=True).encode()
        ).hexdigest()[:8]

    # ---- bookkeeping ----

    @property
    def usable(self) -> bool:
        """True when entries can be stored/adopted (AOT mode); the XLA
        fallback persists compiles on its own, invisibly to put/load."""
        return self.aot_ok

    def count(self, event: str, n: int = 1) -> None:
        with self._lock:
            self.counts[event] = self.counts.get(event, 0) + n
        if self._counter is not None:
            self._counter.inc(n, event=event)

    def credit_saved(self, fresh_compile_s: float,
                     load_s: float) -> float:
        saved = max(0.0, float(fresh_compile_s) - float(load_s))
        if self._saved is not None and saved > 0:
            self._saved.inc(saved)
        return saved

    def entry_path(self, key: dict) -> str:
        canon = compile_ledger.canonical_key(key)
        kh = hashlib.sha256(canon.encode()).hexdigest()[:20]
        return os.path.join(
            self.directory, f"{kh}-{self._fp_hash}{ENTRY_SUFFIX}"
        )

    def _entries(self):
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in names:
            if not name.endswith(ENTRY_SUFFIX):
                continue
            p = os.path.join(self.directory, name)
            try:
                st = os.stat(p)
            except OSError:
                continue
            out.append((p, st.st_size, st.st_mtime))
        return out

    # ---- store / load ----

    def put(self, key: dict, payload, compile_s: float) -> bool:
        """Atomically persist one serialized executable; returns True
        on success.  `compile_s` is the fresh compile this entry will
        spare future processes - the measured-savings credit a later
        load reports."""
        if not self.usable:
            return False
        try:
            blob = pickle.dumps(payload, protocol=4)
            header = {
                "format": 1,
                "key": compile_ledger.normalize_key(key),
                "fingerprint": dict(self.fingerprint),
                "created_unix": round(time.time(), 3),
                "compile_s": round(float(compile_s), 6),
                "payload_sha256": hashlib.sha256(blob).hexdigest(),
                "payload_len": len(blob),
            }
            hdr = json.dumps(header, sort_keys=True).encode()
            path = self.entry_path(key)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(MAGIC)
                f.write(struct.pack(">I", len(hdr)))
                f.write(hdr)
                f.write(blob)
            os.replace(tmp, path)
        except Exception:
            self.count("store_error")
            return False
        self.count("store")
        if self.max_bytes is not None:
            self.gc()
        return True

    def load(self, key: dict) -> Optional[Tuple[object, dict]]:
        """(payload, header) for a valid entry, else None - with the
        reason counted (`disk_miss` / `corrupt` /
        `fingerprint_mismatch`).  A hit refreshes the entry's mtime
        (the GC's LRU clock); a corrupt entry is deleted so later
        processes pay a plain disk_miss instead of re-parsing garbage.
        Never raises."""
        if not self.usable:
            return None
        path = self.entry_path(key)
        if not os.path.exists(path):
            self.count("disk_miss")
            return None

        def _corrupt():
            self.count("corrupt")
            try:
                os.remove(path)
            except OSError:
                pass
            return None

        # Chaos seams (run/faults.py): drive the REAL detection
        # branches, not simulations of them - truncate the entry on
        # disk, or poison the expected fingerprint, then read normally.
        expected_fp = self.fingerprint
        if self.fault_plan is not None:
            ctx = {
                "n": key.get("N"), "timesteps": key.get("timesteps"),
                "scheme": key.get("scheme"), "path": key.get("path"),
                "k": key.get("k"), "dtype": key.get("dtype"),
            }
            if self.fault_plan.fire("progcache-truncate", **ctx):
                from wavetpu.run import faults as _faults

                try:
                    _faults.truncate_tail(path, drop_bytes=64)
                except OSError:
                    pass
            if self.fault_plan.fire("progcache-fingerprint", **ctx):
                expected_fp = dict(self.fingerprint,
                                   wavetpu="injected-other-version")
        try:
            with open(path, "rb") as f:
                if f.read(len(MAGIC)) != MAGIC:
                    return _corrupt()
                raw_len = f.read(4)
                if len(raw_len) != 4:
                    return _corrupt()
                (hdr_len,) = struct.unpack(">I", raw_len)
                hdr = f.read(hdr_len)
                if len(hdr) != hdr_len:
                    return _corrupt()
                header = json.loads(hdr)
                if header.get("fingerprint") != expected_fp:
                    self.count("fingerprint_mismatch")
                    return None
                blob = f.read()
            if (
                len(blob) != header.get("payload_len")
                or hashlib.sha256(blob).hexdigest()
                != header.get("payload_sha256")
            ):
                return _corrupt()
            payload = pickle.loads(blob)
        except Exception:
            return _corrupt()
        try:
            os.utime(path)
        except OSError:
            pass
        self.count("disk_hit")
        return payload, header

    def gc(self) -> int:
        """Evict oldest-accessed entries until the directory fits
        `max_bytes`; the newest entry is never evicted (a budget
        smaller than one program must degrade to keep-latest, not
        keep-nothing).  Returns the eviction count."""
        if self.max_bytes is None:
            return 0
        entries = sorted(self._entries(), key=lambda e: e[2])
        total = sum(e[1] for e in entries)
        evicted = 0
        while total > self.max_bytes and len(entries) > 1:
            path, size, _mtime = entries.pop(0)
            try:
                os.remove(path)
            except OSError:
                continue
            total -= size
            evicted += 1
        if evicted:
            self.count("gc_evict", evicted)
        return evicted

    def entry_keys(self) -> List[dict]:
        """ProgramKey dicts of every ADOPTABLE disk entry: same-
        fingerprint `.wtpc` files whose header parses (headers only -
        no payload read, no pickle).  This is the disk half of the
        /metrics `program_cache.warm_keys` block the fleet router
        bootstraps its affinity table from: a replica that has not yet
        served a tier still attracts its traffic when the shared cache
        dir lets it adopt the program instead of compiling.  Corrupt or
        foreign-fingerprint entries are silently skipped (this is
        advertisement, not adoption - load() keeps the loud path)."""
        if not self.usable:
            return []
        suffix = f"-{self._fp_hash}{ENTRY_SUFFIX}"
        out: List[dict] = []
        for path, _size, _mtime in self._entries():
            if not os.path.basename(path).endswith(suffix):
                continue
            try:
                with open(path, "rb") as f:
                    if f.read(len(MAGIC)) != MAGIC:
                        continue
                    raw_len = f.read(4)
                    if len(raw_len) != 4:
                        continue
                    (hdr_len,) = struct.unpack(">I", raw_len)
                    if hdr_len > 1 << 20:
                        continue
                    header = json.loads(f.read(hdr_len))
            except Exception:
                continue
            key = header.get("key")
            if isinstance(key, dict):
                out.append(key)
        return out

    def stats(self) -> dict:
        """The /metrics `program_cache.progcache` block."""
        entries = self._entries()
        with self._lock:
            counts = dict(self.counts)
        return {
            "enabled": True,
            "dir": self.directory,
            "aot": self.aot_ok,
            "xla_cache": self.xla_cache,
            "xla_fallback": self.xla_fallback,
            "entries": len(entries),
            "bytes": sum(e[1] for e in entries),
            "max_bytes": self.max_bytes,
            "events": counts,
            "aot_probes": probe_results(),
        }


# ----------------------------------------- manifest-driven warmup CLI


def _dtype_from_name(name: str):
    import jax.numpy as jnp

    table = {"f32": jnp.float32, "f64": jnp.float64,
             "bf16": jnp.bfloat16}
    if name not in table:
        raise ValueError(f"unknown dtype {name!r}")
    return table[name]


def build_solver_for_key(pk, interpret: Optional[bool] = None):
    """The (uncompiled) ensemble program a ProgramKey describes - the
    same constructor calls `ServeEngine._program` makes, honoring the
    key's own compute_errors (a manifest key replays what was actually
    served, not what this process would derive)."""
    from wavetpu.core.problem import Problem
    from wavetpu.ensemble import batched as ensemble
    from wavetpu.ensemble import sharded as ens_sharded

    problem = Problem(N=pk.N, Np=1, Lx=pk.Lx, Ly=pk.Ly, Lz=pk.Lz,
                      T=pk.T, timesteps=pk.timesteps)
    if pk.mesh is not None:
        return ens_sharded.ShardedEnsembleSolver(
            problem, pk.batch, pk.mesh,
            dtype=_dtype_from_name(pk.dtype), kernel=pk.path,
            compute_errors=pk.compute_errors, interpret=interpret,
        )
    return ensemble.EnsembleSolver(
        problem, pk.batch, dtype=_dtype_from_name(pk.dtype),
        path=pk.path, k=pk.k, compute_errors=pk.compute_errors,
        interpret=interpret, with_field=pk.with_field, scheme=pk.scheme,
    )


def load_manifest(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        manifest = json.load(f)
    if not isinstance(manifest, dict) or not manifest.get(
        compile_ledger.MANIFEST_FLAG
    ):
        raise ValueError(
            f"{path} is not a wavetpu warmup manifest (missing "
            f"{compile_ledger.MANIFEST_FLAG!r}; produce one with "
            f"`wavetpu ledger-report DIR --emit-warmup-manifest OUT`)"
        )
    keys = manifest.get("keys")
    if not isinstance(keys, list):
        raise ValueError(f"{path}: manifest `keys` must be a list")
    return manifest


def warm_manifest_into_cache(
    manifest: dict, cache: Optional[ProgramCache] = None,
    interpret: Optional[bool] = None, out=None,
) -> dict:
    """Compile (or disk-adopt) every manifest key, storing fresh
    compiles into `cache`; prints one per-key timing line to `out` and
    returns the summary dict.  Per-key failures are recorded and do not
    stop the sweep."""
    import jax

    out = sys.stdout if out is None else out
    n_dev = len(jax.devices())
    summary = {"keys": 0, "disk_hits": 0, "compiled": 0, "skipped": 0,
               "failed": 0, "compile_s": 0.0, "errors": []}
    for raw in manifest.get("keys", ()):
        summary["keys"] += 1
        try:
            pk = compile_ledger.program_key_from_dict(raw)
        except Exception as e:
            summary["failed"] += 1
            summary["errors"].append(f"bad key {raw!r}: {e}")
            print(f"  bad key: {e}", file=out)
            continue
        label = compile_ledger._key_label(
            compile_ledger.key_from_program_key(pk)
        )
        if pk.mesh is not None:
            need = pk.mesh[0] * pk.mesh[1] * pk.mesh[2]
            if need > n_dev:
                summary["skipped"] += 1
                print(f"  {label}: skip (mesh needs {need} devices, "
                      f"{n_dev} available)", file=out)
                continue
        key_dict = compile_ledger.key_from_program_key(pk)
        try:
            t0 = time.perf_counter()
            solver = build_solver_for_key(pk, interpret=interpret)
            if cache is not None and cache.usable:
                entry = cache.load(key_dict)
                if entry is not None:
                    try:
                        solver.adopt_executable(entry[0])
                        dt = time.perf_counter() - t0
                        summary["disk_hits"] += 1
                        print(f"  {label}: disk hit ({dt:.3f}s)",
                              file=out)
                        continue
                    except Exception:
                        cache.count("corrupt")
            pre_hits = (
                cache.xla_hits.hits
                if cache is not None and cache.xla_hits is not None
                else None
            )
            compile_s = solver.compile()
            summary["compiled"] += 1
            summary["compile_s"] += compile_s
            stored = False
            xla_served = (
                pre_hits is not None
                and cache.xla_hits.hits > pre_hits
            )
            if cache is not None and cache.usable and not xla_served:
                payload = solver.executable_payload()
                if payload is not None:
                    stored = cache.put(key_dict, payload, compile_s)
            print(
                f"  {label}: compiled {compile_s:.3f}s"
                + (" -> cached" if stored else ""),
                file=out,
            )
        except Exception as e:
            summary["failed"] += 1
            summary["errors"].append(f"{label}: {e}")
            print(f"  {label}: FAILED ({type(e).__name__}: {e})",
                  file=out)
    summary["compile_s"] = round(summary["compile_s"], 6)
    return summary


_USAGE = (
    "usage: wavetpu warmup --manifest MANIFEST.json "
    "[--program-cache-dir DIR] [--program-cache-max-bytes B] "
    "[--platform NAME]"
)

_KNOWN = ("manifest", "program-cache-dir", "program-cache-max-bytes",
          "platform")


def main(argv: Optional[Sequence[str]] = None) -> int:
    """`wavetpu warmup`: pre-populate a replica's program cache from a
    ledger-report manifest.  Exit 0 on success (skips are not
    failures), 1 when any key failed to build/compile, 2 on usage."""
    from wavetpu.core.flags import split_flags

    argv = list(sys.argv[1:] if argv is None else argv)
    try:
        _, flags = split_flags(argv, _KNOWN, (),
                               allow_positionals=False)
        if "manifest" not in flags:
            raise ValueError("missing --manifest MANIFEST.json")
        manifest = load_manifest(flags["manifest"])
        max_bytes = (
            int(flags["program-cache-max-bytes"])
            if "program-cache-max-bytes" in flags else None
        )
    except (ValueError, OSError, json.JSONDecodeError) as e:
        print(f"error: {e}", file=sys.stderr)
        print(_USAGE, file=sys.stderr)
        return 2

    import jax

    platform = flags.get("platform") or os.environ.get("JAX_PLATFORMS")
    if platform and platform != jax.config.jax_platforms:
        jax.config.update("jax_platforms", platform)

    cache = None
    if "program-cache-dir" in flags:
        cache = ProgramCache(flags["program-cache-dir"],
                             max_bytes=max_bytes)
        mode = (
            "AOT serialized executables" if cache.usable
            else "XLA persistent compilation cache (fallback: "
            + str(aot_capability()[1]) + ")"
            if cache.xla_fallback else "DISABLED (no mechanism)"
        )
        print(f"program cache: {cache.directory} [{mode}]")
    else:
        print("note: no --program-cache-dir; compiles will not "
              "persist beyond this process")

    t0 = time.perf_counter()
    summary = warm_manifest_into_cache(manifest, cache)
    wall = time.perf_counter() - t0
    print(
        f"warmed {summary['keys']} key(s) in {wall:.3f}s: "
        f"{summary['disk_hits']} disk hit(s), "
        f"{summary['compiled']} compiled "
        f"({summary['compile_s']:.3f}s), "
        f"{summary['skipped']} skipped, {summary['failed']} failed"
    )
    return 1 if summary["failed"] else 0


if __name__ == "__main__":
    sys.exit(main())
