"""Dynamic batching: coalesce concurrent solve requests into ensembles.

The request path is the standard inference-serving shape (arXiv:2108.11076
batches simulations the same way an LLM server batches prompts):

 * `submit()` enqueues a `SolveRequest` and returns a future immediately
   (the HTTP handler thread blocks on it; the server stays concurrent).
 * One worker thread drains the queue.  Requests are SHAPE-BUCKETED by
   `SolveRequest.bucket_key()` - everything the compiled program identity
   depends on (problem geometry, scheme, kernel path, k, dtype, field
   presence) - because only same-key requests can share a program.
 * A batch closes when it reaches `max_batch` lanes or `max_wait` seconds
   after its first request - the classic max-batch/max-wait tradeoff
   (batch occupancy vs tail latency).  Non-matching requests seen while
   collecting are stashed and served next round in arrival order.
 * The engine pads the batch to its bucket with masked lanes, runs the
   cached program, watchdogs each lane; every future resolves with ITS
   lane's result (or per-lane health error) plus batch context.

Multi-tenant QoS (docs/serving.md "Priority classes"): every request
carries a `priority` class (interactive | batch | best_effort).  The
stash is one deque PER CLASS, drained by weighted deficit round-robin
(`CLASS_WEIGHTS` 16:4:1): each worker pass credits every backlogged
class its weight, serves the largest deficit (ties go to the higher
static class), and debits the winner the round's total credit - so an
eligible interactive request takes the NEXT pass ahead of a lower-class
chunked march's next chunk slot (the one-chunk-per-pass machinery makes
preemption a dequeue-ordering decision), while the deficit counter
guarantees best_effort is served within ~sum(weights)/1 passes however
hard interactive floods (the starvation bound tests/test_qos.py pins).
With a single backlogged class the deficits stay zeroed and scheduling
is exactly the historical FIFO - the QoS-off fast path.

`BrownoutController` is the adaptive overload ladder: when measured
queue-wait p95 crosses its rung thresholds the batcher sheds
best_effort admissions first, then batch, then defers NEW chunked-march
starts - and de-escalates only after a hysteresis-gated cooldown so the
ladder never flaps.  Shed responses are 503 + a MEASURED Retry-After
(`ServeMetrics.retry_after_s`, the queue-drain estimate that also
replaced the hardcoded queue-full/draining constants).

`ServeMetrics` is the shared counter block /metrics renders: request and
batch counts, occupancy, latency percentiles over a sliding reservoir,
and aggregate Gcell/s across all served lanes.  Since the unified-
telemetry round it WRITES THROUGH an `obs.registry.MetricsRegistry`
(one per server, so test servers never share counters): the JSON
snapshot keeps its exact historical fields while the same state renders
as Prometheus text exposition under `Accept: text/plain`, and every
batch emits a `serve.batch` span (occupancy, padding waste, queue
waits, request ids) into the structured trace when `--telemetry-dir`
is on.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Dict, List, Optional, Sequence, Tuple

from wavetpu.core.problem import Problem
from wavetpu.ensemble.batched import LaneSpec
from wavetpu.obs import ledger as compile_ledger
from wavetpu.obs import tracing
from wavetpu.obs.registry import MetricsRegistry
from wavetpu.obs.report import percentile_nearest_rank
from wavetpu.run import faults, health
from wavetpu.serve.resilience import (
    DeadlineExceededError,
    InvalidStateTokenError,
    PreemptedError,
    ShedError,
    WorkerCrashError,
)

# Priority classes, highest static priority first.  The order IS the
# deficit tie-break and the brownout shed order (best_effort sheds
# first).  CLASS_WEIGHTS drive the deficit round-robin: under a
# two-class backlog the service ratio converges to the weight ratio,
# and the lowest class is served at least once per ~sum(weights)
# worker passes - the starvation bound.
PRIORITY_CLASSES = ("interactive", "batch", "best_effort")
CLASS_WEIGHTS = {"interactive": 16, "batch": 4, "best_effort": 1}
DEFAULT_PRIORITY = "batch"


def normalize_priority(value, default: str = DEFAULT_PRIORITY) -> str:
    """Clamp any caller-supplied priority to a known class (unknown or
    absent values land on `default`, never an error - priority is a
    scheduling hint, not a validation surface)."""
    if isinstance(value, str):
        v = value.strip().lower()
        if v in PRIORITY_CLASSES:
            return v
    return default


class QueueFullError(RuntimeError):
    """`submit()` refused: the bounded request queue is at capacity.
    The HTTP layer maps this to 429 (backpressure, not failure)."""


@dataclasses.dataclass
class SolveRequest:
    """One lane's worth of work plus its program identity.

    `mesh_shape` routes the request through the sharded x batched
    composition (ensemble/sharded.py); only same-mesh requests share a
    program."""

    problem: Problem
    lane: LaneSpec
    scheme: str = "standard"
    path: str = "roll"
    k: int = 1
    dtype_name: str = "f32"
    mesh_shape: Optional[Tuple[int, int, int]] = None
    # Preemptible long solves: continue a previously-checkpointed march
    # (serve/preempt.py state token).  NOT part of bucket_key - a
    # resumed solve never batches anyway (chunked items get unique
    # keys).
    resume_token: Optional[str] = None
    # Tenant label the router stamped (X-Wavetpu-Tenant); rides into
    # spans, per-tenant counters, and ledger lines.  Never part of the
    # program identity.
    tenant: Optional[str] = None
    # QoS class (PRIORITY_CLASSES member; submit() normalizes unknown
    # values to "batch").  Drives the per-class deficit round-robin and
    # the brownout shed order - never the program identity, so classes
    # still coalesce into one batch when their keys match.
    priority: str = DEFAULT_PRIORITY
    # Shadow-solve sampling (serve/shadow.py): True marks the off-hot-
    # path reference twin of a sampled production request.  Never part
    # of the program identity - a shadow coalesces into a production
    # batch of the same key (a free ride) - but a batch of ONLY
    # shadows runs with the circuit breaker bypassed.
    shadow: bool = False

    def bucket_key(self) -> Tuple:
        """Everything the compiled program identity depends on; only
        same-key requests may share a batch."""
        p = self.problem
        return (
            p.N, p.Lx, p.Ly, p.Lz, p.T, p.timesteps,
            self.scheme, self.path,
            self.k if self.path == "kfused" else 1,
            self.dtype_name,
            self.lane.c2tau2_field is not None,
            None if self.mesh_shape is None else tuple(self.mesh_shape),
        )


_LATENCY_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
    30.0, 60.0, 120.0, 300.0,
)
_OCCUPANCY_BUCKETS = (1, 2, 4, 8, 16, 32)


class ServeMetrics:
    """Thread-safe counters for /metrics (shared by scheduler + api).

    All state lives in an `obs.registry.MetricsRegistry` (own one by
    default; `build_server` passes a shared per-server registry so the
    engine's program-cache counters land in the same Prometheus
    exposition).  `snapshot()` takes the REGISTRY lock across the whole
    read - including the exact-percentile latency reservoir, which is
    guarded by the same lock - so a scrape is one consistent cut and can
    never see, e.g., `responses_ok` ahead of `requests_total` or a torn
    occupancy mean.  (The pre-registry ServeMetrics held its own lock in
    snapshot() but each observe_* released it between related fields;
    one registry-wide lock closes that audit for good.)
    """

    def __init__(self, latency_window: int = 1024,
                 registry: Optional[MetricsRegistry] = None):
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self.started = time.time()
        r = self.registry
        self._requests = r.counter(
            "wavetpu_serve_requests_total", "solve requests accepted"
        )
        self._responses = r.counter(
            "wavetpu_serve_responses_total", "responses by outcome",
            ("status",),
        )
        self._rejected = r.counter(
            "wavetpu_serve_rejected_total",
            "requests rejected with 429 (bounded queue full)",
        )
        self._limit_rejected = r.counter(
            "wavetpu_serve_limit_rejected_total",
            "requests refused by request-size limits before scheduling "
            "(413 body bytes, 422 lane cells)", ("limit",),
        )
        self._batches = r.counter(
            "wavetpu_serve_batches_total", "batches executed"
        )
        self._occupancy = r.histogram(
            "wavetpu_serve_batch_occupancy", "real lanes per batch",
            buckets=_OCCUPANCY_BUCKETS,
        )
        self._occupancy_max = r.gauge(
            "wavetpu_serve_batch_occupancy_max",
            "largest batch occupancy seen",
        )
        self._padding = r.counter(
            "wavetpu_serve_padding_lanes_total",
            "masked padding lanes marched (bucket size - occupancy)",
        )
        self._fallbacks = r.counter(
            "wavetpu_serve_fallback_batches_total",
            "batches served by the lane-loop fallback",
        )
        self._cells = r.counter(
            "wavetpu_serve_cells_total", "cell updates served"
        )
        self._solve_seconds = r.counter(
            "wavetpu_serve_solve_seconds_total", "batch solve wall seconds"
        )
        self._latency = r.histogram(
            "wavetpu_serve_request_seconds",
            "end-to-end request latency", buckets=_LATENCY_BUCKETS,
        )
        self._queue_wait = r.histogram(
            "wavetpu_serve_queue_wait_seconds",
            "submit-to-batch-formed wait", buckets=_LATENCY_BUCKETS,
        )
        self._queue_depth = r.gauge(
            "wavetpu_serve_queue_depth",
            "requests submitted but not yet executing",
        )
        self._last_batch_ts = r.gauge(
            "wavetpu_serve_last_batch_timestamp",
            "unix time the last batch finished (0 = none yet)",
        )
        self._deadline_expired = r.counter(
            "wavetpu_serve_deadline_expired_total",
            "requests dropped because their deadline_ms budget expired "
            "before execution (HTTP 504)",
        )
        self._worker_restarts = r.counter(
            "wavetpu_serve_worker_restarts_total",
            "scheduler-worker crashes absorbed by the supervisor "
            "(in-flight futures failed retriable, worker restarted)",
        )
        # Preemptible long solves (serve/preempt.py).
        self._chunks = r.counter(
            "wavetpu_serve_chunks_total",
            "chunks marched by preemptible long solves",
        )
        self._preempted = r.counter(
            "wavetpu_serve_preempted_total",
            "long solves checkpointed/aborted mid-march by reason "
            "(deadline = 504 + token, drain = retriable 503 + token)",
            ("reason",),
        )
        self._resumes = r.counter(
            "wavetpu_serve_resumes_total",
            "long-solve resumptions by source (token = client-supplied "
            "resume_token, crash = in-memory re-enqueue after a worker "
            "crash)",
            ("source",),
        )
        self._tenant_requests = r.counter(
            "wavetpu_serve_tenant_requests_total",
            "solve requests by router-stamped tenant label",
            ("tenant",),
        )
        self._inflight_chunks = r.gauge(
            "wavetpu_serve_inflight_chunk_marches",
            "chunked long solves currently mid-march (march state "
            "held between scheduler rounds; survives worker crashes)",
        )
        # Multi-tenant QoS (docs/serving.md "Priority classes").
        self._class_requests = r.counter(
            "wavetpu_serve_class_requests_total",
            "solve requests admitted by priority class",
            ("class",),
        )
        self._scheduled = r.counter(
            "wavetpu_serve_scheduled_total",
            "requests scheduled onto a worker pass by priority class "
            "(deficit round-robin picks)",
            ("class",),
        )
        self._shed = r.counter(
            "wavetpu_serve_shed_total",
            "admissions refused by the brownout ladder, by rung and "
            "priority class (503 + measured Retry-After)",
            ("rung", "class"),
        )
        self._tenant_shed = r.counter(
            "wavetpu_serve_tenant_shed_total",
            "brownout sheds by router-stamped tenant label",
            ("tenant",),
        )
        self._brownout_rung = r.gauge(
            "wavetpu_serve_brownout_rung",
            "current brownout ladder rung (0 healthy, 1 shedding "
            "best_effort, 2 shedding batch too, 3 deferring chunk "
            "starts)",
        )
        self._chunk_deferred = r.counter(
            "wavetpu_serve_chunk_starts_deferred_total",
            "worker passes that deferred starting a NEW chunked march "
            "because the brownout ladder is at its top rung",
        )
        self._tenant_inflight_rejected = r.counter(
            "wavetpu_serve_tenant_inflight_rejected_total",
            "requests refused by the per-tenant in-flight cap "
            "(--tenant-inflight-cap; 429 + measured Retry-After)",
            ("tenant",),
        )
        self._tenant_spoof_rejected = r.counter(
            "wavetpu_serve_tenant_spoof_rejected_total",
            "direct-to-replica requests whose tenant/priority headers "
            "were IGNORED for lack of the --proxy-token secret "
            "(request still served, untenanted)",
        )
        self._coalesced = r.counter(
            "wavetpu_serve_coalesced_total",
            "requests that rode an identical in-flight solve via "
            "singleflight coalescing instead of enqueueing their own "
            "march (each still counted/charged as a request)",
        )
        # Drain-rate estimator behind `retry_after_s`: (monotonic end
        # time, lanes completed) per batch, guarded by the registry
        # lock like everything else here.
        self._drained: "deque[Tuple[float, int]]" = deque(maxlen=64)
        # Exact-percentile reservoir for the JSON snapshot's historical
        # latency_p50/p95_ms fields (the histogram above serves
        # Prometheus); guarded by the REGISTRY lock so snapshot() is one
        # consistent cut.
        self._latencies = deque(maxlen=latency_window)

    def observe_request(self) -> None:
        self._requests.inc()

    def observe_rejected(self) -> None:
        self._rejected.inc()

    def observe_limit_rejected(self, limit: str) -> None:
        """A request refused by `--max-body-bytes` (limit="body_bytes")
        or `--max-lane-cells` (limit="lane_cells") before it ever
        touched the queue."""
        self._limit_rejected.inc(limit=limit)

    def observe_response(self, ok: bool) -> None:
        self._responses.inc(status="ok" if ok else "error")

    def observe_queue_depth(self, depth: int) -> None:
        self._queue_depth.set(depth)

    def observe_deadline_expired(self) -> None:
        self._deadline_expired.inc()

    def observe_worker_restart(self) -> None:
        self._worker_restarts.inc()

    def observe_chunk(self) -> None:
        self._chunks.inc()

    def observe_chunk_march_started(self) -> None:
        self._inflight_chunks.inc()

    def observe_chunk_march_ended(self) -> None:
        self._inflight_chunks.dec()

    def observe_preempted(self, reason: str) -> None:
        self._preempted.inc(reason=reason)

    def observe_resume(self, source: str) -> None:
        self._resumes.inc(source=source)

    def observe_tenant(self, tenant: Optional[str]) -> None:
        if tenant:
            self._tenant_requests.inc(tenant=tenant)

    def observe_class_request(self, priority: str) -> None:
        self._class_requests.inc(**{"class": priority})

    def observe_scheduled(self, priority: str) -> None:
        self._scheduled.inc(**{"class": priority})

    def observe_shed(self, rung: str, priority: str,
                     tenant: Optional[str] = None) -> None:
        self._shed.inc(**{"rung": rung, "class": priority})
        if tenant:
            self._tenant_shed.inc(tenant=tenant)

    def observe_brownout_rung(self, rung: int) -> None:
        self._brownout_rung.set(rung)

    def observe_chunk_start_deferred(self) -> None:
        self._chunk_deferred.inc()

    def observe_tenant_inflight_rejected(self, tenant: str) -> None:
        self._tenant_inflight_rejected.inc(tenant=tenant)

    def observe_tenant_spoof_rejected(self) -> None:
        self._tenant_spoof_rejected.inc()

    def observe_coalesced(self) -> None:
        self._coalesced.inc()

    def retry_after_s(self, pending: int, fallback: float = 1.0) -> float:
        """MEASURED backoff hint for 429/503 responses: how long until
        `pending` queued lanes drain at the recently observed service
        rate (lanes completed per second over the last minute of
        batches), clamped to [1, 60] seconds.  `fallback` (the
        historical constant for the call site) is returned when no
        batch has completed recently - a cold or idle server has no
        rate to measure, and a fixed small hint beats a wild guess."""
        now = time.monotonic()
        with self.registry.lock:
            samples = [s for s in self._drained if now - s[0] <= 60.0]
        if len(samples) < 2:
            return fallback
        span = now - samples[0][0]
        lanes = sum(n for _, n in samples[1:])
        if span <= 0.0 or lanes <= 0:
            return fallback
        rate = lanes / span
        return min(60.0, max(1.0, (pending + 1) / rate))

    def observe_batch(self, occupancy: int, batched: bool,
                      cells: float, solve_seconds: float,
                      batch_size: Optional[int] = None,
                      queue_waits: Sequence[float] = (),
                      request_ids: Sequence[Optional[str]] = ()) -> None:
        with self.registry.lock:
            self._batches.inc()
            self._occupancy.observe(occupancy)
            if occupancy > self._occupancy_max.value():
                self._occupancy_max.set(occupancy)
            if batch_size is not None and batch_size > occupancy:
                self._padding.inc(batch_size - occupancy)
            if not batched:
                self._fallbacks.inc()
            self._cells.inc(cells)
            self._solve_seconds.inc(solve_seconds)
            self._last_batch_ts.set(time.time())
            self._drained.append((time.monotonic(), occupancy))
            for i, w in enumerate(queue_waits):
                rid = request_ids[i] if i < len(request_ids) else None
                self._queue_wait.observe(
                    w,
                    exemplar={"request_id": rid} if rid else None,
                )

    def observe_latency(self, seconds: float,
                        request_id: Optional[str] = None) -> None:
        """End-to-end request latency.  `request_id` becomes an
        OpenMetrics exemplar on the bucket the observation lands in, so
        a scraped p99 outlier bucket names the exact request to feed
        `wavetpu trace-report --request`."""
        with self.registry.lock:
            self._latencies.append(seconds)
            self._latency.observe(
                seconds,
                exemplar={"request_id": request_id} if request_id else None,
            )

    def _percentile(self, p: float) -> Optional[float]:
        if not self._latencies:
            return None
        return percentile_nearest_rank(sorted(self._latencies), p)

    def last_batch_age(self) -> Optional[float]:
        """Seconds since the last batch finished, or None before any
        batch - the load balancer's idle-vs-wedged discriminator.

        Keyed on the batches COUNTER, not the timestamp gauge: a gauge
        still at its 0.0 default is indistinguishable from a genuine
        t=0 timestamp, so "never executed a batch" (None) and "has
        executed, currently idle" (a number, possibly 0.0) must be told
        apart by whether any batch was ever counted."""
        with self.registry.lock:
            if self._batches.value() == 0:
                return None
            ts = self._last_batch_ts.value()
        return max(0.0, time.time() - ts)

    def snapshot(self) -> dict:
        with self.registry.lock:
            batches = int(self._batches.value())
            occ = self._occupancy._snapshot_value()
            mean_occ = occ["sum"] / batches if batches else None
            p50 = self._percentile(0.50)
            p95 = self._percentile(0.95)
            solve_s = self._solve_seconds.value()
            agg = (
                self._cells.value() / solve_s / 1e9 if solve_s else None
            )
            age = self.last_batch_age()
            return {
                "uptime_seconds": round(time.time() - self.started, 3),
                "requests_total": int(self._requests.value()),
                "responses_ok": int(self._responses.value(status="ok")),
                "responses_error": int(
                    self._responses.value(status="error")
                ),
                "batches_total": batches,
                "batch_occupancy_mean": mean_occ,
                "batch_occupancy_max": int(self._occupancy_max.value()),
                "fallback_batches": int(self._fallbacks.value()),
                "latency_p50_ms": None if p50 is None else round(
                    p50 * 1e3, 3
                ),
                "latency_p95_ms": None if p95 is None else round(
                    p95 * 1e3, 3
                ),
                "aggregate_gcells_per_s": (
                    None if agg is None else round(agg, 4)
                ),
                "queue_depth": int(self._queue_depth.value()),
                "rejected_total": int(self._rejected.value()),
                "limit_rejected_total": int(self._limit_rejected.total()),
                "padding_lanes_total": int(self._padding.value()),
                "last_batch_age_seconds": (
                    None if age is None else round(age, 3)
                ),
                "deadline_expired_total": int(
                    self._deadline_expired.value()
                ),
                "worker_restarts_total": int(
                    self._worker_restarts.value()
                ),
                "chunks_total": int(self._chunks.value()),
                "preempted_total": int(self._preempted.total()),
                "resumed_total": int(self._resumes.total()),
                "shed_total": int(self._shed.total()),
                "brownout_rung": int(self._brownout_rung.value()),
                "coalesced_total": int(self._coalesced.value()),
            }


@dataclasses.dataclass
class _Item:
    request: SolveRequest
    future: Future
    key: Tuple
    # Telemetry: the trace id the HTTP layer minted for this request
    # (None untraced) and the monotonic submit time for queue-wait
    # attribution.
    request_id: Optional[str] = None
    enqueued: float = 0.0
    # Absolute monotonic deadline (None = no budget): the worker drops
    # an already-expired item at batch formation (HTTP 504) instead of
    # marching work nobody is waiting for.
    deadline: Optional[float] = None
    # Preemptible long solves: True routes the item through the chunked
    # march (never batched - its key is unique); `chunk` holds the
    # march's in-memory progress once the first round initialized it
    # (worker-crash recovery resumes from it instead of failing the
    # request).
    chunked: bool = False
    chunk: Optional["_ChunkProgress"] = None
    # Fleet trace context the HTTP layer adopted/minted for this
    # request: (32-hex trace id, 16-hex serve.request wire id), None
    # untraced.  Chunk spans stamp the trace id, and checkpoints
    # persist it so a resume on another replica links back.
    trace_context: Optional[Tuple[str, str]] = None


class _ChunkProgress:
    """In-memory march state of one chunked long solve between rounds
    (the item carries it across the scheduler's interleaving and across
    worker-crash restarts)."""

    __slots__ = (
        "runner", "state", "step", "abs", "rel", "chunks_done",
        "wait_s", "compile_s", "execute_s", "warm", "resumed_from",
        "origin_trace",
    )

    def __init__(self, runner, warm: str, compile_s: float,
                 wait_s: float):
        import numpy as np

        self.runner = runner
        self.state = None
        self.step = 0
        t = runner.problem.timesteps
        self.abs = np.zeros(t + 1, dtype=np.float64)
        self.rel = np.zeros(t + 1, dtype=np.float64)
        self.chunks_done = 0
        self.wait_s = wait_s
        self.compile_s = compile_s
        self.execute_s = 0.0
        self.warm = warm
        self.resumed_from: Optional[int] = None
        # [trace_id, span_w3c_id] of the ORIGINATING request: minted on
        # the first march, carried through checkpoints, so the chunk
        # spans of a solve resumed on another replica (or under a fresh
        # client trace) still link back to where the march began.
        self.origin_trace: Optional[List[str]] = None


class BrownoutController:
    """The adaptive overload ladder (docs/robustness.md "Brownout
    ladder").  Input: queue-wait samples (submit-to-batch-formed
    seconds) the batcher feeds at every batch formation / chunk init.
    Output: a rung in [0, 3] recomputed from the p95 of the samples
    seen in the last `sample_ttl_s` seconds:

        rung 0  healthy            admit everything
        rung 1  p95 >= thresholds[0]  shed best_effort admissions
        rung 2  p95 >= thresholds[1]  shed batch admissions too
        rung 3  p95 >= thresholds[2]  also defer NEW chunked-march
                                      starts (in-flight marches keep
                                      draining; interactive still
                                      admitted at every rung)

    Escalation is immediate (overload hurts NOW); de-escalation is
    hysteresis-gated - one rung at a time, only after `cooldown_s`
    since the last change AND with p95 back under `hysteresis` x the
    current rung's threshold - so the ladder settles instead of
    flapping around a threshold.  Thread-safe; `update()` is cheap
    enough for the submit path (the p95 is recomputed at most every
    `min_interval_s`)."""

    RUNG_NAMES = ("healthy", "shed_best_effort", "shed_batch",
                  "defer_chunk_starts")

    def __init__(self, thresholds=(0.5, 2.0, 8.0), window: int = 256,
                 min_samples: int = 8, hysteresis: float = 0.5,
                 cooldown_s: float = 5.0, sample_ttl_s: float = 30.0,
                 min_interval_s: float = 0.1):
        if len(thresholds) != 3:
            raise ValueError(
                f"thresholds must be 3 ascending seconds, got "
                f"{thresholds!r}"
            )
        t = tuple(float(x) for x in thresholds)
        if not (0 < t[0] <= t[1] <= t[2]):
            raise ValueError(
                f"thresholds must be 3 ascending seconds, got "
                f"{thresholds!r}"
            )
        self.thresholds = t
        self.min_samples = min_samples
        self.hysteresis = hysteresis
        self.cooldown_s = cooldown_s
        self.sample_ttl_s = sample_ttl_s
        self.min_interval_s = min_interval_s
        self._samples: "deque[Tuple[float, float]]" = deque(
            maxlen=window
        )
        self._lock = threading.Lock()
        self._rung = 0
        self._last_change = 0.0
        self._last_update = 0.0
        self._p95 = 0.0

    def observe_wait(self, seconds: float) -> None:
        with self._lock:
            self._samples.append((time.monotonic(), float(seconds)))

    def _compute_p95(self, now: float) -> Optional[float]:
        live = [w for t, w in self._samples
                if now - t <= self.sample_ttl_s]
        if len(live) < self.min_samples:
            return None
        return percentile_nearest_rank(sorted(live), 0.95)

    def update(self) -> int:
        """Recompute (rate-limited) and return the current rung."""
        now = time.monotonic()
        with self._lock:
            if now - self._last_update < self.min_interval_s:
                return self._rung
            self._last_update = now
            p95 = self._compute_p95(now)
            self._p95 = p95 if p95 is not None else 0.0
            if p95 is None:
                # Not enough recent signal: decay toward healthy on
                # the same cooldown cadence as a measured recovery.
                desired = 0
            else:
                desired = 0
                for i, th in enumerate(self.thresholds):
                    if p95 >= th:
                        desired = i + 1
            if desired > self._rung:
                self._rung = desired
                self._last_change = now
            elif desired < self._rung:
                recovered = (
                    p95 is None
                    or p95 <= self.hysteresis
                    * self.thresholds[self._rung - 1]
                )
                if recovered and now - self._last_change \
                        >= self.cooldown_s:
                    self._rung -= 1  # one rung at a time
                    self._last_change = now
            return self._rung

    @property
    def rung(self) -> int:
        with self._lock:
            return self._rung

    def rung_name(self, rung: Optional[int] = None) -> str:
        return self.RUNG_NAMES[self.rung if rung is None else rung]

    def sheds(self, priority: str) -> bool:
        """Does the CURRENT rung shed this class?  Interactive is never
        shed by the ladder (quotas and the bounded queue still apply)."""
        r = self.rung
        if r >= 2:
            return priority in ("batch", "best_effort")
        if r >= 1:
            return priority == "best_effort"
        return False

    def defers_chunk_starts(self) -> bool:
        return self.rung >= 3

    def snapshot(self) -> dict:
        """The /healthz `brownout` block."""
        with self._lock:
            return {
                "rung": self._rung,
                "rung_name": self.RUNG_NAMES[self._rung],
                "queue_wait_p95_s": round(self._p95, 4),
                "thresholds_s": list(self.thresholds),
            }


class DynamicBatcher:
    """The request queue + single batching worker.

    `max_wait` bounds how long the FIRST request of a batch waits for
    company; `max_batch` (usually the engine's largest bucket) bounds the
    batch.  `submit()` is safe from any thread (futures are
    `concurrent.futures.Future`); `close()` joins the worker, then fails
    every still-unresolved future - both the worker's stash and anything
    left in (or racing into) the queue - with a RuntimeError.
    `close(drain=True)` is the graceful-shutdown path: new submits are
    refused, but everything already queued is FLUSHED through the engine
    (batched as usual, no max-wait idling) and every outstanding future
    resolves with its result instead of an error.

    The worker runs under a SUPERVISOR (`_worker_main`): a crash fails
    the in-flight batch's futures with a retriable `WorkerCrashError`
    (503 + Retry-After) and restarts the loop - a wedged scheduler must
    never strand blocked HTTP handlers.  Requests may carry an absolute
    `deadline` (submit kwarg); already-expired items are dropped with
    `DeadlineExceededError` (504) at batch formation instead of being
    marched.  Both are no-ops when unused.

    `length_bucket_steps` is the occupancy/latency knob for diverging
    stop_steps: per-lane masking marches every lane to the batch's
    longest stop, so a 10-step request batched with a 1000-step one
    burns ~990 masked-lane steps of FLOPs.  With the knob set, requests
    are additionally bucketed by stop-length quantum - the quantum
    rounded UP to a multiple of the request's k so bucket boundaries sit
    on the onion's k-block grid - and only same-length-bucket requests
    share a batch: tighter buckets waste fewer masked steps but split
    traffic across more batches (lower occupancy).  Starvation is
    bounded: stashed non-matching requests keep arrival order and the
    worker serves the OLDEST stashed request as the next batch's leader,
    so a request waits at most one batch per distinct key ahead of it.
    """

    def __init__(self, engine, metrics: Optional[ServeMetrics] = None,
                 max_batch: Optional[int] = None, max_wait: float = 0.025,
                 length_bucket_steps: Optional[int] = None,
                 max_queue: Optional[int] = None,
                 fault_plan: Optional[faults.ServeFaultPlan] = None,
                 chunk_threshold: Optional[int] = None,
                 chunk_steps: int = 32,
                 state_store=None,
                 brownout: Optional[BrownoutController] = None):
        self.engine = engine
        self.metrics = metrics if metrics is not None else ServeMetrics()
        # Chaos harness: worker-crash / slow-batch injections fire at
        # this layer.  Default to the engine's plan so one WAVETPU_FAULT
        # budget governs the whole stack (build_server passes the shared
        # plan explicitly; engine-less stubs get None).
        self.fault_plan = (
            fault_plan if fault_plan is not None
            else getattr(engine, "fault_plan", None)
        )
        self.max_batch = (
            engine.max_batch if max_batch is None
            else min(max_batch, engine.max_batch)
        )
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {self.max_batch}")
        if length_bucket_steps is not None and length_bucket_steps < 1:
            raise ValueError(
                f"length_bucket_steps must be >= 1, got "
                f"{length_bucket_steps}"
            )
        if max_queue is not None and max_queue < 0:
            raise ValueError(f"max_queue must be >= 0, got {max_queue}")
        if chunk_threshold is not None and chunk_threshold < 2:
            raise ValueError(
                f"chunk_threshold must be >= 2, got {chunk_threshold}"
            )
        if chunk_steps < 1:
            raise ValueError(f"chunk_steps must be >= 1, got {chunk_steps}")
        self.max_wait = max_wait
        self.length_bucket_steps = length_bucket_steps
        # Preemptible long solves: requests with timesteps >= threshold
        # (None = feature off) march through cached chunk programs
        # (serve/preempt.py), interleaved with ordinary batches, and
        # checkpoint to `state_store` (a SolveStateStore; None = no
        # cross-replica handoff, deadline 504s carry no token).
        self.chunk_threshold = chunk_threshold
        self.chunk_steps = chunk_steps
        self.state_store = state_store
        self._chunk_seq = 0
        # Bounded-queue backpressure: submit() raises QueueFullError
        # (HTTP 429) once this many requests are submitted-but-not-yet-
        # executing.  None = unbounded (the historical behavior).
        self.max_queue = max_queue
        self._depth = 0
        self._q: "queue.Queue[_Item]" = queue.Queue()
        # The stash: one deque PER PRIORITY CLASS, drained by weighted
        # deficit round-robin (_pick_locked).  With one backlogged
        # class the deficits stay zero and scheduling is the historical
        # arrival-order FIFO.
        self._pending = {c: deque() for c in PRIORITY_CLASSES}
        self._deficit = {c: 0.0 for c in PRIORITY_CLASSES}
        # Adaptive overload shedding (None = ladder off: submit never
        # sheds, chunk starts never defer).
        self.brownout = brownout
        # Guards _pending: the worker mutates it between batches and
        # close() sweeps it after the join timeout - which can expire
        # while a drain is still executing batches, so the sweep must
        # not race the worker's stash bookkeeping.
        self._plock = threading.Lock()
        self._closed = False
        self._drain = False
        # Singleflight coalescing (guarded by _plock): coalesce_key ->
        # the in-flight primary _Item.  Only populated when the HTTP
        # layer passes a key (result cache enabled + request eligible);
        # followers chain onto the primary's future and never enter the
        # queue.  Entries unregister via a done-callback on the primary
        # future - every resolution site (worker, close sweep, crash
        # cleanup) resolves futures OUTSIDE _plock, so the callback's
        # _plock acquire cannot deadlock.
        self._singleflight: Dict[str, _Item] = {}
        # The batch the worker currently holds OUTSIDE the queue/stash
        # (supervisor bookkeeping): if the worker crashes mid-batch,
        # these futures must be failed retriable, never stranded.
        self._inflight: List[_Item] = []
        self._worker = threading.Thread(
            target=self._worker_main, name="wavetpu-batcher", daemon=True
        )
        self._worker.start()

    def length_bucket(self, request: SolveRequest) -> int:
        """The request's stop-length bucket id (0 when the knob is off).

        The quantum is rounded up to a multiple of the request's k, so
        every bucket boundary sits on the k-block grid the onion's lane
        masking freezes on."""
        if self.length_bucket_steps is None:
            return 0
        q = self.length_bucket_steps
        k = request.k if request.path == "kfused" else 1
        q = ((q + k - 1) // k) * k
        return (request.lane.stop(request.problem) - 1) // q

    def _item_key(self, request: SolveRequest) -> Tuple:
        return request.bucket_key() + (self.length_bucket(request),)

    def chunk_eligible(self, request: SolveRequest) -> bool:
        """Whether this request CAN march chunked: the single-backend
        standard-scheme tiers the supervisor's chunk runners cover, at
        default phase, full stop, no per-lane field.  Compensated,
        sharded, shifted-phase, partial-stop, and variable-c requests
        run monolithic (documented contract, docs/robustness.md)."""
        from wavetpu.verify import oracle

        r = request
        return (
            self.chunk_threshold is not None
            and hasattr(self.engine, "chunk_runner")
            and r.mesh_shape is None
            and r.scheme == "standard"
            and r.path in ("roll", "pallas", "kfused")
            and r.lane.c2tau2_field is None
            and r.lane.phase == oracle.TWO_PI
            and r.lane.stop(r.problem) == r.problem.timesteps
            and (r.path != "kfused" or r.problem.N % max(1, r.k) == 0)
        )

    def _chunk_mode(self, request: SolveRequest) -> bool:
        """Route through the chunked march?  Long requests past the
        threshold, plus ANY resume (the token's march is already
        chunked).  A resume_token on a request that cannot march
        chunked - or on a replica without the feature - is a client
        error, rejected synchronously (422)."""
        eligible = self.chunk_eligible(request)
        if request.resume_token is not None:
            if not eligible:
                raise InvalidStateTokenError(
                    "resume_token requires a chunk-eligible request "
                    "(standard scheme, roll/pallas/kfused path, default "
                    "phase, full stop, no c2_field) on a replica with "
                    "--chunk-threshold set"
                )
            if self.state_store is None:
                raise InvalidStateTokenError(
                    "this replica has no --solve-state-dir; it cannot "
                    "resume a checkpointed solve"
                )
            return True
        return (
            eligible
            and request.problem.timesteps >= self.chunk_threshold
        )

    def _dec_depth(self, n: int) -> None:
        # Gauge set INSIDE _plock: a set outside could interleave with a
        # concurrent submit and leave a stale depth on an idle server.
        # (Lock order is always _plock -> registry lock, never reversed.)
        with self._plock:
            self._depth = max(0, self._depth - n)
            self.metrics.observe_queue_depth(self._depth)

    def submit(self, request: SolveRequest,
               request_id: Optional[str] = None,
               deadline: Optional[float] = None,
               trace_context: Optional[Tuple[str, str]] = None,
               coalesce_key: Optional[str] = None) -> Future:
        """`deadline` is an absolute `time.monotonic()` bound (None =
        unbounded, the historical behavior): the worker drops the item
        with `DeadlineExceededError` if it is still queued past it.
        `trace_context` is the serving span's (trace id, wire span id):
        chunk spans stamp the trace id and checkpoints carry it so
        resumed marches link back to the originating request.
        `coalesce_key` (the request's content-addressed result key)
        opts this submit into singleflight: if an identical solve is
        already in flight its answer fans out to this caller too (the
        returned future carries `wavetpu_coalesced = True`); otherwise
        this submit becomes the primary later identical submits ride."""
        request.priority = normalize_priority(
            getattr(request, "priority", None)
        )
        if coalesce_key is not None:
            with self._plock:
                primary = self._singleflight.get(coalesce_key)
                if primary is not None and not primary.future.done():
                    follower: Future = Future()
                    follower.wavetpu_coalesced = True

                    def _fanout(pf: Future, f: Future = follower) -> None:
                        if f.done():
                            return
                        exc = pf.exception()
                        if exc is not None:
                            f.set_exception(exc)
                        else:
                            f.set_result(pf.result())

                    primary.future.add_done_callback(_fanout)
                else:
                    primary = None
            if primary is not None:
                # Each coalesced rider is still individually counted
                # (and, at the router, individually quota-charged): the
                # fan-out saves the march, not the accounting.
                self.metrics.observe_coalesced()
                self.metrics.observe_request()
                self.metrics.observe_tenant(request.tenant)
                self.metrics.observe_class_request(request.priority)
                return follower
        # Brownout ladder: overload sheds lower classes AT ADMISSION
        # (before any queue accounting) with a measured Retry-After -
        # a fast retriable 503, never a slow timeout.
        if self.brownout is not None:
            self.brownout.update()
            self.metrics.observe_brownout_rung(self.brownout.rung)
            if self.brownout.sheds(request.priority):
                rung = self.brownout.rung_name()
                self.metrics.observe_shed(
                    rung, request.priority, request.tenant
                )
                raise ShedError(
                    f"overloaded: brownout ladder at rung "
                    f"'{rung}' is shedding {request.priority} "
                    f"requests; retry later",
                    retry_after_s=self.metrics.retry_after_s(
                        self._depth
                    ),
                    rung=rung,
                )
        chunked = self._chunk_mode(request)
        if chunked:
            # A unique key: chunked items never coalesce with (or get
            # taken as batchmates of) anything - the worker marches them
            # one chunk per pass, interleaved with ordinary batches.
            with self._plock:
                self._chunk_seq += 1
                key: Tuple = ("__chunk__", self._chunk_seq)
        else:
            key = self._item_key(request)
        item = _Item(
            request, Future(), key,
            request_id=request_id, enqueued=time.monotonic(),
            deadline=deadline, chunked=chunked,
            trace_context=trace_context,
        )
        # Closed-check + enqueue are ATOMIC against close() (which
        # flips _closed under this same lock): a submit that passes the
        # check has its item IN the queue before close()'s final sweep
        # runs, so the item is either drained or failed fast - a racing
        # submit can never strand a future in a dead queue
        # (tests/test_serve.py pins the drain-vs-submit race).
        with self._plock:
            if self._closed:
                raise RuntimeError("batcher is closed")
            if self.max_queue is not None and self._depth >= self.max_queue:
                self.metrics.observe_rejected()
                raise QueueFullError(
                    f"request queue full ({self._depth} waiting >= "
                    f"max_queue {self.max_queue}); retry later"
                )
            self._depth += 1
            self.metrics.observe_queue_depth(self._depth)
            self._q.put(item)
            if coalesce_key is not None and not chunked:
                self._singleflight[coalesce_key] = item
        if coalesce_key is not None and not chunked:
            # Attached OUTSIDE _plock; fires in whatever thread resolves
            # the primary (always lock-free at that point, see __init__).
            item.future.add_done_callback(
                lambda _f, k=coalesce_key, it=item:
                self._unregister_singleflight(k, it)
            )
        self.metrics.observe_request()
        self.metrics.observe_tenant(request.tenant)
        self.metrics.observe_class_request(request.priority)
        return item.future

    def _unregister_singleflight(self, key: str, item: _Item) -> None:
        with self._plock:
            if self._singleflight.get(key) is item:
                del self._singleflight[key]

    def close(self, timeout: float = 5.0, drain: bool = False) -> None:
        """Stop the worker.  `drain=True` flushes everything already
        queued through the engine first (graceful SIGTERM shutdown):
        outstanding futures resolve with RESULTS; only what the worker
        could not finish within `timeout` is failed."""
        with self._plock:
            # Under _plock so no submit can pass its closed-check and
            # enqueue after the final sweep below (see submit()).
            self._drain = drain
            self._closed = True
        self._q.put(None)  # wake the worker
        self._worker.join(timeout)
        if self._worker.is_alive():
            # The drain outlived the timeout (it does unbounded engine
            # work).  Tell the worker to stop after its in-flight batch
            # and give it a short grace to exit; the sweep below then
            # fails what it could not finish - under _plock, so a
            # worker that is STILL mid-batch cannot race the stash.
            self._drain = False
            self._worker.join(min(timeout, 5.0))
        # Fail EVERY unresolved future: the worker's stash plus anything
        # still in the queue (including a submit that raced past the
        # _closed check) - a blocked HTTP handler must get its 500, not
        # sit out the full request timeout.  After a completed drain
        # there is nothing left here and this is a no-op.
        with self._plock:
            leftovers = [
                i for c in PRIORITY_CLASSES for i in self._pending[c]
            ]
            for c in PRIORITY_CLASSES:
                self._pending[c].clear()
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is not None:
                leftovers.append(item)
        self._dec_depth(len(leftovers))
        for item in leftovers:
            if not item.future.done():
                item.future.set_exception(
                    RuntimeError("server shutting down")
                )
        if self._worker.is_alive():
            # The sweep above may have eaten the wake sentinel; re-post
            # it so a worker still finishing its batch can observe
            # _closed and exit instead of blocking on an empty queue.
            self._q.put(None)

    # ---- worker ----

    def _worker_main(self) -> None:
        """The worker's SUPERVISOR: `_loop` returning means a clean
        shutdown; `_loop` raising means the worker crashed mid-batch (a
        scheduler bug, an injected `serve-worker-crash`, anything the
        per-batch engine try does not cover).  The supervisor fails the
        crashed batch's futures with a retriable `WorkerCrashError`
        (HTTP 503 + Retry-After - a blocked handler must never sit out
        its timeout) and re-enters the loop, so everything still queued
        or stashed keeps getting served.  A short sleep between
        restarts keeps a crash-looping bug from spinning hot."""
        while True:
            try:
                self._loop()
                return
            except Exception as e:
                self._crash_cleanup(e)
                if self._closed and not self._drain:
                    return
                time.sleep(0.05)

    def _crash_cleanup(self, exc: BaseException) -> None:
        items, self._inflight = self._inflight, []
        requeue: List[_Item] = []
        for item in items:
            if item.future.done():
                continue
            if (
                item.chunk is not None
                and not (self._closed and not self._drain)
            ):
                # A chunked long solve keeps its in-memory march state
                # on the item: re-enqueue at the FRONT and resume from
                # the last completed chunk after the worker restart -
                # the client sees nothing (zero-visible-errors half of
                # the serve-chunk-crash drill).
                requeue.append(item)
            else:
                item.future.set_exception(WorkerCrashError(
                    f"scheduler worker crashed mid-batch ({exc!r}); "
                    f"worker restarted - retry the request"
                ))
        if requeue:
            with self._plock:
                for item in reversed(requeue):
                    # Front of the item's CLASS queue: the march
                    # resumes at its own class's next turn, not ahead
                    # of higher classes.
                    self._pending[self._class_of(item)].appendleft(item)
            for _ in requeue:
                self.metrics.observe_resume("crash")
        self.metrics.observe_worker_restart()

    @staticmethod
    def _class_of(item: _Item) -> str:
        return normalize_priority(
            getattr(item.request, "priority", None)
        )

    def _pending_empty(self) -> bool:
        with self._plock:
            return not any(
                self._pending[c] for c in PRIORITY_CLASSES
            )

    def _stash_locked(self, item: _Item) -> None:
        self._pending[self._class_of(item)].append(item)

    def _pick_locked(self) -> Optional[_Item]:
        """One weighted-deficit-round-robin pick (caller holds _plock).

        Each pick credits every BACKLOGGED class its weight, serves the
        class with the largest deficit (ties break to the higher static
        class), then debits the winner the round's total credit.  Net
        effect: service converges to the 16:4:1 weight ratio under
        backlog, a newly-arrived interactive request beats a lower
        class's next turn (its 16-credit first round outbids any
        deficit a lower class can have accrued before its own turn
        comes), and best_effort is served at least once every
        ~sum(weights) picks - the starvation bound.  A class's deficit
        resets when its queue empties (classic DRR: credit never
        banks while idle), so a SINGLE backlogged class runs at
        deficit zero - exactly the historical FIFO, no QoS overhead."""
        nonempty = [c for c in PRIORITY_CLASSES if self._pending[c]]
        if not nonempty:
            return None
        if len(nonempty) == 1:
            c = nonempty[0]
            for k in PRIORITY_CLASSES:
                self._deficit[k] = 0.0
            return self._pending[c].popleft()
        total = 0.0
        for c in nonempty:
            self._deficit[c] += CLASS_WEIGHTS[c]
            total += CLASS_WEIGHTS[c]
        best = max(
            nonempty,
            key=lambda c: (self._deficit[c],
                           -PRIORITY_CLASSES.index(c)),
        )
        self._deficit[best] -= total
        item = self._pending[best].popleft()
        if not self._pending[best]:
            self._deficit[best] = 0.0
        return item

    def _take_pending(self, key, limit: int) -> List[_Item]:
        """Same-key batchmates from EVERY class queue (a matching
        request rides along whatever its class - it is being served
        now, which can only help it)."""
        taken: List[_Item] = []
        with self._plock:
            for c in PRIORITY_CLASSES:
                keep = deque()
                while self._pending[c]:
                    item = self._pending[c].popleft()
                    if item.key == key and len(taken) < limit:
                        taken.append(item)
                    else:
                        keep.append(item)
                self._pending[c].extend(keep)
        return taken

    def _drain_queue(self) -> None:
        """Move everything still in the queue onto the per-class stash
        (arrival order preserved within a class) - the worker's intake
        and the drain path's."""
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                return
            if item is not None:
                with self._plock:
                    self._stash_locked(item)

    def _loop(self) -> None:
        while True:
            if self._closed:
                if not self._drain:
                    return
                self._drain_queue()
                if self._pending_empty():
                    return
            # Intake first so the pick sees EVERY arrival: this is the
            # strict rule - an interactive request that arrived while a
            # lower-class chunk marched is in its class queue before
            # the next pick, and the pick serves it ahead of the
            # march's next chunk slot.
            self._drain_queue()
            with self._plock:
                first = self._pick_locked()
            if first is None:
                item = self._q.get()
                if item is None:
                    continue  # sentinel: loop back to the closed check
                # Serve the dequeued item THIS pass (through the pick,
                # so deficits stay consistent): re-running the closed
                # check here could strand an item a racing close()
                # already popped from the queue's accounting.
                with self._plock:
                    self._stash_locked(item)
                    first = self._pick_locked()
            if first.chunked:
                # Brownout top rung: defer STARTING new marches (keep
                # the item queued at the back of its class) while
                # in-flight marches keep draining.  Never during a
                # drain - flushing queued work is the whole point then.
                if (
                    first.chunk is None
                    and self.brownout is not None
                    and not (self._closed and self._drain)
                    and self.brownout.update() >= 3
                ):
                    self.metrics.observe_chunk_start_deferred()
                    with self._plock:
                        self._stash_locked(first)
                    # Block briefly on the queue so a stash holding
                    # only deferred starts does not spin the worker
                    # hot; fresh arrivals wake it immediately.
                    try:
                        nxt = self._q.get(timeout=0.05)
                    except queue.Empty:
                        continue
                    if nxt is not None:
                        with self._plock:
                            self._stash_locked(nxt)
                    continue
                # One chunk per pass: the march yields the worker back
                # between chunks so short/high-priority traffic
                # interleaves instead of queueing behind a monolithic
                # long solve.
                self.metrics.observe_scheduled(self._class_of(first))
                self._inflight = [first]
                finished = self._chunk_round(first)
                self._inflight = []
                if not finished:
                    # Fresh arrivals (still in the queue) go ahead of
                    # the long solve's next chunk; the item itself goes
                    # to the back of its class's stash.
                    self._drain_queue()
                    with self._plock:
                        self._stash_locked(first)
                continue
            batch = [first]
            batch += self._take_pending(
                first.key, self.max_batch - len(batch)
            )
            # While draining, skip the max-wait idle: flush immediately.
            deadline = time.monotonic() + (
                0.0 if self._closed else self.max_wait
            )
            while len(batch) < self.max_batch:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is None:
                    # Sentinel mid-collection: execute what we have; the
                    # outer loop then drains (or returns, leaving
                    # close() to fail the stash).
                    break
                if nxt.key == first.key:
                    batch.append(nxt)
                else:
                    with self._plock:
                        self._stash_locked(nxt)
            # Supervisor bookkeeping: these items live only in this
            # local list now; if _execute crashes past its engine try,
            # _worker_main fails them retriable instead of stranding.
            for item in batch:
                self.metrics.observe_scheduled(self._class_of(item))
            self._inflight = batch
            self._execute(batch)
            self._inflight = []

    def _execute(self, batch: List[_Item]) -> None:
        req0 = batch[0].request
        # Batch formed: the members' queue wait ends here; they leave
        # the bounded queue's accounting as they enter the engine.
        t_formed = time.monotonic()
        waits = [max(0.0, t_formed - item.enqueued) for item in batch]
        if self.brownout is not None:
            # The ladder's input signal: queue wait at batch formation.
            for w in waits:
                self.brownout.observe_wait(w)
        self._dec_depth(len(batch))
        # Deadline shedding: an item whose budget already expired in
        # queue is dropped HERE (504 with queue attribution), before any
        # compile or device work - marching a lane nobody is waiting for
        # wastes the whole batch's FLOP budget.  No-deadline items (the
        # historical path) are untouched.
        live: List[_Item] = []
        live_waits: List[float] = []
        for item, wait in zip(batch, waits):
            if item.deadline is not None and t_formed >= item.deadline:
                self.metrics.observe_deadline_expired()
                if not item.future.done():
                    item.future.set_exception(DeadlineExceededError(
                        f"deadline expired after {wait * 1e3:.0f} ms in "
                        f"queue (dropped before execution)",
                        queue_s=wait,
                    ))
            else:
                live.append(item)
                live_waits.append(wait)
        if not live:
            return
        batch, waits = live, live_waits
        # Chaos seams: a worker crash escapes to the supervisor (the
        # engine try below must NOT absorb it - it models the thread
        # dying, not the solve failing); a slow batch stalls the worker
        # exactly where a pathological compile or device hang would.
        plan = self.fault_plan
        if plan is not None and plan.active:
            ctx = dict(
                n=req0.problem.N, timesteps=req0.problem.timesteps,
                scheme=req0.scheme, path=req0.path, k=req0.k,
                dtype=req0.dtype_name,
            )
            if plan.fire("worker-crash", **ctx):
                raise faults.InjectedFault(
                    "injected scheduler worker crash"
                )
            slow = plan.fire("slow-batch", **ctx)
            if slow is not None:
                time.sleep(slow.seconds)
        span = tracing.begin_span(
            "serve.batch",
            request_ids=[i.request_id for i in batch if i.request_id],
            occupancy=len(batch), scheme=req0.scheme, path=req0.path,
            k=req0.k, n=req0.problem.N,
            queue_wait_max_ms=round(max(waits) * 1e3, 3),
            tenant=req0.tenant,
        )
        timing: dict = {}
        # A batch of ONLY shadow-solve lanes (serve/shadow.py) must
        # never feed the circuit breaker; one production lane in the
        # batch restores the normal contract.  The kwarg is passed only
        # in the shadow-only case so engine stand-ins with the plain
        # production signature keep working.
        solve_kw: dict = {}
        if all(item.request.shadow for item in batch):
            solve_kw["feed_breaker"] = False
        # Tenant attribution is thread-local (the worker thread, not the
        # handler thread, runs compiles): any ledger line the engine
        # records during this solve carries the batch leader's tenant.
        compile_ledger.set_request_context(tenant=req0.tenant)
        try:
            result, lane_health = self.engine.solve(
                req0.problem,
                [item.request.lane for item in batch],
                scheme=req0.scheme, path=req0.path, k=req0.k,
                dtype_name=req0.dtype_name, mesh=req0.mesh_shape,
                timing=timing, **solve_kw,
            )
        except Exception as e:
            tracing.end_span(span, error=str(e))
            for item in batch:
                if not item.future.done():
                    item.future.set_exception(e)
            return
        finally:
            compile_ledger.clear_request_context()
        t_done = time.monotonic()
        tracing.end_span(
            span, batch_size=result.batch_size, batched=result.batched,
            padding_lanes=result.batch_size - result.n_lanes,
            solve_seconds=round(result.solve_seconds, 6),
        )
        cells = sum(
            req0.problem.cells_per_step * (r.steps_computed or 0)
            for r in result.results
        )
        self.metrics.observe_batch(
            occupancy=result.n_lanes, batched=result.batched,
            cells=cells, solve_seconds=result.solve_seconds,
            batch_size=result.batch_size, queue_waits=waits,
            request_ids=[item.request_id for item in batch],
        )
        padding_lanes = result.batch_size - result.n_lanes
        batch_info = {
            "occupancy": result.n_lanes,
            "batch_size": result.batch_size,
            "batched": result.batched,
            "fallback_reason": result.fallback_reason,
            "path": result.path,
            "padding_lanes": padding_lanes,
            "aggregate_gcells_per_s": round(
                result.aggregate_gcells_per_second, 4
            ),
            "warm": timing.get("warm"),
        }
        # Per-request latency attribution (the Server-Timing header's
        # source): queue = this request's submit-to-batch-formed wait,
        # compile = the batch's cache-miss compile (0 warm), execute =
        # everything after batch formation minus that compile (device
        # march + watchdog + result plumbing), padding = the share of
        # the batch's solve spent marching masked padding lanes -
        # informational waste attribution, a subset of execute, NOT an
        # additive wall-clock component.
        compile_s = float(timing.get("compile_seconds", 0.0))
        execute_s = max(0.0, t_done - t_formed - compile_s)
        padding_s = (
            result.solve_seconds * padding_lanes / result.batch_size
            if result.batch_size else 0.0
        )
        for i, item in enumerate(batch):
            # done() guard: a close() that timed out may have failed
            # this future already; a second set_ would raise
            # InvalidStateError inside the worker.
            if not item.future.done():
                info = dict(batch_info)
                info["timing"] = {
                    "queue_s": waits[i],
                    "compile_s": compile_s,
                    "execute_s": execute_s,
                    "padding_s": padding_s,
                }
                item.future.set_result(
                    (result.results[i], lane_health[i], info)
                )

    # ---- chunked long solves (serve/preempt.py) ----

    def _checkpoint(self, item: _Item) -> Optional[str]:
        """Persist the item's march state -> resume token, or None when
        there is nothing to save or no --solve-state-dir.  Guarded: a
        full disk downgrades the preemption to a token-less abort, it
        never turns into a 500."""
        cp = item.chunk
        if cp is None or cp.state is None or self.state_store is None:
            return None
        try:
            return self.state_store.put(
                cp.runner.identity,
                cp.runner.state_to_numpy(cp.state),
                cp.step, cp.abs, cp.rel,
                origin_trace=cp.origin_trace,
                priority=item.request.priority,
            )
        except Exception:
            return None

    def _chunk_init(self, item: _Item) -> bool:
        """First round: queue accounting, chunk-program acquisition,
        then bootstrap (fresh) or token load (resume).  Returns True
        when the item is RESOLVED (queue-expired deadline, bad token,
        or acquisition failure); False to keep marching."""
        req = item.request
        now = time.monotonic()
        wait = max(0.0, now - item.enqueued)
        if self.brownout is not None:
            self.brownout.observe_wait(wait)
        self._dec_depth(1)
        if item.deadline is not None and now >= item.deadline:
            self.metrics.observe_deadline_expired()
            if not item.future.done():
                item.future.set_exception(DeadlineExceededError(
                    f"deadline expired after {wait * 1e3:.0f} ms in "
                    f"queue (dropped before execution)",
                    queue_s=wait,
                ))
            return True
        plan = self.fault_plan
        compile_ledger.set_request_context(tenant=req.tenant)
        try:
            runner, source, acquire_s = self.engine.chunk_runner(
                req.problem, req.scheme, req.path, req.k,
                req.dtype_name, self.chunk_steps,
            )
            warm_label = (
                "true" if source == "memory"
                else "disk" if source == "disk" else "false"
            )
            cp = _ChunkProgress(
                runner, warm=warm_label, compile_s=acquire_s,
                wait_s=wait,
            )
            if req.resume_token is not None:
                # Chaos seam: serve-handoff-corrupt truncates the
                # checkpoint file between the client presenting the
                # token and the replica loading it - the load below
                # must reject it 422-clean, never traceback (and the
                # breaker never hears it).
                if plan is not None and plan.fire(
                    "handoff-corrupt", n=req.problem.N,
                    timesteps=req.problem.timesteps, scheme=req.scheme,
                    path=req.path, k=req.k, dtype=req.dtype_name,
                ):
                    target = self.state_store.path_for(
                        req.resume_token
                    )
                    import os as _os

                    if _os.path.exists(target):
                        faults.truncate_tail(target)
                meta, step, state_np, abs_p, rel_p = (
                    self.state_store.load(
                        req.resume_token, cp.runner.identity
                    )
                )
                cp.state = cp.runner.prepare(state_np)
                cp.step = step
                cp.abs[: step + 1] = abs_p
                cp.rel[: step + 1] = rel_p
                cp.resumed_from = step
                # Prefer the checkpoint's origin: even when the resume
                # arrives under a fresh client trace, the chunk spans
                # link back to the march's FIRST request.
                origin = meta.get("origin_trace")
                if (isinstance(origin, (list, tuple)) and len(origin) == 2
                        and all(isinstance(x, str) for x in origin)):
                    cp.origin_trace = list(origin)
                elif item.trace_context is not None:
                    cp.origin_trace = list(item.trace_context)
                # The march keeps the class it was ADMITTED at: the
                # checkpoint's priority (clamped by the router when the
                # march began) wins over whatever label the resume
                # request carries - a preempted best_effort solve
                # cannot relabel itself interactive via its token.
                if "priority" in meta:
                    req.priority = normalize_priority(
                        meta.get("priority"), default=req.priority
                    )
                self.metrics.observe_resume("token")
            else:
                state, abs2, rel2, boot_c, boot_s = cp.runner.bootstrap()
                cp.state = state
                cp.step = 1
                cp.abs[:2] = abs2
                cp.rel[:2] = rel2
                cp.compile_s += boot_c
                cp.execute_s += boot_s
                if item.trace_context is not None:
                    cp.origin_trace = list(item.trace_context)
            item.chunk = cp
            self.metrics.observe_chunk_march_started()
            # The future resolves EXACTLY once regardless of how the
            # march ends (completion, drain/deadline preemption with a
            # token, watchdog trip, close-sweep failure, crash fail) -
            # the one safe place to decrement the in-flight gauge.
            item.future.add_done_callback(
                lambda _f: self.metrics.observe_chunk_march_ended()
            )
            return False
        except Exception as e:
            if not item.future.done():
                item.future.set_exception(e)
            return True
        finally:
            compile_ledger.clear_request_context()

    def _chunk_round(self, item: _Item) -> bool:
        """March ONE chunk (or initialize on the first round); returns
        True when the item's future is resolved.  Between rounds the
        worker serves other traffic - the interleaving that keeps short
        requests from queueing behind a monolithic long march.

        Preemption points, checked before each chunk:
          * drain (close(drain=True), the `fleet roll` path):
            checkpoint -> retriable 503 + resume_token;
          * deadline expiry: checkpoint -> 504 + resume_token;
          * per-chunk watchdog AFTER each chunk: a poisoned march 422s
            at the first chunk boundary past the blowup, with the
            last-good step attributed - not after marching the
            remaining thousands of layers.
        A worker crash leaves the march state on the item;
        `_crash_cleanup` re-enqueues it and the next round continues
        from the last completed chunk.  None of these feed the circuit
        breaker."""
        if item.future.done():
            # close() raced and failed it (drain timeout sweep).
            return True
        if item.chunk is None:
            return self._chunk_init(item)
        req = item.request
        cp = item.chunk
        timesteps = req.problem.timesteps
        if self._closed and self._drain:
            token = self._checkpoint(item)
            if token is not None:
                self.metrics.observe_preempted("drain")
                item.future.set_exception(PreemptedError(
                    f"replica draining: long solve checkpointed at "
                    f"step {cp.step}/{timesteps}; resume with the "
                    f"token on any replica sharing --solve-state-dir",
                    resume_token=token,
                ))
                return True
            # No state store: nothing to hand off - finish the march
            # inside the drain like any other queued work.
        if item.deadline is not None and time.monotonic() >= item.deadline:
            token = self._checkpoint(item)
            self.metrics.observe_deadline_expired()
            self.metrics.observe_preempted("deadline")
            item.future.set_exception(DeadlineExceededError(
                f"deadline expired mid-solve at step "
                f"{cp.step}/{timesteps}"
                + ("" if token is None
                   else "; resume with the returned token"),
                resume_token=token,
            ))
            return True
        plan = self.fault_plan
        if plan is not None and plan.active:
            ctx = dict(
                n=req.problem.N, timesteps=timesteps,
                scheme=req.scheme, path=req.path, k=req.k,
                dtype=req.dtype_name,
            )
            if plan.fire("chunk-crash", **ctx):
                # Models the worker thread dying mid-chunk: escapes to
                # the supervisor, which re-enqueues this item with its
                # state intact (see _crash_cleanup) - the client never
                # sees it.
                raise faults.InjectedFault(
                    f"injected worker crash mid-chunk (step {cp.step})"
                )
            # slow-batch applies per CHUNK here (the drills' lever for
            # deterministic mid-march deadline expiry / straddling a
            # roll cutover).
            slow = plan.fire("slow-batch", **ctx)
            if slow is not None:
                time.sleep(slow.seconds)
        length = cp.runner.next_length(cp.step)
        compile_ledger.set_request_context(tenant=req.tenant)
        # Chunk spans run on the scheduler thread, outside the serving
        # request's span stack: stamp the trace id explicitly, and when
        # this march was resumed from another request's checkpoint link
        # back to the originating trace so the joiner can stitch a
        # preempted-and-resumed solve into ONE tree.
        tc = item.trace_context
        origin = cp.origin_trace
        span_trace = tc[0] if tc else (origin[0] if origin else None)
        links = None
        if origin is not None and origin[0] != span_trace:
            links = [{"trace_id": origin[0], "span_id": origin[1]}]
        try:
            with tracing.span(
                "serve.chunk", request_id=item.request_id,
                tenant=req.tenant, path=req.path, start=cp.step,
                length=length, n=req.problem.N,
                trace_id=span_trace, links=links,
            ):
                state, abs_c, rel_c, solve_s, compile_s = (
                    cp.runner.chunk(cp.state, cp.step, length)
                )
        except Exception as e:
            if not item.future.done():
                item.future.set_exception(e)
            return True
        finally:
            compile_ledger.clear_request_context()
        cp.state = state
        cp.abs[cp.step + 1: cp.step + length + 1] = abs_c
        cp.rel[cp.step + 1: cp.step + length + 1] = rel_c
        cp.step += length
        cp.chunks_done += 1
        cp.execute_s += solve_s
        cp.compile_s += compile_s
        self.metrics.observe_chunk()
        if self.engine.watchdog:
            amax = health.state_amax(
                cp.runner.health_arrays(cp.state)
            )
            if not health.healthy(amax, self.engine.max_amp):
                bound = (
                    health.DEFAULT_AMP_BOUND
                    if self.engine.max_amp is None
                    else self.engine.max_amp
                )
                err = (
                    f"numerical-health trip: guarded amax {amax:g} "
                    f"exceeds bound {bound:g} (NaN/Inf count as inf) "
                    f"at step {cp.step} (chunk {cp.chunks_done}); "
                    f"last good step {cp.step - length}"
                )
                item.future.set_result(
                    (None, err, self._chunk_info(item))
                )
                return True
        if cp.step < timesteps:
            return False
        # Complete: the full-march result, bitwise-identical to the
        # unpreempted monolithic solve (bootstrap-to-1 + block-grid
        # chunks replay the same op sequence - the supervisor's
        # invariant).
        marched = timesteps - (cp.resumed_from or 0)
        result = cp.runner.to_result(
            cp.state, cp.abs, cp.rel, timesteps,
            init_s=cp.compile_s, solve_s=cp.execute_s, marched=marched,
        )
        cells = req.problem.cells_per_step * marched
        self.metrics.observe_batch(
            occupancy=1, batched=True, cells=cells,
            solve_seconds=cp.execute_s, batch_size=1,
            queue_waits=[cp.wait_s],
            request_ids=[item.request_id],
        )
        if not item.future.done():
            item.future.set_result(
                (result, None, self._chunk_info(item))
            )
        return True

    def _chunk_info(self, item: _Item) -> dict:
        cp = item.chunk
        agg = (
            item.request.problem.cells_per_step
            * (cp.step - (cp.resumed_from or 0))
            / cp.execute_s / 1e9
            if cp.execute_s else 0.0
        )
        return {
            "occupancy": 1,
            "batch_size": 1,
            "batched": True,
            "fallback_reason": None,
            "path": item.request.path,
            "padding_lanes": 0,
            "aggregate_gcells_per_s": round(agg, 4),
            "warm": cp.warm,
            "chunked": True,
            "chunks": cp.chunks_done,
            "chunk_len": cp.runner.chunk_len,
            "resumed_from": cp.resumed_from,
            "timing": {
                "queue_s": cp.wait_s,
                "compile_s": cp.compile_s,
                "execute_s": cp.execute_s,
                "padding_s": 0.0,
            },
        }
