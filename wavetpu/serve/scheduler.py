"""Dynamic batching: coalesce concurrent solve requests into ensembles.

The request path is the standard inference-serving shape (arXiv:2108.11076
batches simulations the same way an LLM server batches prompts):

 * `submit()` enqueues a `SolveRequest` and returns a future immediately
   (the HTTP handler thread blocks on it; the server stays concurrent).
 * One worker thread drains the queue.  Requests are SHAPE-BUCKETED by
   `SolveRequest.bucket_key()` - everything the compiled program identity
   depends on (problem geometry, scheme, kernel path, k, dtype, field
   presence) - because only same-key requests can share a program.
 * A batch closes when it reaches `max_batch` lanes or `max_wait` seconds
   after its first request - the classic max-batch/max-wait tradeoff
   (batch occupancy vs tail latency).  Non-matching requests seen while
   collecting are stashed and served next round in arrival order.
 * The engine pads the batch to its bucket with masked lanes, runs the
   cached program, watchdogs each lane; every future resolves with ITS
   lane's result (or per-lane health error) plus batch context.

`ServeMetrics` is the shared counter block /metrics renders: request and
batch counts, occupancy, latency percentiles over a sliding reservoir,
and aggregate Gcell/s across all served lanes.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import List, Optional, Tuple

from wavetpu.core.problem import Problem
from wavetpu.ensemble.batched import LaneSpec


@dataclasses.dataclass
class SolveRequest:
    """One lane's worth of work plus its program identity.

    `mesh_shape` routes the request through the sharded x batched
    composition (ensemble/sharded.py); only same-mesh requests share a
    program."""

    problem: Problem
    lane: LaneSpec
    scheme: str = "standard"
    path: str = "roll"
    k: int = 1
    dtype_name: str = "f32"
    mesh_shape: Optional[Tuple[int, int, int]] = None

    def bucket_key(self) -> Tuple:
        """Everything the compiled program identity depends on; only
        same-key requests may share a batch."""
        p = self.problem
        return (
            p.N, p.Lx, p.Ly, p.Lz, p.T, p.timesteps,
            self.scheme, self.path,
            self.k if self.path == "kfused" else 1,
            self.dtype_name,
            self.lane.c2tau2_field is not None,
            None if self.mesh_shape is None else tuple(self.mesh_shape),
        )


class ServeMetrics:
    """Thread-safe counters for /metrics (shared by scheduler + api)."""

    def __init__(self, latency_window: int = 1024):
        self._lock = threading.Lock()
        self.started = time.time()
        self.requests_total = 0
        self.responses_ok = 0
        self.responses_error = 0
        self.batches_total = 0
        self.occupancy_sum = 0
        self.occupancy_max = 0
        self.fallback_batches = 0
        self.cells_total = 0.0
        self.solve_seconds_total = 0.0
        self._latencies = deque(maxlen=latency_window)

    def observe_request(self) -> None:
        with self._lock:
            self.requests_total += 1

    def observe_response(self, ok: bool) -> None:
        with self._lock:
            if ok:
                self.responses_ok += 1
            else:
                self.responses_error += 1

    def observe_batch(self, occupancy: int, batched: bool,
                      cells: float, solve_seconds: float) -> None:
        with self._lock:
            self.batches_total += 1
            self.occupancy_sum += occupancy
            self.occupancy_max = max(self.occupancy_max, occupancy)
            if not batched:
                self.fallback_batches += 1
            self.cells_total += cells
            self.solve_seconds_total += solve_seconds

    def observe_latency(self, seconds: float) -> None:
        with self._lock:
            self._latencies.append(seconds)

    def _percentile(self, p: float) -> Optional[float]:
        if not self._latencies:
            return None
        xs = sorted(self._latencies)
        idx = min(len(xs) - 1, int(round(p * (len(xs) - 1))))
        return xs[idx]

    def snapshot(self) -> dict:
        with self._lock:
            mean_occ = (
                self.occupancy_sum / self.batches_total
                if self.batches_total else None
            )
            p50 = self._percentile(0.50)
            p95 = self._percentile(0.95)
            agg = (
                self.cells_total / self.solve_seconds_total / 1e9
                if self.solve_seconds_total else None
            )
            return {
                "uptime_seconds": round(time.time() - self.started, 3),
                "requests_total": self.requests_total,
                "responses_ok": self.responses_ok,
                "responses_error": self.responses_error,
                "batches_total": self.batches_total,
                "batch_occupancy_mean": mean_occ,
                "batch_occupancy_max": self.occupancy_max,
                "fallback_batches": self.fallback_batches,
                "latency_p50_ms": None if p50 is None else round(
                    p50 * 1e3, 3
                ),
                "latency_p95_ms": None if p95 is None else round(
                    p95 * 1e3, 3
                ),
                "aggregate_gcells_per_s": (
                    None if agg is None else round(agg, 4)
                ),
            }


@dataclasses.dataclass
class _Item:
    request: SolveRequest
    future: Future
    key: Tuple


class DynamicBatcher:
    """The request queue + single batching worker.

    `max_wait` bounds how long the FIRST request of a batch waits for
    company; `max_batch` (usually the engine's largest bucket) bounds the
    batch.  `submit()` is safe from any thread (futures are
    `concurrent.futures.Future`); `close()` joins the worker, then fails
    every still-unresolved future - both the worker's stash and anything
    left in (or racing into) the queue - with a RuntimeError.
    `close(drain=True)` is the graceful-shutdown path: new submits are
    refused, but everything already queued is FLUSHED through the engine
    (batched as usual, no max-wait idling) and every outstanding future
    resolves with its result instead of an error.

    `length_bucket_steps` is the occupancy/latency knob for diverging
    stop_steps: per-lane masking marches every lane to the batch's
    longest stop, so a 10-step request batched with a 1000-step one
    burns ~990 masked-lane steps of FLOPs.  With the knob set, requests
    are additionally bucketed by stop-length quantum - the quantum
    rounded UP to a multiple of the request's k so bucket boundaries sit
    on the onion's k-block grid - and only same-length-bucket requests
    share a batch: tighter buckets waste fewer masked steps but split
    traffic across more batches (lower occupancy).  Starvation is
    bounded: stashed non-matching requests keep arrival order and the
    worker serves the OLDEST stashed request as the next batch's leader,
    so a request waits at most one batch per distinct key ahead of it.
    """

    def __init__(self, engine, metrics: Optional[ServeMetrics] = None,
                 max_batch: Optional[int] = None, max_wait: float = 0.025,
                 length_bucket_steps: Optional[int] = None):
        self.engine = engine
        self.metrics = metrics if metrics is not None else ServeMetrics()
        self.max_batch = (
            engine.max_batch if max_batch is None
            else min(max_batch, engine.max_batch)
        )
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {self.max_batch}")
        if length_bucket_steps is not None and length_bucket_steps < 1:
            raise ValueError(
                f"length_bucket_steps must be >= 1, got "
                f"{length_bucket_steps}"
            )
        self.max_wait = max_wait
        self.length_bucket_steps = length_bucket_steps
        self._q: "queue.Queue[_Item]" = queue.Queue()
        self._pending: "deque[_Item]" = deque()
        # Guards _pending: the worker mutates it between batches and
        # close() sweeps it after the join timeout - which can expire
        # while a drain is still executing batches, so the sweep must
        # not race the worker's stash bookkeeping.
        self._plock = threading.Lock()
        self._closed = False
        self._drain = False
        self._worker = threading.Thread(
            target=self._loop, name="wavetpu-batcher", daemon=True
        )
        self._worker.start()

    def length_bucket(self, request: SolveRequest) -> int:
        """The request's stop-length bucket id (0 when the knob is off).

        The quantum is rounded up to a multiple of the request's k, so
        every bucket boundary sits on the k-block grid the onion's lane
        masking freezes on."""
        if self.length_bucket_steps is None:
            return 0
        q = self.length_bucket_steps
        k = request.k if request.path == "kfused" else 1
        q = ((q + k - 1) // k) * k
        return (request.lane.stop(request.problem) - 1) // q

    def _item_key(self, request: SolveRequest) -> Tuple:
        return request.bucket_key() + (self.length_bucket(request),)

    def submit(self, request: SolveRequest) -> Future:
        if self._closed:
            raise RuntimeError("batcher is closed")
        item = _Item(request, Future(), self._item_key(request))
        self.metrics.observe_request()
        self._q.put(item)
        return item.future

    def close(self, timeout: float = 5.0, drain: bool = False) -> None:
        """Stop the worker.  `drain=True` flushes everything already
        queued through the engine first (graceful SIGTERM shutdown):
        outstanding futures resolve with RESULTS; only what the worker
        could not finish within `timeout` is failed."""
        self._drain = drain
        self._closed = True
        self._q.put(None)  # wake the worker
        self._worker.join(timeout)
        if self._worker.is_alive():
            # The drain outlived the timeout (it does unbounded engine
            # work).  Tell the worker to stop after its in-flight batch
            # and give it a short grace to exit; the sweep below then
            # fails what it could not finish - under _plock, so a
            # worker that is STILL mid-batch cannot race the stash.
            self._drain = False
            self._worker.join(min(timeout, 5.0))
        # Fail EVERY unresolved future: the worker's stash plus anything
        # still in the queue (including a submit that raced past the
        # _closed check) - a blocked HTTP handler must get its 500, not
        # sit out the full request timeout.  After a completed drain
        # there is nothing left here and this is a no-op.
        with self._plock:
            leftovers = list(self._pending)
            self._pending.clear()
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is not None:
                leftovers.append(item)
        for item in leftovers:
            if not item.future.done():
                item.future.set_exception(
                    RuntimeError("server shutting down")
                )
        if self._worker.is_alive():
            # The sweep above may have eaten the wake sentinel; re-post
            # it so a worker still finishing its batch can observe
            # _closed and exit instead of blocking on an empty queue.
            self._q.put(None)

    # ---- worker ----

    def _take_pending(self, key, limit: int) -> List[_Item]:
        taken, keep = [], deque()
        with self._plock:
            while self._pending:
                item = self._pending.popleft()
                if item.key == key and len(taken) < limit:
                    taken.append(item)
                else:
                    keep.append(item)
            self._pending.extend(keep)
        return taken

    def _drain_queue(self) -> None:
        """Move everything still in the queue onto the pending stash
        (arrival order preserved) - the drain path's intake."""
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                return
            if item is not None:
                with self._plock:
                    self._pending.append(item)

    def _loop(self) -> None:
        while True:
            if self._closed:
                if not self._drain:
                    return
                self._drain_queue()
                if not self._pending:
                    return
            with self._plock:
                first = self._pending.popleft() if self._pending else None
            if first is None:
                item = self._q.get()
                if item is None:
                    continue  # sentinel: loop back to the closed check
                first = item
            batch = [first]
            batch += self._take_pending(
                first.key, self.max_batch - len(batch)
            )
            # While draining, skip the max-wait idle: flush immediately.
            deadline = time.monotonic() + (
                0.0 if self._closed else self.max_wait
            )
            while len(batch) < self.max_batch:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is None:
                    # Sentinel mid-collection: execute what we have; the
                    # outer loop then drains (or returns, leaving
                    # close() to fail the stash).
                    break
                if nxt.key == first.key:
                    batch.append(nxt)
                else:
                    with self._plock:
                        self._pending.append(nxt)
            self._execute(batch)

    def _execute(self, batch: List[_Item]) -> None:
        req0 = batch[0].request
        try:
            result, lane_health = self.engine.solve(
                req0.problem,
                [item.request.lane for item in batch],
                scheme=req0.scheme, path=req0.path, k=req0.k,
                dtype_name=req0.dtype_name, mesh=req0.mesh_shape,
            )
        except Exception as e:
            for item in batch:
                if not item.future.done():
                    item.future.set_exception(e)
            return
        cells = sum(
            req0.problem.cells_per_step * (r.steps_computed or 0)
            for r in result.results
        )
        self.metrics.observe_batch(
            occupancy=result.n_lanes, batched=result.batched,
            cells=cells, solve_seconds=result.solve_seconds,
        )
        batch_info = {
            "occupancy": result.n_lanes,
            "batch_size": result.batch_size,
            "batched": result.batched,
            "fallback_reason": result.fallback_reason,
            "path": result.path,
            "aggregate_gcells_per_s": round(
                result.aggregate_gcells_per_second, 4
            ),
        }
        for i, item in enumerate(batch):
            # done() guard: a close() that timed out may have failed
            # this future already; a second set_ would raise
            # InvalidStateError inside the worker.
            if not item.future.done():
                item.future.set_result(
                    (result.results[i], lane_health[i], batch_info)
                )
