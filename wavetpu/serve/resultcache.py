"""Content-addressed result cache: the fleet's memory for answers.

Solves are deterministic - the same `RequestIdentity` (plus the
answer-shaping phase/steps/c2_field fields) yields a bitwise-identical
final state - yet until this tier existed every duplicate request
recomputed from scratch on a chip.  This module is the replica-side
half of the fleet result tier (docs/serving.md "Result cache"): a
bounded in-memory LRU keyed by `wavetpu.progkey.result_key` (the SAME
jax-free derivation the router edge cache uses, so the two tiers hash
a body identically) storing the EXACT serialized `/solve` success
payload, its Server-Timing attribution, and a sha256 payload digest.

Contract:

 * Hits are BYTE-IDENTICAL to the fresh solve whose answer was stored:
   the cache keeps serialized bytes, never a re-encodable object, so a
   dict-ordering or float-formatting drift can never produce a
   response that differs from what a cold client saw.
 * Bounded by bytes (LRU) and by TTL; every entry records the
   environment fingerprint it was computed under
   (serve/progcache.py `env_fingerprint`) and a fingerprint drift is a
   counted miss - a jaxlib upgrade must never replay a stale answer.
 * Integrity over trust: every `get` re-verifies the stored digest.
   Corruption (real, or the `WAVETPU_FAULT=serve-resultcache-corrupt`
   chaos injection) is a COUNTED miss that falls through to a clean
   recompute - never a wrong answer, and never a circuit-breaker event
   (the breaker reasons about compile/execute health; a cache losing
   an entry says nothing about the program).
 * Eligibility is the caller's job (serve/api.py): deterministic full
   solves only, never resume-token or recorded-fallback responses, and
   `Cache-Control: no-cache` bypasses (counted).

Stdlib + obs.registry only; never imports jax (the environment
fingerprint is computed once by build_server and passed IN, so unit
tests and jax-less tooling can construct the cache directly).
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict
from typing import Dict, Optional, Tuple

# Counted outcomes on the events counter - one label per branch so a
# chaos drill can pin "corruption fired AND was counted" exactly.
EVENTS = ("hit", "miss", "store", "evict_lru", "evict_ttl",
          "fingerprint_mismatch", "corrupt", "bypass")

DEFAULT_MAX_BYTES = 64 << 20
DEFAULT_TTL_S = 600.0


def payload_digest(payload: bytes) -> str:
    """The stored entry's integrity digest (sha256 hex over the exact
    response bytes - which embed the final-state error digest the
    report carries, so this is also the answer's content address)."""
    return hashlib.sha256(payload).hexdigest()


class _Entry:
    __slots__ = ("payload", "server_timing", "digest", "fingerprint",
                 "created")

    def __init__(self, payload: bytes, server_timing: Optional[str],
                 fingerprint: Optional[dict], created: float):
        self.payload = payload
        self.server_timing = server_timing
        self.digest = payload_digest(payload)
        self.fingerprint = fingerprint
        self.created = created

    @property
    def size(self) -> int:
        return len(self.payload)


class ResultCache:
    """Thread-safe bounded LRU of serialized /solve success payloads.

    `fingerprint` is the environment identity entries are valid under
    (None = unpinned, unit-test mode); `fault_plan` is the server's
    shared WAVETPU_FAULT plan - the two `resultcache-*` chaos kinds
    fire here, at the exact seam real corruption would land."""

    def __init__(self, max_bytes: int = DEFAULT_MAX_BYTES,
                 ttl_s: float = DEFAULT_TTL_S,
                 fingerprint: Optional[dict] = None,
                 registry=None, fault_plan=None,
                 clock=time.monotonic):
        if max_bytes <= 0:
            raise ValueError(f"max_bytes must be > 0, got {max_bytes}")
        if ttl_s <= 0:
            raise ValueError(f"ttl_s must be > 0, got {ttl_s}")
        self.max_bytes = int(max_bytes)
        self.ttl_s = float(ttl_s)
        self.fingerprint = fingerprint
        self.fault_plan = fault_plan
        self._clock = clock
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        self._bytes = 0
        self._events: Dict[str, int] = {e: 0 for e in EVENTS}
        self._counter = None
        self._bytes_gauge = None
        self._entries_gauge = None
        if registry is not None:
            self._counter = registry.counter(
                "wavetpu_serve_resultcache_events_total",
                "result-cache outcomes (hit/miss/store/evictions/"
                "rejections) on the replica tier",
                ("event",),
            )
            self._bytes_gauge = registry.gauge(
                "wavetpu_serve_resultcache_bytes",
                "bytes of serialized payloads resident in the result "
                "cache",
            )
            self._entries_gauge = registry.gauge(
                "wavetpu_serve_resultcache_entries",
                "entries resident in the result cache",
            )

    # ---- bookkeeping ----

    def _count(self, event: str) -> None:
        self._events[event] += 1
        if self._counter is not None:
            self._counter.inc(event=event)

    def _set_gauges(self) -> None:
        if self._bytes_gauge is not None:
            self._bytes_gauge.set(float(self._bytes))
            self._entries_gauge.set(float(len(self._entries)))

    def _drop(self, key: str) -> None:
        entry = self._entries.pop(key, None)
        if entry is not None:
            self._bytes -= entry.size

    # ---- data path ----

    def get(self, key: str, **fault_ctx) -> Optional[
        Tuple[bytes, Optional[str]]
    ]:
        """The stored (payload_bytes, server_timing) for `key`, or None
        (every non-hit branch is a counted miss variant).  `fault_ctx`
        is the program-identity selector context for the chaos plan."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self._count("miss")
                return None
            if self.fault_plan is not None and entry is not None \
                    and self.fault_plan.fire(
                        "resultcache-corrupt", **fault_ctx
                    ) is not None:
                # Chaos: flip one payload byte IN PLACE so the digest
                # check below - the real rejection branch - fires.
                b = bytearray(entry.payload)
                b[len(b) // 2] ^= 0x01
                entry.payload = bytes(b)
            expected_fp = self.fingerprint
            if self.fault_plan is not None and self.fault_plan.fire(
                    "resultcache-stale-fingerprint", **fault_ctx
            ) is not None:
                # Chaos: this lookup "observes" an environment drift -
                # exactly what a jaxlib upgrade under a warm cache
                # would look like.
                expected_fp = {"poisoned": True}
            if payload_digest(entry.payload) != entry.digest:
                self._drop(key)
                self._count("corrupt")
                self._count("miss")
                self._set_gauges()
                return None
            if entry.fingerprint != expected_fp:
                self._drop(key)
                self._count("fingerprint_mismatch")
                self._count("miss")
                self._set_gauges()
                return None
            if self._clock() - entry.created > self.ttl_s:
                self._drop(key)
                self._count("evict_ttl")
                self._count("miss")
                self._set_gauges()
                return None
            self._entries.move_to_end(key)
            self._count("hit")
            return entry.payload, entry.server_timing

    def put(self, key: str, payload: bytes,
            server_timing: Optional[str] = None) -> bool:
        """Store one success payload (exact bytes).  Returns False when
        the payload alone exceeds the byte bound (never evict the whole
        cache for one oversized answer)."""
        if len(payload) > self.max_bytes:
            return False
        with self._lock:
            self._drop(key)
            entry = _Entry(payload, server_timing, self.fingerprint,
                           self._clock())
            self._entries[key] = entry
            self._bytes += entry.size
            while self._bytes > self.max_bytes and len(self._entries) > 1:
                old_key = next(iter(self._entries))
                if old_key == key:
                    break
                self._drop(old_key)
                self._count("evict_lru")
            self._count("store")
            self._set_gauges()
            return True

    def note_bypass(self) -> None:
        """Count a `Cache-Control: no-cache` bypass (the contract says
        the client CAN opt out; the metrics must show it happening)."""
        with self._lock:
            self._count("bypass")

    # ---- views ----

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "max_bytes": self.max_bytes,
                "ttl_s": self.ttl_s,
                "events": dict(self._events),
            }
