"""stdlib-HTTP JSON front end: `wavetpu serve` / `wavetpu-serve`.

Endpoints (contract in docs/serving.md):

  POST /solve    one solve request -> its own reference-format report.
                 Body: {"N": 32, "timesteps": 20, ...} (fields below).
                 Concurrent requests with the same program identity are
                 coalesced into one batched XLA solve (scheduler.py);
                 each response carries its lane's report plus batch
                 context (occupancy, batched-or-fallback, path).  With
                 --max-queue set, a full queue answers 429 (bounded-
                 queue backpressure) instead of building latency.
                 Every response echoes the request id (`X-Request-Id`:
                 the caller's header if supplied, else server-minted
                 when tracing is on) and carries a `Server-Timing`
                 header attributing the latency - queue/compile/
                 execute (additive; sum ~= total) plus padding (the
                 masked-lane share of the batch solve) and total (the
                 server-measured wall) - so a load generator reads
                 WHERE each request's time went without touching the
                 server's trace files, and the id joins the outlier to
                 `wavetpu trace-report --request ID`.
                 --max-body-bytes refuses oversized bodies with 413 and
                 --max-lane-cells refuses oversized grids with 422,
                 both BEFORE scheduling (counted in /metrics).
                 Resilience contract (docs/robustness.md): a request
                 may carry `deadline_ms` (JSON field, or the
                 `X-Deadline-Ms` header, which wins) - a relative
                 budget from server receipt; expired-in-queue work is
                 dropped with 504 + queue attribution and the handler
                 never outwaits the budget.  429 (queue full) and 503
                 (draining / circuit-broken program / worker crash)
                 carry `Retry-After` and `"retriable": true`; a
                 ProgramKey with K consecutive compile/execute
                 failures is quarantined by the engine's circuit
                 breaker (--breaker-threshold/--breaker-cooldown-s/
                 --no-breaker) while other tiers keep serving.
  GET /healthz   liveness AND readiness: {"status": "ok", "ready",
                 "uptime_seconds", "draining", "warming",
                 "last_batch_age_seconds", "memory_bytes_in_use",
                 "memory_peak_bytes"} - `status` says the process
                 serves HTTP, `ready` says ROUTE HERE (false while the
                 --warmup compile runs or once draining is set); a
                 load balancer distinguishes idle (no traffic, age
                 null/stale but draining false) from wedged; age is
                 null ONLY if no batch was ever executed.
  GET /metrics   request counts, batch occupancy, p50/p95 latency,
                 aggregate Gcell/s, queue depth/rejections, program-
                 cache and fallback state.  Content-negotiated: the
                 default is the historical JSON snapshot; `Accept:
                 text/plain` serves Prometheus text exposition and
                 `Accept: application/openmetrics-text` the OpenMetrics
                 form with request-id EXEMPLARS on latency histogram
                 buckets, all from the same registry cut
                 (docs/observability.md).

Request fields: N (required), Np, Lx, Ly, Lz (floats or "pi"), T,
timesteps, phase (initial time phase, default 2*pi), steps (stop layer,
default timesteps), scheme (standard|compensated - BOTH batch through
the vmapped core, incl. the flagship compensated velocity form), kernel
(auto|roll|pallas), fuse_steps (K >= 2 selects the k-fused onion),
dtype (f32|f64|bf16), c2_field (preset constant|gaussian-lens|two-layer;
standard scheme only), mesh ([MX, MY, MZ] - route through the sharded x
batched composition over that device mesh; standard scheme, no
fuse_steps/c2_field).

A request whose lane trips the numerical-health watchdog (NaN/Inf or
amplitude blowup - e.g. a Courant-unstable config) gets HTTP 422 with the
per-lane error; its batchmates' 200s are unaffected (engine.py).  During
a graceful drain (SIGTERM/SIGINT) new /solve requests get 503 while
queued work flushes to completion.

The server is stdlib-only (http.server.ThreadingHTTPServer): handler
threads block on the batcher future while the single scheduler worker
runs the XLA program - the same thread discipline as any Python
inference server in front of an accelerator.
"""

from __future__ import annotations

import dataclasses
import json
import sys
import threading
import time
from concurrent.futures import TimeoutError as FuturesTimeoutError
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Sequence, Tuple

from wavetpu import progkey
from wavetpu.core.problem import Problem
from wavetpu.obs import tracing

_USAGE = (
    "usage: wavetpu serve [--host H] [--port P] [--max-batch B] "
    "[--max-wait-ms MS] [--bucket-sizes 1,2,4,8] [--max-programs M] "
    "[--length-bucket-steps Q] [--max-queue Q] "
    "[--max-body-bytes B] [--max-lane-cells C] "
    "[--kernel auto|roll|pallas] "
    "[--no-errors] [--max-amp X] [--no-watchdog] [--no-server-timing] "
    "[--breaker-threshold K] [--breaker-cooldown-s S] [--no-breaker] "
    "[--warmup N,TIMESTEPS[,K]] [--warmup-manifest MANIFEST.json] "
    "[--program-cache-dir DIR] [--program-cache-max-bytes B] "
    "[--chunk-threshold T] [--chunk-steps S] "
    "[--solve-state-dir DIR] [--solve-state-ttl-s S] "
    "[--brownout-thresholds P1,P2,P3] [--no-brownout] "
    "[--proxy-token SECRET] [--tenant-inflight-cap N] "
    "[--result-cache] [--result-cache-max-bytes B] "
    "[--result-cache-ttl-s S] "
    "[--shadow-sample-rate P] [--shadow-deadline-s S] "
    "[--platform NAME] "
    "[--telemetry-dir DIR] [--record-trace FILE.jsonl] [--version]"
)

_KNOWN = (
    "host", "port", "max-batch", "max-wait-ms", "bucket-sizes",
    "max-programs", "length-bucket-steps", "max-queue",
    "max-body-bytes", "max-lane-cells", "kernel",
    "no-errors", "max-amp", "no-watchdog", "no-server-timing",
    "breaker-threshold", "breaker-cooldown-s", "no-breaker",
    "warmup", "warmup-manifest", "program-cache-dir",
    "program-cache-max-bytes", "chunk-threshold", "chunk-steps",
    "solve-state-dir", "solve-state-ttl-s",
    "brownout-thresholds", "no-brownout", "proxy-token",
    "tenant-inflight-cap", "result-cache",
    "result-cache-max-bytes", "result-cache-ttl-s",
    "shadow-sample-rate", "shadow-deadline-s", "platform",
    "telemetry-dir", "record-trace", "version",
)
_VALUELESS = ("no-errors", "no-watchdog", "no-server-timing",
              "no-breaker", "no-brownout", "result-cache", "version")


def _split_flags(argv: Sequence[str]) -> dict:
    from wavetpu.core.flags import split_flags

    _, flags = split_flags(argv, _KNOWN, _VALUELESS,
                           allow_positionals=False)
    return flags


def _c2_preset(problem: Problem, spec: str):
    """The CLI's --c2-field presets - one shared table
    (stencil_ref.make_preset_c2tau2_field), so a preset name means the
    same physics on both surfaces."""
    from wavetpu.kernels import stencil_ref

    if spec not in stencil_ref.C2_PRESET_NAMES:
        raise ValueError(
            f"c2_field must be one of "
            f"{sorted(stencil_ref.C2_PRESET_NAMES)}, got {spec!r}"
        )
    return stencil_ref.make_preset_c2tau2_field(problem, spec)


def _jax_platform() -> str:
    import jax

    return jax.default_backend()


def parse_solve_request(body: dict, default_kernel: str = "auto"):
    """Validate a POST /solve body into a SolveRequest (ValueError on any
    bad field - mapped to HTTP 400).

    The program-identity half (geometry, scheme/path/k/dtype/mesh-shape
    checks) is the shared `wavetpu.progkey.identity_from_body` - the
    SAME derivation the fleet router uses for affinity routing, so the
    key the engine caches under and the key the router routes by cannot
    drift.  This function layers on what needs a backend: device-count
    checks, c2-field preset construction, and lane validation."""
    from wavetpu.ensemble.batched import LaneSpec
    from wavetpu.serve.scheduler import SolveRequest

    ident = progkey.identity_from_body(
        body, default_kernel, platform=_jax_platform
    )
    problem = ident.problem
    stop = body.get("steps")
    stop = None if stop is None else int(stop)
    field = None
    if body.get("c2_field"):
        field = _c2_preset(problem, str(body["c2_field"]))
    phase = float(body.get("phase", 2.0 * 3.141592653589793))
    mesh = ident.mesh
    if mesh is not None:
        import jax

        n_dev = mesh[0] * mesh[1] * mesh[2]
        if n_dev > len(jax.devices()):
            raise ValueError(
                f"mesh {mesh} needs {n_dev} devices, only "
                f"{len(jax.devices())} available"
            )
    lane = LaneSpec(phase=phase, stop_step=stop, c2tau2_field=field)
    # Surface lane-level errors (bad stop/k alignment) at parse time so
    # they 400 instead of failing the whole batch later.
    if mesh is not None:
        from wavetpu.ensemble.sharded import _validate as _validate_sh

        _validate_sh(problem, [lane], ident.path, compute_errors=False)
    else:
        from wavetpu.ensemble.batched import _validate

        _validate(problem, [lane], ident.path,
                  ident.k if ident.path == "kfused" else 2,
                  compute_errors=False, scheme=ident.scheme)
    resume_token = body.get("resume_token")
    if resume_token is not None:
        # Format-only gate here (400 for plain junk); the state store
        # re-verifies content hash + identity at load time (422).
        from wavetpu.serve.preempt import SolveStateStore

        if not isinstance(resume_token, str) or \
                not SolveStateStore.valid_token(resume_token):
            raise ValueError(
                "resume_token must be a 64-char lowercase hex string"
            )
    # QoS class: JSON `priority` field (the X-Priority header, when
    # trusted, wins - _handle_solve applies it after this).  Unknown
    # values clamp to the default class rather than 400 - priority is a
    # scheduling hint, and a router ceiling may rewrite it anyway.
    from wavetpu.serve.scheduler import normalize_priority

    return SolveRequest(
        problem=problem, lane=lane, scheme=ident.scheme, path=ident.path,
        k=ident.k, dtype_name=ident.dtype,
        mesh_shape=mesh, resume_token=resume_token,
        priority=normalize_priority(body.get("priority")),
    )


def _ok_payload(result, batch_info: dict, errors_computed: bool) -> dict:
    """The reference report fields for one lane (io/report.py sidecar
    contract) plus the verbatim text report."""
    from wavetpu.io import report

    p = result.problem
    return {
        "status": "ok",
        "report": {
            "problem": dataclasses.asdict(p),
            "courant": p.courant,
            "init_seconds": result.init_seconds,
            "solve_seconds": result.solve_seconds,
            "gcells_per_second": result.gcells_per_second,
            "cells_per_step": p.cells_per_step,
            "final_step": result.final_step,
            "errors_computed": errors_computed,
            "max_abs_error": (
                float(result.abs_errors.max()) if errors_computed else None
            ),
            "abs_errors": (
                [float(x) for x in result.abs_errors]
                if errors_computed else None
            ),
            "rel_errors": (
                [float(x) for x in result.rel_errors]
                if errors_computed else None
            ),
        },
        "report_text": report.format_report(
            result, errors_computed=errors_computed
        ),
        "batch": batch_info,
    }


_RID_ALLOWED = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789-_.:"
)


def sanitize_request_id(raw: Optional[str]) -> Optional[str]:
    """A caller-supplied X-Request-Id, accepted only when it is plainly
    a token (<= 64 chars from [-A-Za-z0-9_.:]) - anything else is
    dropped so header junk can never be reflected into responses, trace
    attrs, or exemplar labels."""
    if not raw:
        return None
    raw = raw.strip()
    if not raw or len(raw) > 64 or not set(raw) <= _RID_ALLOWED:
        return None
    return raw


def sanitize_tenant(raw: Optional[str]) -> Optional[str]:
    """The `X-Wavetpu-Tenant` label the router stamped after API-key
    termination - same token discipline as request ids, so a hostile
    label can never be reflected into metrics labels, span attrs, or
    ledger lines."""
    return sanitize_request_id(raw)


def format_retry_after(seconds: float) -> str:
    """Integer delta-seconds form of a measured backoff (the only form
    `WavetpuClient.parse_retry_after` promises to read), floored at 1 -
    a sub-second hint rounded to 0 would tell clients to hammer."""
    return str(max(1, int(seconds + 0.5)))


def server_timing_header(timing: dict, total_s: float,
                         warm: Optional[str] = None) -> str:
    """RFC-style `Server-Timing` value from the scheduler's per-request
    attribution: queue/compile/execute are the ADDITIVE wall components
    (their sum ~= total up to parse/serialize overhead - the 10%
    contract tests/test_serve.py pins), padding is the informational
    masked-lane share of execute, total is the server-measured wall.
    `warm` (the engine's true/disk/false/fallback program-source label)
    rides as a desc-only entry - the fleet router reads it off each
    response to learn which replica holds which program without an
    extra /metrics round trip."""
    parts = []
    for name, key in (("queue", "queue_s"), ("compile", "compile_s"),
                      ("execute", "execute_s"), ("padding", "padding_s")):
        parts.append(f"{name};dur={timing.get(key, 0.0) * 1e3:.3f}")
    parts.append(f"total;dur={total_s * 1e3:.3f}")
    if warm is not None:
        parts.append(f"warm;desc={warm}")
    return ", ".join(parts)


class ServerState:
    """Everything the handler needs, hung off the HTTPServer instance.

    `draining` flips on SIGTERM/SIGINT: new /solve requests get 503
    while the batcher flushes what is already queued (graceful drain -
    outstanding futures resolve with results, scheduler.close(drain)).

    `max_body_bytes` / `max_lane_cells` are the pre-scheduling request
    size limits (413 / 422); `recorder` (a loadgen.trace.TraceRecorder)
    captures accepted /solve bodies into a replayable scenario trace;
    `server_timing=False` suppresses the Server-Timing response header
    (ops escape hatch, and the A/B arm bench.py's loadgen observer-
    overhead measurement compares against)."""

    def __init__(self, engine, batcher, metrics, default_kernel: str,
                 request_timeout: float = 600.0,
                 max_body_bytes: Optional[int] = None,
                 max_lane_cells: Optional[int] = None,
                 recorder=None, server_timing: bool = True,
                 fault_plan=None, proxy_token: Optional[str] = None,
                 tenant_inflight_cap: Optional[int] = None,
                 result_cache=None,
                 result_cache_fp_tag: Optional[str] = None,
                 shadow=None):
        self.engine = engine
        self.batcher = batcher
        self.metrics = metrics
        self.default_kernel = default_kernel
        self.request_timeout = request_timeout
        self.max_body_bytes = max_body_bytes
        self.max_lane_cells = max_lane_cells
        self.recorder = recorder
        self.server_timing = server_timing
        self.fault_plan = fault_plan
        # Replica-side tenant trust (--proxy-token): with a secret set,
        # X-Wavetpu-Tenant / X-Priority headers are honored ONLY when
        # the request also carries the matching X-Wavetpu-Proxy-Token -
        # i.e. it came through the router, which holds the secret.  A
        # direct-to-replica client without it cannot impersonate a
        # tenant or self-promote its class; the headers are IGNORED
        # (rejection counted) and the request still serves untenanted.
        self.proxy_token = proxy_token
        # Defensive per-tenant concurrency cap (--tenant-inflight-cap):
        # a backstop UNDER the router's authoritative token buckets, so
        # one tenant cannot occupy every handler slot of a replica even
        # if it reaches it directly.  None = off.
        self.tenant_inflight_cap = tenant_inflight_cap
        self._tenant_inflight: dict = {}
        self._tenant_lock = threading.Lock()
        # Content-addressed result cache (serve/resultcache.py; None =
        # off, the default - tier-1 batching semantics rely on identical
        # concurrent requests sharing a BATCH, which --result-cache
        # upgrades to sharing an ANSWER).  `result_cache_fp_tag` is the
        # short environment-fingerprint hash stamped on store responses
        # (`X-Wavetpu-Cache: store;fp=TAG`) so the router's edge tier
        # can flush across fleet upgrades.
        self.result_cache = result_cache
        self.result_cache_fp_tag = result_cache_fp_tag
        # Shadow-solve sampler (serve/shadow.py; None = off, the
        # default): a sampled fraction of eligible /solve responses is
        # re-solved off the hot path with the compensated-f32 reference
        # plan and the measured divergence ledgered (obs/accuracy.py).
        self.shadow = shadow
        self.started = time.time()
        self.draining = False
        # Readiness: `warming` is True while the background --warmup
        # compile runs; /healthz reports ready = not draining and not
        # warming, so a load balancer routes to a replica only once its
        # programs exist and pulls it BEFORE drain kills requests.
        self.warming = False
        self.warmup_error: Optional[str] = None
        # Lazily resolved jax.default_backend(), cached so /healthz
        # polls (the fleet router's membership loop) never re-query it;
        # the router uses it to resolve kernel=auto the same way this
        # replica will.
        self.backend: Optional[str] = None
        self._drain_lock = threading.Lock()
        self._drain_started = False

    def begin_drain(self, httpd) -> bool:
        """Graceful drain, shared by SIGTERM/SIGINT and POST
        /admin/drain: refuse new /solve (503 + Retry-After) immediately
        and stop the accept loop from a daemon thread (shutdown() joins
        serve_forever, so it must never run on a handler thread
        in-line).  Idempotent; returns False when already draining."""
        with self._drain_lock:
            first = not self._drain_started
            self._drain_started = True
            self.draining = True
        if first:
            threading.Thread(target=httpd.shutdown, daemon=True).start()
        return first

    def try_acquire_tenant_slot(self, tenant: Optional[str]) -> bool:
        """Take one in-flight slot for `tenant` (always True with the
        cap off or no tenant label).  Pair with release_tenant_slot."""
        if self.tenant_inflight_cap is None or not tenant:
            return True
        with self._tenant_lock:
            n = self._tenant_inflight.get(tenant, 0)
            if n >= self.tenant_inflight_cap:
                return False
            self._tenant_inflight[tenant] = n + 1
            return True

    def release_tenant_slot(self, tenant: Optional[str]) -> None:
        if self.tenant_inflight_cap is None or not tenant:
            return
        with self._tenant_lock:
            n = self._tenant_inflight.get(tenant, 0) - 1
            if n <= 0:
                self._tenant_inflight.pop(tenant, None)
            else:
                self._tenant_inflight[tenant] = n


class _Handler(BaseHTTPRequestHandler):
    # HTTP/1.1 = persistent connections: the fleet router and the
    # keep-alive WavetpuClient reuse one socket across requests instead
    # of paying a TCP handshake each (BaseHTTPRequestHandler defaults
    # to 1.0/close).  Safe because _send_text is the single send path
    # and always sets Content-Length; responses that skip reading the
    # request body send `Connection: close` so leftover bytes can never
    # be parsed as the next request on the same socket.
    protocol_version = "HTTP/1.1"

    # quiet by default; the scheduler's numbers live in /metrics
    def log_message(self, fmt, *args):  # noqa: D102
        pass

    @property
    def state(self) -> ServerState:
        return self.server.wavetpu_state

    def _backend(self) -> Optional[str]:
        st = self.state
        if st.backend is None:
            try:
                import jax

                st.backend = jax.default_backend()
            except Exception:
                return None
        return st.backend

    def _send(self, code: int, payload,
              headers: Optional[dict] = None) -> None:
        if isinstance(payload, (bytes, bytearray)):
            # A result-cache hit (or a just-stored fresh solve) replays
            # the EXACT serialized payload - bytes, not a re-encodable
            # dict - so hits are byte-identical by construction.
            self._send_raw(code, bytes(payload), "application/json",
                           headers)
            return
        self._send_text(code, json.dumps(payload), "application/json",
                        headers)

    def _send_raw(self, code: int, body: bytes, content_type: str,
                  headers: Optional[dict] = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _send_text(self, code: int, text: str, content_type: str,
                   headers: Optional[dict] = None) -> None:
        self._send_raw(code, text.encode(), content_type, headers)

    def do_GET(self) -> None:  # noqa: N802 (stdlib contract)
        if self.path == "/healthz":
            from wavetpu.obs import perf

            age = self.state.metrics.last_batch_age()
            # Device-memory visibility for the balancer/autoscaler:
            # None on backends without memory_stats() (CPU), else the
            # allocator's live + peak byte counts.  Unit pinned in the
            # field names, like last_batch_age_seconds.
            mem = perf.memory_snapshot()
            # Liveness vs READINESS: "status: ok" = the process serves
            # HTTP (liveness); "ready" = route traffic here (false while
            # the warmup compile is still running, or once draining is
            # set - so a load balancer stops routing BEFORE drain starts
            # failing requests, not after).
            payload = {
                "status": "ok",
                "ready": (
                    not self.state.draining and not self.state.warming
                ),
                "uptime_seconds": round(
                    time.time() - self.state.started, 3
                ),
                "draining": self.state.draining,
                "warming": self.state.warming,
                "last_batch_age_seconds": (
                    None if age is None else round(age, 3)
                ),
                "memory_bytes_in_use": (
                    None if mem is None else mem["bytes_in_use"]
                ),
                "memory_peak_bytes": (
                    None if mem is None else mem["peak_bytes"]
                ),
                "backend": self._backend(),
            }
            brownout = getattr(self.state.batcher, "brownout", None)
            if brownout is not None:
                # The overload ladder's state, for balancers and ops:
                # rung 0 = healthy; higher rungs shed classes
                # (docs/robustness.md "Brownout ladder").
                brownout.update()
                payload["brownout"] = brownout.snapshot()
            if self.state.warmup_error is not None:
                payload["warmup_error"] = self.state.warmup_error
            self._send(200, payload)
        elif self.path == "/metrics":
            accept = self.headers.get("Accept", "") or ""
            # A client that lists application/json at all (e.g. the
            # axios default "application/json, text/plain, */*") gets
            # JSON; Prometheus scrapers send text/plain or openmetrics
            # without it.
            wants_text = (
                "application/json" not in accept
                and ("text/plain" in accept or "openmetrics" in accept)
            )
            if wants_text:
                # Prometheus text exposition - one consistent registry
                # cut (scrape config: docs/observability.md).  An
                # openmetrics Accept additionally gets request-id
                # EXEMPLARS on the latency histogram buckets (+ # EOF).
                openmetrics = "openmetrics" in accept
                self._send_text(
                    200,
                    self.state.metrics.registry.render_prometheus(
                        openmetrics=openmetrics
                    ),
                    "application/openmetrics-text; version=1.0.0; "
                    "charset=utf-8" if openmetrics
                    else "text/plain; version=0.0.4; charset=utf-8",
                )
                return
            snap = self.state.metrics.snapshot()
            snap["program_cache"] = self.state.engine.cache_stats()
            snap["breaker"] = self.state.engine.breaker_stats()
            if self.state.result_cache is not None:
                snap["result_cache"] = self.state.result_cache.snapshot()
            if self.state.shadow is not None:
                snap["shadow"] = self.state.shadow.snapshot()
            self._send(200, snap)
        else:
            self._send(404, {"status": "error", "error": "not found"})

    def do_POST(self) -> None:  # noqa: N802
        if self.path == "/admin/drain":
            # HTTP-equivalent of SIGTERM, for the `wavetpu fleet roll`
            # driver: flip draining (healthz ready -> false, new /solve
            # -> 503 + Retry-After) and stop the accept loop; queued
            # work flushes to completion exactly like the signal path.
            # Idempotent - a second call reports already_draining.
            first = self.state.begin_drain(self.server)
            self._send(200, {
                "status": "ok",
                "draining": True,
                "already_draining": not first,
            }, {"Connection": "close"})
            return
        if self.path != "/solve":
            self._send(404, {"status": "error", "error": "not found"},
                       {"Connection": "close"})
            return
        # Chaos seam: connection drop - close the socket with no
        # response at all, the failure mode a crashed proxy or a
        # severed network produces (the retrying client must absorb it
        # as a transport error).
        plan = self.state.fault_plan
        if plan is not None and plan.active and plan.fire("conn-drop"):
            self.close_connection = True
            try:
                self.connection.close()
            except OSError:
                pass
            return
        # One `serve.request` span per request: its wall time is the
        # end-to-end latency; the scheduler-thread `serve.batch` span
        # that carried it joins on the shared request_id attribute
        # (trace-report --request ID stitches the two).  A caller-
        # supplied X-Request-Id (the loadgen minted one) becomes THE id
        # - so the client-side report and the server-side trace agree
        # on the join key without any translation table.
        rid = sanitize_request_id(self.headers.get("X-Request-Id"))
        rid = rid or tracing.new_id()
        # Fleet trace adoption (docs/observability.md "Distributed
        # tracing"): an inbound W3C `traceparent` (a router attempt, or
        # a bare WavetpuClient) becomes the REMOTE parent of this
        # serve.request span, so the replica's whole tree hangs under
        # the fleet trace id; a traced request with no inbound context
        # mints its own trace id.  The span advertises a 16-hex
        # `w3c_id` the joiner resolves cross-process, and the context
        # is echoed on the response either way - even untraced, the
        # inbound header is reflected so the client's join handle
        # always answers.
        inbound_tp = self.headers.get("traceparent")
        ctx = tracing.parse_traceparent(inbound_tp)
        echo_tp = inbound_tp if ctx else None
        span = None
        self._trace_context: Optional[Tuple[str, str]] = None
        if tracing.enabled():
            trace_id = ctx[0] if ctx else tracing.mint_trace_id()
            w3c = tracing.mint_span_id()
            echo_tp = tracing.format_traceparent(trace_id, w3c)
            self._trace_context = (trace_id, w3c)
            span = tracing.begin_span(
                "serve.request",
                remote=(trace_id, ctx[1] if ctx else None),
                request_id=rid, w3c_id=w3c,
            )
        code = None
        headers: dict = {}
        # Per-tenant in-flight accounting: _handle_solve records the
        # slot it took here; releasing in THIS finally covers every
        # return path (including handler exceptions).
        self._tenant_slot: Optional[str] = None
        # Shadow-solve sampling: _handle_solve stashes (request,
        # lane_result) for an eligible 200 here; the offer happens
        # AFTER _send below, so the primary answer is on the wire
        # before any shadow work exists.
        self._shadow_offer = None
        try:
            code, payload, headers = self._handle_solve(rid)
        finally:
            self.state.release_tenant_slot(self._tenant_slot)
            # An unexpected handler exception must not leak the open
            # span (it would poison this thread's parent stack and
            # vanish from the trace).
            tracing.end_span(
                span, status="exception" if code is None else code
            )
        if rid:
            headers.setdefault("X-Request-Id", rid)
        if echo_tp:
            headers.setdefault("traceparent", echo_tp)
        self._send(code, payload, headers)
        offer = self._shadow_offer
        if offer is not None and self.state.shadow is not None:
            req, lane_result = offer
            self.state.shadow.offer(
                req, lane_result, rid,
                trace_context=getattr(self, "_trace_context", None),
            )

    def _handle_solve(self, rid) -> Tuple[int, dict, dict]:
        from wavetpu.serve.resilience import (
            DeadlineExceededError,
            InvalidStateTokenError,
            PreemptedError,
            QuarantinedError,
            ShedError,
            WorkerCrashError,
        )
        from wavetpu.serve.scheduler import (
            QueueFullError,
            normalize_priority,
        )

        st = self.state
        queue_depth = getattr(st.batcher, "_depth", 0)
        if st.draining:
            # Connection: close because the request body is never read
            # on this path - leftover bytes on a kept-alive socket
            # would be parsed as the next request.  Retry-After is the
            # MEASURED drain estimate for what is still queued (the
            # historical 2 s stands in when no rate has been observed).
            st.metrics.observe_response(False)
            return 503, {
                "status": "error",
                "error": "server draining (shutting down)",
                "retriable": True,
            }, {
                "Retry-After": format_retry_after(
                    st.metrics.retry_after_s(queue_depth, fallback=2.0)
                ),
                "Connection": "close",
            }
        t0 = time.monotonic()
        try:
            length = int(self.headers.get("Content-Length", "0") or 0)
            if length < 0:
                # A negative length would turn rfile.read(length) into
                # read-to-EOF and pin this handler thread forever.
                raise ValueError(length)
        except (TypeError, ValueError):
            # A malformed Content-Length is a 400 like any other bad
            # field, not a dropped connection (or a hung thread).
            st.metrics.observe_response(False)
            return 400, {
                "status": "error",
                "error": "malformed Content-Length header",
            }, {"Connection": "close"}
        if st.max_body_bytes is not None and length > st.max_body_bytes:
            # Refused before the body is even read: an oversized upload
            # must not be buffered just to be thrown away.
            st.metrics.observe_limit_rejected("body_bytes")
            st.metrics.observe_response(False)
            return 413, {
                "status": "error",
                "error": (
                    f"request body {length} bytes exceeds "
                    f"--max-body-bytes {st.max_body_bytes}"
                ),
            }, {"Connection": "close"}
        try:
            body = json.loads(self.rfile.read(length) or b"{}")
            req = parse_solve_request(body, st.default_kernel)
            tenant_hdr = self.headers.get("X-Wavetpu-Tenant")
            prio_hdr = self.headers.get("X-Priority")
            if st.proxy_token is not None and (tenant_hdr or prio_hdr):
                # Replica-side tenant trust: identity/class headers are
                # honored only from the router (it holds --proxy-token).
                # A direct client's claim is IGNORED - the request still
                # serves, untenanted and at its body-declared class.
                if self.headers.get("X-Wavetpu-Proxy-Token") \
                        != st.proxy_token:
                    st.metrics.observe_tenant_spoof_rejected()
                    tenant_hdr = prio_hdr = None
            tenant = sanitize_tenant(tenant_hdr)
            if tenant is not None:
                req = dataclasses.replace(req, tenant=tenant)
            if prio_hdr:
                # The router-stamped (ceiling-clamped) class wins over
                # the body's self-declared one.
                req = dataclasses.replace(req, priority=normalize_priority(
                    prio_hdr, default=req.priority
                ))
            # Deadline contract: `X-Deadline-Ms` header (proxy-settable,
            # wins) or JSON `deadline_ms` - a RELATIVE budget in ms from
            # server receipt.  None (the historical default) disables
            # every deadline path bit-for-bit.
            raw_dl = self.headers.get("X-Deadline-Ms")
            if raw_dl is None:
                raw_dl = body.get("deadline_ms")
            deadline = deadline_ms = None
            if raw_dl is not None:
                deadline_ms = float(raw_dl)
                if not deadline_ms > 0:
                    raise ValueError(
                        f"deadline_ms must be > 0, got {deadline_ms}"
                    )
                deadline = t0 + deadline_ms / 1e3
        except (ValueError, TypeError, json.JSONDecodeError) as e:
            st.metrics.observe_response(False)
            return 400, {"status": "error", "error": str(e)}, {}
        cells = req.problem.cells_per_step
        if st.max_lane_cells is not None and cells > st.max_lane_cells:
            # A parseable but oversized grid is rejected BEFORE it can
            # occupy a scheduler slot or force a huge program compile.
            st.metrics.observe_limit_rejected("lane_cells")
            st.metrics.observe_response(False)
            return 422, {
                "status": "error",
                "error": (
                    f"lane grid (N+1)^3 = {cells} cells exceeds "
                    f"--max-lane-cells {st.max_lane_cells}"
                ),
            }, {}
        if st.recorder is not None:
            # Accepted traffic only (post-validation, post-limits): the
            # recorded trace replays cleanly instead of re-issuing junk.
            st.recorder.record(body, request_id=rid)
        # Content-addressed result cache (serve/resultcache.py), probed
        # BEFORE the batcher: a hit answers without a queue slot, a
        # tenant in-flight slot, or a march.  Eligibility is
        # conservative - deterministic full solves only, never a
        # resume-token request; `Cache-Control: no-cache` opts this
        # request out of the lookup (counted bypass) while its fresh
        # answer still refreshes the entry.
        cache_key = None
        if st.result_cache is not None and \
                progkey.result_cache_eligible(body):
            try:
                cache_key = progkey.result_key(
                    body, st.default_kernel, platform=_jax_platform
                )
            except ValueError:
                cache_key = None
        if cache_key is not None:
            cc = (self.headers.get("Cache-Control") or "").lower()
            if "no-cache" in cc:
                st.result_cache.note_bypass()
            else:
                hit = st.result_cache.get(
                    cache_key,
                    n=req.problem.N, timesteps=req.problem.timesteps,
                    scheme=req.scheme, path=req.path, k=req.k,
                    dtype=req.dtype_name,
                )
                if hit is not None:
                    payload_bytes, _orig_timing = hit
                    headers = {"X-Wavetpu-Cache": "hit"}
                    if st.server_timing:
                        headers["Server-Timing"] = (
                            f"cache;desc=hit, total;dur="
                            f"{(time.monotonic() - t0) * 1e3:.3f}"
                        )
                    st.metrics.observe_response(True)
                    return 200, payload_bytes, headers
        if not st.try_acquire_tenant_slot(req.tenant):
            # Defensive per-tenant in-flight cap (--tenant-inflight-cap):
            # the router's token buckets are the authoritative quota;
            # this is the replica's backstop against a tenant that
            # bypasses or outraces them.  429 like quota exhaustion,
            # with the measured queue-drain estimate as the hint.
            st.metrics.observe_tenant_inflight_rejected(req.tenant)
            st.metrics.observe_response(False)
            return 429, {
                "status": "error",
                "error": (
                    f"tenant {req.tenant!r} is at its in-flight cap "
                    f"({st.tenant_inflight_cap})"
                ),
                "retriable": True,
            }, {"Retry-After": format_retry_after(
                st.metrics.retry_after_s(queue_depth)
            )}
        self._tenant_slot = req.tenant
        try:
            fut = st.batcher.submit(
                req, request_id=rid, deadline=deadline,
                trace_context=getattr(self, "_trace_context", None),
                coalesce_key=cache_key,
            )
        except QueueFullError as e:
            # Bounded-queue backpressure: shed load NOW instead of
            # stacking latency the client will time out on anyway,
            # with a Retry-After hint so a well-behaved client backs
            # off instead of hammering.  (Sub-millisecond rejections
            # stay out of the latency reservoir - they would drag p50
            # to ~0 under overload.)  Retry-After is MEASURED: the
            # queue-drain estimate from recent batch throughput, not a
            # constant - a deep backlog says "come back later", a
            # transient blip says "1s".
            st.metrics.observe_response(False)
            return 429, {
                "status": "error", "error": str(e), "retriable": True,
            }, {"Retry-After": format_retry_after(
                st.metrics.retry_after_s(queue_depth)
            )}
        except ShedError as e:
            # Brownout ladder: queue-wait p95 over threshold and this
            # request's class is at/below the rung being shed.  The
            # replica is overloaded, not broken - retriable 503 whose
            # Retry-After is the measured drain estimate the ladder
            # computed at shed time.
            st.metrics.observe_response(False)
            return 503, {
                "status": "error", "error": str(e), "retriable": True,
                "shed_rung": e.rung,
            }, {"Retry-After": format_retry_after(e.retry_after_s)}
        except Exception as e:
            # A closed batcher ("batcher is closed" during shutdown)
            # gets its 500 JSON, not a connection reset - the
            # historical handler's contract.
            st.metrics.observe_response(False)
            return 500, {"status": "error", "error": str(e)}, {}
        # The handler never outwaits the caller's deadline: with a
        # budget set, the wait on the future is bounded by it (plus a
        # small grace for a result racing in), so "no future ever hangs
        # past its deadline" holds even when the scheduler is wedged
        # mid-batch.  Without a budget the historical request_timeout
        # stands.
        wait_s = st.request_timeout
        if deadline is not None:
            wait_s = min(
                wait_s, max(0.0, deadline - time.monotonic()) + 0.050
            )
        try:
            lane_result, lane_error, batch_info = fut.result(wait_s)
        except DeadlineExceededError as e:
            # The scheduler dropped it (in queue, or mid-march between
            # chunks): 504 with attribution.  A chunked long solve's
            # expiry additionally carries `resume_token` - the
            # checkpointed march, resubmittable with a fresh budget on
            # any replica sharing --solve-state-dir.
            st.metrics.observe_response(False)
            payload = {
                "status": "error", "error": str(e),
                "deadline_ms": deadline_ms,
            }
            if e.queue_s is not None:
                payload["queue_ms"] = round(e.queue_s * 1e3, 3)
            if getattr(e, "resume_token", None) is not None:
                payload["resume_token"] = e.resume_token
            return 504, payload, {}
        except PreemptedError as e:
            # A draining replica checkpointed the march: retriable 503
            # whose body carries the resume token (the fleet router /
            # client re-inject it on the retry, which lands on the
            # rolled successor and continues from the last chunk).
            st.metrics.observe_response(False)
            payload = {
                "status": "error", "error": str(e), "retriable": True,
            }
            if e.resume_token is not None:
                payload["resume_token"] = e.resume_token
            return 503, payload, {
                "Retry-After": str(max(1, int(e.retry_after_s + 0.5))),
            }
        except InvalidStateTokenError as e:
            # Client error, never retriable, never a traceback: bad
            # format, corrupt/expired checkpoint, identity mismatch.
            st.metrics.observe_response(False)
            return 422, {"status": "error", "error": str(e)}, {}
        except QuarantinedError as e:
            # Circuit-broken program key: shed with the remaining
            # cooldown as the Retry-After hint.
            st.metrics.observe_response(False)
            return 503, {
                "status": "error", "error": str(e), "retriable": True,
            }, {"Retry-After": str(max(1, int(e.retry_after_s + 0.5)))}
        except WorkerCrashError as e:
            # The scheduler worker died mid-batch and was restarted:
            # the request itself is fine - retriable 503, never a hang.
            # Retry-After from the drain estimate: the restarted worker
            # re-marches the requeued backlog before fresh retries land.
            st.metrics.observe_response(False)
            return 503, {
                "status": "error", "error": str(e), "retriable": True,
            }, {"Retry-After": format_retry_after(
                st.metrics.retry_after_s(queue_depth)
            )}
        except FuturesTimeoutError:
            st.metrics.observe_response(False)
            # 504 only when the DEADLINE is what ran out: a budget
            # longer than request_timeout can cap the wait at the
            # timeout with budget to spare, and that case must keep the
            # historical (retriable-by-the-client) timeout 500, not
            # masquerade as an expired deadline.
            if deadline is not None and time.monotonic() >= deadline:
                return 504, {
                    "status": "error",
                    "error": (
                        f"deadline_ms {deadline_ms:g} expired while the "
                        f"request was in flight (queue + execute "
                        f"exceeded the budget)"
                    ),
                    "deadline_ms": deadline_ms,
                }, {}
            return 500, {
                "status": "error",
                "error": (
                    f"request timed out after {wait_s:g}s"
                ),
            }, {}
        except Exception as e:
            st.metrics.observe_response(False)
            return 500, {"status": "error", "error": str(e)}, {}
        finally:
            st.metrics.observe_latency(time.monotonic() - t0,
                                       request_id=rid)
        headers = {}
        timing = batch_info.get("timing")
        if st.server_timing and timing is not None:
            headers["Server-Timing"] = server_timing_header(
                timing, time.monotonic() - t0,
                warm=batch_info.get("warm"),
            )
        if lane_error is not None:
            st.metrics.observe_response(False)
            return 422, {
                "status": "error",
                "error": lane_error,
                "batch": batch_info,
            }, headers
        errors_computed = (
            st.engine.compute_errors and req.lane.c2tau2_field is None
        )
        st.metrics.observe_response(True)
        if st.shadow is not None and not getattr(req, "shadow", False):
            # Offered after the response is sent (do_POST); the sampler
            # does its own eligibility/rate/busy checks there.
            self._shadow_offer = (req, lane_result)
        payload = _ok_payload(lane_result, batch_info, errors_computed)
        if cache_key is None:
            return 200, payload, headers
        # Serialize ONCE: the stored entry and this response are the
        # same bytes, so a later hit is byte-identical by construction.
        body_bytes = json.dumps(payload).encode()
        if getattr(fut, "wavetpu_coalesced", False):
            # A singleflight rider - the primary's answer fanned out to
            # this request; the primary stores, this one just says so.
            headers["X-Wavetpu-Cache"] = "coalesced"
        elif batch_info.get("batched") and \
                batch_info.get("fallback_reason") is None:
            if st.result_cache.put(cache_key, body_bytes,
                                   headers.get("Server-Timing")):
                headers["X-Wavetpu-Cache"] = (
                    f"store;fp={st.result_cache_fp_tag or 'none'}"
                )
        return 200, body_bytes, headers


def build_server(
    host: str = "127.0.0.1",
    port: int = 0,
    bucket_sizes: Sequence[int] = (1, 2, 4, 8),
    max_batch: Optional[int] = None,
    max_wait: float = 0.025,
    max_programs: int = 8,
    compute_errors: bool = True,
    watchdog: bool = True,
    max_amp: Optional[float] = None,
    default_kernel: str = "auto",
    interpret: Optional[bool] = None,
    length_bucket_steps: Optional[int] = None,
    max_queue: Optional[int] = None,
    max_body_bytes: Optional[int] = None,
    max_lane_cells: Optional[int] = None,
    record_trace: Optional[str] = None,
    server_timing: bool = True,
    breaker_threshold: Optional[int] = 3,
    breaker_cooldown_s: float = 30.0,
    fault_plan=None,
    program_cache_dir: Optional[str] = None,
    program_cache_max_bytes: Optional[int] = None,
    chunk_threshold: Optional[int] = None,
    chunk_steps: int = 32,
    solve_state_dir: Optional[str] = None,
    solve_state_ttl_s: float = 3600.0,
    brownout: bool = True,
    brownout_thresholds: Sequence[float] = (0.5, 2.0, 8.0),
    proxy_token: Optional[str] = None,
    tenant_inflight_cap: Optional[int] = None,
    result_cache: bool = False,
    result_cache_max_bytes: Optional[int] = None,
    result_cache_ttl_s: Optional[float] = None,
    shadow_sample_rate: float = 0.0,
    shadow_deadline_s: float = 120.0,
) -> Tuple[ThreadingHTTPServer, ServerState]:
    """Assemble engine + batcher + HTTP server (port 0 = ephemeral; the
    bound port is `httpd.server_address[1]`).  Returned httpd is not yet
    serving - call `serve_forever()` (main does) or drive it from a
    thread (tests do).  `length_bucket_steps` turns on stop-length
    bucketing in the scheduler (masked-lane FLOP control - see
    DynamicBatcher); `max_queue` bounds the request queue (full ->
    429); `max_body_bytes`/`max_lane_cells` refuse oversized requests
    before scheduling (413/422); `record_trace` captures accepted
    /solve traffic into a replayable loadgen scenario trace.
    `breaker_threshold`/`breaker_cooldown_s` configure the per-
    ProgramKey circuit breaker (None disables); `fault_plan` (a
    run/faults.ServeFaultPlan, default WAVETPU_FAULT) is ONE shared
    chaos-injection plan across engine, scheduler, and handler so
    count-limited budgets mean what they say.  Engine and metrics share
    ONE MetricsRegistry so the Prometheus exposition at /metrics is a
    single consistent cut.  `program_cache_dir` adds the persistent
    disk tier under the engine's program LRU (serve/progcache.py), so
    compiled programs survive process restarts.  `chunk_threshold`
    routes solves with that many timesteps or more through the
    preemptible chunked march (serve/preempt.py; None = historical
    monolithic path only); `solve_state_dir` enables mid-flight
    checkpoints + resume tokens (shared across replicas =
    cross-replica handoff), GC'd after `solve_state_ttl_s`.
    `brownout`/`brownout_thresholds` configure the adaptive overload
    ladder (queue-wait p95 over the rungs sheds best_effort, then
    batch, then defers chunk starts; --no-brownout disables);
    `proxy_token` gates tenant/priority headers to router-stamped
    requests only, and `tenant_inflight_cap` bounds any one tenant's
    concurrent in-flight solves at this replica.  `result_cache`
    (--result-cache, default OFF) turns on the content-addressed
    result tier (serve/resultcache.py): byte-identical replay of
    deterministic full-solve answers plus singleflight coalescing of
    identical in-flight requests, bounded by
    `result_cache_max_bytes`/`result_cache_ttl_s` and invalidated on
    environment-fingerprint drift.  `shadow_sample_rate`
    (--shadow-sample-rate, default 0 = off) re-solves that fraction of
    eligible /solve responses off the hot path with the
    compensated-f32 reference plan and ledgers the measured divergence
    (serve/shadow.py, obs/accuracy.py); `shadow_deadline_s` caps each
    shadow's scheduler budget."""
    from wavetpu.obs.registry import MetricsRegistry
    from wavetpu.run import faults
    from wavetpu.serve.engine import ServeEngine
    from wavetpu.serve.scheduler import (
        BrownoutController, DynamicBatcher, ServeMetrics,
    )

    registry = MetricsRegistry()
    if fault_plan is None:
        fault_plan = faults.serve_plan_from_env()
    engine = ServeEngine(
        bucket_sizes=bucket_sizes, max_programs=max_programs,
        compute_errors=compute_errors, interpret=interpret,
        watchdog=watchdog, max_amp=max_amp, registry=registry,
        breaker_threshold=breaker_threshold,
        breaker_cooldown_s=breaker_cooldown_s, fault_plan=fault_plan,
        program_cache_dir=program_cache_dir,
        program_cache_max_bytes=program_cache_max_bytes,
    )
    metrics = ServeMetrics(registry=registry)
    state_store = None
    if solve_state_dir is not None:
        from wavetpu.serve.preempt import SolveStateStore

        state_store = SolveStateStore(solve_state_dir,
                                      ttl_s=solve_state_ttl_s)
    bo = (
        BrownoutController(thresholds=tuple(brownout_thresholds))
        if brownout else None
    )
    batcher = DynamicBatcher(
        engine, metrics=metrics, max_batch=max_batch, max_wait=max_wait,
        length_bucket_steps=length_bucket_steps, max_queue=max_queue,
        fault_plan=fault_plan, chunk_threshold=chunk_threshold,
        chunk_steps=chunk_steps, state_store=state_store, brownout=bo,
    )
    recorder = None
    if record_trace is not None:
        from wavetpu.loadgen.trace import TraceRecorder

        recorder = TraceRecorder(record_trace)
    rcache = None
    rcache_fp_tag = None
    if result_cache:
        import hashlib

        from wavetpu.serve import progcache as _progcache
        from wavetpu.serve import resultcache as _resultcache

        # The environment identity entries are valid under (a jaxlib
        # upgrade invalidates, docs/serving.md "Result cache") -
        # computed HERE so resultcache.py itself stays jax-free.
        try:
            fp = _progcache.env_fingerprint()
        except Exception:
            fp = None
        rcache = _resultcache.ResultCache(
            max_bytes=(result_cache_max_bytes
                       or _resultcache.DEFAULT_MAX_BYTES),
            ttl_s=result_cache_ttl_s or _resultcache.DEFAULT_TTL_S,
            fingerprint=fp, registry=registry, fault_plan=fault_plan,
        )
        rcache_fp_tag = hashlib.sha256(
            json.dumps(fp, sort_keys=True).encode()
        ).hexdigest()[:8]
    shadow = None
    if shadow_sample_rate > 0.0:
        from wavetpu.serve.shadow import ShadowSampler

        shadow = ShadowSampler(
            batcher, registry, shadow_sample_rate,
            fault_plan=fault_plan, deadline_s=shadow_deadline_s,
        )
    httpd = ThreadingHTTPServer((host, port), _Handler)
    httpd.wavetpu_state = ServerState(
        engine, batcher, metrics, default_kernel,
        max_body_bytes=max_body_bytes, max_lane_cells=max_lane_cells,
        recorder=recorder, server_timing=server_timing,
        fault_plan=fault_plan, proxy_token=proxy_token,
        tenant_inflight_cap=tenant_inflight_cap,
        result_cache=rcache, result_cache_fp_tag=rcache_fp_tag,
        shadow=shadow,
    )
    return httpd, httpd.wavetpu_state


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    try:
        flags = _split_flags(argv)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        print(_USAGE, file=sys.stderr)
        return 2
    if "version" in flags:
        from wavetpu import __version__

        print(f"wavetpu-serve {__version__}")
        return 0
    try:
        host = flags.get("host", "127.0.0.1")
        port = int(flags.get("port", "8077"))
        buckets = tuple(
            int(x) for x in flags.get("bucket-sizes", "1,2,4,8").split(",")
        )
        max_batch = (
            int(flags["max-batch"]) if "max-batch" in flags else None
        )
        max_wait = float(flags.get("max-wait-ms", "25")) / 1e3
        max_programs = int(flags.get("max-programs", "8"))
        length_bucket_steps = (
            int(flags["length-bucket-steps"])
            if "length-bucket-steps" in flags else None
        )
        max_queue = (
            int(flags["max-queue"]) if "max-queue" in flags else None
        )
        max_body_bytes = (
            int(flags["max-body-bytes"])
            if "max-body-bytes" in flags else None
        )
        max_lane_cells = (
            int(flags["max-lane-cells"])
            if "max-lane-cells" in flags else None
        )
        max_amp = float(flags["max-amp"]) if "max-amp" in flags else None
        breaker_threshold = (
            None if "no-breaker" in flags
            else int(flags.get("breaker-threshold", "3"))
        )
        breaker_cooldown_s = float(flags.get("breaker-cooldown-s", "30"))
        kernel = flags.get("kernel", "auto")
        if kernel not in ("auto", "roll", "pallas"):
            raise ValueError(
                f"--kernel must be auto|roll|pallas, got {kernel}"
            )
        warmup_parts = None
        if "warmup" in flags:
            warmup_parts = [int(x) for x in flags["warmup"].split(",")]
            if len(warmup_parts) not in (2, 3):
                raise ValueError("--warmup wants N,TIMESTEPS[,K]")
        warmup_manifest = None
        if "warmup-manifest" in flags:
            # Parsed at flag time (a typo'd path or a non-manifest JSON
            # is a usage error, not a silent forever-unready replica).
            from wavetpu.serve import progcache as _progcache

            warmup_manifest = _progcache.load_manifest(
                flags["warmup-manifest"]
            )
        program_cache_max_bytes = (
            int(flags["program-cache-max-bytes"])
            if "program-cache-max-bytes" in flags else None
        )
        chunk_threshold = (
            int(flags["chunk-threshold"])
            if "chunk-threshold" in flags else None
        )
        chunk_steps = int(flags.get("chunk-steps", "32"))
        solve_state_ttl_s = float(flags.get("solve-state-ttl-s", "3600"))
        brownout_thresholds = tuple(
            float(x)
            for x in flags.get("brownout-thresholds", "0.5,2,8").split(",")
        )
        if len(brownout_thresholds) != 3:
            raise ValueError(
                "--brownout-thresholds wants P1,P2,P3 (three seconds "
                "values, ascending)"
            )
        tenant_inflight_cap = (
            int(flags["tenant-inflight-cap"])
            if "tenant-inflight-cap" in flags else None
        )
        result_cache_max_bytes = (
            int(flags["result-cache-max-bytes"])
            if "result-cache-max-bytes" in flags else None
        )
        result_cache_ttl_s = (
            float(flags["result-cache-ttl-s"])
            if "result-cache-ttl-s" in flags else None
        )
        shadow_sample_rate = float(flags.get("shadow-sample-rate", "0"))
        if not 0.0 <= shadow_sample_rate <= 1.0:
            raise ValueError(
                "--shadow-sample-rate must be in [0, 1], got "
                f"{shadow_sample_rate}"
            )
        shadow_deadline_s = float(flags.get("shadow-deadline-s", "120"))
    except (ValueError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        print(_USAGE, file=sys.stderr)
        return 2

    import os

    import jax

    platform = flags.get("platform") or os.environ.get("JAX_PLATFORMS")
    if platform and platform != jax.config.jax_platforms:
        jax.config.update("jax_platforms", platform)

    httpd, state = build_server(
        host=host, port=port, bucket_sizes=buckets, max_batch=max_batch,
        max_wait=max_wait, max_programs=max_programs,
        compute_errors="no-errors" not in flags,
        watchdog="no-watchdog" not in flags, max_amp=max_amp,
        default_kernel=kernel, length_bucket_steps=length_bucket_steps,
        max_queue=max_queue, max_body_bytes=max_body_bytes,
        max_lane_cells=max_lane_cells,
        record_trace=flags.get("record-trace"),
        server_timing="no-server-timing" not in flags,
        breaker_threshold=breaker_threshold,
        breaker_cooldown_s=breaker_cooldown_s,
        program_cache_dir=flags.get("program-cache-dir"),
        program_cache_max_bytes=program_cache_max_bytes,
        chunk_threshold=chunk_threshold, chunk_steps=chunk_steps,
        solve_state_dir=flags.get("solve-state-dir"),
        solve_state_ttl_s=solve_state_ttl_s,
        brownout="no-brownout" not in flags,
        brownout_thresholds=brownout_thresholds,
        proxy_token=flags.get("proxy-token"),
        tenant_inflight_cap=tenant_inflight_cap,
        result_cache="result-cache" in flags,
        result_cache_max_bytes=result_cache_max_bytes,
        result_cache_ttl_s=result_cache_ttl_s,
        shadow_sample_rate=shadow_sample_rate,
        shadow_deadline_s=shadow_deadline_s,
    )
    if state.engine.progcache is not None:
        pc = state.engine.progcache
        mode = (
            "AOT serialized executables" if pc.usable
            else "XLA persistent-cache fallback" if pc.xla_fallback
            else "DISABLED (no mechanism)"
        )
        print(f"program cache: {pc.directory} [{mode}]")
    if state.recorder is not None:
        print(f"recording accepted /solve traffic: {flags['record-trace']}")
    if state.shadow is not None:
        print(
            f"shadow sampling: rate={state.shadow.rate} "
            f"deadline_s={state.shadow.deadline_s}"
        )
    telemetry = None
    serving = False
    try:
        if "telemetry-dir" in flags:
            # Tracing (request/batch/compile spans) + heartbeat snapshots
            # of THIS server's registry, tailable while it serves.
            from wavetpu.obs import telemetry as _tel

            telemetry = _tel.start(
                flags["telemetry-dir"], registry=state.metrics.registry
            )
            print(f"telemetry: {flags['telemetry-dir']}")
        if warmup_parts is not None or warmup_manifest is not None:
            # Warm in the BACKGROUND so /healthz answers `ready: false`
            # while the compile runs (the load balancer's routing
            # signal) instead of the listen backlog silently queueing
            # probes until the compile finishes.  A warmup failure is
            # recorded (healthz `warmup_error`) and the replica keeps
            # serving - requests compile on demand like any cold key.
            # --warmup (single tier, all buckets) and --warmup-manifest
            # (every key a ledger-report manifest names, through the
            # engine so disk adoptions land in the LRU too) share ONE
            # thread: readiness flips only once BOTH are done.
            state.warming = True

            def _warm():
                try:
                    if warmup_parts is not None:
                        wp = Problem(N=warmup_parts[0],
                                     timesteps=warmup_parts[1])
                        k = (warmup_parts[2]
                             if len(warmup_parts) == 3 else 1)
                        path = "kfused" if k > 1 else (
                            "pallas" if jax.default_backend() == "tpu"
                            else "roll"
                        )
                        warmed = state.engine.warmup(wp, path=path,
                                                     k=max(k, 2))
                        print(
                            f"warmed buckets {warmed} for N={wp.N} "
                            f"path={path}"
                        )
                    if warmup_manifest is not None:
                        from wavetpu.obs import ledger as _ledger_mod

                        n_dev = len(jax.devices())
                        done = skipped = failed = 0
                        for raw in warmup_manifest.get("keys", ()):
                            try:
                                pk = _ledger_mod.program_key_from_dict(
                                    raw
                                )
                                if pk.mesh is not None and (
                                    pk.mesh[0] * pk.mesh[1] * pk.mesh[2]
                                    > n_dev
                                ):
                                    skipped += 1
                                    continue
                                mp = Problem(
                                    N=pk.N, Np=1, Lx=pk.Lx, Ly=pk.Ly,
                                    Lz=pk.Lz, T=pk.T,
                                    timesteps=pk.timesteps,
                                )
                                if "@chunk" in pk.path:
                                    # A preemptible chunked-march key
                                    # (path "roll@chunk64"): warm it
                                    # through the engine's chunk-runner
                                    # tier - the vmapped program path
                                    # would refuse the suffix.
                                    base, _, clen = pk.path.partition(
                                        "@chunk"
                                    )
                                    state.engine.chunk_runner(
                                        mp, pk.scheme, base, pk.k,
                                        pk.dtype, int(clen),
                                    )
                                    done += 1
                                elif state.engine.program(
                                    mp, pk.scheme, pk.path, pk.k,
                                    pk.dtype, pk.with_field, pk.batch,
                                    pk.mesh,
                                ) is not None:
                                    done += 1
                                else:
                                    skipped += 1
                            except Exception as e:
                                failed += 1
                                print(f"manifest warmup key failed: "
                                      f"{e}", file=sys.stderr)
                        print(
                            f"manifest warmup: {done} warmed, "
                            f"{skipped} skipped, {failed} failed"
                        )
                        if failed:
                            state.warmup_error = (
                                f"{failed} manifest key(s) failed"
                            )
                except Exception as e:
                    state.warmup_error = str(e)
                    print(f"warmup failed: {e}", file=sys.stderr)
                finally:
                    state.warming = False

            threading.Thread(
                target=_warm, name="wavetpu-warmup", daemon=True
            ).start()

        bound = httpd.server_address
        print(
            f"wavetpu serve on http://{bound[0]}:{bound[1]} "
            f"(backend={jax.default_backend()}, max_batch="
            f"{state.batcher.max_batch}, max_wait="
            f"{state.batcher.max_wait * 1e3:g}ms, buckets="
            f"{state.engine.bucket_sizes})"
        )
        import signal

        def _shutdown(signum, frame):
            # Graceful drain: refuse new /solve (503) immediately, stop
            # the accept loop, and let the finally block flush what is
            # queued.  Shared with POST /admin/drain (fleet roll).
            state.begin_drain(httpd)

        signal.signal(signal.SIGTERM, _shutdown)
        signal.signal(signal.SIGINT, _shutdown)
        serving = True
        httpd.serve_forever()
    finally:
        # Once serving, drain=True resolves every outstanding future
        # with its RESULT (queued batches are flushed through the
        # engine) instead of erroring them; the generous timeout covers
        # a full batch solve.  Before serve started (a warmup compile
        # failure, a bad telemetry dir) there is nothing to drain -
        # close fast, and never leak the batcher worker thread, the
        # listening socket, or a running heartbeat daemon / bound
        # process tracer to an in-process caller.
        state.batcher.close(timeout=120.0 if serving else 5.0,
                            drain=serving)
        httpd.server_close()
        if state.recorder is not None:
            state.recorder.close()
        if telemetry is not None:
            telemetry.stop()
    print("wavetpu serve: shut down cleanly (drained)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
