"""Shadow-solve sampling: measured accuracy telemetry off the hot path.

`wavetpu serve --shadow-sample-rate P` re-solves a sampled fraction of
eligible production requests with the REFERENCE plan - compensated f32
on the roll path, the most accurate config the solver family has - and
ledgers the measured L-infinity divergence of the SERVED plan's answer
vs its reference twin (obs/accuracy.py, `source: "shadow"`).  That is
accuracy telemetry even where no analytic oracle exists (custom c2
fields, shifted phases): the oracle-error ledger lines cover requests
the server could verify analytically; shadow divergence covers the
rest, and for bf16/onion plans it measures exactly the rounding gap
the speed-accuracy plan table (`wavetpu plan-report`) trades against.

The shadow contract (every clause chaos-drilled in tests):

 * OFF THE HOT PATH - the primary response is computed, sent, and
   byte-identical whether or not its shadow runs; the sampler only
   ever runs AFTER the primary 200 is on the wire.
 * best_effort priority - a shadow enters the scheduler at the lowest
   QoS class, so the deficit round-robin starves it before any
   production class feels it.
 * deadline-capped - a shadow that cannot be served within
   `deadline_s` is dropped by the scheduler like any expired-budget
   request (counted as a shadow failure, nothing more).
 * ONE IN FLIGHT - a second sample while one shadow runs is skipped
   (counted), so shadow load is bounded at one lane regardless of P.
 * NEVER feeds the circuit breaker - a batch of only shadow lanes runs
   with the breaker bypassed (engine.solve(feed_breaker=False)), so a
   failing reference plan can never quarantine a program production
   traffic depends on.
 * chaos seam `WAVETPU_FAULT=serve-shadow-fail` crashes the shadow
   worker before the twin runs, proving a shadow failure is counted
   and invisible to the primary.

Shadow spans (`serve.shadow`) adopt the origin request's trace context
as their remote parent, so `wavetpu trace-report --request ID` shows
the sampled request and its reference twin in one tree.

Eligibility (the rest is counted under
`wavetpu_shadow_skipped_total{reason}`):

  reason           skipped when
  ---------------  ------------------------------------------------
  unsampled        the rate draw said no (or rate is 0)
  reference-plan   the request already IS the reference plan -
                   divergence would be identically zero
  resume           resume-token continuation (partial march; the
                   twin would not solve the same thing)
  mesh             sharded request (the reference twin is single-
                   device by definition)
  busy             one shadow already in flight
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from typing import Optional, Tuple

from wavetpu.obs import accuracy, tracing

# The reference plan: the flagship compensated velocity form in f32 on
# the roll path - the lowest-error config the bench has measured
# (max_abs_err 5.7e-6 at N=512/1000 vs 0.66 for the bf16 onion).
REFERENCE_SCHEME = "compensated"
REFERENCE_PATH = "roll"
REFERENCE_DTYPE = "f32"

DEFAULT_DEADLINE_S = 120.0

_SKIP_REASONS = ("unsampled", "reference-plan", "resume", "mesh", "busy")


class ShadowSampler:
    """One per server (ServerState.shadow); `offer()` is the only hot-
    path touch point and does a rate draw + a non-blocking busy check
    before spawning the off-path worker."""

    def __init__(self, batcher, registry, rate: float,
                 fault_plan=None, deadline_s: float = DEFAULT_DEADLINE_S,
                 seed: Optional[int] = None):
        if not 0.0 <= rate <= 1.0:
            raise ValueError(
                f"--shadow-sample-rate must be in [0, 1], got {rate}"
            )
        self.batcher = batcher
        self.registry = registry
        self.rate = float(rate)
        self.fault_plan = fault_plan
        self.deadline_s = float(deadline_s)
        self._rng = random.Random(seed)
        self._busy = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._solves = registry.counter(
            "wavetpu_shadow_solves_total",
            "completed shadow solves (divergence measured + ledgered)",
        )
        self._failures = registry.counter(
            "wavetpu_shadow_failures_total",
            "shadow solves that crashed, timed out, or were injected "
            "to fail - never visible to the primary answer",
        )
        self._skipped = registry.counter(
            "wavetpu_shadow_skipped_total",
            "offered requests not shadowed, by reason", ("reason",),
        )

    # ---- eligibility ----

    def _is_reference(self, request) -> bool:
        k = request.k if request.path == "kfused" else 1
        return (
            request.scheme == REFERENCE_SCHEME
            and request.path == REFERENCE_PATH
            and k == 1
            and request.dtype_name == REFERENCE_DTYPE
        )

    def ineligible_reason(self, request) -> Optional[str]:
        """None = eligible; else the skip-counter reason label."""
        if request.resume_token is not None:
            return "resume"
        if request.mesh_shape is not None:
            return "mesh"
        if self._is_reference(request):
            return "reference-plan"
        return None

    def reference_request(self, request):
        """The reference twin: same problem, same lane (phase, stop
        step, c2 field all ride along - the twin must solve the SAME
        physics), reference plan, best_effort class.  A c2-field lane
        keeps the standard scheme (the compensated velocity form has
        no field variant) - still the f32 roll reference for that
        physics."""
        scheme = (
            "standard" if request.lane.c2tau2_field is not None
            else REFERENCE_SCHEME
        )
        return dataclasses.replace(
            request, scheme=scheme, path=REFERENCE_PATH, k=1,
            dtype_name=REFERENCE_DTYPE, resume_token=None,
            priority="best_effort", shadow=True,
        )

    # ---- hot-path touch point ----

    def offer(self, request, lane_result, request_id: Optional[str],
              trace_context: Optional[Tuple[str, str]] = None) -> bool:
        """Called by the HTTP handler AFTER a successful primary
        response is ready; returns True when a shadow was launched.
        Everything here is host-side bookkeeping - the twin itself
        runs on the sampler's own daemon thread."""
        reason = self.ineligible_reason(request)
        if reason is None and (
            self.rate <= 0.0
            or (self.rate < 1.0 and self._rng.random() >= self.rate)
        ):
            reason = "unsampled"
        if reason is None and not self._busy.acquire(blocking=False):
            reason = "busy"
        if reason is not None:
            self._skipped.inc(reason=reason)
            return False
        t = threading.Thread(
            target=self._run, name="wavetpu-shadow", daemon=True,
            args=(request, lane_result, request_id, trace_context),
        )
        self._thread = t
        t.start()
        return True

    # ---- off-path worker ----

    def _run(self, request, lane_result, request_id, trace_context):
        span = None
        try:
            if tracing.enabled():
                span = tracing.begin_span(
                    "serve.shadow", remote=trace_context,
                    request_id=request_id,
                    scheme=request.scheme, path=request.path,
                    k=request.k, dtype=request.dtype_name,
                )
            # Chaos seam: the shadow worker dies before the twin runs.
            # Fired HERE - outside the engine - so the drill also
            # proves the breaker never hears a shadow crash.
            plan = self.fault_plan
            if plan is not None and plan.active and plan.fire(
                "shadow-fail", n=request.problem.N,
                timesteps=request.problem.timesteps,
                scheme=request.scheme, path=request.path,
                k=request.k, dtype=request.dtype_name,
            ):
                from wavetpu.run.faults import InjectedFault

                raise InjectedFault("injected shadow-solve crash")
            div = self._solve_twin(request, lane_result, request_id,
                                   trace_context)
            self._solves.inc()
            if span is not None:
                tracing.end_span(span, status="ok", divergence=div)
                span = None
        except Exception as e:
            # ANY shadow failure is a counter tick and nothing else -
            # the primary answer went out before this thread existed.
            self._failures.inc()
            if span is not None:
                tracing.end_span(span, error=str(e))
                span = None
        finally:
            if span is not None:
                tracing.end_span(span, status="ok")
            self._busy.release()

    def _solve_twin(self, request, lane_result, request_id,
                    trace_context) -> float:
        import numpy as np

        ref_req = self.reference_request(request)
        rid = f"{request_id}.shadow" if request_id else None
        deadline = time.monotonic() + self.deadline_s
        fut = self.batcher.submit(
            ref_req, request_id=rid, deadline=deadline,
            trace_context=trace_context,
        )
        ref_result, ref_error, _info = fut.result(self.deadline_s + 5.0)
        if ref_error is not None:
            raise RuntimeError(f"reference twin unhealthy: {ref_error}")
        served = np.asarray(lane_result.u_cur, dtype=np.float32)
        ref = np.asarray(ref_result.u_cur, dtype=np.float32)
        div = float(np.max(np.abs(served - ref)))
        problem = request.problem
        steps = (
            getattr(lane_result, "steps_computed", None)
            or problem.timesteps
        )
        plan = accuracy.make_plan(
            request.scheme, request.path, request.k,
            request.dtype_name,
            with_field=request.lane.c2tau2_field is not None,
        )
        accuracy.record_error_metrics(self.registry, plan, div,
                                      shadow=True)
        accuracy.record_accuracy(
            plan, problem.N, problem.timesteps, div,
            float(lane_result.solve_seconds or 0.0),
            float(problem.cells_per_step) * steps, source="shadow",
        )
        return div

    # ---- introspection ----

    def wait_idle(self, timeout: float = 60.0) -> bool:
        """Join the in-flight shadow, if any (tests + drain): True when
        no shadow is running on return."""
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout)
            return not t.is_alive()
        return True

    def snapshot(self) -> dict:
        """The /metrics JSON `shadow` block."""
        skipped = {
            reason: self._skipped.value(reason=reason)
            for reason in _SKIP_REASONS
            if self._skipped.value(reason=reason)
        }
        return {
            "rate": self.rate,
            "solves": self._solves.value(),
            "failures": self._failures.value(),
            "skipped": skipped,
        }
