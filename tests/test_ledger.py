"""Compile-cost ledger contracts (wavetpu/obs/ledger.py).

The acceptance drill: a fabricated two-restart session's what-if
savings must equal the duplicate keys' MEASURED cold-compile seconds
exactly, the warmup manifest must round-trip through ProgramKey
parsing, the ledger must survive telemetry rotation untouched
(append-only durability), and every record path must be a zero-file-I/O
no-op when telemetry is unconfigured.
"""

import json
import os

import pytest

from wavetpu.obs import ledger, telemetry, tracing


def _key(**over):
    base = dict(
        N=512, Lx=1.0, Ly=1.0, Lz=1.0, T=1.0, timesteps=1000,
        scheme="compensated", path="kfused", k=4, dtype="f32",
        with_field=False, compute_errors=True, batch=4, mesh=None,
    )
    base.update(over)
    return base


class TestLedgerDurability:
    def test_appends_across_two_process_lifetimes(self, tmp_path):
        """Two CompileLedger instances on one path = two simulated
        process lifetimes: entries accumulate, and the cold verdict is
        per-PROCESS (a restarted process is cold on a key the old one
        compiled - exactly what the what-if exists to count)."""
        p = str(tmp_path / "compile_ledger.jsonl")
        led1 = ledger.CompileLedger(p)
        led1.record(_key(), 30.25, ts=1.0, pid=111)
        led1.record(_key(batch=8), 31.5, ts=2.0, pid=111)
        led1.close()
        led2 = ledger.CompileLedger(p)  # "restart"
        rec = led2.record(_key(), 28.75, ts=10.0, pid=222)
        assert rec["cold"] is True  # fresh process: cold again
        rec2 = led2.record(_key(), 0.01, ts=11.0, pid=222)
        assert rec2["cold"] is False  # same process: in-process recompile
        led2.close()
        entries = ledger.load_ledger(p)
        assert len(entries) == 4
        assert [e["pid"] for e in entries] == [111, 111, 222, 222]

    def test_what_if_savings_equal_duplicate_cold_seconds(self, tmp_path):
        """The pinned acceptance: on a recorded two-restart session the
        persistent-cache what-if saves EXACTLY the sum of the duplicate
        keys' measured cold-compile seconds, and saved + residual equals
        the total recorded compile seconds."""
        p = str(tmp_path / "compile_ledger.jsonl")
        led = ledger.CompileLedger(p)
        # restart 1: two keys compile cold
        led.record(_key(), 30.25, ts=1.0, pid=111)
        led.record(_key(batch=8), 31.5, ts=2.0, pid=111)
        led.close()
        led = ledger.CompileLedger(p)
        # restart 2: BOTH keys recompile cold (the duplicate set) plus
        # one genuinely new key (not a duplicate, not saved)
        led.record(_key(), 28.75, ts=10.0, pid=222)
        led.record(_key(batch=8), 29.5, ts=11.0, pid=222)
        led.record(_key(scheme="standard", path="pallas", k=1),
                   5.125, ts=12.0, pid=222)
        led.close()
        agg = ledger.aggregate(ledger.load_ledger(p))
        wi = agg["what_if_persistent_cache"]
        assert wi["saved_s"] == 28.75 + 29.5  # exact, the measured values
        assert wi["served_compiles"] == 2
        assert agg["recompiled_across_restarts"] == 2
        assert wi["saved_s"] + wi["residual_s"] == agg["total_compile_s"]
        assert agg["processes"] == 2
        assert agg["distinct_keys"] == 3

    def test_in_process_warm_recompiles_not_credited(self, tmp_path):
        """Eviction churn (cold=False recompiles inside one process) is
        counted in total spend but never in the cross-process what-if -
        its cost is jax-cache dependent, so crediting it would inflate
        the savings claim."""
        p = str(tmp_path / "compile_ledger.jsonl")
        led = ledger.CompileLedger(p)
        led.record(_key(), 30.0, ts=1.0, pid=111)
        led.record(_key(), 0.5, ts=2.0, pid=111)  # churn: cold=False
        led.close()
        agg = ledger.aggregate(ledger.load_ledger(p))
        assert agg["what_if_persistent_cache"]["saved_s"] == 0.0
        assert agg["total_compile_s"] == 30.5
        assert agg["recompiled_across_restarts"] == 0

    def test_ledger_exempt_from_telemetry_rotation(self, tmp_path):
        """Rotation interplay: a tiny max_bytes rotates trace.jsonl
        (segments appear) while compile_ledger.jsonl keeps EVERY entry
        in one un-rotated file - the append-only durability the
        cross-restart accounting depends on."""
        d = str(tmp_path / "tel")
        tel = telemetry.start(d, interval=60.0, max_bytes=512, keep=2)
        try:
            for i in range(40):
                tracing.event("spam", i=i, pad="x" * 64)
                ledger.record_compile(_key(batch=i + 1), 1.0 + i,
                                      ts=float(i), pid=999)
        finally:
            tel.stop()
        assert os.path.exists(os.path.join(d, "trace.jsonl.1"))  # rotated
        lp = os.path.join(d, ledger.LEDGER_FILENAME)
        assert not os.path.exists(lp + ".1")  # ledger never rotates
        entries = ledger.load_ledger(lp)
        assert len(entries) == 40
        assert [e["key"]["batch"] for e in entries] == list(range(1, 41))

    def test_unconfigured_record_is_zero_file_io(self, tmp_path,
                                                 monkeypatch):
        """PR 5 discipline: with no telemetry, record_compile touches no
        file (nothing appears even in cwd)."""
        monkeypatch.chdir(tmp_path)
        ledger.disable()
        assert not ledger.enabled()
        ledger.record_compile(_key(), 1.0)
        assert list(tmp_path.iterdir()) == []

    def test_telemetry_configures_and_stops_ledger(self, tmp_path):
        d = str(tmp_path / "tel")
        tel = telemetry.start(d, interval=60.0)
        try:
            assert ledger.enabled()
            assert ledger.get_ledger().path == os.path.join(
                d, ledger.LEDGER_FILENAME
            )
        finally:
            tel.stop()
        assert not ledger.enabled()


class TestWarmupManifest:
    def test_manifest_shape_and_key_round_trip(self, tmp_path):
        """The manifest is the exact input shape for direction 2's
        `wavetpu warmup --manifest`: flag field, version, and every key
        round-trips dict -> ProgramKey -> dict bitwise (mesh tuples
        included)."""
        p = str(tmp_path / "compile_ledger.jsonl")
        led = ledger.CompileLedger(p)
        led.record(_key(), 30.0, ts=1.0, pid=1)
        led.record(_key(), 29.0, ts=2.0, pid=2)  # duplicate: one manifest key
        led.record(_key(scheme="standard", path="pallas", k=1,
                        mesh=[2, 1, 1]), 7.0, ts=3.0, pid=1)
        led.close()
        manifest = ledger.warmup_manifest(ledger.load_ledger(p))
        assert manifest[ledger.MANIFEST_FLAG] is True
        assert manifest["version"] == 1
        assert len(manifest["keys"]) == 2
        from wavetpu.serve.engine import ProgramKey

        for kd in manifest["keys"]:
            pk = ledger.program_key_from_dict(kd)
            assert isinstance(pk, ProgramKey)
            if kd["mesh"] is not None:
                assert pk.mesh == tuple(kd["mesh"])
            assert ledger.key_from_program_key(pk) == kd

    def test_unknown_key_fields_rejected(self):
        with pytest.raises(ValueError, match="unknown ProgramKey"):
            ledger.normalize_key(_key(bogus=1))


class TestLedgerReportCLI:
    def _fabricate(self, tmp_path):
        p = str(tmp_path / "compile_ledger.jsonl")
        led = ledger.CompileLedger(p)
        led.record(_key(), 30.25, ts=1.0, pid=111)
        # second "process": explicit cold=True (one writer instance here,
        # so the per-process auto-verdict would say warm)
        led.record(_key(), 28.75, ts=10.0, pid=222, cold=True)
        led.close()
        return p

    def test_report_accepts_dir_or_file(self, tmp_path, capsys):
        self._fabricate(tmp_path)
        assert ledger.main([str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "what-if persistent AOT cache" in out
        assert "28.750s saved" in out
        assert "recompiled across restarts: 1 key(s)" in out

    def test_report_json_and_manifest(self, tmp_path, capsys):
        p = self._fabricate(tmp_path)
        mpath = str(tmp_path / "warmup.json")
        assert ledger.main(
            [p, "--json", "--emit-warmup-manifest", mpath]
        ) == 0
        out = capsys.readouterr().out
        agg = json.loads(out[: out.rindex("}") + 1])
        assert agg["what_if_persistent_cache"]["saved_s"] == 28.75
        with open(mpath) as f:
            manifest = json.load(f)
        assert manifest[ledger.MANIFEST_FLAG] is True
        assert len(manifest["keys"]) == 1

    def test_usage_errors(self, tmp_path, capsys):
        assert ledger.main([]) == 2
        assert ledger.main(["--bogus"]) == 2
        assert ledger.main([str(tmp_path / "missing.jsonl")]) == 2
        capsys.readouterr()

    def test_malformed_lines_skipped(self, tmp_path, capsys):
        """Junk in the append-only file - non-JSON, foreign record
        types, a key with fields this version does not know (a newer
        wavetpu wrote it), a missing compile_s - is skipped and
        counted, never a crash: the report must survive any ledger a
        past or future version appended to."""
        p = self._fabricate(tmp_path)
        future_key = dict(_key(), novel_field="from-the-future")
        with open(p, "a") as f:
            f.write("not json\n{\"type\": \"other\"}\n")
            f.write(json.dumps({
                "type": "compile", "ts": 20.0, "pid": 3, "cold": True,
                "compile_s": 1.0, "key": future_key,
            }) + "\n")
            f.write(json.dumps({
                "type": "compile", "ts": 21.0, "pid": 3, "cold": True,
                "key": _key(),  # no compile_s
            }) + "\n")
        entries = ledger.load_ledger(p)
        assert len(entries) == 2
        assert ledger.main([p]) == 0  # report still runs clean
        capsys.readouterr()


class TestEngineLedgerIntegration:
    def test_engine_compiles_land_in_ledger(self, tmp_path):
        """The serve seam: a cache miss appends one cold entry whose key
        round-trips to the exact ProgramKey the engine compiled; a hit
        appends nothing; an eviction-forced recompile appends a
        cold=False entry."""
        from wavetpu.core.problem import Problem
        from wavetpu.serve.engine import ProgramKey, ServeEngine

        d = str(tmp_path / "tel")
        tel = telemetry.start(d, interval=60.0)
        try:
            problem = Problem(N=8, timesteps=4)
            eng = ServeEngine(bucket_sizes=(1,), max_programs=1,
                              interpret=True)
            assert eng.program(
                problem, "standard", "roll", 1, "f32", False, 1
            ) is not None
            eng.program(problem, "standard", "roll", 1, "f32", False, 1)
            # force an eviction, then recompile the first key
            other = Problem(N=8, timesteps=6)
            eng.program(other, "standard", "roll", 1, "f32", False, 1)
            eng.program(problem, "standard", "roll", 1, "f32", False, 1)
        finally:
            tel.stop()
        entries = ledger.load_ledger(
            os.path.join(d, ledger.LEDGER_FILENAME)
        )
        assert len(entries) == 3  # miss, miss, recompile (no hit entry)
        assert [e["cold"] for e in entries] == [True, True, False]
        pk = ledger.program_key_from_dict(entries[0]["key"])
        assert pk == ProgramKey.for_batch(
            problem, "standard", "roll", 1, "f32", False, True, 1
        )
