"""Independent numpy implementation of the reference *scheme* in the
reference's own indexing: an (N+1)^3 grid with a duplicated periodic seam
node in x and explicit Dirichlet faces in y/z.

Written from the numerical scheme described in SURVEY.md section 0 (leapfrog +
7-point Laplacian, Taylor half-step bootstrap, seam update with first-step
coefficients), NOT ported from the C++ sources.  Its purpose is to pin the
framework's fundamental-domain (N,N,N) formulation to the reference's
(N+1)^3-with-seam formulation: tests assert the two agree to rounding error,
which proves the seam-free design is the same scheme.

Deliberately slow and obvious; f64; small N only.
"""

from __future__ import annotations

import numpy as np

from wavetpu.core.problem import Problem
from wavetpu.verify.oracle import full_analytic_grid


def _interior_lap(v: np.ndarray, p: Problem) -> np.ndarray:
    """7-pt Laplacian on interior points [1..N-1]^3 of an (N+1)^3 layer."""
    c = v[1:-1, 1:-1, 1:-1]
    return (
        (v[2:, 1:-1, 1:-1] - 2 * c + v[:-2, 1:-1, 1:-1]) / p.hx**2
        + (v[1:-1, 2:, 1:-1] - 2 * c + v[1:-1, :-2, 1:-1]) / p.hy**2
        + (v[1:-1, 1:-1, 2:] - 2 * c + v[1:-1, 1:-1, :-2]) / p.hz**2
    )


def _seam_lap(v: np.ndarray, p: Problem) -> np.ndarray:
    """Laplacian on the x = N seam plane, interior (j,k), with the periodic
    wrap: x-neighbours are N-1 and 1 (node 0 duplicates node N)."""
    N = v.shape[0] - 1
    c = v[N, 1:-1, 1:-1]
    return (
        (v[N - 1, 1:-1, 1:-1] - 2 * c + v[1, 1:-1, 1:-1]) / p.hx**2
        + (v[N, 2:, 1:-1] - 2 * c + v[N, :-2, 1:-1]) / p.hy**2
        + (v[N, 1:-1, 2:] - 2 * c + v[N, 1:-1, :-2]) / p.hz**2
    )


def _zero_faces(layer: np.ndarray) -> None:
    N = layer.shape[0] - 1
    layer[:, 0, :] = 0.0
    layer[:, N, :] = 0.0
    layer[:, :, 0] = 0.0
    layer[:, :, N] = 0.0


def solve_reference(p: Problem) -> np.ndarray:
    """Full history (timesteps+1, N+1, N+1, N+1), float64."""
    N, ts = p.N, p.timesteps
    a2t2 = p.a2 * p.tau * p.tau
    u = np.zeros((ts + 1, N + 1, N + 1, N + 1), dtype=np.float64)

    # layer 0: analytic everywhere
    u[0] = full_analytic_grid(p, 0)

    # layer 1: zero faces, seam half-step, interior half-step
    _zero_faces(u[1])
    u[1][N, 1:-1, 1:-1] = u[0][N, 1:-1, 1:-1] + 0.5 * a2t2 * _seam_lap(u[0], p)
    u[1][0, 1:-1, 1:-1] = u[1][N, 1:-1, 1:-1]
    u[1][1:-1, 1:-1, 1:-1] = u[0][1:-1, 1:-1, 1:-1] + 0.5 * a2t2 * _interior_lap(
        u[0], p
    )
    _zero_faces(u[1])  # faces of the seam planes stay zero

    # layers n >= 2: leapfrog
    for n in range(2, ts + 1):
        _zero_faces(u[n])
        u[n][N, 1:-1, 1:-1] = (
            2 * u[n - 1][N, 1:-1, 1:-1]
            - u[n - 2][N, 1:-1, 1:-1]
            + a2t2 * _seam_lap(u[n - 1], p)
        )
        u[n][0, 1:-1, 1:-1] = u[n][N, 1:-1, 1:-1]
        u[n][1:-1, 1:-1, 1:-1] = (
            2 * u[n - 1][1:-1, 1:-1, 1:-1]
            - u[n - 2][1:-1, 1:-1, 1:-1]
            + a2t2 * _interior_lap(u[n - 1], p)
        )
    return u


def solve_reference_variable_c(p: Problem, c2_fn) -> np.ndarray:
    """Full history (timesteps+1, N+1, N+1, N+1), float64, under a
    spatially varying squared wave speed c^2(x, y, z).

    Written from the scheme, not from any implementation under test: the
    leapfrog update u^{n+1} = 2u^n - u^{n-1} + tau^2 c^2(x) lap(u^n) with
    the pointwise coefficient, the Taylor half-step bootstrap
    u^1 = u^0 + (tau^2 c^2(x)/2) lap(u^0), the duplicated periodic seam
    in x (node 0 == node N, wrapped neighbours N-1 and 1), and zeroed
    Dirichlet faces in y/z - exactly `solve_reference` with the scalar
    a^2 tau^2 replaced by the per-node field.  `c2_fn` takes
    broadcastable (x, y, z) coordinate arrays (same convention as
    `stencil_ref.make_c2tau2_field`).

    The fundamental-domain mapping is history[:, :N, :N, :N]: node i of
    the (N+1)-grid sits at x = i*hx, which is the framework's stored
    point i for i < N (the seam node N duplicates node 0).
    """
    N, ts = p.N, p.timesteps
    x = (np.arange(N + 1, dtype=np.float64) * p.hx)[:, None, None]
    y = (np.arange(N + 1, dtype=np.float64) * p.hy)[None, :, None]
    z = (np.arange(N + 1, dtype=np.float64) * p.hz)[None, None, :]
    c2t2 = np.broadcast_to(
        np.asarray(c2_fn(x, y, z), dtype=np.float64) * p.tau * p.tau,
        (N + 1, N + 1, N + 1),
    )
    ci = c2t2[1:-1, 1:-1, 1:-1]   # interior coefficient
    cs = c2t2[N, 1:-1, 1:-1]      # seam-plane coefficient
    u = np.zeros((ts + 1, N + 1, N + 1, N + 1), dtype=np.float64)

    u[0] = full_analytic_grid(p, 0)

    _zero_faces(u[1])
    u[1][N, 1:-1, 1:-1] = (
        u[0][N, 1:-1, 1:-1] + 0.5 * cs * _seam_lap(u[0], p)
    )
    u[1][0, 1:-1, 1:-1] = u[1][N, 1:-1, 1:-1]
    u[1][1:-1, 1:-1, 1:-1] = (
        u[0][1:-1, 1:-1, 1:-1] + 0.5 * ci * _interior_lap(u[0], p)
    )
    _zero_faces(u[1])

    for n in range(2, ts + 1):
        _zero_faces(u[n])
        u[n][N, 1:-1, 1:-1] = (
            2 * u[n - 1][N, 1:-1, 1:-1]
            - u[n - 2][N, 1:-1, 1:-1]
            + cs * _seam_lap(u[n - 1], p)
        )
        u[n][0, 1:-1, 1:-1] = u[n][N, 1:-1, 1:-1]
        u[n][1:-1, 1:-1, 1:-1] = (
            2 * u[n - 1][1:-1, 1:-1, 1:-1]
            - u[n - 2][1:-1, 1:-1, 1:-1]
            + ci * _interior_lap(u[n - 1], p)
        )
    return u


def reference_errors(p: Problem, history: np.ndarray):
    """Post-hoc per-layer L-inf abs/rel errors over interior [1..N-1]^3,
    the reference's `calculate_error` metric."""
    ts = history.shape[0] - 1
    abs_e = np.zeros(ts + 1)
    rel_e = np.zeros(ts + 1)
    for n in range(ts + 1):
        f = full_analytic_grid(p, n)
        d = np.abs(history[n] - f)[1:-1, 1:-1, 1:-1]
        abs_e[n] = d.max()
        with np.errstate(divide="ignore", invalid="ignore"):
            r = d / np.abs(f)[1:-1, 1:-1, 1:-1]
        r = np.where(np.isnan(r), 0.0, r)
        rel_e[n] = r.max()
    return abs_e, rel_e
