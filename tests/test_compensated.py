"""Compensated (Kahan) incremental leapfrog: the f32 accuracy scheme.

The round-3 verdict's accuracy gate: at the flagship N=512/1000 config the
standard f32 path reads 1.09e-3 L-inf error - ~280x the ~4e-6
discretization bound - because each step loses the tiny increment's low
bits against O(1) state.  The compensated scheme (stencil_ref
.compensated_step) accumulates the increment in its own buffer with a
two-sum carry; measured on v5e at N=512/1000: 5.69e-6 (within 1.5x of the
bound, 191x better than standard).  These tests pin the mechanism at
CPU-sized configs, including the long-run rounding growth the round-3
verdict flagged as untested.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from wavetpu.core.problem import Problem
from wavetpu.kernels import stencil_pallas, stencil_ref
from wavetpu.solver import leapfrog


def test_compensated_matches_f64_where_standard_drifts():
    """1000-step f32 run: standard-scheme rounding reaches ~1e-3 vs the
    f64 truth while the compensated scheme stays at representation level
    (~1e-7) - a four-order-of-magnitude separation."""
    p = Problem(N=32, timesteps=1000)
    u64 = np.asarray(leapfrog.solve(p, dtype=jnp.float64).u_cur)
    u32 = np.asarray(leapfrog.solve(p).u_cur, np.float64)
    uc = np.asarray(leapfrog.solve_compensated(p).u_cur, np.float64)
    std_drift = np.abs(u32 - u64).max()
    comp_drift = np.abs(uc - u64).max()
    assert std_drift > 1e-4          # rounding visibly dominates standard
    assert comp_drift < 1e-6         # compensation holds representation level
    assert comp_drift < std_drift / 100.0


def test_compensated_pallas_matches_roll(small_problem):
    """The fused Pallas compensated kernel (interpret mode) is bitwise
    against the jnp reference: identical op order per cell."""
    rc = leapfrog.solve_compensated(small_problem)
    rp = leapfrog.solve_compensated(
        small_problem,
        comp_step_fn=stencil_pallas.make_compensated_step_fn(interpret=True),
    )
    np.testing.assert_array_equal(
        np.asarray(rc.u_cur), np.asarray(rp.u_cur)
    )
    np.testing.assert_array_equal(rc.abs_errors, rp.abs_errors)


def test_compensated_step_algebraically_leapfrog(small_problem):
    """In f64 (where rounding is negligible at this size), the compensated
    scheme reproduces the standard leapfrog: the two forms are the same
    recurrence."""
    r_std = leapfrog.solve(small_problem, dtype=jnp.float64)
    r_cmp = leapfrog.solve_compensated(small_problem, dtype=jnp.float64)
    np.testing.assert_allclose(
        np.asarray(r_cmp.u_cur), np.asarray(r_std.u_cur),
        atol=1e-13, rtol=0.0,
    )
    np.testing.assert_allclose(
        r_cmp.abs_errors, r_std.abs_errors, atol=1e-13, rtol=0.0
    )


def test_compensated_rejects_bf16(small_problem):
    with pytest.raises(ValueError, match="bf16"):
        leapfrog.solve_compensated(small_problem, dtype=jnp.bfloat16)


def test_compensated_errors_layer0_zero_and_bounded(small_problem):
    r = leapfrog.solve_compensated(small_problem)
    assert r.abs_errors[0] == 0.0
    assert np.isfinite(r.abs_errors).all()
    assert r.abs_errors.max() < 1e-2


@pytest.mark.parametrize("kernel", ["roll", "pallas"])
@pytest.mark.parametrize("mesh_shape", [(2, 2, 2), (8, 1, 1)])
def test_sharded_compensated_matches_single(small_problem, mesh_shape,
                                            kernel):
    """The compensated scheme on the sharded backend (f32) stays within
    one f32 ulp of the single-device compensated solver across meshes,
    kernels, and the seam-across-shards case."""
    from wavetpu.solver import sharded

    single = leapfrog.solve_compensated(small_problem)

    res = sharded.solve_sharded(
        small_problem, mesh_shape=mesh_shape, kernel=kernel,
        scheme="compensated",
    )
    np.testing.assert_allclose(
        sharded.gather_fundamental(res.u_cur, small_problem),
        np.asarray(single.u_cur),
        atol=2e-7, rtol=0.0,
    )


def test_sharded_compensated_uneven_grid():
    from wavetpu.solver import sharded

    p = Problem(N=13, timesteps=6)
    single = leapfrog.solve_compensated(p)
    res = sharded.solve_sharded(
        p, mesh_shape=(4, 1, 1), kernel="pallas", scheme="compensated"
    )
    np.testing.assert_allclose(
        sharded.gather_fundamental(res.u_cur, p),
        np.asarray(single.u_cur),
        atol=2e-7, rtol=0.0,
    )
    u = np.asarray(res.u_cur)
    assert np.all(u[13:] == 0.0)


def test_sharded_compensated_rejects_overlap_and_field(small_problem):
    from wavetpu.kernels import stencil_ref
    from wavetpu.solver import sharded

    with pytest.raises(ValueError, match="overlap"):
        sharded.solve_sharded(
            small_problem, mesh_shape=(2, 2, 2), scheme="compensated",
            overlap=True,
        )
    field = stencil_ref.make_c2tau2_field(
        small_problem, lambda x, y, z: small_problem.a2
    )
    with pytest.raises(ValueError, match="variable-c"):
        sharded.solve_sharded(
            small_problem, mesh_shape=(2, 2, 2), scheme="compensated",
            c2tau2_field=field, compute_errors=False,
        )


def test_compensated_checkpoint_resume_bitwise(small_problem, tmp_path):
    """Kill-and-resume on the compensated scheme: the checkpoint stores
    (u, v, carry) and the resumed run is bitwise-equal to the
    uninterrupted one."""
    from wavetpu.io import checkpoint

    full = leapfrog.solve_compensated(small_problem)
    half = leapfrog.solve_compensated(small_problem, stop_step=5)
    assert half.comp_v is not None
    path = checkpoint.save_checkpoint(str(tmp_path / "ck.npz"), half)
    assert checkpoint.checkpoint_scheme(path) == "compensated"
    resumed = checkpoint.resume_solve(path)
    np.testing.assert_array_equal(
        np.asarray(resumed.u_cur), np.asarray(full.u_cur)
    )
    np.testing.assert_array_equal(
        np.asarray(resumed.comp_carry), np.asarray(full.comp_carry)
    )
    np.testing.assert_array_equal(resumed.abs_errors[6:], full.abs_errors[6:])


def test_sharded_compensated_checkpoint_resume_bitwise(
    small_problem, tmp_path
):
    """Per-shard checkpoint of the sharded compensated scheme: meta carries
    the scheme tag, shards carry v/carry, resume is bitwise."""
    from wavetpu.io import checkpoint
    from wavetpu.solver import sharded

    full = sharded.solve_sharded(
        small_problem, mesh_shape=(2, 2, 2), kernel="pallas",
        scheme="compensated",
    )
    half = sharded.solve_sharded(
        small_problem, mesh_shape=(2, 2, 2), kernel="pallas",
        scheme="compensated", stop_step=5,
    )
    ck = str(tmp_path / "ckdir")
    checkpoint.save_sharded_checkpoint(ck, half)
    _, _, _, _, scheme = checkpoint.load_sharded_meta(ck)
    assert scheme == "compensated"
    resumed = checkpoint.resume_sharded_solve(ck, kernel="pallas")
    np.testing.assert_array_equal(
        np.asarray(resumed.u_cur), np.asarray(full.u_cur)
    )
    np.testing.assert_array_equal(resumed.abs_errors[6:], full.abs_errors[6:])


def test_cli_compensated_preemption_workflow(tmp_path, capsys):
    """The full CLI preemption workflow under --scheme compensated: the
    resumed run picks up the scheme from the checkpoint and matches the
    uninterrupted run's error tail."""
    import json
    import os

    from wavetpu import cli

    base = ["16", "1", "1", "1", "1", "1", "10", "--backend", "single",
            "--scheme", "compensated"]
    full_dir, part_dir, res_dir = (
        str(tmp_path / d) for d in ("full", "part", "res")
    )
    ck = str(tmp_path / "ck.npz")
    assert cli.main(base + ["--out-dir", full_dir]) == 0
    assert cli.main(
        base + ["--out-dir", part_dir, "--stop-step", "6",
                "--save-state", ck]
    ) == 0
    assert cli.main(["--resume", ck, "--out-dir", res_dir]) == 0
    out = capsys.readouterr().out
    assert "scheme: compensated" in out  # inherited from the checkpoint
    full = json.load(open(os.path.join(full_dir, "output_N16_Np1_TPU.json")))
    res = json.load(open(os.path.join(res_dir, "output_N16_Np1_TPU.json")))
    assert res["abs_errors"][7:] == full["abs_errors"][7:]
    # It genuinely RESUMED (layers <= checkpoint step are zeroed in a
    # resumed run's report) - a from-scratch re-solve would fill them.
    assert all(e == 0.0 for e in res["abs_errors"][:7])

    # A contradicting explicit --scheme is rejected, and scheme-conditional
    # flag guards apply to the scheme inherited from the checkpoint.
    assert cli.main(
        ["--resume", ck, "--scheme", "standard", "--out-dir", res_dir]
    ) == 2
    assert cli.main(["--resume", ck, "--phase-timing"]) == 2
    capsys.readouterr()


def test_cli_scheme_compensated(tmp_path, capsys):
    import json
    import os

    from wavetpu import cli

    rc = cli.main(
        ["16", "1", "1", "1", "1", "1", "5", "--backend", "single",
         "--scheme", "compensated", "--out-dir", str(tmp_path)]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "scheme: compensated" in out
    side = json.load(open(tmp_path / "output_N16_Np1_TPU.json"))
    assert np.isfinite(side["max_abs_error"])


def test_cli_scheme_validation(capsys):
    from wavetpu import cli

    base = ["16", "1", "1", "1", "1", "1", "5"]
    assert cli.main(base + ["--scheme", "kahan"]) == 2
    assert cli.main(
        base + ["--scheme", "compensated", "--dtype", "bf16"]
    ) == 2
    capsys.readouterr()
