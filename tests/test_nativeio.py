"""Native checkpoint IO: CRC32, async writer, WTS1 container, fallback.

The C++ library (wavetpu/io/native/ckptio.cc) compiles on first use; these
tests exercise BOTH the native path and the pure-Python fallback and pin
that the two produce byte-identical files - the format is the contract,
the implementation is an accelerator.
"""

import os
import struct
import zlib

import numpy as np
import pytest

from wavetpu.io import nativeio


@pytest.fixture
def fallback(monkeypatch):
    """Force the pure-Python IO path."""
    monkeypatch.setattr(nativeio, "_lib", None)
    monkeypatch.setattr(nativeio, "_lib_tried", True)


def test_native_builds():
    """The toolchain in this image must produce the library (the fallback
    exists for exotic deployments, not for CI)."""
    assert nativeio.native_available()


@pytest.mark.parametrize("n", [0, 1, 7, 8, 64, 100_003])
def test_crc32_matches_zlib(n):
    rng = np.random.default_rng(n)
    data = rng.integers(0, 256, n, dtype=np.uint8).tobytes()
    assert nativeio.crc32(data) == zlib.crc32(data) & 0xFFFFFFFF
    # seeded / incremental
    assert (
        nativeio.crc32(data[n // 2:], nativeio.crc32(data[: n // 2]))
        == zlib.crc32(data) & 0xFFFFFFFF
    )


def _roundtrip(tmp_path, name):
    path = str(tmp_path / name)
    chunks = [b"hello ", b"", b"checkpoint " * 1000, os.urandom(12345)]
    w = nativeio.AsyncFileWriter(path)
    for c in chunks:
        w.write(c)
    crc = w.finish()
    blob = open(path, "rb").read()
    assert blob == b"".join(chunks)
    assert crc == zlib.crc32(blob) & 0xFFFFFFFF
    assert not os.path.exists(w.tmp_path)
    return blob


def test_async_writer_roundtrip(tmp_path):
    _roundtrip(tmp_path, "native.bin")


def test_async_writer_roundtrip_fallback(tmp_path, fallback):
    _roundtrip(tmp_path, "fallback.bin")


def test_async_writer_abort(tmp_path):
    path = str(tmp_path / "aborted.bin")
    w = nativeio.AsyncFileWriter(path)
    w.write(b"partial data")
    w.abort()
    assert not os.path.exists(path)
    assert not os.path.exists(w.tmp_path)


def _sample_arrays():
    rng = np.random.default_rng(0)
    f32 = rng.standard_normal((4, 6, 8)).astype(np.float32)
    bf16_bits = rng.integers(0, 2**16, (3, 5), dtype=np.uint16)
    return {
        "u_cur": (f32, "float32"),
        "u_prev": (f32 * 2, "float32"),
        "packed": (bf16_bits, "bfloat16"),
    }


def test_container_roundtrip(tmp_path):
    path = str(tmp_path / "shard.wts")
    arrays = _sample_arrays()
    nativeio.write_container_sync(path, arrays, meta={"step": 7})
    out, meta = nativeio.read_container(path)
    assert meta == {"step": 7}
    for name, (arr, tag) in arrays.items():
        got, got_tag = out[name]
        assert got_tag == tag
        np.testing.assert_array_equal(got, arr)


def test_container_native_and_fallback_bytes_identical(
    tmp_path, monkeypatch
):
    arrays = _sample_arrays()
    p_native = str(tmp_path / "n.wts")
    nativeio.write_container_sync(p_native, arrays, meta={"step": 3})
    monkeypatch.setattr(nativeio, "_lib", None)
    monkeypatch.setattr(nativeio, "_lib_tried", True)
    p_py = str(tmp_path / "p.wts")
    nativeio.write_container_sync(p_py, arrays, meta={"step": 3})
    assert open(p_native, "rb").read() == open(p_py, "rb").read()


def test_container_detects_corruption(tmp_path):
    path = str(tmp_path / "shard.wts")
    nativeio.write_container_sync(path, _sample_arrays(), meta={"step": 1})
    blob = bytearray(open(path, "rb").read())
    blob[len(blob) // 2] ^= 0x40  # flip one payload bit
    open(path, "wb").write(bytes(blob))
    with pytest.raises(ValueError, match="CRC mismatch"):
        nativeio.read_container(path)
    # verify=False skips the check (for forensic inspection)
    nativeio.read_container(path, verify=False)


def test_container_detects_truncation(tmp_path):
    path = str(tmp_path / "shard.wts")
    nativeio.write_container_sync(path, _sample_arrays(), meta={"step": 1})
    blob = open(path, "rb").read()
    open(path, "wb").write(blob[: len(blob) - 20])
    with pytest.raises(ValueError, match="truncated"):
        nativeio.read_container(path)


def test_container_rejects_foreign_file(tmp_path):
    path = str(tmp_path / "not_a_ckpt")
    open(path, "wb").write(b"something else entirely" * 10)
    with pytest.raises(ValueError, match="not a WTS1"):
        nativeio.read_container(path)


def test_sharded_checkpoint_legacy_npz_still_loads(tmp_path):
    """A pre-WTS1 per-shard checkpoint (.npz shards) still resumes."""
    import jax

    from wavetpu.core.problem import Problem
    from wavetpu.io import checkpoint as ckpt
    from wavetpu.solver import sharded

    p = Problem(N=16, timesteps=8)
    part = sharded.solve_sharded(p, mesh_shape=(2, 1, 1), stop_step=4)
    path = str(tmp_path / "ck")
    ckpt.save_sharded_checkpoint(path, part)
    # Rewrite every WTS1 shard in the legacy .npz layout and delete it.
    for fn in sorted(os.listdir(path)):
        if not fn.endswith(".wts"):
            continue
        fields, meta = nativeio.read_container(os.path.join(path, fn))
        legacy = {"step": meta["step"]}
        for name, (arr, tag) in fields.items():
            legacy[name] = arr
            legacy[f"{name}_dtype"] = tag
        np.savez(os.path.join(path, fn[:-4] + ".npz"), **legacy)
        os.remove(os.path.join(path, fn))
    res = ckpt.resume_sharded_solve(path)
    full = sharded.solve_sharded(p, mesh_shape=(2, 1, 1))
    np.testing.assert_array_equal(
        np.asarray(res.u_cur), np.asarray(full.u_cur)
    )


def test_sharded_checkpoint_corrupt_shard_rejected(tmp_path):
    from wavetpu.core.problem import Problem
    from wavetpu.io import checkpoint as ckpt
    from wavetpu.solver import sharded

    p = Problem(N=16, timesteps=8)
    part = sharded.solve_sharded(p, mesh_shape=(2, 1, 1), stop_step=4)
    path = str(tmp_path / "ck")
    ckpt.save_sharded_checkpoint(path, part)
    shard = next(
        os.path.join(path, f) for f in sorted(os.listdir(path))
        if f.endswith(".wts")
    )
    blob = bytearray(open(shard, "rb").read())
    blob[len(blob) // 2] ^= 0x01
    open(shard, "wb").write(bytes(blob))
    with pytest.raises(ValueError, match="CRC mismatch"):
        ckpt.resume_sharded_solve(path)


def test_missing_wts_shard_reported_by_current_name(tmp_path):
    """A lost .wts shard is reported as the missing .wts file, not as a
    legacy .npz the user never had."""
    from wavetpu.core.problem import Problem
    from wavetpu.io import checkpoint as ckpt
    from wavetpu.solver import sharded

    p = Problem(N=16, timesteps=8)
    part = sharded.solve_sharded(p, mesh_shape=(2, 1, 1), stop_step=4)
    path = str(tmp_path / "ck")
    ckpt.save_sharded_checkpoint(path, part)
    os.remove(os.path.join(path, "shard_0_0_0.wts"))
    with pytest.raises(FileNotFoundError, match=r"shard_0_0_0\.wts"):
        ckpt.load_sharded_checkpoint(path)


def test_write_after_finish_raises(tmp_path):
    path = str(tmp_path / "done.bin")
    w = nativeio.AsyncFileWriter(path)
    w.write(b"data")
    w.finish()
    with pytest.raises((IOError, ValueError)):
        w.write(b"more")
