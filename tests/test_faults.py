"""Fault-injection drills (run/faults.py): every corruption-rejection
branch in io/checkpoint.py + io/nativeio.py actually fires, the stale-tmp
cleanup satellite holds, and the CLI's supervised exit-code contract
(0/2/3/4) survives injected faults - no injected fault ever produces a
completed-looking result."""

import json
import os

import numpy as np
import pytest

from wavetpu import cli
from wavetpu.core.problem import Problem
from wavetpu.io import checkpoint
from wavetpu.run import faults
from wavetpu.solver import leapfrog, sharded


@pytest.fixture(scope="module")
def sharded_ckpt_state(tmp_path_factory):
    """One tiny sharded half-run checkpoint shared by the on-disk fault
    drills (each test re-copies it so the faults stay independent)."""
    p = Problem(N=16, timesteps=6)
    res = sharded.solve_sharded(
        p, mesh_shape=(2, 1, 1), kernel="roll", stop_step=3
    )
    d = tmp_path_factory.mktemp("ck") / "ck"
    checkpoint.save_sharded_checkpoint(str(d), res)
    return p, res, str(d)


def _copy_dir(src, dst):
    import shutil

    shutil.copytree(src, dst)
    return str(dst)


def _first_shard(d):
    return os.path.join(
        d, sorted(f for f in os.listdir(d) if f.endswith(".wts"))[0]
    )


def test_bitflip_rejected_by_crc(sharded_ckpt_state, tmp_path):
    _, _, src = sharded_ckpt_state
    d = _copy_dir(src, tmp_path / "flip")
    faults.flip_byte(_first_shard(d))
    with pytest.raises(ValueError, match="CRC mismatch"):
        checkpoint.load_sharded_checkpoint(d)


def test_truncated_wts_rejected(sharded_ckpt_state, tmp_path):
    _, _, src = sharded_ckpt_state
    d = _copy_dir(src, tmp_path / "trunc")
    faults.truncate_tail(_first_shard(d), drop_bytes=64)
    with pytest.raises(ValueError, match="truncated checkpoint"):
        checkpoint.load_sharded_checkpoint(d)


def test_stale_step_shard_rejected(sharded_ckpt_state, tmp_path):
    """A CRC-VALID shard carrying an older step than meta (the
    interrupted save-over-older-checkpoint) is rejected as mixed-step -
    the CRC branch must not be the only line of defense."""
    _, _, src = sharded_ckpt_state
    d = _copy_dir(src, tmp_path / "stale")
    faults.rewrite_shard_step(d, new_step=2)
    with pytest.raises(ValueError, match="interrupted mid-save"):
        checkpoint.load_sharded_checkpoint(d)


def test_stale_wts_with_good_legacy_falls_back(sharded_ckpt_state,
                                               tmp_path):
    """The WTS/legacy mixed-step fallback: when the stale WTS shard sits
    next to a legacy .npz shard that DOES carry meta's step, the loader
    assembles from the legacy file instead of failing."""
    p, res, src = sharded_ckpt_state
    d = _copy_dir(src, tmp_path / "legacy")
    shard = os.path.basename(_first_shard(d))
    starts = shard[len("shard_"):-len(".wts")]
    # Write the legacy twin with the CORRECT step from the real state.
    from wavetpu.io import nativeio

    fields, meta = nativeio.read_container(os.path.join(d, shard))
    np.savez(
        os.path.join(d, f"shard_{starts}.npz"),
        step=meta["step"],
        **{k: a for k, (a, _) in fields.items()},
    )
    faults.rewrite_shard_step(d, new_step=1, shard_name=shard)
    _, u_prev, u_cur, step, _, _, _ = checkpoint.load_sharded_checkpoint(d)
    assert step == 3
    np.testing.assert_array_equal(
        np.asarray(u_cur), np.asarray(res.u_cur)
    )


def test_truncated_npz_resume_is_clean_cli_error(small_problem, tmp_path,
                                                 capsys):
    half = leapfrog.solve(small_problem, stop_step=3)
    path = checkpoint.save_checkpoint(str(tmp_path / "ck.npz"), half)
    faults.truncate_tail(path, drop_bytes=256)
    assert cli.main(["--resume", path]) == 2
    assert "cannot load checkpoint" in capsys.readouterr().err


def test_save_cleans_stale_tmps_and_load_ignores_them(
    sharded_ckpt_state, tmp_path
):
    """A crashed writer's `*.tmp-<pid>*` debris neither survives the next
    save into the directory nor confuses the loader."""
    p, res, src = sharded_ckpt_state
    d = _copy_dir(src, tmp_path / "tmps")
    shard = os.path.basename(_first_shard(d))
    stale = [
        os.path.join(d, f"{shard}.tmp-99999"),
        os.path.join(d, "meta.npz.tmp-99999.npz"),
    ]
    for s in stale:
        with open(s, "wb") as f:
            f.write(b"\0" * 64)
    # The loader opens exact filenames only: debris is ignored.
    _, _, u_cur, step, _, _, _ = checkpoint.load_sharded_checkpoint(d)
    assert step == 3
    # The next save into the directory removes its files' stale temps.
    checkpoint.save_sharded_checkpoint(d, res)
    for s in stale:
        assert not os.path.exists(s), s


def test_cli_supervised_exit_codes_and_resume(tmp_path, capsys,
                                              monkeypatch):
    """The full CLI drill: env-injected preemption -> exit 3 with the
    resumable path printed; --resume of the rotation root completes with
    the uninterrupted run's error tail; an env-injected NaN -> exit 4;
    supervised flags are validated."""
    base = ["16", "1", "1", "1", "1", "1", "10", "--backend", "single"]
    full_dir = str(tmp_path / "full")
    assert cli.main(base + ["--out-dir", full_dir]) == 0
    rot = str(tmp_path / "rot")
    monkeypatch.setenv(faults.ENV_FAULT, "preempt:5")
    rc = cli.main(
        base + ["--ckpt-every", "3", "--ckpt-dir", rot,
                "--out-dir", str(tmp_path / "pre")]
    )
    assert rc == 3
    out = capsys.readouterr().out
    assert "resumable checkpoint:" in out
    monkeypatch.delenv(faults.ENV_FAULT)
    # Resume THE ROTATION ROOT (the latest pointer resolves inside).
    rc = cli.main(
        ["--resume", rot, "--ckpt-every", "3",
         "--out-dir", str(tmp_path / "res")]
    )
    assert rc == 0
    capsys.readouterr()
    full = json.load(
        open(os.path.join(full_dir, "output_N16_Np1_TPU.json"))
    )
    res = json.load(
        open(os.path.join(str(tmp_path / "res"),
                          "output_N16_Np1_TPU.json"))
    )
    assert res["abs_errors"][8:] == full["abs_errors"][8:]
    assert res["run_config"]["supervised"] is True
    # Injected NaN: watchdog halt, never a completed-looking exit 0.
    monkeypatch.setenv(faults.ENV_FAULT, "nan:5")
    rc = cli.main(
        base + ["--ckpt-every", "3", "--ckpt-dir",
                str(tmp_path / "rot4"),
                "--out-dir", str(tmp_path / "wd")]
    )
    assert rc == 4
    assert "watchdog" in capsys.readouterr().out
    monkeypatch.delenv(faults.ENV_FAULT)
    # Flag validation: supervised options demand --ckpt-every; a
    # supervised run cannot also --stop-step; --ckpt-every needs a dir.
    assert cli.main(base + ["--retries", "2"]) == 2
    assert cli.main(
        base + ["--ckpt-every", "3", "--ckpt-dir", rot,
                "--stop-step", "5"]
    ) == 2
    assert cli.main(base + ["--ckpt-every", "3"]) == 2
    assert cli.main(base + ["--ckpt-every", "0", "--ckpt-dir", rot]) == 2
    capsys.readouterr()


def test_cli_watchdog_catches_unstable_config(tmp_path, capsys):
    """A genuinely Courant-unstable run (no injection at all) trips the
    amplitude guard instead of reporting a garbage error norm."""
    rc = cli.main(
        ["16", "1", "1", "1", "1", "10", "10", "--backend", "single",
         "--ckpt-every", "4", "--ckpt-dir", str(tmp_path / "rot"),
         "--out-dir", str(tmp_path)]
    )
    assert rc == 4
    out = capsys.readouterr().out
    assert "watchdog: numerical-health trip" in out
