"""Multi-tenant QoS: priority classes, per-tenant quotas, brownout.

The acceptance pins for the QoS layer (ISSUE: priority-class
scheduling, per-tenant quotas, adaptive overload shedding):

 * the class ladder is ONE ladder - quota.py's stdlib-only duplicate
   must stay identical to the scheduler's;
 * WDRR keeps an interactive flood from starving best_effort, and a
   single backlogged class pays zero QoS (plain FIFO);
 * a low-priority chunked march preempted per-chunk by interactive
   traffic finishes BITWISE identical to its unloaded run;
 * token buckets answer 429 with the MEASURED refill wait, and the
   retrying client honors exactly the value the server computed;
 * the brownout ladder escalates immediately and de-escalates one
   hysteresis-gated rung at a time, never shedding interactive;
 * replicas only trust tenant/priority headers carrying the router's
   --proxy-token (spoofs are counted and served untenanted);
 * the router clamps a tenant's self-claimed class to its ceiling and
   stamps the effective one downstream;
 * loadgen's tenants mix + per-tenant report/gate close the loop.
"""

import json
import threading
import time
import types
import urllib.error
import urllib.request

import numpy as np
import pytest

from wavetpu.core.problem import Problem
from wavetpu.ensemble import batched as eb
from wavetpu.fleet import quota
from wavetpu.loadgen import report as lg_report
from wavetpu.loadgen import runner, trace
from wavetpu.serve import scheduler as sched
from wavetpu.serve.api import build_server, format_retry_after
from wavetpu.serve.engine import ServeEngine
from wavetpu.serve.resilience import ShedError
from wavetpu.serve.scheduler import (
    BrownoutController,
    DynamicBatcher,
    ServeMetrics,
    SolveRequest,
)

from tests.test_obs import parse_prometheus


# ---- the one class ladder ----

class TestClassLadder:
    def test_quota_ladder_identical_to_scheduler_ladder(self):
        # quota.py duplicates the tuple (the router must not import the
        # jax-transitive serve package); this pin is the only thing
        # keeping the two from drifting.
        assert quota.PRIORITY_CLASSES == sched.PRIORITY_CLASSES
        assert quota.DEFAULT_PRIORITY == sched.DEFAULT_PRIORITY

    def test_normalize_is_lenient_never_raises(self):
        for fn in (quota.normalize_priority, sched.normalize_priority):
            assert fn(" Interactive ") == "interactive"
            assert fn("best_effort") == "best_effort"
            assert fn(None) == "batch"
            assert fn("turbo") == "batch"
            assert fn(7) == "batch"
            assert fn("junk", default="best_effort") == "best_effort"

    def test_clamp_demotes_never_promotes(self):
        assert quota.clamp_priority("interactive", "batch") == "batch"
        assert quota.clamp_priority("best_effort", "batch") \
            == "best_effort"
        assert quota.clamp_priority("batch", "interactive") == "batch"

    def test_effective_priority_default_then_ceiling(self):
        cfg = quota.TenantConfig(
            tenant="t", priority="batch", priority_ceiling="batch"
        )
        assert cfg.effective_priority(None) == "batch"
        # a self-promotion past the ceiling is clamped, not an error
        assert cfg.effective_priority("interactive") == "batch"
        assert cfg.effective_priority("best_effort") == "best_effort"
        assert cfg.effective_priority("junk") == "batch"

    def test_parse_tenant_entry_clamps_default_to_ceiling(self):
        cfg = quota.parse_tenant_entry("k", {
            "tenant": "t", "priority": "interactive",
            "priority_ceiling": "batch",
        })
        assert cfg.priority == "batch"
        assert cfg.priority_ceiling == "batch"


# ---- token buckets + pricing ----

class TestQuota:
    def test_bucket_starts_full_and_measures_refill(self):
        b = quota.TokenBucket(rate=10.0, burst=2.0)
        ok, retry = b.try_take(2.0)
        assert ok and retry == 0.0
        ok, retry = b.try_take(1.5)
        assert not ok
        # measured wait for 1.5 tokens at 10/s: ~0.15 s (minus the
        # sliver refilled since the first take)
        assert 0.05 < retry <= 0.15
        # the refused take left the bucket untouched
        assert b.tokens() < 0.1

    def test_bucket_refills_toward_burst_cap(self):
        b = quota.TokenBucket(rate=100.0, burst=5.0)
        b.try_take(5.0)
        time.sleep(0.12)
        assert b.tokens() == pytest.approx(5.0, abs=0.5)  # capped

    def test_price_cells_is_geometric_times_path_weight(self):
        assert quota.price_cells({"N": 8, "timesteps": 6}) \
            == pytest.approx(9 ** 3 * 6)
        # unparseable bodies price 0 (the replica 400s them anyway)
        assert quota.price_cells(None) == 0.0
        assert quota.price_cells({"N": "x"}) == 0.0
        assert quota.price_cells({"N": -4, "timesteps": 6}) == 0.0

    def test_admit_clamps_oversized_cost_to_one_full_bucket(self):
        # a request bigger than the burst pays one full refill instead
        # of being unreachable forever
        cfg = quota.TenantConfig(
            tenant="t", cells_per_s=10.0, cells_burst=10.0
        )
        qm = quota.QuotaManager()
        ok, _ = qm.admit(cfg, cells=50.0)
        assert ok  # full bucket covers the clamped cost
        ok, retry = qm.admit(cfg, cells=50.0)
        assert not ok
        assert 0.5 < retry <= 1.0  # ~10 tokens / 10 per s

    def test_cells_refusal_does_not_refund_the_rps_token(self):
        cfg = quota.TenantConfig(
            tenant="t", rps=100.0, burst=100.0,
            cells_per_s=10.0, cells_burst=10.0,
        )
        qm = quota.QuotaManager()
        assert qm.admit(cfg, cells=10.0)[0]
        assert not qm.admit(cfg, cells=10.0)[0]
        # two requests arrived -> two rps tokens spent, no refund for
        # the refused one (oversized floods must not probe for free)
        assert qm._rps["t"].tokens() == pytest.approx(98.0, abs=0.5)
        assert qm.rejected_per_tenant == {"t": 1}
        assert qm.snapshot()["quota_rejected_per_tenant"] == {"t": 1}

    def test_default_buckets_cover_passthrough_tenants(self):
        qm = quota.QuotaManager(default_rps=1000.0)
        assert qm.enforces_anything
        cfg = quota.TenantConfig(tenant="walkin")  # all-None limits
        assert qm.admit(cfg, cells=0.0)[0]
        assert "walkin" in qm._rps
        assert not quota.QuotaManager().enforces_anything


# ---- measured Retry-After, server + client sides ----

class TestRetryAfter:
    def test_format_rounds_up_to_at_least_one_second(self):
        assert format_retry_after(0.2) == "1"
        assert format_retry_after(1.4) == "1"
        assert format_retry_after(1.6) == "2"

    def test_metrics_fallback_when_no_drain_history(self):
        m = ServeMetrics()
        assert m.retry_after_s(5) == 1.0
        assert m.retry_after_s(5, fallback=3.5) == 3.5

    def test_client_honors_exactly_the_servers_computation(self):
        # the pin: server-side measured seconds -> wire header ->
        # client parse round-trips to the same honored wait
        from wavetpu.client import parse_retry_after
        m = ServeMetrics()
        wire = format_retry_after(m.retry_after_s(4, fallback=2.6))
        assert parse_retry_after({"Retry-After": wire}) == 3.0


# ---- WDRR scheduling ----

class _GateEngine:
    """max_batch=1 stub whose solve() blocks until released - each
    release exposes exactly one scheduler pick, so `order` IS the
    worker's pick sequence."""

    max_batch = 1

    def __init__(self):
        self.order = []
        self.entered = threading.Semaphore(0)
        self.release = threading.Semaphore(0)

    def solve(self, problem, lanes, scheme, path, k, dtype_name,
              mesh=None, timing=None):
        self.order.append(problem.timesteps)
        self.entered.release()
        self.release.acquire()
        if timing is not None:
            timing["compile_seconds"] = 0.0
            timing["warm"] = "true"
        results = [
            types.SimpleNamespace(steps_computed=problem.timesteps)
            for _ in lanes
        ]
        return types.SimpleNamespace(
            results=results, n_lanes=len(lanes), batch_size=len(lanes),
            batched=True, fallback_reason=None, path=path,
            solve_seconds=0.0, aggregate_gcells_per_second=1.0,
        ), [None] * len(lanes)


def _qreq(timesteps, priority):
    # distinct timesteps -> distinct program keys, so nothing coalesces
    # and the engine-observed order is the raw pick order
    return SolveRequest(
        problem=Problem(N=8, timesteps=timesteps),
        lane=eb.LaneSpec(), priority=priority,
    )


def _drive(classes_by_timesteps):
    """Submit one request per (timesteps, class), with the worker held
    inside the FIRST solve so the rest stash as one backlog; release
    everything and return the engine's pick order as class names."""
    eng = _GateEngine()
    b = DynamicBatcher(eng, max_wait=0.001)
    mapping = dict(classes_by_timesteps)
    futs = []
    try:
        head_t, head_c = classes_by_timesteps[0]
        futs.append(b.submit(_qreq(head_t, head_c)))
        eng.entered.acquire(timeout=10)  # worker is inside solve #1
        for t, c in classes_by_timesteps[1:]:
            futs.append(b.submit(_qreq(t, c)))
        for _ in classes_by_timesteps[1:]:
            eng.release.release()
            eng.entered.acquire(timeout=10)
        eng.release.release()  # let the last solve return
        for f in futs:
            f.result(30)
    finally:
        eng.release.release()
        b.close()
    return [mapping[t] for t in eng.order]


class TestWDRR:
    def test_single_class_is_plain_arrival_order_fifo(self):
        plan = [(3 + i, "batch") for i in range(6)]
        assert _drive(plan) == ["batch"] * 6
        # and the engine saw strict arrival order (no reordering cost
        # for the pre-QoS single-tenant deployment)
        eng = _GateEngine()
        b = DynamicBatcher(eng, max_wait=0.001)
        try:
            futs = [b.submit(_qreq(3 + i, "batch")) for i in range(6)]
            eng.entered.acquire(timeout=10)
            for _ in range(5):
                eng.release.release()
                eng.entered.acquire(timeout=10)
            eng.release.release()
            for f in futs:
                f.result(30)
        finally:
            eng.release.release()
            b.close()
        assert eng.order == sorted(eng.order)

    def test_interactive_flood_does_not_starve_best_effort(self):
        # 40 interactive stacked against 2 best_effort: DRR's bound
        # serves best_effort at least once every ~sum(weights)=17
        # picks, so BOTH drain well before the flood does.
        plan = [(100, "best_effort"), (101, "best_effort")]
        plan += [(3 + i, "interactive") for i in range(40)]
        # head item (occupying the worker) is interactive so the two
        # best_effort submissions land in an already-contended stash
        plan = [plan[2]] + plan[:2] + plan[3:]
        order = _drive(plan)
        be = [i for i, c in enumerate(order) if c == "best_effort"]
        assert len(be) == 2
        # contention holds them back at first (interactive outbids)...
        assert be[0] > 1
        # ...but the starvation bound (one best_effort turn per
        # ~sum(weights) picks) drains both long before the flood ends
        assert be[0] <= 17
        assert be[-1] <= 2 * 17
        assert be[-1] < len(order) - 1

    def test_fresh_interactive_beats_backlogged_lower_class(self):
        # strict rule: an eligible interactive request takes the NEXT
        # pick ahead of a backlogged batch queue - its first-round
        # 16-credit outbids any deficit batch can have banked.
        eng = _GateEngine()
        b = DynamicBatcher(eng, max_wait=0.001)
        try:
            f0 = b.submit(_qreq(50, "batch"))
            eng.entered.acquire(timeout=10)
            futs = [b.submit(_qreq(3 + i, "batch")) for i in range(4)]
            fi = b.submit(_qreq(40, "interactive"))
            eng.release.release()            # finish the head batch
            eng.entered.acquire(timeout=10)  # pick #2 is now chosen
            for _ in range(4):
                eng.release.release()
                eng.entered.acquire(timeout=10)
            eng.release.release()
            f0.result(30)
            fi.result(30)
            for f in futs:
                f.result(30)
        finally:
            eng.release.release()
            b.close()
        assert eng.order[1] == 40  # the interactive one, next pass

    def test_class_counters_land_in_the_registry(self):
        m = ServeMetrics()
        eng = _GateEngine()
        b = DynamicBatcher(eng, metrics=m, max_wait=0.001)
        try:
            f = b.submit(_qreq(3, "interactive"))
            eng.entered.acquire(timeout=10)
            eng.release.release()
            f.result(30)
        finally:
            eng.release.release()
            b.close()
        assert m._class_requests.value(
            **{"class": "interactive"}
        ) == 1
        assert m._scheduled.value(**{"class": "interactive"}) == 1


# ---- brownout ladder ----

class TestBrownout:
    def _hot(self, bo, n=10, wait=1.0):
        for _ in range(n):
            bo.observe_wait(wait)

    def test_rejects_malformed_thresholds(self):
        with pytest.raises(ValueError):
            BrownoutController(thresholds=(1.0, 2.0))
        with pytest.raises(ValueError):
            BrownoutController(thresholds=(3.0, 2.0, 1.0))
        with pytest.raises(ValueError):
            BrownoutController(thresholds=(0.0, 1.0, 2.0))

    def test_escalates_immediately_across_rungs(self):
        bo = BrownoutController(
            thresholds=(0.1, 0.2, 0.3), min_samples=4,
            min_interval_s=0.0,
        )
        assert bo.update() == 0  # too few samples: healthy
        self._hot(bo, wait=0.15)
        assert bo.update() == 1
        self._hot(bo, wait=5.0)
        assert bo.update() == 3  # straight to the top, no ladder-climb

    def test_shed_policy_never_touches_interactive(self):
        bo = BrownoutController(min_interval_s=0.0)
        for rung, sheds in ((0, set()), (1, {"best_effort"}),
                            (2, {"batch", "best_effort"}),
                            (3, {"batch", "best_effort"})):
            bo._rung = rung
            assert {c for c in sched.PRIORITY_CLASSES
                    if bo.sheds(c)} == sheds
        assert bo.defers_chunk_starts()  # still at rung 3
        bo._rung = 2
        assert not bo.defers_chunk_starts()

    def test_recovery_is_one_rung_at_a_time(self):
        bo = BrownoutController(
            thresholds=(0.1, 0.2, 0.3), min_samples=4,
            min_interval_s=0.0, cooldown_s=0.0, sample_ttl_s=0.2,
        )
        self._hot(bo, wait=5.0)
        assert bo.update() == 3
        time.sleep(0.25)  # the hot samples age out of the TTL window
        assert bo.update() == 2  # never 3 -> 0 in one step
        assert bo.update() == 1
        assert bo.update() == 0
        snap = bo.snapshot()
        assert snap["rung_name"] == "healthy"
        assert snap["thresholds_s"] == [0.1, 0.2, 0.3]

    def test_cooldown_gates_deescalation(self):
        bo = BrownoutController(
            thresholds=(0.1, 0.2, 0.3), min_samples=4,
            min_interval_s=0.0, cooldown_s=60.0, sample_ttl_s=0.2,
        )
        self._hot(bo, wait=5.0)
        assert bo.update() == 3
        time.sleep(0.25)
        assert bo.update() == 3  # healthy signal but inside cooldown

    def test_submit_sheds_with_measured_retry_after(self):
        bo = BrownoutController(
            thresholds=(0.01, 10.0, 20.0), min_samples=4,
            min_interval_s=0.0,
        )
        for _ in range(8):
            bo.observe_wait(0.5)
        m = ServeMetrics()
        b = DynamicBatcher(_GateEngine(), metrics=m, max_wait=0.001,
                           brownout=bo)
        try:
            with pytest.raises(ShedError) as ei:
                b.submit(_qreq(3, "best_effort"))
            assert ei.value.rung == "shed_best_effort"
            assert ei.value.retry_after_s > 0
            # interactive and batch still board at rung 1
            fi = b.submit(_qreq(4, "interactive"))
            fb = b.submit(_qreq(5, "batch"))
            eng = b.engine
            eng.entered.acquire(timeout=10)
            eng.release.release()
            eng.entered.acquire(timeout=10)
            eng.release.release()
            fi.result(30)
            fb.result(30)
        finally:
            b.engine.release.release()
            b.close()
        assert m.snapshot()["shed_total"] == 1
        assert m._shed.value(
            rung="shed_best_effort", **{"class": "best_effort"}
        ) == 1


# ---- the bitwise isolation drill ----

class TestIsolationDrill:
    """A best_effort chunked march preempted per-chunk by interactive
    traffic must finish BITWISE identical to its unloaded run - QoS
    reorders work, it never touches numerics."""

    THRESHOLD = 8
    CHUNK = 4

    @pytest.fixture(scope="class")
    def eng(self):
        return ServeEngine(bucket_sizes=(1, 2), interpret=True)

    def _batcher(self, eng):
        return DynamicBatcher(
            eng, max_wait=0.005, chunk_threshold=self.THRESHOLD,
            chunk_steps=self.CHUNK,
        )

    def test_preempted_low_priority_march_is_bitwise_identical(
        self, eng
    ):
        p = Problem(N=8, timesteps=17)
        b = self._batcher(eng)
        try:
            control = b.submit(
                SolveRequest(problem=p, lane=eb.LaneSpec(),
                             priority="best_effort")
            ).result(300)[0]
        finally:
            b.close()
        b = self._batcher(eng)
        short = Problem(N=8, timesteps=3)
        try:
            long_fut = b.submit(SolveRequest(
                problem=p, lane=eb.LaneSpec(), priority="best_effort",
            ))
            # interactive pressure throughout the march: each chunk
            # slot competes with a fresh interactive arrival
            shorts = []
            for i in range(6):
                shorts.append(b.submit(SolveRequest(
                    problem=short, lane=eb.LaneSpec(phase=1.0 + i),
                    priority="interactive",
                )))
                time.sleep(0.01)
            short_res = [f.result(300) for f in shorts]
            res, health, info = long_fut.result(300)
        finally:
            b.close()
        assert health is None
        assert info["chunked"] is True and info["chunks"] == 4
        assert all(h is None for _, h, _ in short_res)
        # the drill's point: identical bits, loaded or not
        assert np.array_equal(np.asarray(res.u_cur),
                              np.asarray(control.u_cur))
        assert np.array_equal(np.asarray(res.u_prev),
                              np.asarray(control.u_prev))
        assert np.array_equal(np.asarray(res.abs_errors),
                              np.asarray(control.abs_errors))


# ---- replica-side tenant trust over HTTP ----

@pytest.fixture(scope="module")
def qos_server():
    httpd, state = build_server(
        port=0, max_wait=0.05, default_kernel="roll", interpret=True,
        proxy_token="sek", tenant_inflight_cap=2,
    )
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    yield base, state
    httpd.shutdown()
    state.batcher.close()
    httpd.server_close()


def _post(base, body, headers=None):
    req = urllib.request.Request(
        base + "/solve", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=120) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _metric(base, name, **labels):
    """One sample's value from a live /metrics scrape (0.0 when the
    labeled sample has not been emitted yet)."""
    req = urllib.request.Request(
        base + "/metrics", headers={"Accept": "text/plain"}
    )
    with urllib.request.urlopen(req, timeout=30) as r:
        samples, _types = parse_prometheus(r.read().decode())
    for key, value in samples.items():
        sample = key if "{" in key else key + "{"
        sname, _, rest = sample.partition("{")
        if sname != name:
            continue
        if all(f'{k}="{v}"' in rest for k, v in labels.items()):
            return value
    return 0.0


class TestReplicaTenantTrust:
    BODY = {"N": 8, "timesteps": 3, "kernel": "roll"}

    def test_spoofed_headers_are_ignored_and_counted(self, qos_server):
        base, _state = qos_server
        before = _metric(
            base, "wavetpu_serve_tenant_spoof_rejected_total"
        )
        code, payload, _h = _post(base, self.BODY, headers={
            "X-Wavetpu-Tenant": "mallory", "X-Priority": "interactive",
            "X-Wavetpu-Proxy-Token": "wrong",
        })
        assert code == 200 and payload["status"] == "ok"  # served...
        assert _metric(
            base, "wavetpu_serve_tenant_spoof_rejected_total"
        ) == before + 1  # ...but untenanted, and the spoof is counted
        assert _metric(
            base, "wavetpu_serve_tenant_requests_total",
            tenant="mallory",
        ) == 0.0
        assert _metric(
            base, "wavetpu_serve_class_requests_total",
            **{"class": "interactive"},
        ) == 0.0

    def test_router_token_unlocks_tenant_and_priority(self, qos_server):
        base, _state = qos_server
        code, payload, _h = _post(base, self.BODY, headers={
            "X-Wavetpu-Tenant": "alice", "X-Priority": "interactive",
            "X-Wavetpu-Proxy-Token": "sek",
        })
        assert code == 200 and payload["status"] == "ok"
        assert _metric(
            base, "wavetpu_serve_tenant_requests_total", tenant="alice",
        ) == 1.0
        assert _metric(
            base, "wavetpu_serve_class_requests_total",
            **{"class": "interactive"},
        ) == 1.0

    def test_body_priority_needs_no_token(self, qos_server):
        # priority in the BODY is the direct-client path: it only picks
        # a class (no tenant impersonation), so it needs no token
        base, _state = qos_server
        code, _p, _h = _post(
            base, {**self.BODY, "priority": "best_effort"}
        )
        assert code == 200
        assert _metric(
            base, "wavetpu_serve_class_requests_total",
            **{"class": "best_effort"},
        ) == 1.0

    def test_inflight_cap_acquire_release(self, qos_server):
        _base, state = qos_server
        assert state.try_acquire_tenant_slot("bob")
        assert state.try_acquire_tenant_slot("bob")
        assert not state.try_acquire_tenant_slot("bob")  # cap = 2
        assert state.try_acquire_tenant_slot("carol")  # per-tenant
        state.release_tenant_slot("bob")
        assert state.try_acquire_tenant_slot("bob")
        for _ in range(2):
            state.release_tenant_slot("bob")
        state.release_tenant_slot("carol")
        state.release_tenant_slot("ghost")  # never acquired: no-op

    def test_healthz_carries_the_brownout_block(self, qos_server):
        base, _state = qos_server
        with urllib.request.urlopen(base + "/healthz", timeout=30) as r:
            payload = json.loads(r.read())
        bo = payload["brownout"]
        assert bo["rung"] == 0 and bo["rung_name"] == "healthy"
        assert len(bo["thresholds_s"]) == 3


# ---- router quota + priority stamping, end to end ----

class TestRouterQoS:
    BODY = {"N": 8, "timesteps": 3, "kernel": "roll"}
    CELLS = float(9 ** 3 * 3)

    @pytest.fixture(scope="class")
    def stack(self):
        from wavetpu.fleet.router import build_router
        httpd, state = build_server(
            port=0, max_wait=0.05, default_kernel="roll",
            interpret=True, proxy_token="sek",
        )
        threading.Thread(
            target=httpd.serve_forever, daemon=True
        ).start()
        member = f"http://127.0.0.1:{httpd.server_address[1]}"
        keys = {
            "vk": quota.TenantConfig(
                tenant="victim", priority="interactive",
            ),
            "ak": quota.TenantConfig(
                tenant="aggressor", priority="best_effort",
                priority_ceiling="best_effort",
                cells_per_s=self.CELLS, cells_burst=self.CELLS,
            ),
        }
        rh, rs = build_router(
            [member], poll_interval_s=0.5, api_keys=keys,
            proxy_token="sek",
        )
        threading.Thread(target=rh.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{rh.server_address[1]}"
        yield base, member, rs
        rs.stop_poller()
        rh.shutdown()
        rh.server_close()
        httpd.shutdown()
        state.batcher.close()
        httpd.server_close()

    def test_quota_429_carries_refill_accurate_retry_after(
        self, stack
    ):
        base, _member, rs = stack
        # warm the program via the unlimited tenant so the aggressor's
        # two probes are back to back (a cold compile would refill the
        # bucket mid-measurement)
        code, _p, _h = _post(base, self.BODY, headers={"X-Api-Key": "vk"})
        assert code == 200
        hdr = {"X-Api-Key": "ak"}
        code, _p, _h = _post(base, self.BODY, headers=hdr)
        assert code == 200  # the full bucket covers request #1
        code, payload, h = _post(base, self.BODY, headers=hdr)
        assert code == 429
        assert payload["retriable"] is True
        retry = payload["retry_after_s"]
        # one full bucket of cells at CELLS/s refills in <= 1 s, and
        # most of it is still owed right after the spend
        assert 0.5 < retry <= 1.0
        assert h["Retry-After"] == str(max(1, int(retry + 0.5)))
        # honoring the measured value is sufficient: the bucket can
        # afford the request again exactly then
        time.sleep(retry)
        code, _p, _h = _post(base, self.BODY, headers=hdr)
        assert code == 200
        snap = rs.snapshot()
        assert snap["quota_rejected_per_tenant"]["aggressor"] >= 1

    def test_router_stamps_clamped_priority_downstream(self, stack):
        base, member, _rs = stack
        # the aggressor claims interactive; its ceiling is best_effort
        before = _metric(
            member, "wavetpu_serve_class_requests_total",
            **{"class": "best_effort"},
        )
        code = None
        for _ in range(4):  # ride out any bucket debt from prior tests
            code, _p, _h = _post(base, self.BODY, headers={
                "X-Api-Key": "ak", "X-Priority": "interactive",
            })
            if code == 200:
                break
            time.sleep(1.05)
        assert code == 200
        assert _metric(
            member, "wavetpu_serve_class_requests_total",
            **{"class": "best_effort"},
        ) == before + 1

    def test_victim_defaults_to_interactive(self, stack):
        base, member, _rs = stack
        before = _metric(
            member, "wavetpu_serve_class_requests_total",
            **{"class": "interactive"},
        )
        code, _p, _h = _post(base, self.BODY, headers={
            "X-Api-Key": "vk",
        })
        assert code == 200
        assert _metric(
            member, "wavetpu_serve_class_requests_total",
            **{"class": "interactive"},
        ) == before + 1
        assert _metric(
            member, "wavetpu_serve_tenant_requests_total",
            tenant="victim",
        ) >= 1.0

    def test_router_metrics_render_quota_counters(self, stack):
        base, _member, _rs = stack
        req = urllib.request.Request(
            base + "/metrics", headers={"Accept": "text/plain"}
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            text = r.read().decode()
        assert "wavetpu_router_quota_rejected_total" in text
        assert 'wavetpu_router_tenant_quota_rejected_total' \
            '{tenant="aggressor"}' in text


# ---- loadgen: tenants mix, per-tenant report + gate ----

class TestLoadgenQoS:
    def _scenarios(self):
        return trace.default_scenarios(n=8, timesteps=6)

    def test_gen_tenants_is_deterministic_and_labeled(self):
        kw = dict(victim_key="vk", aggressor_key="ak",
                  aggressor_mult=4)
        a = trace.generate("tenants", 10.0, 4.0,
                           scenarios=self._scenarios(), seed=7, **kw)
        b = trace.generate("tenants", 10.0, 4.0,
                           scenarios=self._scenarios(), seed=7, **kw)
        assert a == b
        tenants = {r["tenant"] for r in a}
        assert tenants == {"victim", "aggressor"}
        for r in a:
            if r["tenant"] == "victim":
                assert r["priority"] == "interactive"
                assert r["api_key"] == "vk"
            else:
                assert r["priority"] == "best_effort"
                assert r["api_key"] == "ak"
                assert r["body"]["timesteps"] == 6 * 4
        assert [r["t"] for r in a] == sorted(r["t"] for r in a)

    def test_trace_roundtrip_preserves_qos_fields(self, tmp_path):
        records = trace.generate(
            "tenants", 5.0, 4.0, scenarios=self._scenarios(), seed=3,
            victim_key="vk", aggressor_key="ak",
        )
        path = str(tmp_path / "t.jsonl")
        trace.save_scenario_trace(path, records)
        loaded = trace.load_scenario_trace(path)
        assert [r.get("tenant") for r in loaded] \
            == [r["tenant"] for r in records]
        assert [r.get("priority") for r in loaded] \
            == [r["priority"] for r in records]

    def _outcome(self, i, status, tenant, priority, latency=0.01):
        return runner.RequestOutcome(
            index=i, scenario="s", request_id=f"r{i}", status=status,
            latency_s=latency, t_sent=0.0, tenant=tenant,
            priority=priority,
        )

    def _report(self, outcomes):
        result = runner.ReplayResult(
            outcomes=outcomes, warmup_outcomes=[], metrics_before={},
            metrics_after={}, wall_seconds=1.0, mode="open",
            concurrency=1, speed=1.0, targets=["http://x"],
        )
        return lg_report.build_report(result, target="http://x")

    def test_report_breaks_down_by_tenant_and_class(self):
        outs = [
            self._outcome(0, 200, "victim", "interactive"),
            self._outcome(1, 200, "victim", "interactive"),
            self._outcome(2, 429, "aggressor", "best_effort"),
            self._outcome(3, 500, "aggressor", "best_effort"),
        ]
        report = self._report(outs)
        v = report["tenants"]["victim"]
        a = report["tenants"]["aggressor"]
        assert v["requests"] == 2 and v["errors"] == 0
        assert v["error_rate"] == 0.0 and v["p95_ms"] is not None
        assert a["rejected_429"] == 1 and a["errors"] == 1
        assert a["reject_rate"] == 0.5 and a["error_rate"] == 0.5
        assert report["classes"]["interactive"]["requests"] == 2
        assert report["classes"]["best_effort"]["requests"] == 2

    def test_untenanted_report_keeps_its_pre_qos_shape(self):
        report = self._report([
            self._outcome(0, 200, "", ""),
            self._outcome(1, 200, "", ""),
        ])
        assert "tenants" not in report
        assert "classes" not in report

    def test_gate_enforces_tenant_slos(self):
        report = self._report([
            self._outcome(0, 200, "victim", "interactive", 0.010),
            self._outcome(1, 500, "victim", "interactive", 0.500),
            self._outcome(2, 429, "aggressor", "best_effort"),
        ])
        # relax the aggregate budgets so only the tenant_slos speak:
        # the crafted 500 would otherwise also fire DEFAULT_SLO's
        # strict overall error_budget=0
        slo = {"error_budget": 1.0, "reject_budget": 1.0, "tenant_slos": {
            "victim": {"error_budget": 0.0, "p95_budget_ms": 100.0},
            "aggressor": {"reject_budget": 0.0},
            "ghost": {"error_budget": 0.0},
        }}
        names = {v["slo"] for v in lg_report.gate(report, slo=slo)}
        assert names == {
            "tenant:victim:error_budget",
            "tenant:victim:p95_budget_ms",
            "tenant:aggressor:reject_budget",
            "tenant:ghost",
        }
        # the passing configuration is quiet
        ok = {"error_budget": 1.0, "reject_budget": 1.0,
              "tenant_slos": {"victim": {"p95_budget_ms": 1000.0}}}
        assert lg_report.gate(report, slo=ok) == []
        # and the gate text surfaces the breakdown
        text = lg_report.format_gate(
            lg_report.gate(report, slo=ok), report, None
        )
        assert "tenant:victim" in text

    def test_gate_rejects_unknown_tenant_slo_keys(self):
        report = self._report([
            self._outcome(0, 200, "victim", "interactive"),
        ])
        with pytest.raises(ValueError, match="unknown tenant SLO"):
            lg_report.gate(report, slo={
                "tenant_slos": {"victim": {"p50_budget_ms": 1.0}},
            })

    def test_cli_parses_repeatable_tenant_slo_flags(self):
        from wavetpu.loadgen.cli import _parse_tenant_slos
        parsed = _parse_tenant_slos([
            "victim:error-budget=0",
            "victim:p95-budget-ms=150",
            "aggressor:reject-budget=0.5",
        ])
        assert parsed == {
            "victim": {"error_budget": 0.0, "p95_budget_ms": 150.0},
            "aggressor": {"reject_budget": 0.5},
        }
        for bad in ("victim", "victim:error-budget", "x=1",
                    "victim:p50-budget-ms=1"):
            with pytest.raises(ValueError):
                _parse_tenant_slos([bad])


class TestCheckpointPriorityStickiness:
    def test_put_records_priority_in_meta(self, tmp_path):
        # a preempted best_effort march stays best_effort across a
        # handoff however the resume request is labeled (the class was
        # clamped at ORIGINAL admission)
        from wavetpu.serve.preempt import SolveStateStore
        store = SolveStateStore(str(tmp_path / "state"))
        token = store.put(
            {"N": 8, "timesteps": 17, "chunk_len": 4},
            [np.zeros((9, 9, 9), np.float32)] * 2,
            4,
            np.zeros(18, np.float64), np.zeros(18, np.float64),
            priority="best_effort",
        )
        meta = store.load(token)[0]
        assert meta["priority"] == "best_effort"
