"""loadgen contracts: trace format + generators, replay runner, report,
regression gate, and the acceptance drills.

The acceptance-level tests drive the REAL pipeline end to end against
an in-process `wavetpu serve`:

 * record -> replay -> report: real /solve traffic captured by the
   server-side recorder replays through the HTTP runner and produces a
   loadgen_report.json with the pinned field set;
 * self-consistency: the same warmed server replayed twice produces two
   reports whose regression gate PASSES;
 * injected slowdown: a server misconfigured with a 10x max-wait makes
   the p99 gate FAIL with a non-zero CLI exit.
"""

import json
import threading

import pytest

from wavetpu.loadgen import report as lg_report
from wavetpu.loadgen import runner, trace
from wavetpu.loadgen.cli import main as loadgen_main
from wavetpu.serve.api import build_server


# ---- trace format + generators ----


class TestTraceFormat:
    def test_generate_is_deterministic(self):
        a = trace.generate("poisson", 5.0, 3.0, seed=7)
        b = trace.generate("poisson", 5.0, 3.0, seed=7)
        c = trace.generate("poisson", 5.0, 3.0, seed=8)
        assert a == b
        assert a != c

    def test_save_load_round_trip(self, tmp_path):
        recs = trace.generate("uniform", 4.0, 2.0, seed=1)
        path = str(tmp_path / "t.jsonl")
        trace.save_scenario_trace(path, recs)
        loaded = trace.load_scenario_trace(path)
        assert loaded == recs

    def test_records_are_time_ordered_and_bounded(self):
        for mix in trace.MIXES:
            recs = trace.generate(mix, 6.0, 4.0, seed=2)
            ts = [r["t"] for r in recs]
            assert ts == sorted(ts)
            assert all(0 <= t < 6.0 + 1e-9 for t in ts)
            assert all(isinstance(r["body"], dict) for r in recs)

    def test_mix_spans_scenario_knobs(self):
        """The default tier set varies the knobs the ISSUE names:
        steps, scheme, phase, c2-field presets, and (advisory) error
        budgets - plus two distinct timesteps (program identities)."""
        recs = trace.generate("uniform", 30.0, 4.0, seed=0)
        bodies = [r["body"] for r in recs]
        assert any(b.get("scheme") == "compensated" for b in bodies)
        assert any(b.get("c2_field") for b in bodies)
        assert any(b.get("phase") for b in bodies)
        assert any(b.get("steps") for b in bodies)
        assert len({b.get("timesteps") for b in bodies}) >= 2
        assert any("error_budget" in r for r in recs)

    def test_hotkey_mix_is_cache_adversarial(self):
        recs = trace.generate("hotkey", 30.0, 6.0, seed=0, distinct=10)
        # more distinct program identities (timesteps values) than the
        # serve default --max-programs 8: the LRU must thrash
        assert len({r["body"]["timesteps"] for r in recs}) > 8
        hot = sum(1 for r in recs if r["scenario"] == "small-standard")
        assert 0 < hot < len(recs)

    def test_load_rejects_broken_records(self, tmp_path):
        path = tmp_path / "bad.jsonl"
        path.write_text('{"t": -1, "body": {"N": 8}}\n')
        with pytest.raises(ValueError, match="'t'"):
            trace.load_scenario_trace(str(path))
        path.write_text('{"t": 0}\n')
        with pytest.raises(ValueError, match="body"):
            trace.load_scenario_trace(str(path))
        path.write_text("")
        with pytest.raises(ValueError, match="empty"):
            trace.load_scenario_trace(str(path))

    def test_scenario_label_derivation(self):
        assert trace.scenario_label({"N": 8, "timesteps": 20}) == \
            "N8/20-standard"
        label = trace.scenario_label({
            "N": 16, "timesteps": 10, "scheme": "compensated",
            "fuse_steps": 4, "kernel": "pallas",
        })
        assert "k4" in label and "compensated" in label

    def test_generate_cli(self, tmp_path, capsys):
        out = str(tmp_path / "t.jsonl")
        assert loadgen_main([
            "generate", "--out", out, "--mix", "diurnal",
            "--duration", "10", "--qps", "3", "--seed", "5",
        ]) == 0
        assert "wrote" in capsys.readouterr().out
        assert trace.load_scenario_trace(out)
        assert loadgen_main(["generate"]) == 2  # missing --out
        assert loadgen_main([
            "generate", "--out", out, "--mix", "nope"
        ]) == 2


# ---- server-timing parsing ----


class TestServerTimingParse:
    def test_parse(self):
        st = runner.parse_server_timing(
            "queue;dur=1.5, compile;dur=0.000, execute;dur=45.25, "
            "padding;dur=2, total;dur=47"
        )
        assert st["queue"] == pytest.approx(0.0015)
        assert st["execute"] == pytest.approx(0.04525)
        assert st["total"] == pytest.approx(0.047)

    def test_parse_tolerates_junk(self):
        assert runner.parse_server_timing(None) == {}
        assert runner.parse_server_timing("") == {}
        st = runner.parse_server_timing("a;dur=x, b;dur=3;desc=hi,,")
        assert st == {"b": 0.003}


# ---- report + gate on fabricated data ----


def _fake_report(p99=100.0, rps=10.0, error_rate=0.0, reject_rate=0.0):
    return {
        "loadgen_report": True,
        "requests": 100,
        "latency_ms": {"p50_ms": p99 / 2, "p95_ms": p99 * 0.9,
                       "p99_ms": p99, "mean_ms": p99 / 2,
                       "max_ms": p99},
        "requests_per_s": rps,
        "error_rate": error_rate,
        "reject_rate": reject_rate,
    }


class TestGate:
    def test_pass_when_within_budgets(self):
        assert lg_report.gate(
            _fake_report(), baseline=_fake_report()
        ) == []

    def test_absolute_p99_budget(self):
        v = lg_report.gate(
            _fake_report(p99=200.0), slo={"p99_budget_ms": 150.0}
        )
        assert [x["slo"] for x in v] == ["p99_budget_ms"]

    def test_error_budget_default_is_strict(self):
        v = lg_report.gate(_fake_report(error_rate=0.02))
        assert [x["slo"] for x in v] == ["error_budget"]
        assert lg_report.gate(
            _fake_report(error_rate=0.02), slo={"error_budget": 0.05}
        ) == []

    def test_reject_budget_optional(self):
        assert lg_report.gate(_fake_report(reject_rate=0.5)) == []
        v = lg_report.gate(
            _fake_report(reject_rate=0.5), slo={"reject_budget": 0.1}
        )
        assert [x["slo"] for x in v] == ["reject_budget"]

    def test_p99_regression_vs_baseline(self):
        base = _fake_report(p99=100.0)
        assert lg_report.gate(
            _fake_report(p99=140.0), baseline=base
        ) == []  # +40% < default 50%
        v = lg_report.gate(_fake_report(p99=160.0), baseline=base)
        assert [x["slo"] for x in v] == ["p99_regression_pct"]

    def test_throughput_floor_vs_baseline(self):
        base = _fake_report(rps=10.0)
        assert lg_report.gate(
            _fake_report(rps=6.0), baseline=base
        ) == []  # -40% > default -50% floor
        v = lg_report.gate(_fake_report(rps=4.0), baseline=base)
        assert [x["slo"] for x in v] == ["throughput_floor_pct"]

    def test_unknown_slo_key_is_loud(self):
        with pytest.raises(ValueError, match="unknown SLO"):
            lg_report.gate(_fake_report(), slo={"p99": 1.0})

    def test_format_gate_names_violations(self):
        base = _fake_report(p99=100.0)
        new = _fake_report(p99=300.0)
        v = lg_report.gate(new, baseline=base)
        text = lg_report.format_gate(v, new, base)
        assert "FAIL" in text and "p99_regression_pct" in text
        assert "-> FAIL" in text
        assert "-> PASS" in lg_report.format_gate([], base, base)

    def test_gate_cli_exit_codes(self, tmp_path, capsys):
        ok = tmp_path / "ok.json"
        slow = tmp_path / "slow.json"
        base = tmp_path / "base.json"
        base.write_text(json.dumps(_fake_report(p99=100.0)))
        ok.write_text(json.dumps(_fake_report(p99=110.0)))
        slow.write_text(json.dumps(_fake_report(p99=400.0)))
        assert loadgen_main([
            "gate", str(ok), "--baseline", str(base)
        ]) == 0
        assert "PASS" in capsys.readouterr().out
        assert loadgen_main([
            "gate", str(slow), "--baseline", str(base)
        ]) == 1
        assert "FAIL" in capsys.readouterr().out
        # the knob widens the gate back to passing
        assert loadgen_main([
            "gate", str(slow), "--baseline", str(base),
            "--p99-regression-pct", "400",
        ]) == 0
        # usage errors are 2, not violations
        assert loadgen_main(["gate", str(ok)]) == 2
        assert loadgen_main([
            "gate", str(tmp_path / "nope.json"), "--baseline", str(base)
        ]) == 2
        # a non-report JSON is refused
        bad = tmp_path / "bad.json"
        bad.write_text("{}")
        assert loadgen_main([
            "gate", str(bad), "--baseline", str(base)
        ]) == 2


# ---- HTTP end to end ----


@pytest.fixture()
def server(tmp_path):
    """In-process serve stack with traffic recording on."""
    record = str(tmp_path / "recorded.jsonl")
    httpd, state = build_server(
        port=0, max_wait=0.02, default_kernel="roll", interpret=True,
        record_trace=record,
    )
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    yield base, state, record
    httpd.shutdown()
    state.batcher.close()
    httpd.server_close()
    if state.recorder is not None:
        state.recorder.close()


def _mini_scenarios():
    # Two tiers, one program identity dominant: small and fast on the
    # CI CPU backend while still exercising per-tier reporting.
    return [
        {"name": "a", "weight": 3, "error_budget": 1e-3,
         "body": {"N": 8, "timesteps": 4}},
        {"name": "b", "weight": 1,
         "body": {"N": 8, "timesteps": 4, "phase": 1.0}},
    ]


class TestPreflight:
    def test_ok(self, server):
        base, _, _ = server
        health = runner.preflight(base)
        assert health["status"] == "ok"

    def test_draining_server_refused(self, server):
        base, state, _ = server
        state.draining = True
        try:
            with pytest.raises(runner.PreflightError, match="draining"):
                runner.preflight(base)
        finally:
            state.draining = False

    def test_unreachable_refused_and_cli_exit_2(self, tmp_path):
        with pytest.raises(runner.PreflightError, match="cannot reach"):
            runner.preflight("http://127.0.0.1:9")  # discard port
        path = str(tmp_path / "t.jsonl")
        trace.save_scenario_trace(
            path, trace.generate("uniform", 1.0, 2.0,
                                 scenarios=_mini_scenarios())
        )
        assert loadgen_main([
            "replay", path, "--target", "http://127.0.0.1:9",
        ]) == 2


class TestReplayRoundTrip:
    def test_record_replay_report_fields(self, server, tmp_path):
        """The tentpole round trip: real traffic -> recorded trace ->
        replay -> report with the pinned field set."""
        base, state, record = server
        # 1. offer real traffic (the recorder captures it)
        seed_trace = trace.generate(
            "uniform", 1.0, 6.0, scenarios=_mini_scenarios(), seed=4
        )
        first = runner.replay(base, seed_trace, mode="closed",
                              concurrency=2, timeout=300)
        assert all(o.status == 200 for o in first.outcomes)
        # 2. the recorded file is itself a loadable scenario trace of
        # exactly the accepted requests
        recorded = trace.load_scenario_trace(record)
        assert len(recorded) == len(seed_trace)
        assert all(r["body"]["N"] == 8 for r in recorded)
        assert all("id" in r or "scenario" in r for r in recorded)
        # 3. replay the RECORDED trace and build the report
        res = runner.replay(base, recorded, mode="closed",
                            concurrency=2, warmup=2, timeout=300)
        rep = lg_report.build_report(res, trace_path=record, target=base)
        assert rep["loadgen_report"] is True
        assert rep["requests"] == len(recorded)
        assert rep["ok"] == len(recorded)
        assert rep["errors"] == 0 and rep["rejected_429"] == 0
        lat = rep["latency_ms"]
        assert lat["p50_ms"] <= lat["p95_ms"] <= lat["p99_ms"] \
            <= lat["max_ms"]
        # per-tier rows exist with their own percentiles
        assert set(rep["tiers"]) >= {"N8/4-standard"}
        for tier in rep["tiers"].values():
            assert tier["requests"] >= 1
            assert tier["p99_ms"] >= tier["p50_ms"]
        # server-side window deltas: occupancy, compiles, throughput
        srv = rep["server"]
        assert srv["batches"] >= 1
        assert srv["occupancy_mean"] >= 1.0
        assert srv["cold_compiles"] == 0  # warmed by the first replay
        assert srv["warm_hits"] >= 1
        assert srv["aggregate_gcells_per_s"] is not None
        # Server-Timing attribution made it through the HTTP client
        assert rep["server_timing_mean_ms"] is not None
        assert rep["server_timing_mean_ms"]["execute"] > 0
        # the slowest requests carry join handles (minted request ids)
        assert rep["slowest_requests"][0]["request_id"].startswith("lg-")

    def test_open_loop_honors_trace_spacing(self, server):
        base, _, _ = server
        recs = [
            {"t": 0.0, "scenario": "a", "body": {"N": 8, "timesteps": 4}},
            {"t": 0.4, "scenario": "a",
             "body": {"N": 8, "timesteps": 4, "phase": 1.0}},
        ]
        res = runner.replay(base, recs, mode="open", warmup=1,
                            timeout=300)
        assert res.wall_seconds >= 0.4  # waited for the second arrival
        assert [o.status for o in res.outcomes] == [200, 200]
        # speed=4 compresses the same trace
        res = runner.replay(base, recs, mode="open", speed=4.0,
                            timeout=300)
        assert res.outcomes[1].t_sent < 0.4

    def test_replay_cli_writes_report(self, server, tmp_path, capsys):
        base, _, _ = server
        path = str(tmp_path / "t.jsonl")
        out = str(tmp_path / "report.json")
        trace.save_scenario_trace(
            path, trace.generate("uniform", 1.0, 4.0,
                                 scenarios=_mini_scenarios())
        )
        assert loadgen_main([
            "replay", path, "--target", base, "--mode", "closed",
            "--concurrency", "2", "--warmup", "2", "--out", out,
            "--timeout", "300",
        ]) == 0
        assert "replayed" in capsys.readouterr().out
        rep = lg_report.load_report(out)
        assert rep["ok"] == rep["requests"]


class TestSoakAndRetries:
    """The resilience-round loadgen satellites: `--duration` soak mode
    (loop the trace until a wall-clock budget elapses) and `--retries`
    (the retrying WavetpuClient behind the runner)."""

    def test_closed_loop_duration_soak_loops_the_trace(self, server):
        base, _, _ = server
        recs = trace.generate(
            "uniform", 0.2, 10.0, scenarios=_mini_scenarios(), seed=3
        )
        res = runner.replay(base, recs, mode="closed", concurrency=2,
                            duration=1.5, timeout=300)
        assert res.wall_seconds >= 1.5
        # the trace (2 requests) looped: more outcomes than records
        assert len(res.outcomes) > len(recs)
        assert all(o.status == 200 for o in res.outcomes)
        rep = lg_report.build_report(res, target=base)
        assert rep["requests"] == len(res.outcomes)
        assert rep["attempts_total"] == rep["requests"]  # no retries

    def test_open_loop_duration_extends_schedule(self):
        recs = [
            {"t": 0.0, "scenario": "a", "body": {"N": 8}},
            {"t": 0.3, "scenario": "b", "body": {"N": 8}},
        ]
        ext = runner.extend_for_duration(recs, duration=1.0)
        assert len(ext) > len(recs)
        ts = [r["t"] for r in ext]
        assert ts == sorted(ts)
        assert len(ts) == len(set(ts))  # laps never collide
        assert all(t < 1.0 for t in ts)
        # speed compresses: a 2x speed fits twice the laps
        assert len(runner.extend_for_duration(recs, 1.0, speed=2.0)) \
            > len(ext)

    def test_bad_duration_and_retries_rejected(self, server):
        base, _, _ = server
        recs = [{"t": 0.0, "scenario": "a", "body": {"N": 8}}]
        with pytest.raises(ValueError, match="duration"):
            runner.replay(base, recs, duration=0.0)
        with pytest.raises(ValueError, match="retries"):
            runner.replay(base, recs, retries=-1)

    def test_retries_absorb_injected_connection_drops(self, tmp_path):
        """The chaos half: a server that drops the first two
        connections produces transport errors without retries and a
        clean report WITH them - attempts accounting pins that the
        retries actually happened."""
        from wavetpu.run import faults

        plan = faults.parse_serve_spec("serve-conn-drop:count=2")
        httpd, state = build_server(
            port=0, max_wait=0.02, default_kernel="roll",
            interpret=True, fault_plan=plan,
        )
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        recs = trace.generate(
            "uniform", 0.5, 8.0, scenarios=_mini_scenarios(), seed=6
        )
        try:
            res = runner.replay(base, recs, mode="closed",
                                concurrency=1, retries=3, timeout=300)
            assert all(o.status == 200 for o in res.outcomes)
            # both drops were absorbed by retries (they may land on one
            # logical request - its retry can be the second drop - or
            # on two)
            retried = [o for o in res.outcomes if o.attempts > 1]
            assert sum(o.attempts - 1 for o in res.outcomes) == 2
            assert 1 <= len(retried) <= 2
            rep = lg_report.build_report(res, target=base)
            assert rep["errors"] == 0
            assert rep["retried_requests"] == len(retried)
            assert rep["attempts_total"] == rep["requests"] + 2
            # Per-tier breakout: the tier rows must partition the
            # aggregate retry accounting exactly (which tier absorbed
            # the drops is the question the aggregate-only fields hid).
            assert sum(
                t["attempts_total"] for t in rep["tiers"].values()
            ) == rep["attempts_total"]
            assert sum(
                t["retried_requests"] for t in rep["tiers"].values()
            ) == rep["retried_requests"]
            retried_tiers = {o.scenario for o in retried}
            for tier, row in rep["tiers"].items():
                assert (row["retried_requests"] > 0) == (
                    tier in retried_tiers
                )
        finally:
            httpd.shutdown()
            state.batcher.close()
            httpd.server_close()

    def test_replay_cli_slo_gate_without_baseline(
        self, server, tmp_path, capsys
    ):
        """`replay --error-budget 0` gates a baseline-less replay (the
        nightly chaos smoke's zero-client-visible-errors check)."""
        base, _, _ = server
        path = str(tmp_path / "t.jsonl")
        trace.save_scenario_trace(
            path, trace.generate("uniform", 0.5, 6.0,
                                 scenarios=_mini_scenarios(), seed=2)
        )
        assert loadgen_main([
            "replay", path, "--target", base, "--mode", "closed",
            "--concurrency", "2", "--timeout", "300",
            "--retries", "2", "--error-budget", "0",
        ]) == 0
        out = capsys.readouterr().out
        assert "-> PASS" in out and "retries:" in out
        # a RELATIVE-only flag set does not gate a baseline-less
        # replay (relative gates need a baseline; the strict default
        # error budget must not kick in off an unrelated flag)
        assert loadgen_main([
            "replay", path, "--target", base, "--mode", "closed",
            "--concurrency", "2", "--timeout", "300",
            "--p99-regression-pct", "300",
        ]) == 0
        assert "-> " not in capsys.readouterr().out  # no gate ran


# ---- multi-target fan-out (repeated --target) ----


def _second_server():
    httpd, state = build_server(
        port=0, max_wait=0.02, default_kernel="roll", interpret=True,
    )
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, state, f"http://127.0.0.1:{httpd.server_address[1]}"


class TestMultiTarget:
    def test_round_robin_reaches_both_and_per_target_breakdown(
        self, server
    ):
        """`replay([url1, url2])`: requests fan out round-robin, warmup
        serves every tier at EVERY target, the bracketing /metrics cuts
        are summed fleet-wide, and the report grows a per-target
        breakdown."""
        base1, _, _ = server
        h2, s2, base2 = _second_server()
        try:
            records = trace.generate(
                "uniform", 1.0, 6.0, scenarios=_mini_scenarios(), seed=5
            )
            res = runner.replay(
                [base1, base2], records, mode="closed", concurrency=2,
                warmup=2, timeout=300,
            )
            assert res.targets == [base1, base2]
            assert {o.target for o in res.outcomes} == {base1, base2}
            # warmup = one request per tier per TARGET (one replica
            # warm is not the fleet warm)
            assert len(res.warmup_outcomes) == 4
            assert {o.target for o in res.warmup_outcomes} == \
                {base1, base2}
            # summed metrics cuts: the fleet-wide accepted-request
            # counter grew by warmup + measured requests
            name = "wavetpu_serve_requests_total"
            grown = (res.metrics_after.get(name, 0.0)
                     - res.metrics_before.get(name, 0.0))
            assert grown == len(res.outcomes)
            rep = lg_report.build_report(res, target=[base1, base2])
            assert rep["targets"] == [base1, base2]
            per = rep["per_target"]
            assert set(per) == {base1, base2}
            assert sum(r["requests"] for r in per.values()) == \
                rep["requests"]
            for row in per.values():
                assert row["ok"] == row["requests"]
                assert row["errors"] == 0
                assert row["p95_ms"] >= 0.0
        finally:
            h2.shutdown()
            s2.batcher.close()
            h2.server_close()

    def test_cli_repeated_target_flag(self, server, tmp_path, capsys):
        base1, _, _ = server
        h2, s2, base2 = _second_server()
        try:
            path = str(tmp_path / "t.jsonl")
            trace.save_scenario_trace(path, trace.generate(
                "uniform", 1.0, 4.0, scenarios=_mini_scenarios(),
                seed=12,
            ))
            out = str(tmp_path / "rep.json")
            assert loadgen_main([
                "replay", path, "--target", base1, "--target", base2,
                "--mode", "closed", "--concurrency", "2",
                "--warmup", "2", "--out", out, "--timeout", "300",
            ]) == 0
            printed = capsys.readouterr().out
            # the per-target summary lines name both replicas
            assert base1 in printed and base2 in printed
            rep = lg_report.load_report(out)
            assert rep["targets"] == [base1, base2]
            assert set(rep["per_target"]) == {base1, base2}
        finally:
            h2.shutdown()
            s2.batcher.close()
            h2.server_close()


class TestAcceptance:
    """ISSUE acceptance: self-consistency gate passes on a warmed
    server; an injected slowdown fails the p99 gate with exit != 0."""

    def _trace(self, tmp_path):
        path = str(tmp_path / "t.jsonl")
        trace.save_scenario_trace(path, trace.generate(
            "uniform", 1.0, 8.0, scenarios=_mini_scenarios(), seed=9
        ))
        return path

    def test_self_consistency_then_injected_slowdown(
        self, server, tmp_path, capsys
    ):
        base, _, record = server
        path = self._trace(tmp_path)
        r1 = str(tmp_path / "r1.json")
        r2 = str(tmp_path / "r2.json")
        common = ["--target", base, "--mode", "closed",
                  "--concurrency", "2", "--warmup", "2",
                  "--timeout", "300"]
        assert loadgen_main(["replay", path, *common, "--out", r1]) == 0
        # replay 2 vs replay 1 on the same warmed server: the gate
        # passes (generous tolerances - CI CPU latencies at N=8 scale
        # are noisy; the injected failure below is a 50x signal)
        assert loadgen_main([
            "replay", path, *common, "--out", r2, "--baseline", r1,
            "--p99-regression-pct", "400",
            "--throughput-floor-pct", "80",
        ]) == 0
        assert "-> PASS" in capsys.readouterr().out

        # the slowdown: the same stack misconfigured with a 25x
        # max-wait (500 ms vs 20 ms) - every batch idles out the window
        slow_httpd, slow_state = build_server(
            port=0, max_wait=0.5, default_kernel="roll", interpret=True,
        )
        t = threading.Thread(
            target=slow_httpd.serve_forever, daemon=True
        )
        t.start()
        slow_base = f"http://127.0.0.1:{slow_httpd.server_address[1]}"
        try:
            # Baseline = the fully-warmed second report (r1 still
            # carries the bucket-2 first-contact compile in its p99).
            # Tolerance 150%: far above replay-to-replay noise, far
            # below the ~25x wait injection (+400%+ observed).
            rc = loadgen_main([
                "replay", path, "--target", slow_base, "--mode",
                "closed", "--concurrency", "2", "--warmup", "2",
                "--timeout", "300", "--baseline", r2,
                "--p99-regression-pct", "150",
            ])
        finally:
            slow_httpd.shutdown()
            slow_state.batcher.close()
            slow_httpd.server_close()
        assert rc == 1  # the p99 gate tripped, exit != 0
        assert "p99_regression_pct" in capsys.readouterr().out
