"""Per-shard checkpoint/resume and the CLI kernel/backend plumbing.

The sharded checkpoint is one meta file + one .npz per shard (no host
gather); resume re-enters the sharded scan and must reproduce the
uninterrupted run bitwise, like the single-device path.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from wavetpu import cli
from wavetpu.core.problem import Problem
from wavetpu.io import checkpoint
from wavetpu.solver import sharded


def test_sharded_checkpoint_roundtrip(small_problem, tmp_path):
    half = sharded.solve_sharded(
        small_problem, mesh_shape=(2, 2, 2), stop_step=5
    )
    ck = str(tmp_path / "ckdir")
    checkpoint.save_sharded_checkpoint(ck, half)
    assert os.path.exists(os.path.join(ck, "meta.npz"))
    # 8 shards, one file each.
    shard_files = [f for f in os.listdir(ck) if f.startswith("shard_")]
    assert len(shard_files) == 8

    problem, u_prev, u_cur, step, mesh_shape, scheme, aux = (
        checkpoint.load_sharded_checkpoint(ck)
    )
    assert scheme == "standard" and aux is None
    assert problem == small_problem
    assert step == 5
    assert mesh_shape == (2, 2, 2)
    np.testing.assert_array_equal(np.asarray(u_cur), np.asarray(half.u_cur))
    np.testing.assert_array_equal(
        np.asarray(u_prev), np.asarray(half.u_prev)
    )
    # The loaded arrays are properly sharded over the rebuilt mesh.
    assert len(u_cur.sharding.device_set) == 8


@pytest.mark.parametrize("kernel", ["roll", "pallas"])
def test_sharded_resume_solve_bitwise(small_problem, tmp_path, kernel):
    full = sharded.solve_sharded(
        small_problem, mesh_shape=(2, 2, 2), kernel=kernel
    )
    half = sharded.solve_sharded(
        small_problem, mesh_shape=(2, 2, 2), kernel=kernel, stop_step=5
    )
    ck = str(tmp_path / "ckdir")
    checkpoint.save_sharded_checkpoint(ck, half)
    resumed = checkpoint.resume_sharded_solve(ck, kernel=kernel)
    np.testing.assert_array_equal(
        np.asarray(resumed.u_cur), np.asarray(full.u_cur)
    )
    np.testing.assert_array_equal(
        resumed.abs_errors[6:], full.abs_errors[6:]
    )


def test_sharded_checkpoint_bf16(small_problem, tmp_path):
    half = sharded.solve_sharded(
        small_problem, mesh_shape=(2, 2, 2), dtype=jnp.bfloat16, stop_step=4
    )
    ck = str(tmp_path / "ckdir")
    checkpoint.save_sharded_checkpoint(ck, half)
    _, u_prev, u_cur, _, _, _, _ = checkpoint.load_sharded_checkpoint(ck)
    assert u_cur.dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(u_cur).view(np.uint16),
        np.asarray(half.u_cur).view(np.uint16),
    )


def test_resolve_kernel():
    assert cli.resolve_kernel("auto", "tpu") == "pallas"
    assert cli.resolve_kernel("auto", "cpu") == "roll"
    assert cli.resolve_kernel("pallas", "cpu") == "pallas"
    assert cli.resolve_kernel("roll", "tpu") == "roll"
    with pytest.raises(ValueError):
        cli.resolve_kernel("cuda", "tpu")


def test_cli_kernel_selection_printed(tmp_path, capsys):
    """The CLI reports which hot kernel it selected; auto on CPU is roll."""
    base = ["16", "1", "1", "1", "1", "1", "5", "--out-dir", str(tmp_path)]
    assert cli.main(base + ["--backend", "single"]) == 0
    out = capsys.readouterr().out
    assert "kernel: roll" in out

    assert (
        cli.main(base + ["--backend", "single", "--kernel", "pallas"]) == 0
    )
    out = capsys.readouterr().out
    assert "kernel: pallas" in out


def test_cli_kernel_pallas_matches_roll(tmp_path, capsys):
    """Explicit --kernel pallas (interpret mode on CPU) reproduces the roll
    result through the full CLI path, single and sharded."""
    base = ["16", "1", "1", "1", "1", "1", "5"]
    for extra, name in [
        (["--backend", "single"], "single"),
        (["--mesh", "2,2,2"], "sharded"),
    ]:
        d_roll = str(tmp_path / f"roll_{name}")
        d_pal = str(tmp_path / f"pallas_{name}")
        assert cli.main(
            base + extra + ["--kernel", "roll", "--out-dir", d_roll]
        ) == 0
        assert cli.main(
            base + extra + ["--kernel", "pallas", "--out-dir", d_pal]
        ) == 0
        capsys.readouterr()
        fn = [f for f in os.listdir(d_roll) if f.endswith(".json")][0]
        roll = json.load(open(os.path.join(d_roll, fn)))
        pal = json.load(open(os.path.join(d_pal, fn)))
        np.testing.assert_allclose(
            pal["abs_errors"], roll["abs_errors"], rtol=1e-4, atol=1e-7
        )


def test_cli_sharded_preemption_workflow(tmp_path, capsys):
    """Sharded stop-step + save-state + directory resume == uninterrupted
    sharded run on the error tail - the workflow the round-3 verdict said
    the CLI refused."""
    base = ["16", "1", "1", "1", "1", "1", "10", "--mesh", "2,2,2"]
    full_dir, part_dir, res_dir = (
        str(tmp_path / d) for d in ("full", "part", "res")
    )
    ck = str(tmp_path / "ckdir")
    assert cli.main(base + ["--out-dir", full_dir]) == 0
    assert (
        cli.main(
            base
            + ["--out-dir", part_dir, "--stop-step", "6", "--save-state", ck]
        )
        == 0
    )
    assert os.path.isdir(ck)
    assert cli.main(["--resume", ck, "--out-dir", res_dir]) == 0
    capsys.readouterr()
    full = json.load(open(os.path.join(full_dir, "output_N16_Np8_TPU.json")))
    res = json.load(open(os.path.join(res_dir, "output_N16_Np8_TPU.json")))
    assert res["abs_errors"][7:] == full["abs_errors"][7:]


def test_mixed_step_checkpoint_rejected(small_problem, tmp_path):
    """A checkpoint interrupted while overwriting an older one (shards at
    different steps than meta) must fail loudly, not resume silently."""
    ck = str(tmp_path / "ckdir")
    half = sharded.solve_sharded(
        small_problem, mesh_shape=(2, 2, 2), stop_step=4
    )
    checkpoint.save_sharded_checkpoint(ck, half)
    # Simulate: one shard got overwritten by a newer (step-7) save.
    from wavetpu.io import nativeio

    shard = os.path.join(ck, "shard_0_0_0.wts")
    fields, _meta = nativeio.read_container(shard)
    nativeio.write_container_sync(shard, fields, meta={"step": 7})
    with pytest.raises(ValueError, match="interrupted mid-save"):
        checkpoint.load_sharded_checkpoint(ck)


def test_cli_npz_resume_rejects_sharded_flags(tmp_path, capsys):
    """A single-device .npz resume combined with --mesh/--backend sharded
    must error out, not silently discard the checkpointed state."""
    from wavetpu.solver import leapfrog

    half = leapfrog.solve(small := Problem(N=16, timesteps=10), stop_step=5)
    ck = checkpoint.save_checkpoint(str(tmp_path / "ck.npz"), half)
    assert cli.main(["--resume", ck, "--mesh", "2,1,1"]) == 2
    assert cli.main(["--resume", ck, "--backend", "sharded"]) == 2
    err = capsys.readouterr().err
    assert "single-device .npz" in err


def test_cli_overlap_flag(tmp_path, capsys):
    rc = cli.main(
        ["16", "1", "1", "1", "1", "1", "5", "--mesh", "2,2,2",
         "--overlap", "--out-dir", str(tmp_path)]
    )
    assert rc == 0
    capsys.readouterr()
    side = json.load(open(tmp_path / "output_N16_Np8_TPU.json"))
    assert np.isfinite(side["max_abs_error"])


def test_cli_overlap_single_rejected(capsys):
    rc = cli.main(
        ["16", "1", "1", "1", "1", "1", "5", "--backend", "single",
         "--overlap"]
    )
    assert rc == 2
    capsys.readouterr()
