"""Sharded-solver kernel injection: Pallas on every shard, overlap mode,
bf16, variable-c, and stop/resume - the round-4 gates.

The flagship composition (3D decomposition + the fused hot kernel in one
program per shard) is the analog of the reference's MPI+CUDA binary
(cuda_sol.cpp:381-443 driving cuda_sol_kernels.cu:24-47 per rank).  On the
8-virtual-CPU mesh the Pallas kernel runs in interpret mode - identical
program structure, no Mosaic.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from wavetpu.core.problem import Problem
from wavetpu.kernels import stencil_ref
from wavetpu.solver import leapfrog, sharded

MESHES = [(1, 1, 1), (2, 2, 2), (8, 1, 1), (1, 2, 4)]


def _gather(res, problem):
    return sharded.gather_fundamental(res.u_cur, problem)


@pytest.mark.parametrize("mesh_shape", MESHES)
def test_sharded_pallas_matches_single(small_problem, mesh_shape):
    """Sharded+Pallas == single-device, including the x seam across shards
    (8,1,1).  f64 so only op-order rounding differs."""
    single = leapfrog.solve(small_problem, dtype=jnp.float64)
    multi = sharded.solve_sharded(
        small_problem, mesh_shape=mesh_shape, dtype=jnp.float64,
        kernel="pallas",
    )
    np.testing.assert_allclose(
        _gather(multi, small_problem), np.asarray(single.u_cur),
        atol=1e-12, rtol=0.0,
    )
    np.testing.assert_allclose(
        multi.abs_errors, single.abs_errors, atol=1e-12, rtol=0.0
    )


@pytest.mark.parametrize("mesh_shape", [(2, 2, 2), (8, 1, 1)])
@pytest.mark.parametrize("kernel", ["roll", "pallas"])
def test_sharded_overlap_matches_serial(small_problem, mesh_shape, kernel):
    """Overlap mode (bulk update concurrent with ppermute, faces patched)
    produces the same answer as the serialized exchange."""
    serial = sharded.solve_sharded(
        small_problem, mesh_shape=mesh_shape, dtype=jnp.float64,
        kernel=kernel,
    )
    ov = sharded.solve_sharded(
        small_problem, mesh_shape=mesh_shape, dtype=jnp.float64,
        kernel=kernel, overlap=True,
    )
    np.testing.assert_allclose(
        np.asarray(ov.u_cur), np.asarray(serial.u_cur), atol=1e-12, rtol=0.0
    )
    np.testing.assert_allclose(
        ov.abs_errors, serial.abs_errors, atol=1e-12, rtol=0.0
    )


def test_sharded_overlap_requires_even_split():
    with pytest.raises(ValueError, match="overlap"):
        sharded.solve_sharded(
            Problem(N=13, timesteps=4), mesh_shape=(4, 1, 1), overlap=True
        )


@pytest.mark.parametrize("mesh_shape", [(4, 1, 1), (2, 2, 2), (1, 4, 1)])
def test_sharded_pallas_uneven_grid(mesh_shape):
    """Pallas kernel + pad-and-mask uneven shards, incl. r_last=1: the hi
    ghost is absorbed into the first pad plane (halo.absorb_hi_ghosts)."""
    p = Problem(N=13, timesteps=6)
    single = leapfrog.solve(p, dtype=jnp.float64)
    multi = sharded.solve_sharded(
        p, mesh_shape=mesh_shape, dtype=jnp.float64, kernel="pallas"
    )
    np.testing.assert_allclose(
        _gather(multi, p), np.asarray(single.u_cur), atol=1e-12, rtol=0.0
    )
    # Pad cells stay zero (the kernel's global mask re-zeroes them).
    u = np.asarray(multi.u_cur)
    assert np.all(u[13:] == 0.0)
    assert np.all(u[:, 13:] == 0.0)
    assert np.all(u[:, :, 13:] == 0.0)


def test_sharded_bf16_matches_single(small_problem):
    """bf16 state / f32 accum on the sharded backend: bitwise vs the
    single-device bf16 solver (same rounding points on the pallas path)."""
    single = leapfrog.solve(small_problem, dtype=jnp.bfloat16)
    multi = sharded.solve_sharded(
        small_problem, mesh_shape=(2, 2, 2), dtype=jnp.bfloat16,
        kernel="pallas",
    )
    np.testing.assert_array_equal(
        np.asarray(_gather(multi, small_problem)).view(np.uint16),
        np.asarray(single.u_cur).view(np.uint16),
    )
    assert multi.u_cur.dtype == jnp.bfloat16


@pytest.mark.parametrize("kernel", ["roll", "pallas"])
def test_sharded_variable_c(small_problem, kernel):
    """A genuinely varying c^2(x,y,z): sharded (field as a sharded runtime
    argument) == single-device ParamStep path."""
    p = small_problem
    vf = stencil_ref.make_c2tau2_field(
        p, lambda x, y, z: p.a2 * (1.0 + 0.3 * np.sin(2.0 * np.pi * x))
    )
    single = leapfrog.solve(
        p, dtype=jnp.float64,
        step_fn=stencil_ref.make_variable_c_step(vf), compute_errors=False,
    )
    multi = sharded.solve_sharded(
        p, mesh_shape=(2, 2, 2), dtype=jnp.float64, kernel=kernel,
        c2tau2_field=vf, compute_errors=False,
    )
    np.testing.assert_allclose(
        _gather(multi, p), np.asarray(single.u_cur), atol=1e-12, rtol=0.0
    )


def test_sharded_variable_c_constant_field_equals_constant_path(
    small_problem,
):
    """tau^2 c^2 == a2tau2 everywhere must reproduce the constant-speed
    solver exactly (same kernel, field slab vs scalar coefficient)."""
    p = small_problem
    field = stencil_ref.make_c2tau2_field(p, lambda x, y, z: p.a2)
    const = sharded.solve_sharded(
        p, mesh_shape=(2, 2, 2), dtype=jnp.float64, kernel="pallas"
    )
    var = sharded.solve_sharded(
        p, mesh_shape=(2, 2, 2), dtype=jnp.float64, kernel="pallas",
        c2tau2_field=field, compute_errors=False,
    )
    np.testing.assert_allclose(
        np.asarray(var.u_cur), np.asarray(const.u_cur), atol=1e-13, rtol=0.0
    )


@pytest.mark.parametrize("kernel", ["roll", "pallas"])
def test_sharded_stop_resume_bitwise(small_problem, kernel):
    """Kill-and-resume on the sharded backend reproduces the uninterrupted
    run bitwise (identical per-step op sequence)."""
    p = small_problem
    full = sharded.solve_sharded(p, mesh_shape=(2, 2, 2), kernel=kernel)
    half = sharded.solve_sharded(
        p, mesh_shape=(2, 2, 2), kernel=kernel, stop_step=5
    )
    assert half.final_step == 5
    resumed = sharded.resume_sharded(
        p, half.u_prev, half.u_cur, 5, mesh_shape=(2, 2, 2), kernel=kernel
    )
    np.testing.assert_array_equal(
        np.asarray(resumed.u_cur), np.asarray(full.u_cur)
    )
    np.testing.assert_array_equal(
        resumed.abs_errors[6:], full.abs_errors[6:]
    )
    assert np.all(resumed.abs_errors[:6] == 0.0)
