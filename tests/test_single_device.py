"""M0 gates: the fundamental-domain solver reproduces the reference scheme.

Strategy (SURVEY.md section 4): the analytic oracle is the test fixture; the
independent (N+1)^3-with-seam numpy implementation (tests/reference_impl.py)
pins the seam-free design to the reference's formulation.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from wavetpu.core.problem import Problem
from wavetpu.solver import leapfrog
from tests import reference_impl


@pytest.fixture(scope="module")
def ref_history(small_problem):
    return reference_impl.solve_reference(small_problem)


def test_matches_reference_scheme(small_problem, ref_history):
    """Every layer of the (N,N,N) fundamental-domain solve equals the
    (N+1)^3 seam formulation to rounding error."""
    hist = leapfrog.solve_history(small_problem, dtype=jnp.float64)
    assert hist.shape[0] == ref_history.shape[0]
    for n in range(hist.shape[0]):
        full = leapfrog.to_reference_grid(hist[n])
        np.testing.assert_allclose(
            full, ref_history[n], atol=1e-12, rtol=0.0,
            err_msg=f"layer {n} mismatch",
        )


def test_seam_duplication_consistency(small_problem, ref_history):
    """In the reference formulation the x=0 and x=N planes are identical -
    sanity check of the independent implementation itself."""
    # Layer 0 is analytic, where sin(2*pi*N*hx/Lx) = sin(2*pi) is a ~1e-16
    # float, not exactly sin(0); from layer 1 on the seam is an exact copy.
    np.testing.assert_allclose(ref_history[0][0], ref_history[0][-1], atol=1e-15)
    for n in range(1, ref_history.shape[0]):
        np.testing.assert_array_equal(ref_history[n][0], ref_history[n][-1])


def test_fused_errors_match_posthoc(small_problem, ref_history):
    """Fused per-layer errors == post-hoc errors of the seam formulation."""
    res = leapfrog.solve(small_problem, dtype=jnp.float64)
    ref_abs, ref_rel = reference_impl.reference_errors(small_problem, ref_history)
    np.testing.assert_allclose(res.abs_errors, ref_abs, atol=1e-12)
    # The reference's relative error divides by |f| ~ 1e-16 on the analytic
    # solution's nodal planes, so its max is rounding noise (SURVEY.md 2.4.4)
    # and cannot be compared across implementations.  Check the faithful rel
    # metric is at least as large as abs, and that a denominator-thresholded
    # rel computed from both histories agrees.
    assert np.all(res.rel_errors >= res.abs_errors - 1e-15)
    from wavetpu.verify import oracle
    from wavetpu.solver.leapfrog import solve_history, to_reference_grid

    hist = solve_history(small_problem, dtype=jnp.float64)
    for n in range(hist.shape[0]):
        f = oracle.full_analytic_grid(small_problem, n)
        den_ok = np.abs(f) > 1e-3
        sl = (slice(1, -1),) * 3
        ours = np.abs(to_reference_grid(hist[n]) - f)
        refs = np.abs(ref_history[n] - f)
        r1 = np.where(den_ok, ours / np.where(den_ok, np.abs(f), 1.0), 0.0)[sl].max()
        r2 = np.where(den_ok, refs / np.where(den_ok, np.abs(f), 1.0), 0.0)[sl].max()
        np.testing.assert_allclose(r1, r2, rtol=1e-6, atol=1e-12)


def test_layer0_error_is_zero(small_problem, medium_problem):
    """The reported layer-0 error is zero by definition (leapfrog.py), so
    additionally pin the *actual* layer-0 state against the host-f64 oracle
    - otherwise the definitional zero could mask a broken bootstrap."""
    from wavetpu.verify import oracle

    for p in (small_problem, medium_problem):
        res = leapfrog.solve(p, dtype=jnp.float64)
        assert res.abs_errors[0] == 0.0
        assert res.rel_errors[0] == 0.0
        hist = leapfrog.solve_history(p, dtype=jnp.float64)
        f0 = oracle.full_analytic_grid(p, 0)[:-1, :-1, :-1]
        f0[:, 0, :] = 0.0
        f0[:, :, 0] = 0.0
        true_layer0_err = np.abs(np.asarray(hist[0]) - f0).max()
        assert true_layer0_err < 1e-14, true_layer0_err


def test_dirichlet_invariant(small_problem):
    res = leapfrog.solve(small_problem, dtype=jnp.float64)
    u = np.asarray(res.u_cur)
    assert np.all(u[:, 0, :] == 0.0)
    assert np.all(u[:, :, 0] == 0.0)


def test_error_stays_bounded(medium_problem):
    """A correct, stable run keeps L-inf error O(tau^2 + h^2); instability or
    indexing bugs explode it (SURVEY.md section 4.1)."""
    res = leapfrog.solve(medium_problem, dtype=jnp.float64)
    assert res.abs_errors.max() < 1e-2
    assert np.isfinite(res.abs_errors).all()


def test_convergence_second_order():
    """Halving h and tau together divides the error by ~4 (leapfrog is
    second order in both)."""
    e = []
    for n, ts in [(16, 32), (32, 64)]:
        p = Problem(N=n, timesteps=ts)
        res = leapfrog.solve(p, dtype=jnp.float64)
        e.append(res.abs_errors[-1])
    ratio = e[0] / e[1]
    assert 3.0 < ratio < 5.0, f"convergence ratio {ratio}"


def test_f32_matches_f64_to_single_precision(small_problem):
    r32 = leapfrog.solve(small_problem, dtype=jnp.float32)
    r64 = leapfrog.solve(small_problem, dtype=jnp.float64)
    np.testing.assert_allclose(r32.abs_errors, r64.abs_errors, atol=5e-6)


def test_problem_cli_contract():
    p = Problem.from_argv(["128", "4", "pi", "1.0", "pi"])
    assert p.N == 128 and p.Np == 4
    assert p.Lx == pytest.approx(np.pi)
    assert p.Ly == 1.0 and p.Lz == pytest.approx(np.pi)
    assert p.T == 1.0 and p.timesteps == 20
    p2 = Problem.from_argv(["64", "1", "1", "1", "1", "2.0", "40"])
    assert p2.T == 2.0 and p2.timesteps == 40 and p2.tau == pytest.approx(0.05)
