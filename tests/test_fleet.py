"""Fleet-tier contracts: the shared ProgramKey derivation (router ==
engine, pinned so they cannot drift), health-gated membership, the
warm-key affinity table, the router proxy seam (scripted members: no
jax), drain-during-inflight absorption (real two-replica fleet +
WAVETPU_FAULT chaos at one member), and the rolling-deploy acceptance
drill (closed-loop replay through the router while one replica is
rolled: zero client-visible errors, zero fresh compiles, >= 90%% of
warm-key requests landing on a holder).
"""

import json
import random
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from wavetpu import progkey
from wavetpu.client import WavetpuClient
from wavetpu.fleet.affinity import (
    AffinityTable,
    warm_label_from_server_timing,
)
from wavetpu.fleet.membership import (
    EJECTED,
    JOINING,
    LEAVING,
    LEFT,
    UP,
    MembershipTable,
)
from wavetpu.fleet.router import build_router, load_api_keys
from wavetpu.fleet import roll as fleet_roll
from wavetpu.loadgen import report as lg_report
from wavetpu.loadgen import runner, trace
from wavetpu.run import faults
from wavetpu.serve.api import build_server, parse_solve_request


# ---- the shared key derivation: router == engine, pinned ----


class TestSharedKeyDerivation:
    BODIES = [
        {"N": 8, "timesteps": 4},
        {"N": 8, "timesteps": 4, "phase": 1.0},   # same identity
        {"N": 12, "timesteps": 6, "Lx": "pi", "dtype": "f64"},
        {"N": 8, "timesteps": 4, "scheme": "compensated"},
        {"N": 8, "timesteps": 4, "kernel": "pallas", "fuse_steps": 2},
        {"N": 8, "timesteps": 4, "c2_field": "gaussian-lens"},
        {"N": 8, "timesteps": 4, "mesh": [1, 1, 2]},
    ]

    def test_router_identity_matches_engine_program_key(self):
        """THE drift pin: for every body shape the fleet serves, the
        affinity key the router derives (progkey.identity_from_body,
        no jax) equals the affinity projection of the ProgramKey the
        engine actually caches under (parse_solve_request -> the
        engine's for_batch key)."""
        for body in self.BODIES:
            ident = progkey.identity_from_body(body, platform="cpu")
            req = parse_solve_request(body)
            engine_key = progkey.ProgramKey.for_batch(
                req.problem, req.scheme, req.path, req.k,
                req.dtype_name,
                with_field=req.lane.c2tau2_field is not None,
                compute_errors=True, batch=4, mesh=req.mesh_shape,
            )
            assert ident.affinity_key() == progkey.affinity_key(
                engine_key
            ), body

    def test_affinity_key_ignores_batch_and_compute_errors(self):
        ident = progkey.identity_from_body(
            {"N": 8, "timesteps": 4}, platform="cpu"
        )
        keys = {
            progkey.affinity_key(ident.program_key(b, ce))
            for b in (1, 2, 4, 8) for ce in (True, False)
        }
        assert keys == {ident.affinity_key()}

    def test_identity_rejects_what_the_server_rejects(self):
        for body in (
            {"timesteps": 4},                       # missing N
            {"N": 8, "scheme": "magic"},
            {"N": 8, "dtype": "f16"},
            {"N": 8, "fuse_steps": 2, "kernel": "roll"},
            {"N": 8, "scheme": "compensated", "dtype": "bf16"},
            {"N": 8, "mesh": [2, 2]},
            {"N": 8, "mesh": [1, 1, 2], "fuse_steps": 2,
             "kernel": "pallas"},
        ):
            with pytest.raises(ValueError):
                progkey.identity_from_body(body, platform="cpu")

    def test_warm_keys_flatten_dedup_and_skip_malformed(self):
        kd = progkey.key_from_program_key(
            progkey.identity_from_body(
                {"N": 8, "timesteps": 4}, platform="cpu"
            ).program_key(4, True)
        )
        other = dict(kd, batch=8)           # same tier, other bucket
        warm = {
            "memory": [kd, "junk", None],
            "disk": [other, {"not": "a key"}],
        }
        aks = progkey.warm_keys_to_affinity(warm)
        assert aks == [progkey.affinity_key_from_dict(kd)]

    def test_warm_label_parse(self):
        h = ("queue;dur=1.2, compile;dur=0.0, execute;dur=45, "
             "warm;desc=disk, total;dur=50")
        assert warm_label_from_server_timing(h) == "disk"
        assert warm_label_from_server_timing("execute;dur=4") is None
        assert warm_label_from_server_timing(None) is None


# ---- membership state machine (fake transport, zero sockets) ----


class _FakeFleet:
    """Scriptable fetch: per-url healthz/metrics payloads or raised
    transport errors."""

    def __init__(self):
        self.health = {}     # url -> dict | Exception
        self.prom = {}       # url -> str
        self.warm = {}       # url -> warm_keys dict

    def fetch(self, base_url, path, timeout, accept=None):
        url = base_url.rstrip("/")
        if path == "/healthz":
            h = self.health.get(url, ConnectionRefusedError("down"))
            if isinstance(h, Exception):
                raise h
            return 200, json.dumps(h)
        if path == "/metrics":
            h = self.health.get(url)
            if isinstance(h, Exception) or h is None:
                raise ConnectionRefusedError("down")
            if accept == "application/json":
                return 200, json.dumps({
                    "queue_depth": 0,
                    "program_cache": {
                        "warm_keys": self.warm.get(url, {}),
                    },
                })
            return 200, self.prom.get(url, "")
        raise AssertionError(f"unexpected path {path}")


READY = {"status": "ok", "ready": True, "backend": "cpu"}
DRAINING = {"status": "ok", "ready": False, "draining": True}


class TestMembership:
    def _table(self, urls, **kw):
        fleet = _FakeFleet()
        for u in urls:
            fleet.health[u] = dict(READY)
        table = MembershipTable(urls, fetch=fleet.fetch, **kw)
        return fleet, table

    def test_joining_to_up_on_ready(self):
        fleet, table = self._table(["http://a:1"])
        assert table.get("http://a:1").state == JOINING
        table.poll_once()
        assert table.get("http://a:1").state == UP
        assert table.routable_urls() == ["http://a:1"]

    def test_ready_false_ejects_immediately_and_readmits(self):
        fleet, table = self._table(["http://a:1"])
        table.poll_once()
        fleet.health["http://a:1"] = dict(DRAINING)
        table.poll_once()
        m = table.get("http://a:1")
        assert m.state == EJECTED and not table.routable_urls()
        fleet.health["http://a:1"] = dict(READY)
        table.poll_once()
        assert m.state == UP  # recovery re-admits, no operator action

    def test_transport_failures_eject_at_threshold_only(self):
        fleet, table = self._table(["http://a:1"], fail_threshold=3)
        table.poll_once()
        fleet.health["http://a:1"] = ConnectionRefusedError("boom")
        table.poll_once()
        table.poll_once()
        assert table.get("http://a:1").state == UP  # 2 < threshold
        table.poll_once()
        assert table.get("http://a:1").state == EJECTED
        fleet.health["http://a:1"] = dict(READY)
        table.poll_once()
        m = table.get("http://a:1")
        assert m.state == UP and m.consecutive_failures == 0

    def test_leave_retire_freezes_counters_for_aggregation(self):
        fleet, table = self._table(["http://a:1", "http://b:2"])
        fleet.prom["http://a:1"] = "wavetpu_x_total 5\n"
        fleet.prom["http://b:2"] = "wavetpu_x_total 7\n"
        table.poll_once()
        assert table.aggregate_prom(refresh=False) == {
            "wavetpu_x_total": 12.0
        }
        table.leave("http://a:1")
        assert table.get("http://a:1").state == LEAVING
        assert table.routable_urls() == ["http://b:2"]
        table.retire("http://a:1")
        assert table.get("http://a:1").state == LEFT
        # a is gone from the network...
        fleet.health["http://a:1"] = ConnectionRefusedError("gone")
        fleet.prom["http://b:2"] = "wavetpu_x_total 9\n"
        table.poll_once()
        # ...but its final counters stay in the sum: monotonic deltas
        # across a roll.
        assert table.aggregate_prom(refresh=False) == {
            "wavetpu_x_total": 14.0
        }

    def test_join_baseline_excludes_prejoin_history(self):
        """A member admitted mid-flight (the /admin/join path) must
        contribute only growth SINCE join to the fleet aggregate - its
        manifest-warmup compiles happened before it was fleet."""
        fleet, table = self._table(["http://a:1"])
        fleet.prom["http://a:1"] = "wavetpu_x_total 5\n"
        table.poll_once()
        # the successor arrives carrying 3 pre-join compiles and a
        # nonzero gauge
        fleet.health["http://b:2"] = dict(READY)
        fleet.prom["http://b:2"] = (
            "wavetpu_x_total 3\nwavetpu_gauge 2\n"
        )
        m = table.add("http://b:2", baseline=True)
        table.poll_member(m)
        agg = table.aggregate_prom(refresh=False)
        # counter baselined away; the gauge passes through absolute
        assert agg["wavetpu_x_total"] == 5.0
        assert agg["wavetpu_gauge"] == 2.0
        # growth after join counts
        fleet.prom["http://b:2"] = (
            "wavetpu_x_total 4\nwavetpu_gauge 0\n"
        )
        table.poll_once()
        agg = table.aggregate_prom(refresh=False)
        assert agg["wavetpu_x_total"] == 6.0
        assert agg["wavetpu_gauge"] == 0.0

    def test_poll_feeds_affinity_warm_keys(self):
        aff = AffinityTable(rng=random.Random(0))
        fleet = _FakeFleet()
        fleet.health["http://a:1"] = dict(READY)
        kd = progkey.key_from_program_key(
            progkey.identity_from_body(
                {"N": 8, "timesteps": 4}, platform="cpu"
            ).program_key(4, True)
        )
        fleet.warm["http://a:1"] = {"memory": [kd], "disk": []}
        table = MembershipTable(
            ["http://a:1"], fetch=fleet.fetch, affinity=aff
        )
        table.poll_once()
        ak = progkey.affinity_key_from_dict(kd)
        assert aff.holders(ak) == {"http://a:1"}
        assert table.get("http://a:1").warm_key_count == 1


# ---- affinity table ----


class TestAffinityTable:
    AK1, AK2 = '{"k": 1}', '{"k": 2}'

    def test_poll_replace_and_response_add(self):
        t = AffinityTable(rng=random.Random(0))
        t.observe_response("http://a", self.AK1, "false")  # just compiled
        t.observe_response("http://a", self.AK2, "fallback")  # no program
        assert t.holders(self.AK1) == {"http://a"}
        assert t.holders(self.AK2) == set()
        # poll REPLACES a's set; response-learned key not in the poll
        # is dropped (evicted server-side)
        t.observe_response("http://b", self.AK1, "disk")
        t.observe_warm_keys("http://a", {"memory": [], "disk": []})
        assert t.holders(self.AK1) == {"http://b"}

    def test_choose_counts_hit_rerouted_cold_unkeyed(self):
        t = AffinityTable(rng=random.Random(0))
        load = lambda u: 0.0  # noqa: E731
        t.observe_response("http://a", self.AK1, "true")
        assert t.choose(self.AK1, ["http://a", "http://b"], load) \
            == "http://a"
        # holder exists but is not a candidate (ejected): rerouted
        assert t.choose(self.AK1, ["http://b"], load) == "http://b"
        t.choose(self.AK2, ["http://a", "http://b"], load)   # cold
        t.choose(None, ["http://a"], load)                   # unkeyed
        s = t.stats()
        assert (s["hits"], s["rerouted"], s["cold"], s["unkeyed"]) \
            == (1, 1, 1, 1)
        assert s["hit_rate"] == 0.5

    def test_p2c_prefers_lower_load(self):
        t = AffinityTable(rng=random.Random(42))
        loads = {"http://a": 9.0, "http://b": 0.0}
        picks = {
            t.choose(None, ["http://a", "http://b"], loads.get)
            for _ in range(16)
        }
        assert picks == {"http://b"}  # both sampled each time: 2 of 2

    def test_forget_member(self):
        t = AffinityTable(rng=random.Random(0))
        t.observe_response("http://a", self.AK1, "true")
        t.forget_member("http://a")
        assert t.holders(self.AK1) == set()
        assert t.known_keys() == 0


# ---- scripted members: the router proxy seam without jax ----


class _ScriptedMember:
    """A fake replica speaking the serve contract's fleet-facing
    subset: /healthz, /metrics (JSON + Prometheus), /solve (scripted
    or default-200 with a warm label), /admin/drain."""

    def __init__(self, warm_keys=None, prom="wavetpu_y_total 1\n"):
        self.lock = threading.Lock()
        self.ready = True
        self.draining = False
        self.warm_keys = warm_keys or {"memory": [], "disk": []}
        self.prom = prom
        self.solve_script = []   # (status, payload, headers) or "drop"
        self.solves = 0
        self.seen_headers = []   # per /solve attempt: request headers
        self.seen_bodies = []    # per /solve attempt: raw request body

        state = self

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _send(self, code, payload, headers=None,
                      content_type="application/json"):
                raw = (payload if isinstance(payload, bytes)
                       else json.dumps(payload).encode())
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(raw)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(raw)

            def do_GET(self):
                if self.path == "/healthz":
                    with state.lock:
                        self._send(200, {
                            "status": "ok",
                            "ready": state.ready and not state.draining,
                            "draining": state.draining,
                            "backend": "cpu",
                        })
                elif self.path == "/metrics":
                    accept = self.headers.get("Accept", "") or ""
                    if "application/json" in accept:
                        with state.lock:
                            self._send(200, {
                                "queue_depth": 0,
                                "program_cache": {
                                    "warm_keys": state.warm_keys,
                                },
                            })
                    else:
                        with state.lock:
                            self._send(
                                200, state.prom.encode(),
                                content_type="text/plain",
                            )
                else:
                    self._send(404, {"error": "not found"})

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0) or 0)
                raw = self.rfile.read(length)
                if self.path == "/solve":
                    with state.lock:
                        state.seen_headers.append(dict(self.headers))
                        state.seen_bodies.append(raw)
                if self.path == "/admin/drain":
                    with state.lock:
                        state.draining = True
                    self._send(200, {"status": "ok", "draining": True},
                               {"Connection": "close"})
                    return
                with state.lock:
                    state.solves += 1
                    if state.draining:
                        self._send(503, {
                            "status": "error", "error": "draining",
                            "retriable": True,
                        }, {"Retry-After": "2", "Connection": "close"})
                        return
                    step = (state.solve_script.pop(0)
                            if state.solve_script else None)
                if step == "drop":
                    self.close_connection = True
                    self.connection.close()
                    return
                if step is not None:
                    self._send(*step)
                    return
                self._send(200, {"status": "ok", "report": {}}, {
                    "Server-Timing": "execute;dur=1, warm;desc=true",
                })

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        ).start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _start_router(member_urls, **kw):
    kw.setdefault("poll_interval_s", 60.0)  # tests poll explicitly
    kw.setdefault("rng", random.Random(0))
    httpd, state = build_router(member_urls, **kw)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, state, f"http://127.0.0.1:{httpd.server_address[1]}"


def _post(base, path, body, timeout=30, headers=None):
    import urllib.error

    req = urllib.request.Request(
        base + path, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _get(base, path, accept=None, timeout=30):
    req = urllib.request.Request(
        base + path, headers={"Accept": accept} if accept else {}
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.read().decode()


class TestRouterProxy:
    BODY = {"N": 8, "timesteps": 4}

    def _ak(self, body=None):
        return progkey.identity_from_body(
            body or self.BODY, platform="cpu"
        ).affinity_key()

    def test_routes_warm_key_to_advertised_holder(self):
        """Bootstrap affinity: B advertises the key in its /metrics
        warm_keys (disk inheritance); every request for it lands on B
        even though A is equally healthy."""
        kd = progkey.key_from_program_key(
            progkey.identity_from_body(
                self.BODY, platform="cpu"
            ).program_key(4, True)
        )
        a = _ScriptedMember()
        b = _ScriptedMember(warm_keys={"memory": [], "disk": [kd]})
        httpd, state, base = _start_router([a.url, b.url])
        try:
            for _ in range(4):
                code, _, headers = _post(base, "/solve", self.BODY)
                assert code == 200
                assert headers["X-Wavetpu-Member"] == b.url
            assert a.solves == 0 and b.solves == 4
            assert state.affinity.stats()["hits"] == 4
        finally:
            httpd.shutdown(); httpd.server_close()
            state.stop_poller()
            a.close(); b.close()

    def test_response_warm_label_builds_affinity(self):
        """No poll data at all: the first (cold) response's warm label
        pins the key to whichever member served it."""
        a, b = _ScriptedMember(), _ScriptedMember()
        httpd, state, base = _start_router([a.url, b.url])
        try:
            _, _, headers = _post(base, "/solve", self.BODY)
            first = headers["X-Wavetpu-Member"]
            for _ in range(5):
                _, _, h = _post(base, "/solve", self.BODY)
                assert h["X-Wavetpu-Member"] == first
            s = state.affinity.stats()
            assert s["cold"] == 1 and s["hits"] == 5
        finally:
            httpd.shutdown(); httpd.server_close()
            state.stop_poller()
            a.close(); b.close()

    def test_draining_503_retried_on_live_member_not_surfaced(self):
        """Satellite: the cutover seam.  A drained member's 503 +
        Retry-After is absorbed by the ROUTER (retried onto the live
        member); a zero-retry client sees only 200s."""
        kd = progkey.key_from_program_key(
            progkey.identity_from_body(
                self.BODY, platform="cpu"
            ).program_key(4, True)
        )
        # a advertises the key -> every first pick deterministically
        # lands on a, which is ALREADY draining (the router learns only
        # at the next poll - exactly the cutover race).
        a = _ScriptedMember(warm_keys={"memory": [kd], "disk": []})
        b = _ScriptedMember()
        # b's responses carry no warm label, so b never becomes a
        # holder and every first pick keeps landing on (draining) a.
        b.solve_script = [(200, {"status": "ok"}, {})] * 4
        httpd, state, base = _start_router([a.url, b.url])
        try:
            a.draining = True
            for _ in range(4):
                code, payload, headers = _post(base, "/solve", self.BODY)
                assert code == 200, payload
                assert headers["X-Wavetpu-Member"] == b.url
            snap = state.snapshot()
            # every request first hit draining a, was retried onto b,
            # and none failed
            assert snap["exhausted_total"] == 0
            assert snap["retried_requests"] == 4
            assert a.solves == 4 and b.solves == 4
        finally:
            httpd.shutdown(); httpd.server_close()
            state.stop_poller()
            a.close(); b.close()

    def test_connection_drop_retried_on_other_member(self):
        kd = progkey.key_from_program_key(
            progkey.identity_from_body(
                self.BODY, platform="cpu"
            ).program_key(4, True)
        )
        a = _ScriptedMember(warm_keys={"memory": [kd], "disk": []})
        b = _ScriptedMember()
        a.solve_script = ["drop"]  # first hit at holder a: severed conn
        httpd, state, base = _start_router([a.url, b.url])
        try:
            for _ in range(3):
                code, payload, _ = _post(base, "/solve", self.BODY)
                assert code == 200, payload
            assert state.snapshot()["retried_requests"] >= 1
            assert a.solves >= 1 and b.solves >= 1
        finally:
            httpd.shutdown(); httpd.server_close()
            state.stop_poller()
            a.close(); b.close()

    def test_all_members_down_yields_retriable_503(self):
        a, b = _ScriptedMember(), _ScriptedMember()
        a.draining = True
        b.draining = True
        httpd, state, base = _start_router([a.url, b.url])
        try:
            code, payload, headers = _post(base, "/solve", self.BODY)
            assert code == 503
            assert payload.get("retriable") is True or \
                "Retry-After" in headers
            assert "Retry-After" in headers
            assert state.snapshot()["exhausted_total"] == 1
        finally:
            httpd.shutdown(); httpd.server_close()
            state.stop_poller()
            a.close(); b.close()

    def test_malformed_body_forwarded_replica_owns_the_400(self):
        a = _ScriptedMember()
        a.solve_script = [(400, {"status": "error",
                                 "error": "missing required field N"},
                           {})]
        httpd, state, base = _start_router([a.url])
        try:
            code, payload, _ = _post(base, "/solve", {"junk": True})
            assert code == 400 and "missing" in payload["error"]
            assert state.snapshot()["unparseable_total"] == 1
        finally:
            httpd.shutdown(); httpd.server_close()
            state.stop_poller()
            a.close()

    def test_healthz_and_admin_join_leave(self):
        a, b = _ScriptedMember(), _ScriptedMember()
        httpd, state, base = _start_router([a.url])
        try:
            _, text = _get(base, "/healthz")
            h = json.loads(text)
            assert h["ready"] is True and h["members_up"] == 1
            code, payload, _ = _post(base, "/admin/join", {"url": b.url})
            assert code == 200
            assert payload["member"]["state"] == "up"  # synchronous poll
            _, text = _get(base, "/healthz")
            assert json.loads(text)["members_up"] == 2
            code, _, _ = _post(
                base, "/admin/leave",
                {"url": a.url, "sync": True, "drain_wait_s": 2.0},
            )
            assert code == 200
            assert a.draining is True  # router POSTed /admin/drain
            m = state.table.get(a.url)
            assert m.state == LEFT
            code, payload, _ = _post(base, "/admin/leave",
                                     {"url": "http://nope:1"})
            assert code == 404
        finally:
            httpd.shutdown(); httpd.server_close()
            state.stop_poller()
            a.close(); b.close()

    def test_metrics_aggregation_monotonic_across_leave(self):
        a = _ScriptedMember(prom="wavetpu_y_total 5\n")
        b = _ScriptedMember(prom="wavetpu_y_total 3\n")
        httpd, state, base = _start_router([a.url, b.url])
        try:
            _, text = _get(base, "/metrics", accept="text/plain")
            samples = runner.parse_prometheus_text(text)
            assert samples["wavetpu_y_total"] == 8.0
            assert "wavetpu_router_requests_total" in samples
            _post(base, "/admin/leave",
                  {"url": a.url, "sync": True, "drain_wait_s": 1.0})
            a.close()  # the process is gone
            b.prom = "wavetpu_y_total 4\n"
            _, text = _get(base, "/metrics", accept="text/plain")
            samples = runner.parse_prometheus_text(text)
            # a's final 5 frozen in, b refreshed to 4: still monotonic
            assert samples["wavetpu_y_total"] == 9.0
            assert samples['wavetpu_router_members{state="left"}'] == 1
        finally:
            httpd.shutdown(); httpd.server_close()
            state.stop_poller()
            b.close()

    def test_json_metrics_expose_affinity_and_members(self):
        a = _ScriptedMember()
        httpd, state, base = _start_router([a.url])
        try:
            _post(base, "/solve", self.BODY)
            _, text = _get(base, "/metrics")
            snap = json.loads(text)
            assert snap["router"] is True
            assert set(snap["affinity"]) >= {
                "hits", "rerouted", "cold", "hit_rate", "known_keys",
            }
            assert snap["members"][0]["proxied_total"] == 1
        finally:
            httpd.shutdown(); httpd.server_close()
            state.stop_poller()
            a.close()


# ---- real fleet: chaos at one member, absorbed at the router seam ----


def _hget(headers: dict, name: str):
    return {k.lower(): v for k, v in headers.items()}.get(name.lower())


class TestRouterDeadlineBudget:
    """Satellite: the router forwards X-Deadline-Ms DECREMENTED by its
    own wall, refuses doomed retries below --min-retry-budget-ms, and
    re-injects a draining member's resume_token into the retried body
    (the cross-replica solve handoff seam, scripted - no jax)."""

    BODY = {"N": 8, "timesteps": 4}

    def _pin(self, member):
        """A warm-key advertisement pinning BODY's first pick to
        `member` (the test needs attempt order deterministic)."""
        kd = progkey.key_from_program_key(
            progkey.identity_from_body(
                self.BODY, platform="cpu"
            ).program_key(4, True)
        )
        member.warm_keys = {"memory": [kd], "disk": []}

    def test_deadline_decremented_and_token_reinjected_on_retry(self):
        token = "ab" * 32
        m1, m2 = _ScriptedMember(), _ScriptedMember()
        self._pin(m1)
        m1.solve_script = [(503, {
            "status": "error", "error": "draining: checkpointed",
            "retriable": True, "resume_token": token,
        }, {"Retry-After": "1"})]
        httpd, state, base = _start_router([m1.url, m2.url])
        try:
            state.table.poll_once()
            code, payload, _ = _post(
                base, "/solve", self.BODY,
                headers={"X-Deadline-Ms": "200000"},
            )
            assert code == 200
            assert m1.solves == 1 and m2.solves == 1
            # both attempts carried a budget; the retry's is the
            # REMAINING budget, never more than the original
            d1 = float(_hget(m1.seen_headers[0], "X-Deadline-Ms"))
            d2 = float(_hget(m2.seen_headers[0], "X-Deadline-Ms"))
            assert 0 < d1 <= 200000
            assert 0 < d2 <= d1
            # the drained member's token rode the retry into m2's body
            retried = json.loads(m2.seen_bodies[0])
            assert retried["resume_token"] == token
            snap = state.snapshot()
            assert snap["resume_handoffs_total"] == 1
            assert snap["retried_requests"] == 1
        finally:
            httpd.shutdown(); httpd.server_close()
            state.stop_poller()
            m1.close(); m2.close()

    def test_retry_below_min_budget_surfaces_last_answer(self):
        m1, m2 = _ScriptedMember(), _ScriptedMember()
        self._pin(m1)
        m1.solve_script = [(503, {
            "status": "error", "error": "draining", "retriable": True,
        }, {"Retry-After": "1"})]
        httpd, state, base = _start_router(
            [m1.url, m2.url], min_retry_budget_ms=10_000_000.0,
        )
        try:
            state.table.poll_once()
            code, payload, _ = _post(
                base, "/solve", self.BODY,
                headers={"X-Deadline-Ms": "200000"},
            )
            # remaining budget < the floor: no second attempt, the
            # 503 stands (still retriable - the CLIENT may have more
            # budget tomorrow, the router just won't burn it now)
            assert code == 503
            assert m1.solves == 1 and m2.solves == 0
            assert state.snapshot()["budget_stops_total"] == 1
        finally:
            httpd.shutdown(); httpd.server_close()
            state.stop_poller()
            m1.close(); m2.close()

    def test_budget_burned_router_side_is_a_router_504(self):
        m1 = _ScriptedMember()
        httpd, state, base = _start_router([m1.url])
        try:
            code, payload, _ = _post(
                base, "/solve", self.BODY,
                headers={"X-Deadline-Ms": "0"},
            )
            assert code == 504
            assert "router" in payload["error"]
            assert m1.solves == 0  # no replica marched doomed work
        finally:
            httpd.shutdown(); httpd.server_close()
            state.stop_poller()
            m1.close()

    def test_unparseable_budget_forwarded_replica_owns_the_400(self):
        m1 = _ScriptedMember()
        httpd, state, base = _start_router([m1.url])
        try:
            code, _, _ = _post(
                base, "/solve", self.BODY,
                headers={"X-Deadline-Ms": "soon"},
            )
            assert code == 200  # scripted member answers; contract is
            assert m1.solves == 1  # "forwarded, not router-rejected"
            assert _hget(m1.seen_headers[0], "X-Deadline-Ms") == "soon"
        finally:
            httpd.shutdown(); httpd.server_close()
            state.stop_poller()
            m1.close()


class TestRouterApiKeys:
    """Satellite carry-over: API keys terminate at the router; the
    mapped tenant label - never the caller's claim - travels on as
    X-Wavetpu-Tenant."""

    BODY = {"N": 8, "timesteps": 4}

    def test_load_api_keys_parses_and_validates(self, tmp_path):
        p = tmp_path / "keys.json"
        # PR-12 flat schema: plain tenant-label strings normalize to
        # identity-only configs (no quotas, default classes).
        p.write_text(json.dumps({"k1": "acme", "k2": "umbrella"}))
        keys = load_api_keys(str(p))
        assert {k: c.tenant for k, c in keys.items()} == {
            "k1": "acme", "k2": "umbrella"
        }
        assert keys["k1"].rps is None
        assert keys["k1"].cells_per_s is None
        assert keys["k1"].priority == "batch"
        assert keys["k1"].priority_ceiling == "interactive"
        # QoS schema: config objects carry quota + class policy; a
        # default class above the ceiling is clamped at parse time.
        p.write_text(json.dumps({
            "k1": "acme",
            "k2": {"tenant": "umbrella", "priority": "interactive",
                   "priority_ceiling": "batch", "rps": 5,
                   "burst": 10, "cells_per_s": 1e6},
        }))
        keys = load_api_keys(str(p))
        assert keys["k1"].tenant == "acme"
        c = keys["k2"]
        assert c.tenant == "umbrella"
        assert c.priority == "batch"  # clamped at the ceiling
        assert c.priority_ceiling == "batch"
        assert c.rps == 5 and c.burst == 10 and c.cells_per_s == 1e6
        assert c.cells_burst is None
        for bad in (["k1"], {}, {"k": 5}, {"": "t"}, {"k": ""},
                    {"k": {}}, {"k": {"tenant": ""}},
                    {"k": {"tenant": "t", "rps": 0}},
                    {"k": {"tenant": "t", "rps": "fast"}}):
            p.write_text(json.dumps(bad))
            with pytest.raises(ValueError):
                load_api_keys(str(p))

    def test_keys_gate_solve_and_stamp_the_mapped_tenant(self):
        m = _ScriptedMember()
        httpd, state, base = _start_router(
            [m.url], api_keys={"k1": "acme"}
        )
        try:
            # no key / unknown key: 401 with a challenge, nothing
            # forwarded
            code, _, headers = _post(base, "/solve", self.BODY)
            assert code == 401
            assert _hget(headers, "WWW-Authenticate") == "Bearer"
            code, _, _ = _post(base, "/solve", self.BODY,
                               headers={"X-Api-Key": "nope"})
            assert code == 401
            assert m.solves == 0
            # Bearer form; a spoofed tenant claim is REPLACED by the
            # key's mapped label
            code, _, _ = _post(base, "/solve", self.BODY, headers={
                "Authorization": "Bearer k1",
                "X-Wavetpu-Tenant": "evil",
            })
            assert code == 200
            assert _hget(m.seen_headers[-1], "X-Wavetpu-Tenant") == "acme"
            # X-Api-Key form
            code, _, _ = _post(base, "/solve", self.BODY,
                               headers={"X-Api-Key": "k1"})
            assert code == 200
            assert _hget(m.seen_headers[-1], "X-Wavetpu-Tenant") == "acme"
            snap = state.snapshot()
            assert snap["auth_rejected_total"] == 2
            assert snap["requests_per_tenant"] == {"acme": 2}
            # health stays unauthenticated (probes, fleet tooling)
            code, _ = _get(base, "/healthz")
            assert code == 200
        finally:
            httpd.shutdown(); httpd.server_close()
            state.stop_poller()
            m.close()

    def test_keys_off_passes_the_tenant_header_through(self):
        m = _ScriptedMember()
        httpd, state, base = _start_router([m.url])
        try:
            code, _, _ = _post(base, "/solve", self.BODY,
                               headers={"X-Wavetpu-Tenant": "acme"})
            assert code == 200
            assert _hget(m.seen_headers[0], "X-Wavetpu-Tenant") == "acme"
            assert state.snapshot()["requests_per_tenant"] == {
                "acme": 1
            }
        finally:
            httpd.shutdown(); httpd.server_close()
            state.stop_poller()
            m.close()


class TestRouterTracing:
    """The router's half of the fleet trace contract
    (docs/observability.md "Distributed tracing"): an untraced router
    forwards and echoes the inbound traceparent verbatim; a traced one
    adopts it as the remote parent of `router.request`, re-parents
    each upstream attempt under a fresh wire id, and marks retries."""

    BODY = {"N": 8, "timesteps": 4}

    def test_untraced_router_forwards_and_echoes_verbatim(self):
        m = _ScriptedMember()
        httpd, state, base = _start_router([m.url])
        tp = "00-" + "ab" * 16 + "-" + "12" * 8 + "-01"
        try:
            code, _body, hdrs = _post(
                base, "/solve", self.BODY,
                headers={"traceparent": tp},
            )
            assert code == 200
            assert _hget(hdrs, "traceparent") == tp
            assert _hget(m.seen_headers[0], "traceparent") == tp
            # no inbound context: nothing invented, nothing echoed
            code, _body, hdrs = _post(base, "/solve", self.BODY)
            assert code == 200
            assert _hget(hdrs, "traceparent") is None
            assert _hget(m.seen_headers[1], "traceparent") is None
        finally:
            httpd.shutdown(); httpd.server_close()
            state.stop_poller()
            m.close()

    def test_traced_router_spans_reparent_the_attempt(self, tmp_path):
        from wavetpu.obs import tracing
        m = _ScriptedMember()
        httpd, state, base = _start_router(
            [m.url], telemetry_dir=str(tmp_path / "rt")
        )
        tid, wire = "ab" * 16, "12" * 8
        try:
            code, _body, hdrs = _post(
                base, "/solve", self.BODY,
                headers={"traceparent": f"00-{tid}-{wire}-01",
                         "X-Request-Id": "req-tr-1"},
            )
            assert code == 200
            # echo carries the router's OWN context on the same trace
            echoed = tracing.parse_traceparent(
                _hget(hdrs, "traceparent")
            )
            assert echoed is not None
            assert echoed[0] == tid and echoed[1] != wire
            # the member saw the ATTEMPT's wire id, not the client's
            fwd = tracing.parse_traceparent(
                _hget(m.seen_headers[0], "traceparent")
            )
            assert fwd is not None
            assert fwd[0] == tid
            assert fwd[1] not in (wire, echoed[1])
        finally:
            httpd.shutdown(); httpd.server_close()
            state.stop_poller()
            state.tracer.close()
            m.close()
        recs = [
            json.loads(l)
            for l in open(str(tmp_path / "rt" / "trace.jsonl"))
        ]
        req = [r for r in recs if r["kind"] == "router.request"]
        att = [r for r in recs if r["kind"] == "router.attempt"]
        assert len(req) == 1 and len(att) == 1
        assert req[0]["trace_id"] == tid
        assert req[0]["parent_id"] == wire        # the client's wire id
        assert req[0]["attrs"]["w3c_id"] == echoed[1]
        assert req[0]["attrs"]["request_id"] == "req-tr-1"
        assert att[0]["trace_id"] == tid
        assert att[0]["parent_id"] == req[0]["span_id"]
        assert att[0]["attrs"]["w3c_id"] == fwd[1]
        assert att[0]["attrs"]["member"] == m.url

    def test_traced_retry_is_marked_and_stays_one_trace(self, tmp_path):
        # affinity pins the first attempt at holder `a`, whose severed
        # connection forces the cross-member retry onto `b`
        kd = progkey.key_from_program_key(
            progkey.identity_from_body(
                self.BODY, platform="cpu"
            ).program_key(4, True)
        )
        a = _ScriptedMember(warm_keys={"memory": [kd], "disk": []})
        b = _ScriptedMember()
        a.solve_script = ["drop"]
        httpd, state, base = _start_router(
            [a.url, b.url], telemetry_dir=str(tmp_path / "rt")
        )
        tid = "cd" * 16
        try:
            code, _body, _hdrs = _post(
                base, "/solve", self.BODY,
                headers={"traceparent": f"00-{tid}-{'34' * 8}-01"},
            )
            assert code == 200
            assert a.solves == 1 and b.solves == 1
        finally:
            httpd.shutdown(); httpd.server_close()
            state.stop_poller()
            state.tracer.close()
            a.close(); b.close()
        recs = [
            json.loads(l)
            for l in open(str(tmp_path / "rt" / "trace.jsonl"))
        ]
        atts = [r for r in recs if r["kind"] == "router.attempt"]
        retries = [r for r in recs if r["kind"] == "router.retry"]
        assert len(atts) == 2 and len(retries) == 1
        assert all(r["trace_id"] == tid for r in atts + retries)
        # both attempts carry DISTINCT wire ids under one request span
        assert (atts[0]["attrs"]["w3c_id"]
                != atts[1]["attrs"]["w3c_id"])
        assert atts[0]["parent_id"] == atts[1]["parent_id"]


def _start_replica(**kw):
    kw.setdefault("max_wait", 0.02)
    kw.setdefault("default_kernel", "roll")
    kw.setdefault("interpret", True)
    httpd, state = build_server(port=0, **kw)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, state, f"http://127.0.0.1:{httpd.server_address[1]}"


def _stop_replica(httpd, state):
    try:
        httpd.shutdown()
    except Exception:
        pass
    state.batcher.close(timeout=30.0, drain=False)
    httpd.server_close()


class TestFleetChaos:
    def test_member_faults_absorbed_by_router_zero_retry_client(self):
        """Satellite: WAVETPU_FAULT conn-drop + worker-crash specs at
        ONE member of a two-replica fleet.  The router retries the
        transport error and the crashed-worker 503 onto the live
        member, so even a ZERO-retry client sees only 200s."""
        plan = faults.parse_serve_spec(
            "serve-conn-drop:after=1,count=1;"
            "serve-worker-crash:after=1,count=1"
        )
        h1, s1, u1 = _start_replica(fault_plan=plan)
        h2, s2, u2 = _start_replica()
        httpd, state, base = _start_router(
            [u1, u2], poll_interval_s=60.0, proxy_timeout=60.0
        )
        try:
            # Warm u1 DIRECTLY (the after=1 budgets skip this request
            # and its batch), then poll: u1 now advertises the key, so
            # the router's first routed pick lands on the faulted
            # member - the seam the chaos must cross.
            direct = WavetpuClient(u1, retries=0, timeout=60.0)
            assert direct.solve({"N": 8, "timesteps": 4}).ok
            state.table.poll_once()
            client = WavetpuClient(base, retries=0, timeout=60.0)
            outs = []
            for i in range(20):
                # distinct phases dodge request coalescing; loop until
                # both faults have fired through the router
                outs.append(client.solve(
                    {"N": 8, "timesteps": 4, "phase": 1.0 + i}
                ))
                fired = {
                    s["kind"]: s["fired"] for s in plan.snapshot()
                }
                if (fired.get("conn-drop") and
                        fired.get("worker-crash")):
                    break
            assert all(o.ok for o in outs), [
                (o.status, o.error) for o in outs if not o.ok
            ]
            assert all(o.attempts == 1 for o in outs)  # zero retries
            fired = {s["kind"]: s["fired"] for s in plan.snapshot()}
            assert fired["conn-drop"] == 1
            assert fired["worker-crash"] == 1
            assert state.snapshot()["retried_requests"] >= 2
        finally:
            httpd.shutdown(); httpd.server_close()
            state.stop_poller()
            _stop_replica(h1, s1)
            _stop_replica(h2, s2)


# ---- acceptance: the rolling-deploy drill ----


class TestRollingDeployDrill:
    def test_roll_under_load_zero_errors_zero_cold_compiles(
        self, tmp_path
    ):
        """ISSUE acceptance: closed-loop replay THROUGH THE ROUTER over
        a two-replica fleet while one replica is rolled out and its
        successor (sharing the persistent program cache) rolled in -
        via the real `fleet roll` driver against the router's admin
        API.  Asserts: zero client-visible errors, ZERO fresh compiles
        in the replay window (--max-cold-compiles 0 equivalent on the
        router-fronted report: the successor disk-adopts, never
        recompiles), and >= 90%% of warm-key requests routed to a
        holder (affinity hit rate from the router's /metrics)."""
        cache_dir = str(tmp_path / "progcache")
        # max_batch=1: closed-loop concurrency 3 would otherwise
        # coalesce into bucket-2 programs the sequential warmup never
        # compiled - a batcher first-contact cost, not a cutover cost.
        # Pinning the bucket makes "zero fresh compiles" measure the
        # roll alone.
        rep_kw = dict(program_cache_dir=cache_dir, max_batch=1)
        h1, s1, u1 = _start_replica(**rep_kw)
        h2, s2, u2 = _start_replica(**rep_kw)
        httpd, state, base = _start_router(
            [u1, u2], poll_interval_s=0.3, proxy_timeout=120.0,
        )
        scenarios = [
            {"name": "t4", "weight": 2, "body": {"N": 8, "timesteps": 4}},
            {"name": "t6", "weight": 1, "body": {"N": 8, "timesteps": 6}},
        ]
        records = trace.generate(
            "uniform", 4.0, 8.0, scenarios=scenarios, seed=11
        )
        h3 = s3 = None
        roll_result = {}

        def _roll():
            nonlocal h3, s3
            # the successor: same shared program cache -> every program
            # the fleet compiled is a DISK ADOPTION, not a compile
            h, s, u = _start_replica(**rep_kw)
            h3, s3 = h, s
            roll_result["url"] = u
            roll_result["rc"] = fleet_roll.roll(
                base, old_url=u1, new_url=u,
                spawn_argv=None, manifest_path=None,
                timeout_s=60.0, leave_sync=True,
                log=lambda *a, **k: None,
            )

        roller = threading.Thread(target=_roll, daemon=True)
        try:
            # warmup=2: both tiers compiled + disk-persisted per the
            # affinity-routed holder BEFORE the measured window
            deadline = threading.Timer(1.0, roller.start)
            deadline.start()
            result = runner.replay(
                base, records, mode="closed", concurrency=3,
                warmup=2, timeout=120.0, retries=2, duration=10.0,
            )
            roller.join(90.0)
            assert roll_result.get("rc") == 0, roll_result
            report = lg_report.build_report(result, target=base)
            # 1. zero client-visible errors across the cutover
            assert report["errors"] == 0, report
            # 2. zero fresh compiles in the replay window: the gate the
            # CI smoke runs as --max-cold-compiles 0 --error-budget 0
            violations = lg_report.gate(report, slo={
                "error_budget": 0.0, "max_cold_compiles": 0,
            })
            assert violations == [], violations
            # 3. affinity kept landing warm keys on holders (>= 90%)
            aff = state.snapshot()["affinity"]
            assert aff["hit_rate"] is not None
            assert aff["hit_rate"] >= 0.90, aff
            # 4. the roll really happened: predecessor retired, the
            # successor served traffic
            assert state.table.get(u1).state == LEFT
            per_member = {
                row["url"]: row["proxied_total"]
                for row in state.snapshot()["members"]
            }
            assert per_member.get(roll_result["url"], 0) > 0, per_member
        finally:
            httpd.shutdown(); httpd.server_close()
            state.stop_poller()
            _stop_replica(h1, s1)
            _stop_replica(h2, s2)
            if h3 is not None:
                _stop_replica(h3, s3)

    def test_roll_hands_off_inflight_long_solve(self, tmp_path, capsys):
        """ISSUE tentpole acceptance (drain-roll leg): a chunked long
        solve is IN FLIGHT at the predecessor when `fleet roll` drains
        it.  The drain checkpoints the march (503 + resume_token), the
        router re-injects the token on its member retry, and the
        successor - sharing --solve-state-dir - resumes from the last
        completed chunk.  The zero-retry client sees ONE attempt, a
        200, and a report exactly equal to an unpreempted run's.

        Tracing leg: router and both replicas write telemetry, and ONE
        command - `wavetpu trace-report --dir routerT --dir replA
        --dir replB --request ID` - reconstructs the handed-off solve
        as a single tree under the client's trace id: router attempts,
        both replicas' serve.request spans, the drain-handoff mark,
        and chunk spans from BOTH sides of the preemption."""
        from wavetpu.cli import main as cli_main
        from wavetpu.obs import report as trace_report
        from wavetpu.obs import tracing
        router_t = str(tmp_path / "routerT")
        repl_a = str(tmp_path / "replA")
        repl_b = str(tmp_path / "replB")
        # the in-process stand-in for per-replica --telemetry-dir: the
        # module tracer is replica A's until the drain completes, then
        # replica B's (the router owns its own Tracer either way)
        tracing.configure(repl_a + "/trace.jsonl")
        state_dir = str(tmp_path / "state")
        body = {"N": 8, "timesteps": 33}
        chunk_kw = dict(chunk_threshold=8, chunk_steps=4,
                        solve_state_dir=state_dir)
        # every chunk round of the long tier sleeps 0.5s at the
        # predecessor: the march is still mid-flight when the roll's
        # drain lands (the successor carries no fault - resumed chunks
        # run at full speed)
        plan = faults.parse_serve_spec(
            "serve-slow-batch:seconds=0.5,timesteps=33"
        )
        h1, s1, u1 = _start_replica(fault_plan=plan, **chunk_kw)
        httpd, state, base = _start_router(
            [u1], poll_interval_s=0.3, proxy_timeout=120.0,
            telemetry_dir=router_t,
        )
        h3 = s3 = None
        u3 = None
        victim = {}
        roll_result = {}
        try:
            # control: the same long solve, unpreempted (also warms
            # u1's chunk programs, so the victim marches immediately)
            direct = WavetpuClient(u1, retries=0, timeout=120.0)
            control = direct.solve(body)
            assert control.ok, (control.status, control.error)
            assert control.payload["batch"]["chunked"] is True
            base_chunks = s1.metrics.snapshot()["chunks_total"]

            def _solve():
                client = WavetpuClient(base, retries=0, timeout=120.0)
                victim["out"] = client.solve(body)

            vt = threading.Thread(target=_solve, daemon=True)
            vt.start()
            # wait until the victim's march is genuinely mid-flight
            deadline = time.monotonic() + 30.0
            while (s1.metrics.snapshot()["chunks_total"] <= base_chunks
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            assert s1.metrics.snapshot()["chunks_total"] > base_chunks

            # successor: clean (no fault plan), same shared state dir
            h3, s3, u3 = _start_replica(**chunk_kw)

            def _roll():
                roll_result["rc"] = fleet_roll.roll(
                    base, old_url=u1, new_url=u3,
                    spawn_argv=None, manifest_path=None,
                    timeout_s=60.0, leave_sync=True,
                    log=lambda *a, **k: None,
                )

            rt = threading.Thread(target=_roll, daemon=True)
            rt.start()
            # a real serve process drains its batcher in main()'s
            # finally once /admin/drain stops the accept loop; the
            # in-process replica does that step here
            deadline = time.monotonic() + 30.0
            while not s1.draining and time.monotonic() < deadline:
                time.sleep(0.02)
            assert s1.draining
            # the successor's spans go to its own telemetry dir (in a
            # real fleet this is B's --telemetry-dir; records still
            # racing out of A's drain merge fine - the joiner reads
            # every --dir)
            tracing.configure(repl_b + "/trace.jsonl")
            s1.batcher.close(timeout=60.0, drain=True)
            rt.join(90.0)
            vt.join(90.0)
            assert roll_result.get("rc") == 0, roll_result
            out = victim.get("out")
            assert out is not None and out.ok, (
                out and (out.status, out.error, out.payload)
            )
            # the handoff was invisible: ONE attempt (zero client
            # retries), answered by the successor
            assert out.attempts == 1
            assert out.headers.get("X-Wavetpu-Member") == u3
            # exact parity with the unpreempted control: the report's
            # per-checkpoint error lists are the full float values
            cr, vr = control.payload["report"], out.payload["report"]
            assert vr["final_step"] == cr["final_step"] == 33
            assert vr["abs_errors"] == cr["abs_errors"]
            assert vr["rel_errors"] == cr["rel_errors"]
            # the resume really crossed replicas via the shared dir
            assert out.payload["batch"]["resumed_from"] >= 1
            assert s1.metrics.snapshot()["preempted_total"] >= 1
            assert s3.metrics.snapshot()["resumed_total"] == 1
            assert state.snapshot()["resume_handoffs_total"] == 1
            assert state.table.get(u1).state == LEFT
        finally:
            httpd.shutdown(); httpd.server_close()
            state.stop_poller()
            _stop_replica(h1, s1)
            if h3 is not None:
                _stop_replica(h3, s3)
            if state.tracer is not None:
                state.tracer.close()
            tracing.disable()
        # ---- the one-command joiner over all three telemetry dirs ----
        rid = out.request_id
        tid = out.trace_id
        assert rid and tid
        paths = [
            d + "/trace.jsonl" for d in (router_t, repl_a, repl_b)
        ]
        # the router handler thread ends its span just AFTER the
        # response bytes reach the client - give the flush a moment
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            recs = trace_report.load_traces(paths)
            view = trace_report.request_view(recs, rid)
            if any(r["kind"] == "router.request" for r in view):
                break
            time.sleep(0.05)
        kinds = {r["kind"] for r in view}
        assert {"router.request", "router.attempt",
                "router.drain_handoff", "serve.request",
                "serve.chunk"} <= kinds, kinds
        # ONE trace id spans client->router->A->drain->B
        assert {r.get("trace_id")
                for r in view if r.get("trace_id")} == {tid}
        # both replicas answered this request...
        assert len([r for r in view
                    if r["kind"] == "serve.request"]) == 2
        # ...and chunk spans exist on BOTH sides of the preemption
        # (two distinct tracer namespaces marched chunks)
        assert len({r["span_id"].split("-")[0] for r in view
                    if r["kind"] == "serve.chunk"}) == 2
        # the pinned one-command form: `wavetpu trace-report` over the
        # three dirs reconstructs and annotates the same tree
        rc = cli_main([
            "trace-report", "--dir", router_t, "--dir", repl_a,
            "--dir", repl_b, "--request", rid,
        ])
        assert rc == 0
        text = capsys.readouterr().out
        assert "joined across 3 processes" in text
        assert "<-hop" in text
        assert "router.drain_handoff" in text
