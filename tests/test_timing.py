"""Phase-timing probes (solver/timing.py) on the 8-virtual-device CPU mesh."""

from wavetpu.solver import timing


def test_phase_breakdown_sharded(small_problem):
    pb = timing.measure_phase_breakdown(
        small_problem, mesh_shape=(2, 2, 2), iters=4, repeats=2
    )
    assert pb.loop_seconds > 0.0
    assert pb.exchange_seconds >= 0.0
    assert pb.steps_measured == 4
    assert pb.total_seconds == pb.loop_seconds + pb.exchange_seconds


def test_phase_breakdown_single_device(small_problem):
    pb = timing.measure_phase_breakdown(
        small_problem, mesh_shape=(1, 1, 1), iters=4, repeats=2
    )
    assert pb.loop_seconds > 0.0
    assert pb.exchange_seconds >= 0.0
