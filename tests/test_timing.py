"""Phase-timing probes (solver/timing.py) on the 8-virtual-device CPU mesh.

Round-3 verdict item 10: the probe must time the production step body (bc
mask + selected kernel), not a hand-rolled approximation of it.
"""

import pytest

from wavetpu.solver import sharded, timing


def test_phase_breakdown_sharded(small_problem):
    pb = timing.measure_phase_breakdown(
        small_problem, mesh_shape=(2, 2, 2), iters=4, repeats=2
    )
    assert pb.loop_seconds > 0.0
    assert pb.exchange_seconds >= 0.0
    assert pb.steps_measured == 4
    assert pb.total_seconds == pb.loop_seconds + pb.exchange_seconds


def test_phase_breakdown_single_device(small_problem):
    pb = timing.measure_phase_breakdown(
        small_problem, mesh_shape=(1, 1, 1), iters=4, repeats=2
    )
    assert pb.loop_seconds > 0.0
    assert pb.exchange_seconds >= 0.0


@pytest.mark.parametrize("kernel", ["roll", "pallas"])
def test_probe_uses_production_step(small_problem, monkeypatch, kernel):
    """The probe builds its step through sharded._make_local_step - the
    same factory the production solver uses - with the same kernel
    selection, once with exchange on and once off."""
    calls = []
    real = sharded._make_local_step

    def spy(problem, topo, dtype, kern, overlap, interpret, exchange=True):
        calls.append({"kernel": kern, "exchange": exchange})
        return real(problem, topo, dtype, kern, overlap, interpret,
                    exchange=exchange)

    monkeypatch.setattr(sharded, "_make_local_step", spy)
    timing.measure_phase_breakdown(
        small_problem, mesh_shape=(2, 2, 2), kernel=kernel,
        iters=2, repeats=1,
    )
    assert {c["kernel"] for c in calls} == {kernel}
    assert {c["exchange"] for c in calls} == {True, False}


def test_phase_breakdown_pallas_kernel(small_problem):
    """The probe runs the Pallas kernel (interpret mode on CPU) end to
    end - the shipped --kernel pallas path is what gets timed."""
    pb = timing.measure_phase_breakdown(
        small_problem, mesh_shape=(2, 2, 2), kernel="pallas",
        iters=2, repeats=1,
    )
    assert pb.loop_seconds > 0.0


def test_phase_breakdown_kfused(small_problem):
    """fuse_steps > 1 probes the x-sharded k-fused program: k-block scans
    with and without ppermute ghosts, scaled by the layers covered."""
    pb = timing.measure_phase_breakdown(
        small_problem, mesh_shape=(2, 1, 1), fuse_steps=4,
        iters=2, repeats=1,
    )
    assert pb.loop_seconds > 0.0
    assert pb.exchange_seconds >= 0.0
    assert pb.steps_measured == 8  # 2 blocks x k=4 layers


def test_phase_breakdown_kfused_xy_mesh(small_problem):
    """The k-fused probe covers (MX, MY, 1) meshes (round-5): the
    y-extended-block program is timed exactly as production runs it."""
    pb = timing.measure_phase_breakdown(
        small_problem, mesh_shape=(2, 2, 1), fuse_steps=4,
        iters=2, repeats=1,
    )
    assert pb.loop_seconds > 0.0
    assert pb.exchange_seconds >= 0.0
    assert pb.steps_measured == 8


def test_phase_breakdown_kfused_comp(small_problem):
    """scheme="compensated" with fuse_steps > 1 probes the velocity-form
    onion (round-6): (u, v, carry) state, u AND v exchanging ghosts, on
    1D and 2D meshes, including the carry-less bf16-increment mode."""
    import jax.numpy as jnp

    pb = timing.measure_phase_breakdown(
        small_problem, mesh_shape=(2, 1, 1), fuse_steps=4,
        scheme="compensated", iters=2, repeats=1,
    )
    assert pb.loop_seconds > 0.0
    assert pb.exchange_seconds >= 0.0
    assert pb.steps_measured == 8
    pb_xy = timing.measure_phase_breakdown(
        small_problem, mesh_shape=(2, 2, 1), fuse_steps=4,
        scheme="compensated", iters=2, repeats=1,
    )
    assert pb_xy.loop_seconds > 0.0
    pb_inc = timing.measure_phase_breakdown(
        small_problem, mesh_shape=(2, 1, 1), fuse_steps=4,
        scheme="compensated", v_dtype=jnp.bfloat16, iters=2, repeats=1,
    )
    assert pb_inc.loop_seconds > 0.0


def test_phase_breakdown_kfused_rejects_3d_mesh(small_problem):
    with pytest.raises(ValueError, match=r"\(MX, MY, 1\)"):
        timing.measure_phase_breakdown(
            small_problem, mesh_shape=(2, 2, 2), fuse_steps=4,
            iters=1, repeats=1,
        )
    with pytest.raises(ValueError, match="even"):
        # Uneven decompositions have no probe (CLI rejects the combo).
        timing.measure_phase_breakdown(
            type(small_problem)(
                N=small_problem.N - 1, Np=1, Lx=1.0, Ly=1.0, Lz=1.0,
                T=1.0, timesteps=small_problem.timesteps,
            ),
            mesh_shape=(2, 1, 1), fuse_steps=4, iters=1, repeats=1,
        )
