"""Ensemble-core contracts (wavetpu/ensemble/batched.py).

The load-bearing invariant: every lane of a batched solve is BITWISE
identical to the same problem solved solo on the same path - including
per-lane phases, per-lane stop layers (frozen by masking), per-lane
c2tau2 fields, and batches padded with masked filler lanes.  A change to
either the ensemble lane programs or the solo solvers that breaks these
equalities is a correctness regression, not a tolerance issue.
"""

import dataclasses

import numpy as np
import pytest

from wavetpu.core.problem import Problem
from wavetpu.ensemble import batched as eb
from wavetpu.kernels import stencil_pallas, stencil_ref
from wavetpu.solver import kfused, kfused_comp, leapfrog


def _bitwise(a, b):
    return np.array_equal(np.asarray(a), np.asarray(b))


@pytest.fixture(scope="module")
def problem():
    return Problem(N=16, timesteps=9)


@pytest.fixture(scope="module")
def lanes():
    # default phase, shifted phase, shifted phase + early stop
    return [
        eb.LaneSpec(),
        eb.LaneSpec(phase=1.0),
        eb.LaneSpec(phase=0.5, stop_step=5),
    ]


def _assert_lane_parity(res, solos):
    assert res.batched, res.fallback_reason
    assert res.fallback_reason is None
    for got, solo in zip(res.results, solos):
        assert _bitwise(got.u_cur, solo.u_cur)
        assert _bitwise(got.u_prev, solo.u_prev)
        assert got.final_step == solo.final_step
        assert np.array_equal(got.abs_errors, solo.abs_errors)
        assert np.array_equal(got.rel_errors, solo.rel_errors)


class TestLaneParity:
    def test_roll(self, problem, lanes):
        res = eb.solve_ensemble(problem, lanes, path="roll")
        solos = [
            leapfrog.solve(
                problem, phase=lane.phase, stop_step=lane.stop(problem)
            )
            for lane in lanes
        ]
        _assert_lane_parity(res, solos)

    def test_pallas(self, problem, lanes):
        res = eb.solve_ensemble(
            problem, lanes, path="pallas", interpret=True
        )
        solos = [
            leapfrog.solve(
                problem,
                step_fn=stencil_pallas.make_step_fn(interpret=True),
                phase=lane.phase,
                stop_step=lane.stop(problem),
            )
            for lane in lanes
        ]
        _assert_lane_parity(res, solos)

    def test_kfused(self, problem, lanes):
        res = eb.solve_ensemble(
            problem, lanes, path="kfused", k=2, interpret=True
        )
        solos = [
            kfused.solve_kfused(
                problem, k=2, interpret=True, phase=lane.phase,
                stop_step=lane.stop(problem),
            )
            for lane in lanes
        ]
        _assert_lane_parity(res, solos)

    def test_kfused_remainder_tail(self, lanes):
        # (10 - 1) % 2 == 1: the batch runs the masked 1-step tail the
        # solo march also runs.
        p10 = Problem(N=16, timesteps=10)
        res = eb.solve_ensemble(
            p10, lanes, path="kfused", k=2, interpret=True
        )
        solos = [
            kfused.solve_kfused(
                p10, k=2, interpret=True, phase=lane.phase,
                stop_step=lane.stop(p10),
            )
            for lane in lanes
        ]
        _assert_lane_parity(res, solos)


class TestPadding:
    def test_padded_lanes_leave_real_lanes_bitwise_unchanged(
        self, problem, lanes
    ):
        plain = eb.solve_ensemble(problem, lanes, path="roll")
        padded = eb.solve_ensemble(problem, lanes, path="roll", pad_to=8)
        assert padded.batch_size == 8
        assert padded.n_lanes == 3
        assert len(padded.results) == 3
        for a, b in zip(padded.results, plain.results):
            assert _bitwise(a.u_cur, b.u_cur)
            assert _bitwise(a.u_prev, b.u_prev)
            assert np.array_equal(a.abs_errors, b.abs_errors)

    def test_padding_lane_freezes_on_every_k_grid(self):
        lane = eb.padding_lane()
        assert lane.stop_step == 1  # (1-1) % k == 0 for all k

    def test_pad_below_batch_rejected(self, problem, lanes):
        with pytest.raises(ValueError, match="pad_to"):
            eb.solve_ensemble(problem, lanes, path="roll", pad_to=2)


class TestFields:
    @pytest.fixture(scope="class")
    def field(self, problem):
        return stencil_ref.make_c2tau2_field(
            problem,
            lambda x, y, z: problem.a2 * (
                1.0 - 0.3 * np.exp(
                    -((x - 0.5) ** 2 + (y - 0.5) ** 2 + (z - 0.5) ** 2)
                    / 0.1
                )
            ),
        )

    def test_roll_field_parity(self, problem, field):
        lanes = [
            eb.LaneSpec(c2tau2_field=field),
            eb.LaneSpec(stop_step=7),
        ]
        res = eb.solve_ensemble(
            problem, lanes, path="roll", compute_errors=False
        )
        assert res.batched
        solo0 = leapfrog.solve(
            problem, step_fn=stencil_ref.make_variable_c_step(field),
            compute_errors=False,
        )
        assert _bitwise(res.results[0].u_cur, solo0.u_cur)
        # The field-less lane rides the variable-c kernel with the
        # CONSTANT tau^2 a^2 field (fill_fields) - bitwise the solo
        # variable-c solve with that constant field.
        const = np.full((problem.N,) * 3, problem.a2tau2)
        solo1 = leapfrog.solve(
            problem, step_fn=stencil_ref.make_variable_c_step(const),
            compute_errors=False, stop_step=7,
        )
        assert _bitwise(res.results[1].u_cur, solo1.u_cur)

    def test_field_batch_rejects_shifted_phase(self, problem, field):
        with pytest.raises(ValueError, match="analytic layer-1"):
            eb.solve_ensemble(
                problem,
                [eb.LaneSpec(c2tau2_field=field), eb.LaneSpec(phase=0.7)],
                path="roll", compute_errors=False,
            )

    def test_pallas_field_parity(self, problem, field):
        res = eb.solve_ensemble(
            problem, [eb.LaneSpec(c2tau2_field=field), eb.LaneSpec()],
            path="pallas", compute_errors=False, interpret=True,
        )
        assert res.batched
        solo = leapfrog.solve(
            problem,
            step_fn=stencil_pallas.make_step_fn(
                interpret=True, c2tau2_field=field
            ),
            compute_errors=False,
        )
        assert _bitwise(res.results[0].u_cur, solo.u_cur)

    def test_kfused_field_parity(self, problem, field):
        res = eb.solve_ensemble(
            problem, [eb.LaneSpec(c2tau2_field=field), eb.LaneSpec()],
            path="kfused", k=2, compute_errors=False, interpret=True,
        )
        assert res.batched
        solo = kfused.solve_kfused(
            problem, k=2, interpret=True, compute_errors=False,
            c2tau2_field=field,
        )
        assert _bitwise(res.results[0].u_cur, solo.u_cur)

    def test_field_with_errors_rejected(self, problem, field):
        with pytest.raises(ValueError, match="no analytic oracle"):
            eb.solve_ensemble(
                problem, [eb.LaneSpec(c2tau2_field=field)], path="roll",
                compute_errors=True,
            )

    def test_field_shape_checked(self, problem):
        with pytest.raises(ValueError, match="shape"):
            eb.solve_ensemble(
                problem,
                [eb.LaneSpec(c2tau2_field=np.zeros((4, 4, 4)))],
                path="roll", compute_errors=False,
            )


class TestCompensatedLaneParity:
    """The tentpole contract: flagship compensated (Kahan) lanes batch
    through the vmapped core BITWISE equal to their solo compensated
    solves - state, error vectors, shifted phases, early stops, padded
    batches - on all three paths.  `solve_ensemble` must never report a
    compensated fallback on a backend where the path vmaps."""

    def test_roll(self, problem, lanes):
        res = eb.solve_ensemble(
            problem, lanes, scheme="compensated", path="roll"
        )
        solos = [
            leapfrog.solve_compensated(
                problem, phase=lane.phase, stop_step=lane.stop(problem)
            )
            for lane in lanes
        ]
        _assert_lane_parity(res, solos)

    def test_pallas(self, problem, lanes):
        res = eb.solve_ensemble(
            problem, lanes, scheme="compensated", path="pallas",
            interpret=True,
        )
        solos = [
            leapfrog.solve_compensated(
                problem,
                comp_step_fn=stencil_pallas.make_compensated_step_fn(
                    interpret=True
                ),
                phase=lane.phase, stop_step=lane.stop(problem),
            )
            for lane in lanes
        ]
        _assert_lane_parity(res, solos)

    def test_kfused_velocity_onion(self, problem, lanes):
        res = eb.solve_ensemble(
            problem, lanes, scheme="compensated", path="kfused", k=2,
            interpret=True,
        )
        solos = [
            kfused_comp.solve_kfused_comp(
                problem, k=2, interpret=True, phase=lane.phase,
                stop_step=lane.stop(problem),
            )
            for lane in lanes
        ]
        _assert_lane_parity(res, solos)

    def test_kfused_remainder_tail(self, lanes):
        # (10 - 1) % 2 == 1: the batch runs the masked k=1 tail through
        # the SAME velocity-form kernel the solo march does.
        p10 = Problem(N=16, timesteps=10)
        res = eb.solve_ensemble(
            p10, lanes, scheme="compensated", path="kfused", k=2,
            interpret=True,
        )
        solos = [
            kfused_comp.solve_kfused_comp(
                p10, k=2, interpret=True, phase=lane.phase,
                stop_step=lane.stop(p10),
            )
            for lane in lanes
        ]
        _assert_lane_parity(res, solos)

    def test_masked_padding_leaves_real_lanes_bitwise_unchanged(
        self, problem, lanes
    ):
        plain = eb.solve_ensemble(
            problem, lanes, scheme="compensated", path="kfused", k=2,
            interpret=True,
        )
        padded = eb.solve_ensemble(
            problem, lanes, scheme="compensated", path="kfused", k=2,
            interpret=True, pad_to=8,
        )
        assert padded.batch_size == 8 and padded.n_lanes == 3
        for a, b in zip(padded.results, plain.results):
            assert _bitwise(a.u_cur, b.u_cur)
            assert _bitwise(a.u_prev, b.u_prev)
            assert np.array_equal(a.abs_errors, b.abs_errors)
            assert np.array_equal(a.rel_errors, b.rel_errors)

    def test_no_compensated_fallback_on_vmapping_backends(self, problem):
        # Acceptance pin: fallback_reason must not mention the
        # compensated scheme on any path that vmaps on this backend.
        for path, k in (("roll", 1), ("pallas", 1), ("kfused", 2)):
            res = eb.solve_ensemble(
                problem, [eb.LaneSpec()], scheme="compensated",
                path=path, k=k, interpret=True,
            )
            assert res.batched, (path, res.fallback_reason)
            assert res.fallback_reason is None

    def test_compensated_field_batch_rejected(self, problem):
        field = np.full((problem.N,) * 3, problem.a2tau2)
        with pytest.raises(ValueError, match="compensated"):
            eb.solve_ensemble(
                problem, [eb.LaneSpec(c2tau2_field=field)],
                scheme="compensated", path="roll", compute_errors=False,
            )


class TestFallbacks:
    def test_probe_failure_falls_back_with_reason(
        self, problem, lanes, monkeypatch
    ):
        monkeypatch.setattr(
            eb, "vmap_capability",
            lambda *a, **k: (False, "forced-by-test"),
        )
        res = eb.solve_ensemble(problem, lanes, path="roll")
        assert res.batched is False
        assert "forced-by-test" in res.fallback_reason
        # The fallback still honors per-lane identity.
        solo = leapfrog.solve(problem, phase=1.0)
        assert _bitwise(res.results[1].u_cur, solo.u_cur)

    def test_compensated_probe_failure_lane_loop_honors_phase(
        self, problem, monkeypatch
    ):
        # The lane-loop fallback for the compensated scheme must pass
        # each lane's phase through to the solo compensated solver.
        monkeypatch.setattr(
            eb, "vmap_capability",
            lambda *a, **k: (False, "forced-by-test"),
        )
        res = eb.solve_ensemble(
            problem, [eb.LaneSpec(phase=1.0)], scheme="compensated",
            path="kfused", k=2, interpret=True,
        )
        assert res.batched is False
        solo = kfused_comp.solve_kfused_comp(
            problem, k=2, interpret=True, phase=1.0
        )
        assert _bitwise(res.results[0].u_cur, solo.u_cur)

    def test_probe_verdict_is_cached_per_scheme(self):
        eb._PROBE_CACHE.clear()
        try:
            ok1, _ = eb.vmap_capability("roll", interpret=True)
            assert ok1
            assert len(eb._PROBE_CACHE) == 1
            ok2, _ = eb.vmap_capability("roll", interpret=True)
            assert ok2 and len(eb._PROBE_CACHE) == 1
            # the compensated scheme probes (and caches) separately
            ok3, _ = eb.vmap_capability(
                "roll", interpret=True, scheme="compensated"
            )
            assert ok3 and len(eb._PROBE_CACHE) == 2
            probes = eb.probe_results()
            assert len(probes) == 2
            assert {p["scheme"] for p in probes} == {
                "standard", "compensated"
            }
            assert all(p["ok"] for p in probes)
        finally:
            eb._PROBE_CACHE.clear()


class TestValidation:
    def test_empty_batch_rejected(self, problem):
        with pytest.raises(ValueError, match="at least one lane"):
            eb.solve_ensemble(problem, [], path="roll")

    def test_bad_path_rejected(self, problem):
        with pytest.raises(ValueError, match="path"):
            eb.solve_ensemble(problem, [eb.LaneSpec()], path="cuda")

    def test_stop_out_of_range(self, problem):
        with pytest.raises(ValueError, match="stop_step"):
            eb.solve_ensemble(
                problem, [eb.LaneSpec(stop_step=99)], path="roll"
            )

    def test_kfused_misaligned_stop_rejected(self, problem):
        # stop=4: (4-1) % 2 != 0 and 4 != timesteps -> a lane cannot
        # freeze mid-block.
        with pytest.raises(ValueError, match="k-block"):
            eb.solve_ensemble(
                problem, [eb.LaneSpec(stop_step=4)], path="kfused", k=2,
                interpret=True,
            )

    def test_kfused_k_must_divide_n(self, problem):
        with pytest.raises(ValueError, match="divide"):
            eb.solve_ensemble(
                problem, [eb.LaneSpec()], path="kfused", k=3,
                interpret=True,
            )

    def test_solo_solvers_reject_phase_with_variable_c(self, problem):
        # The solver-level twin of the lane check: a shifted phase has
        # no analytic layer-1 bootstrap under variable c, and the solo
        # APIs must refuse rather than silently initialize from the
        # constant-speed solution.
        field = np.full((problem.N,) * 3, problem.a2tau2)
        with pytest.raises(ValueError, match="analytic"):
            kfused.solve_kfused(
                problem, k=2, interpret=True, compute_errors=False,
                c2tau2_field=field, phase=1.0,
            )
        with pytest.raises(ValueError, match="analytic"):
            leapfrog.solve(
                problem,
                step_fn=stencil_ref.make_variable_c_step(field),
                compute_errors=False, phase=1.0,
            )


class TestPhaseAccuracy:
    """The phase-shifted IVP has nonzero initial velocity u_t(0) =
    -a_t sin(phase) Sx Sy Sz; without the tau * u_t(0) layer-1 term
    (leapfrog.phase_velocity_coeff) the solver integrates a DIFFERENT
    problem than the oracle measures and the reported "error" is O(1) -
    the serving-path defect this suite pins against regression."""

    def test_shifted_phase_errors_stay_discretization_small(self):
        p = Problem(N=32, timesteps=20)
        ref = leapfrog.solve(p).abs_errors.max()
        for ph in (1.0, 0.5, 5.98):
            e = leapfrog.solve(p, phase=ph).abs_errors.max()
            # without the velocity term these sit at 0.27-0.94 (O(1));
            # with it they are the same discretization class as the
            # reference phase (~1e-3 at N=32/20 f32)
            assert e < 10 * ref, f"phase={ph}: {e} vs ref {ref}"

    def test_kfused_shifted_phase_accuracy(self):
        p = Problem(N=32, timesteps=20)
        e = kfused.solve_kfused(
            p, k=4, interpret=True, phase=1.0
        ).abs_errors.max()
        assert e < 1e-2

    def test_default_phase_is_the_reference_program(self, problem):
        # phase=2*pi must be bit-identical to the phase-less call (the
        # velocity term is statically absent at the reference phase).
        a = leapfrog.solve(problem)
        b = leapfrog.solve(problem, phase=2.0 * np.pi)
        assert _bitwise(a.u_cur, b.u_cur)
        assert np.array_equal(a.abs_errors, b.abs_errors)


class TestResultShape:
    def test_aggregate_throughput_sums_lanes(self, problem, lanes):
        res = eb.solve_ensemble(problem, lanes, path="roll")
        cells = sum(
            problem.cells_per_step * lane.stop(problem) for lane in lanes
        )
        expect = cells / res.solve_seconds / 1e9
        assert res.aggregate_gcells_per_second == pytest.approx(expect)

    def test_error_arrays_trimmed_to_lane_stop(self, problem, lanes):
        res = eb.solve_ensemble(problem, lanes, path="roll")
        assert len(res.results[2].abs_errors) == 5 + 1
        assert res.results[2].steps_computed == 5

    def test_lane_spec_defaults(self, problem):
        lane = eb.LaneSpec()
        assert lane.stop(problem) == problem.timesteps
        assert dataclasses.replace(lane, stop_step=3).stop(problem) == 3
