"""Multi-process launch: jax.distributed.initialize + rank-0 gating.

The round-3 verdict item 7: every reference variant gates its output on
rank 0 (mpi_new.cpp:356-371); the CLI's --distributed flag reproduces that
contract.  The smoke test runs the REAL CLI in two OS processes over a
Gloo-backed 2-process CPU cluster (1 local device each, mesh (2,1,1)) and
checks that exactly one process writes the report - the multi-host path
exercised without a pod.
"""

import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from wavetpu.core.problem import Problem
from wavetpu.solver import sharded

# The two-process gates need a jaxlib whose CPU backend implements
# multiprocess collectives (the Gloo path, selected via the
# jax_cpu_collectives_implementation config).  On older jaxlibs the CPU
# compiler refuses outright ("Multiprocess computations aren't
# implemented on the CPU backend"), so the gates are skipped rather than
# failing on an environment capability the code cannot supply.
pytestmark = pytest.mark.skipif(
    not hasattr(jax.config, "jax_cpu_collectives_implementation"),
    reason="this jaxlib's CPU backend has no multiprocess collectives",
)

def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch(pid: int, out_dir: str, port: int, extra=()):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # 1 local CPU device per process
    env.update(
        JAX_PLATFORMS="cpu",
        JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
        JAX_NUM_PROCESSES="2",
        JAX_PROCESS_ID=str(pid),
    )
    return subprocess.Popen(
        [
            sys.executable, "-m", "wavetpu.cli",
            "16", "1", "1", "1", "1", "1", "5",
            "--distributed", "--mesh", "2,1,1", "--out-dir", out_dir,
            *extra,
        ],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )


def test_two_process_cli_writes_one_report(tmp_path):
    out0 = str(tmp_path / "p0")
    out1 = str(tmp_path / "p1")
    os.makedirs(out0)
    os.makedirs(out1)
    # Separate out dirs per process: a write by the non-main process would
    # be visible as a file in out1.
    port = _free_port()
    procs = [_launch(0, out0, port), _launch(1, out1, port)]
    outs = [p.communicate(timeout=240)[0] for p in procs]
    assert procs[0].returncode == 0, outs[0]
    assert procs[1].returncode == 0, outs[1]

    # Exactly one report, written by process 0.
    assert os.listdir(out1) == []
    files = sorted(os.listdir(out0))
    assert files == [
        "output_N16_Np2_TPU.json", "output_N16_Np2_TPU.txt"
    ]
    # Process 0 speaks; process 1 stays silent (Gloo's own connection
    # banner is not ours to suppress).
    assert "C = " in outs[0]
    assert "report:" in outs[0]
    assert "C = " not in outs[1]
    assert "report:" not in outs[1]

    # And the distributed answer equals the in-process sharded solve.
    side = json.load(open(os.path.join(out0, "output_N16_Np2_TPU.json")))
    local = sharded.solve_sharded(
        Problem(N=16, timesteps=5), mesh_shape=(2, 1, 1)
    )
    np.testing.assert_allclose(
        side["abs_errors"], local.abs_errors, rtol=1e-5, atol=1e-8
    )


def test_two_process_kfused(tmp_path):
    """The x-sharded k-fused solver also runs multi-process: 2 OS
    processes, 1 device each, --fuse-steps 2, rank-0 gating intact and
    errors matching the in-process run."""
    from wavetpu.solver import sharded_kfused

    out0 = str(tmp_path / "p0")
    out1 = str(tmp_path / "p1")
    os.makedirs(out0)
    os.makedirs(out1)
    port = _free_port()
    extra = ("--fuse-steps", "2")
    procs = [
        _launch(0, out0, port, extra), _launch(1, out1, port, extra)
    ]
    outs = [p.communicate(timeout=240)[0] for p in procs]
    assert procs[0].returncode == 0, outs[0]
    assert procs[1].returncode == 0, outs[1]
    assert os.listdir(out1) == []
    assert "fuse-steps: 2" in outs[0]

    side = json.load(open(os.path.join(out0, "output_N16_Np2_TPU.json")))
    local = sharded_kfused.solve_sharded_kfused(
        Problem(N=16, timesteps=5), n_shards=2, k=2, interpret=True
    )
    np.testing.assert_allclose(
        side["abs_errors"], local.abs_errors, rtol=1e-5, atol=1e-8
    )


def test_two_process_compensated_kfused(tmp_path):
    """The distributed FLAGSHIP (velocity-form compensated k-fusion) runs
    multi-process: 2 OS processes, 1 device each, rank-0 gating intact
    and errors matching the in-process run."""
    from wavetpu.solver import kfused_comp

    out0 = str(tmp_path / "p0")
    out1 = str(tmp_path / "p1")
    os.makedirs(out0)
    os.makedirs(out1)
    port = _free_port()
    extra = ("--scheme", "compensated", "--fuse-steps", "2")
    procs = [
        _launch(0, out0, port, extra), _launch(1, out1, port, extra)
    ]
    outs = [p.communicate(timeout=240)[0] for p in procs]
    assert procs[0].returncode == 0, outs[0]
    assert procs[1].returncode == 0, outs[1]
    assert os.listdir(out1) == []
    assert "scheme: compensated" in outs[0]
    assert "fuse-steps: 2" in outs[0]

    side = json.load(open(os.path.join(out0, "output_N16_Np2_TPU.json")))
    local = kfused_comp.solve_kfused_comp_sharded(
        Problem(N=16, timesteps=5), n_shards=2, k=2, interpret=True
    )
    np.testing.assert_allclose(
        side["abs_errors"], local.abs_errors, rtol=1e-4, atol=1e-7
    )
