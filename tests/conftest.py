"""Test environment: CPU backend with 8 virtual devices, x64 enabled.

This is the "fake backend" the reference lacks (SURVEY.md section 4): the
sharded solver's multi-chip semantics are exercised on an 8-device CPU mesh
(`--xla_force_host_platform_device_count=8`) without TPU hardware, and f64 is
available for parity against the native C++ oracle.

Hermeticity note: this image pre-imports jax at interpreter startup (a
sitecustomize hook registering the TPU PJRT plugin) and exports
JAX_PLATFORMS=tpu-ish, so mutating that env var here is too late.  Backend
*initialization* is lazy, however, so `jax.config.update("jax_platforms")`
plus an XLA_FLAGS mutation (both read at first backend creation) pin the
suite to CPU regardless of the caller's environment.
"""

import os

# XLA_FLAGS is read when the CPU client is created (lazily), so mutating it
# here is still early enough even though jax is already imported.
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402

from wavetpu.core.problem import Problem  # noqa: E402


def pytest_sessionstart(session):
    devs = jax.devices()
    assert devs[0].platform == "cpu", f"suite must run on CPU, got {devs}"
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"


@pytest.fixture(scope="session")
def small_problem():
    return Problem(N=16, Np=1, Lx=1.0, Ly=1.0, Lz=1.0, T=1.0, timesteps=10)


@pytest.fixture(scope="session")
def medium_problem():
    return Problem(N=32, Np=1, Lx=1.0, Ly=1.0, Lz=1.0, T=1.0, timesteps=20)
