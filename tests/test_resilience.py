"""Serve-path resilience contracts: deadlines, load shedding, worker
supervision, circuit-breaking program quarantine, the retrying client,
and the chaos harness.

The acceptance-level drill at the bottom is the ISSUE's chaos scenario:
injected compile failures on one tier plus a mid-replay scheduler-worker
kill, driven by the retrying client - it must complete with ZERO
client-visible 5xx (all absorbed by retry/backoff), the poisoned tier's
breaker must open while other tiers keep serving, and no future may
hang past its deadline.
"""

import json
import random
import threading
import time
import types
import urllib.request

import pytest

from wavetpu.client import (
    RETRIABLE_STATUSES,
    WavetpuClient,
    parse_retry_after,
)
from wavetpu.core.problem import Problem
from wavetpu.ensemble import batched as eb
from wavetpu.run import faults
from wavetpu.serve.api import build_server
from wavetpu.serve.engine import ServeEngine
from wavetpu.serve.resilience import (
    CircuitBreaker,
    DeadlineExceededError,
    QuarantinedError,
    WorkerCrashError,
)
from wavetpu.serve.scheduler import (
    DynamicBatcher,
    ServeMetrics,
    SolveRequest,
)


def _req(problem, **kw):
    return SolveRequest(problem=problem, lane=eb.LaneSpec(**kw))


class _FakeEngine:
    """Engine stub (mirrors test_serve's) recording batch occupancies."""

    max_batch = 4

    def __init__(self):
        self.batches = []

    def solve(self, problem, lanes, scheme, path, k, dtype_name,
              mesh=None, timing=None):
        if timing is not None:
            timing["compile_seconds"] = 0.0
            timing["warm"] = "true"
        self.batches.append(len(lanes))
        results = [
            types.SimpleNamespace(steps_computed=problem.timesteps)
            for _ in lanes
        ]
        res = types.SimpleNamespace(
            results=results, n_lanes=len(lanes), batch_size=len(lanes),
            batched=True, fallback_reason=None, path=path,
            solve_seconds=0.01, aggregate_gcells_per_second=1.0,
        )
        return res, [None] * len(lanes)


# ---- circuit breaker unit contracts ----


class TestCircuitBreaker:
    def test_opens_after_k_consecutive_failures_and_sheds(self):
        br = CircuitBreaker(threshold=3, cooldown_s=60.0)
        key = ("tier-a",)
        err = RuntimeError("compile exploded")
        br.admit(key)  # closed: free
        br.record_failure(key, err)
        br.admit(key)  # 1 failure < threshold: still closed
        br.record_failure(key, err)
        br.admit(key)
        br.record_failure(key, err)  # third consecutive: opens
        with pytest.raises(QuarantinedError) as ei:
            br.admit(key)
        assert 0 < ei.value.retry_after_s <= 60.0
        assert "quarantined" in str(ei.value)
        snap = br.snapshot()
        assert snap["open"] == 1
        assert snap["keys"][0]["state"] == "open"
        assert "compile exploded" in snap["keys"][0]["last_error"]

    def test_success_resets_consecutive_count(self):
        br = CircuitBreaker(threshold=2, cooldown_s=60.0)
        key = ("tier-a",)
        br.record_failure(key, RuntimeError("x"))
        br.record_success(key)  # intermittent failure never quarantines
        br.record_failure(key, RuntimeError("x"))
        br.admit(key)  # still closed: the count reset between failures

    def test_half_open_probe_closes_on_success(self):
        br = CircuitBreaker(threshold=1, cooldown_s=0.05)
        key = ("tier-a",)
        br.record_failure(key, RuntimeError("x"))
        with pytest.raises(QuarantinedError):
            br.admit(key)
        time.sleep(0.08)
        br.admit(key)  # cooldown elapsed: this call is the probe
        br.record_success(key)
        br.admit(key)  # closed again
        assert br.snapshot()["open"] == 0
        # history survives: the key row still records its open
        assert br.snapshot()["keys"][0]["opens"] == 1

    def test_half_open_probe_failure_reopens(self):
        br = CircuitBreaker(threshold=2, cooldown_s=0.05)
        key = ("tier-a",)
        br.record_failure(key, RuntimeError("x"))
        br.record_failure(key, RuntimeError("x"))
        time.sleep(0.08)
        br.admit(key)  # probe
        br.record_failure(key, RuntimeError("still broken"))
        with pytest.raises(QuarantinedError):
            br.admit(key)  # a SINGLE failed probe re-opened it

    def test_keys_are_independent(self):
        br = CircuitBreaker(threshold=1, cooldown_s=60.0)
        br.record_failure(("a",), RuntimeError("x"))
        with pytest.raises(QuarantinedError):
            br.admit(("a",))
        br.admit(("b",))  # the healthy tier is untouched

    def test_bad_config_rejected(self):
        with pytest.raises(ValueError):
            CircuitBreaker(threshold=0)
        with pytest.raises(ValueError):
            CircuitBreaker(cooldown_s=0.0)


# ---- serve fault plan (the chaos harness core) ----


class TestServeFaultPlan:
    def test_parse_env_multi_spec_mixed_with_run_side(self):
        env = {faults.ENV_FAULT: (
            "nan:5;serve-compile-fail:timesteps=12,count=2;"
            "serve-worker-crash:after=3,count=1"
        )}
        # the run-side half still resolves to its chunk hook
        assert faults.hook_from_env(env) is not None
        plan = faults.serve_plan_from_env(env)
        assert plan is not None and plan.active
        snap = plan.snapshot()
        assert [s["kind"] for s in snap] == \
            ["compile-fail", "worker-crash"]
        assert snap[0]["match"] == {"timesteps": "12"}
        assert snap[0]["remaining"] == 2
        assert snap[1]["after"] == 3

    def test_run_only_env_yields_no_plan_and_serve_only_no_hook(self):
        assert faults.serve_plan_from_env({faults.ENV_FAULT: "nan:5"}) \
            is None
        assert faults.hook_from_env(
            {faults.ENV_FAULT: "serve-conn-drop:count=1"}
        ) is None
        assert faults.serve_plan_from_env({}) is None

    def test_unknown_kind_and_selector_are_loud(self):
        with pytest.raises(ValueError, match="unknown serve fault"):
            faults.parse_serve_spec("serve-meteor-strike")
        with pytest.raises(ValueError, match="selector"):
            faults.parse_serve_spec("serve-compile-fail:color=red")
        with pytest.raises(ValueError, match="key=value"):
            faults.parse_serve_spec("serve-slow-batch:0.5")
        # conn-drop fires before the body is parsed: a selector would
        # silently never match, so it is refused at parse time
        with pytest.raises(ValueError, match="no selector"):
            faults.parse_serve_spec("serve-conn-drop:n=64")

    def test_multiple_run_side_specs_stay_loud(self):
        # The historical one-run-fault-per-drill contract: silently
        # running only the first would make the second assertion
        # vacuous.
        with pytest.raises(ValueError, match="at most one"):
            faults.hook_from_env({faults.ENV_FAULT: "nan:5;preempt:9"})

    def test_selector_count_and_after_budgets(self):
        plan = faults.parse_serve_spec(
            "serve-compile-fail:timesteps=7,count=2,after=1"
        )
        ctx = {"timesteps": 7, "scheme": "standard"}
        assert plan.fire("compile-fail", **ctx) is None  # after skips 1
        assert plan.fire("compile-fail", **ctx) is not None
        assert plan.fire("compile-fail", timesteps=8) is None  # no match
        assert plan.fire("compile-fail", **ctx) is not None
        assert plan.fire("compile-fail", **ctx) is None  # budget spent
        assert plan.fire("worker-crash", **ctx) is None  # wrong kind

    def test_firings_counted_in_registry(self):
        from wavetpu.obs.registry import MetricsRegistry

        plan = faults.parse_serve_spec("serve-conn-drop:count=3")
        reg = MetricsRegistry()
        plan.bind_registry(reg)
        plan.fire("conn-drop")
        plan.fire("conn-drop")
        c = reg.counter(
            "wavetpu_serve_fault_injections_total", labelnames=("kind",)
        )
        assert c.value(kind="conn-drop") == 2


# ---- deadlines in the scheduler ----


class TestDeadlines:
    def test_expired_in_queue_dropped_before_engine(self):
        eng = _FakeEngine()
        metrics = ServeMetrics()
        b = DynamicBatcher(eng, metrics=metrics, max_wait=0.05)
        p = Problem(N=8, timesteps=3)
        try:
            fut = b.submit(_req(p), deadline=time.monotonic() - 0.001)
            with pytest.raises(DeadlineExceededError) as ei:
                fut.result(10)
            assert ei.value.queue_s is not None
            assert eng.batches == []  # never reached the engine
            assert metrics.snapshot()["deadline_expired_total"] == 1
        finally:
            b.close()

    def test_mixed_batch_live_lane_survives_expired_batchmate(self):
        eng = _FakeEngine()
        b = DynamicBatcher(eng, max_wait=0.3)
        p = Problem(N=8, timesteps=3)
        try:
            dead = b.submit(_req(p), deadline=time.monotonic() - 0.001)
            live = b.submit(_req(p, phase=1.0),
                            deadline=time.monotonic() + 60.0)
            res, health, info = live.result(10)
            assert health is None
            with pytest.raises(DeadlineExceededError):
                dead.result(10)
            assert eng.batches == [1]  # the expired lane was not padded in
        finally:
            b.close()

    def test_no_deadline_is_the_historical_path(self):
        eng = _FakeEngine()
        b = DynamicBatcher(eng, max_wait=0.05)
        p = Problem(N=8, timesteps=3)
        try:
            fut = b.submit(_req(p))
            res, health, info = fut.result(10)
            assert health is None
        finally:
            b.close()

    def test_http_deadline_504_from_json_field_and_header(self):
        # A slow batch (injected) makes the in-flight deadline expire:
        # the handler answers 504 within the budget, never hanging.
        plan = faults.parse_serve_spec("serve-slow-batch:seconds=0.6")
        httpd, state = build_server(
            port=0, max_wait=0.02, default_kernel="roll",
            interpret=True, fault_plan=plan,
        )
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            body = {"N": 8, "timesteps": 4, "deadline_ms": 150}
            t0 = time.monotonic()
            code, payload, _ = _post_full(base, body)
            took = time.monotonic() - t0
            assert code == 504
            assert payload["deadline_ms"] == 150
            assert "deadline" in payload["error"]
            assert took < 0.6  # returned at the deadline, not the batch
            # header form wins over the JSON field
            code, payload, _ = _post_full(
                base, {"N": 8, "timesteps": 4, "deadline_ms": 60000},
                headers={"X-Deadline-Ms": "150"},
            )
            assert code == 504
            assert payload["deadline_ms"] == 150
            # bad budgets are 400s
            code, payload, _ = _post_full(
                base, {"N": 8, "timesteps": 4, "deadline_ms": -5}
            )
            assert code == 400
        finally:
            httpd.shutdown()
            state.batcher.close()
            httpd.server_close()

    def test_timeout_with_unexpired_deadline_is_500_not_504(self):
        """A budget LONGER than the server's request timeout can cap
        the future wait at the timeout with budget to spare - that is
        the historical timeout 500 (retriable by the client), not an
        expired-deadline 504."""
        plan = faults.parse_serve_spec("serve-slow-batch:seconds=1.0")
        httpd, state = build_server(
            port=0, max_wait=0.02, default_kernel="roll",
            interpret=True, fault_plan=plan,
        )
        state.request_timeout = 0.2  # the timeout loses, not the budget
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            code, payload, _ = _post_full(
                base, {"N": 8, "timesteps": 4, "deadline_ms": 600000}
            )
            assert code == 500
            assert "timed out" in payload["error"]
        finally:
            httpd.shutdown()
            state.batcher.close()
            httpd.server_close()

    def test_generous_deadline_serves_normally(self):
        httpd, state = build_server(
            port=0, max_wait=0.02, default_kernel="roll", interpret=True,
        )
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            code, payload, _ = _post_full(
                base, {"N": 8, "timesteps": 4, "deadline_ms": 600000}
            )
            assert code == 200
            assert payload["report"]["final_step"] == 4
        finally:
            httpd.shutdown()
            state.batcher.close()
            httpd.server_close()


# ---- worker supervision ----


class TestWorkerSupervision:
    def test_crash_fails_inflight_retriable_and_worker_restarts(self):
        plan = faults.parse_serve_spec("serve-worker-crash:count=1")
        eng = _FakeEngine()
        metrics = ServeMetrics()
        b = DynamicBatcher(eng, metrics=metrics, max_wait=0.05,
                           fault_plan=plan)
        p = Problem(N=8, timesteps=3)
        try:
            fut = b.submit(_req(p))
            with pytest.raises(WorkerCrashError, match="retry"):
                fut.result(10)
            # the supervisor restarted the worker: the next submit is
            # served normally, not stranded behind a dead thread
            res, health, info = b.submit(_req(p, phase=1.0)).result(10)
            assert health is None
            assert metrics.snapshot()["worker_restarts_total"] == 1
        finally:
            b.close()

    def test_repeated_crashes_never_strand_queued_requests(self):
        plan = faults.parse_serve_spec("serve-worker-crash:count=3")
        eng = _FakeEngine()
        b = DynamicBatcher(eng, max_wait=0.02, fault_plan=plan)
        p = Problem(N=8, timesteps=3)
        try:
            futs = [b.submit(_req(p, phase=1.0 + i)) for i in range(5)]
            for f in futs:
                try:
                    f.result(15)  # result OR a fast crash error -
                except WorkerCrashError:
                    pass          # - never a hang
            # keep submitting: the crash budget (3) is finite, so the
            # supervisor must eventually restart into a serving worker
            for i in range(6):
                try:
                    res, health, _ = b.submit(
                        _req(p, phase=10.0 + i)
                    ).result(15)
                    assert health is None
                    break
                except WorkerCrashError:
                    continue
            else:
                pytest.fail("service never resumed after crash budget")
        finally:
            b.close()

    def test_http_worker_crash_maps_to_retriable_503(self):
        plan = faults.parse_serve_spec("serve-worker-crash:count=1")
        httpd, state = build_server(
            port=0, max_wait=0.02, default_kernel="roll",
            interpret=True, fault_plan=plan,
        )
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            code, payload, headers = _post_full(
                base, {"N": 8, "timesteps": 4}
            )
            assert code == 503
            assert payload["retriable"] is True
            assert "Retry-After" in headers
            # and the server recovered
            code, _, _ = _post_full(base, {"N": 8, "timesteps": 4})
            assert code == 200
        finally:
            httpd.shutdown()
            state.batcher.close()
            httpd.server_close()


# ---- engine quarantine + injections ----


class TestEngineQuarantine:
    def test_compile_failures_open_breaker_other_tier_serves(self):
        plan = faults.parse_serve_spec(
            "serve-compile-fail:timesteps=9"  # unlimited: a dead tier
        )
        eng = ServeEngine(
            bucket_sizes=(1, 2), interpret=True, breaker_threshold=2,
            breaker_cooldown_s=60.0, fault_plan=plan,
        )
        poisoned = Problem(N=8, timesteps=9)
        healthy = Problem(N=8, timesteps=4)
        for _ in range(2):
            with pytest.raises(faults.InjectedFault):
                eng.solve(poisoned, [eb.LaneSpec()], path="roll")
        # breaker open: the third request sheds WITHOUT compiling
        misses_before = eng.misses
        with pytest.raises(QuarantinedError) as ei:
            eng.solve(poisoned, [eb.LaneSpec()], path="roll")
        assert eng.misses == misses_before  # no compile attempt
        assert ei.value.retry_after_s > 0
        # the healthy tier is untouched by its neighbor's quarantine
        res, health = eng.solve(healthy, [eb.LaneSpec()], path="roll")
        assert health == [None]
        stats = eng.breaker_stats()
        assert stats["enabled"] and stats["open"] == 1
        assert "steps=9" in stats["keys"][0]["key"]

    def test_breaker_key_spans_buckets(self):
        # Both buckets of one tier share a breaker: failures at bucket 1
        # quarantine bucket 2 as well (the tier is poisoned, not the
        # bucket).
        plan = faults.parse_serve_spec("serve-compile-fail:timesteps=9")
        eng = ServeEngine(
            bucket_sizes=(1, 2), interpret=True, breaker_threshold=2,
            breaker_cooldown_s=60.0, fault_plan=plan,
        )
        p = Problem(N=8, timesteps=9)
        for _ in range(2):
            with pytest.raises(faults.InjectedFault):
                eng.solve(p, [eb.LaneSpec()], path="roll")
        with pytest.raises(QuarantinedError):
            eng.solve(p, [eb.LaneSpec(), eb.LaneSpec(phase=1.0)],
                      path="roll")

    def test_half_open_probe_recovers_after_transient_fault(self):
        plan = faults.parse_serve_spec(
            "serve-compile-fail:timesteps=9,count=2"  # transient
        )
        eng = ServeEngine(
            bucket_sizes=(1,), interpret=True, breaker_threshold=2,
            breaker_cooldown_s=0.1, fault_plan=plan,
        )
        p = Problem(N=8, timesteps=9)
        for _ in range(2):
            with pytest.raises(faults.InjectedFault):
                eng.solve(p, [eb.LaneSpec()], path="roll")
        with pytest.raises(QuarantinedError):
            eng.solve(p, [eb.LaneSpec()], path="roll")
        time.sleep(0.15)
        # cooldown elapsed -> this is the half-open probe; the fault
        # budget is exhausted so it compiles fine and closes the breaker
        res, health = eng.solve(p, [eb.LaneSpec()], path="roll")
        assert health == [None]
        assert eng.breaker_stats()["open"] == 0

    def test_breaker_disabled_is_the_historical_path(self):
        eng = ServeEngine(bucket_sizes=(1,), interpret=True,
                          breaker_threshold=None)
        assert eng.breaker is None
        assert eng.breaker_stats() == {"enabled": False}
        p = Problem(N=8, timesteps=3)
        res, health = eng.solve(p, [eb.LaneSpec()], path="roll")
        assert health == [None]

    def test_watchdog_trips_do_not_feed_the_breaker(self):
        # A Courant-unstable REQUEST is the client's fault: 60 of them
        # in a row must not quarantine the tier for valid requests.
        from wavetpu.serve.api import _c2_preset

        p = Problem(N=8, T=26.0, timesteps=60)
        eng = ServeEngine(bucket_sizes=(1,), interpret=True,
                          breaker_threshold=2)
        for _ in range(3):
            _, health = eng.solve(
                p, [eb.LaneSpec(c2tau2_field=_c2_preset(p, "two-layer"))],
                path="roll",
            )
            assert health[0] is not None  # tripped
        assert eng.breaker_stats()["open"] == 0

    def test_execute_nan_injection_caught_by_watchdog(self):
        plan = faults.parse_serve_spec("serve-execute-nan:count=1")
        eng = ServeEngine(bucket_sizes=(1,), interpret=True,
                          fault_plan=plan)
        p = Problem(N=8, timesteps=3)
        _, health = eng.solve(p, [eb.LaneSpec()], path="roll")
        assert health[0] is not None and "amax" in health[0]
        # budget spent: the next solve is clean
        _, health = eng.solve(p, [eb.LaneSpec()], path="roll")
        assert health == [None]


# ---- the retrying client ----


class _ScriptedHandler:
    """A tiny scripted /solve server: pops the next (status, body,
    headers) per request, recording what it saw."""

    def __init__(self, script):
        self.script = list(script)
        self.seen = []
        self.lock = threading.Lock()


def _scripted_server(script):
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    state = _ScriptedHandler(script)

    class H(BaseHTTPRequestHandler):
        # keep-alive, like the real serve handler - lets the client
        # tests below exercise connection-reuse accounting
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):
            pass

        def do_POST(self):
            length = int(self.headers.get("Content-Length", "0") or 0)
            body = json.loads(self.rfile.read(length) or b"{}")
            with state.lock:
                state.seen.append({
                    "body": body,
                    "rid": self.headers.get("X-Request-Id"),
                })
                status, payload, headers = (
                    state.script.pop(0) if state.script
                    else (200, {"status": "ok"}, {})
                )
            if status == -1:  # drop the connection
                self.close_connection = True
                self.connection.close()
                return
            raw = json.dumps(payload).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(raw)))
            for k, v in headers.items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(raw)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, state, f"http://127.0.0.1:{httpd.server_address[1]}"


class TestClient:
    def _client(self, base, **kw):
        kw.setdefault("rng", random.Random(7))
        kw.setdefault("sleep", lambda s: None)
        return WavetpuClient(base, **kw)

    def test_retries_absorb_503_and_reuse_request_id(self):
        httpd, state, base = _scripted_server([
            (503, {"status": "error", "error": "worker crashed",
                   "retriable": True}, {"Retry-After": "0"}),
            (200, {"status": "ok", "report": {}}, {}),
        ])
        try:
            out = self._client(base, retries=3).solve(
                {"N": 8}, request_id="cl-test-1"
            )
            assert out.ok and out.attempts == 2
            assert out.retries[0]["status"] == 503
            # the SAME id rode both attempts (the trace-join contract)
            assert [s["rid"] for s in state.seen] == \
                ["cl-test-1", "cl-test-1"]
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_honors_retry_after_header(self):
        sleeps = []
        httpd, state, base = _scripted_server([
            (429, {"status": "error", "error": "queue full"},
             {"Retry-After": "2"}),
            (200, {"status": "ok"}, {}),
        ])
        try:
            out = self._client(
                base, retries=1, sleep=sleeps.append
            ).solve({"N": 8})
            assert out.ok and sleeps == [2.0]
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_non_retriable_4xx_returns_immediately(self):
        httpd, state, base = _scripted_server([
            (400, {"status": "error", "error": "missing N"}, {}),
        ])
        try:
            out = self._client(base, retries=5).solve({})
            assert out.status == 400 and out.attempts == 1
            assert "missing N" in out.error
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_connection_drop_is_retriable(self):
        httpd, state, base = _scripted_server([
            (-1, None, None),  # dropped connection
            (200, {"status": "ok"}, {}),
        ])
        try:
            out = self._client(base, retries=2).solve({"N": 8})
            assert out.ok and out.attempts == 2
            assert out.retries[0]["status"] == 0
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_deadline_bounds_attempts_and_rides_the_body(self):
        clock = {"t": 0.0}
        httpd, state, base = _scripted_server([
            (503, {"status": "error", "error": "x"}, {"Retry-After": "5"}),
            (503, {"status": "error", "error": "x"}, {"Retry-After": "5"}),
        ])

        def sleep(s):
            clock["t"] += s
            time.sleep(0)  # never actually wait in the test

        try:
            out = self._client(base, retries=10, sleep=sleep).solve(
                {"N": 8}, deadline_s=3.0
            )
            # Retry-After 5 s exceeds the 3 s budget: exactly one
            # attempt, then the client gives up instead of sleeping
            # past its own deadline.
            assert not out.ok and out.attempts == 1
            assert "deadline" in out.error
            # the remaining budget rode the body as deadline_ms
            sent = state.seen[0]["body"]
            assert 0 < sent["deadline_ms"] <= 3000
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_keepalive_reuses_connection_across_requests(self):
        httpd, state, base = _scripted_server([])  # default 200s
        try:
            c = self._client(base, retries=0)
            assert c.solve({"N": 8}).ok
            assert c.solve({"N": 8}).ok
            assert c.solve({"N": 8}).ok
            # one socket carried all three requests
            assert c.connections_opened == 1
            assert c.requests_on_reused_connection == 2
            assert c.connection_resets == 0
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_connection_close_header_retires_socket_orderly(self):
        httpd, state, base = _scripted_server([
            (200, {"status": "ok"}, {"Connection": "close"}),
            (200, {"status": "ok"}, {}),
        ])
        try:
            c = self._client(base, retries=0)
            assert c.solve({"N": 8}).ok
            assert c.solve({"N": 8}).ok
            # the announced close forced a reconnect, but it is NOT a
            # reset - that counter only tracks surprise failures
            assert c.connections_opened == 2
            assert c.connection_resets == 0
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_stale_kept_alive_socket_costs_one_status0_retry(self):
        httpd, state, base = _scripted_server([
            (200, {"status": "ok"}, {}),
            (-1, None, None),  # server kills the kept-alive socket
        ])
        try:
            c = self._client(base, retries=2)
            assert c.solve({"N": 8}).ok
            out = c.solve({"N": 8})
            # the dead socket cost one retriable status-0 attempt and
            # one counted reset; the retry reconnected and succeeded
            assert out.ok and out.attempts == 2
            assert out.retries[0]["status"] == 0
            assert c.connection_resets == 1
            assert c.connections_opened == 2
            # request 3 rides the fresh socket again
            assert c.solve({"N": 8}).ok
            assert c.requests_on_reused_connection >= 1
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_retriable_statuses_pinned(self):
        assert RETRIABLE_STATUSES == {0, 429, 500, 503}
        assert parse_retry_after({"Retry-After": "3"}) == 3.0
        assert parse_retry_after({"Retry-After": "junk"}) is None
        assert parse_retry_after({}) is None

    def test_bad_config_rejected(self):
        with pytest.raises(ValueError):
            WavetpuClient("http://x", retries=-1)
        with pytest.raises(ValueError):
            WavetpuClient("http://x", deadline_s=0)


# ---- HTTP helpers (shared shape with test_serve) ----


def _post_full(base, body, timeout=120, headers=None):
    import urllib.error

    req = urllib.request.Request(
        base + "/solve", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


# ---- acceptance: the chaos drill ----


class TestChaosDrill:
    def test_chaos_drill_zero_client_visible_errors(self):
        """ISSUE acceptance: injected compile failures on one tier
        (transient, breaker-opening) + a mid-replay worker kill + a
        dropped connection, all driven by the retrying client: every
        logical request succeeds, the poisoned tier's breaker opened
        while the healthy tier kept serving, injections are counted,
        and nothing hangs past its deadline."""
        plan = faults.parse_serve_spec(
            "serve-compile-fail:timesteps=9,count=2;"
            "serve-worker-crash:after=2,count=1;"
            "serve-conn-drop:after=1,count=1"
        )
        httpd, state = build_server(
            port=0, max_wait=0.02, default_kernel="roll",
            interpret=True, fault_plan=plan,
            breaker_threshold=2, breaker_cooldown_s=0.3,
        )
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        client = WavetpuClient(
            base, retries=8, timeout=60.0, backoff_base_s=0.02,
            backoff_max_s=0.3, rng=random.Random(3),
        )
        outcomes = [None] * 10
        t0 = time.monotonic()

        def fire(i):
            body = (
                {"N": 8, "timesteps": 9} if i % 2 else
                {"N": 8, "timesteps": 4, "phase": 1.0 + i}
            )
            outcomes[i] = client.solve(body, deadline_s=45.0)

        threads = [
            threading.Thread(target=fire, args=(i,)) for i in range(10)
        ]
        for t in threads:
            t.start()
            time.sleep(0.03)  # staggered: the crash lands mid-replay
        for t in threads:
            t.join(90)
        took = time.monotonic() - t0
        # 1. zero client-visible failures: retry/backoff absorbed
        # compile faults, the worker kill, and the dropped connection
        assert all(o is not None and o.ok for o in outcomes), [
            (o.status, o.error) for o in outcomes if o and not o.ok
        ]
        # 2. faults actually fired and were absorbed (not a vacuous run)
        assert any(o.attempts > 1 for o in outcomes)
        fired = {s["kind"]: s["fired"] for s in plan.snapshot()}
        assert fired["compile-fail"] == 2
        assert fired["worker-crash"] == 1
        assert fired["conn-drop"] == 1
        # 3. the poisoned tier's breaker opened (and has since closed
        # via the half-open probe) while the healthy tier served
        stats = state.engine.breaker_stats()
        assert any(k["opens"] >= 1 for k in stats["keys"])
        assert stats["open"] == 0  # recovered by the probe
        # 4. no future outlived its deadline (45 s budget, generous
        # margin for CI)
        assert took < 80.0
        # 5. the injections are visible in the registry counter
        code, snap = _get_json(base, "/metrics")
        assert snap["worker_restarts_total"] == 1
        assert snap["breaker"]["enabled"] is True
        httpd.shutdown()
        state.batcher.close()
        httpd.server_close()

    def test_happy_path_response_unchanged_with_resilience_live(self):
        """Acceptance: with the breaker on (default) and no fault or
        deadline, the /solve response carries exactly the historical
        payload shape - the resilience layer is invisible until used."""
        httpd, state = build_server(
            port=0, max_wait=0.02, default_kernel="roll", interpret=True,
        )
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            code, payload, _ = _post_full(base, {"N": 8, "timesteps": 4})
            assert code == 200
            assert set(payload) == {
                "status", "report", "report_text", "batch"
            }
            assert set(payload["batch"]) == {
                "occupancy", "batch_size", "batched", "fallback_reason",
                "path", "padding_lanes", "aggregate_gcells_per_s",
                "warm", "timing",
            }
        finally:
            httpd.shutdown()
            state.batcher.close()
            httpd.server_close()


def _get_json(base, path):
    with urllib.request.urlopen(base + path, timeout=30) as r:
        return r.status, json.loads(r.read())
