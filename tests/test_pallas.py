"""Pallas kernel parity vs the jnp-roll semantic reference.

The fused kernel (kernels/stencil_pallas.py) must agree with
`stencil_ref.leapfrog_step` / `taylor_half_step` to rounding error on
identical inputs (SURVEY.md section 4(e)).  Runs in interpret mode on the
CPU test backend; the on-chip throughput side is bench.py's job.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from wavetpu.core.problem import Problem
from wavetpu.kernels import stencil_pallas, stencil_ref
from wavetpu.solver import leapfrog


def _random_state(n, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    u_prev = jnp.asarray(rng.standard_normal((n, n, n)), dtype)
    u = jnp.asarray(rng.standard_normal((n, n, n)), dtype)
    # Establish the Dirichlet invariant the solver maintains.
    return stencil_ref.apply_dirichlet(u_prev), stencil_ref.apply_dirichlet(u)


@pytest.mark.parametrize("block_x", [1, 2, 4])
def test_leapfrog_step_matches_ref(small_problem, block_x):
    """Interior + periodic wrap + Dirichlet all agree for every slab depth
    (block_x=1 exercises the pure halo-plane path, >1 the slab interior)."""
    u_prev, u = _random_state(small_problem.N)
    want = stencil_ref.leapfrog_step(u_prev, u, small_problem)
    got = stencil_pallas.leapfrog_step(
        u_prev, u, small_problem, block_x=block_x, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-6, rtol=1e-6
    )


def test_taylor_half_step_matches_ref(small_problem):
    u0, _ = _random_state(small_problem.N, seed=1)
    want = stencil_ref.taylor_half_step(u0, small_problem)
    got = stencil_pallas.taylor_half_step(
        u0, small_problem, block_x=2, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-6, rtol=1e-6
    )


def test_full_solve_with_pallas_step(small_problem):
    """End-to-end: the solver with the Pallas step reproduces the reference
    solver's fields and per-layer error trajectory."""
    ref = leapfrog.solve(small_problem)
    pal = leapfrog.solve(
        small_problem,
        step_fn=stencil_pallas.make_step_fn(block_x=2, interpret=True),
    )
    np.testing.assert_allclose(
        np.asarray(pal.u_cur), np.asarray(ref.u_cur), atol=1e-5, rtol=0.0
    )
    np.testing.assert_allclose(
        pal.abs_errors, ref.abs_errors, atol=1e-6, rtol=1e-4
    )


def test_dirichlet_planes_zeroed(small_problem):
    u_prev, u = _random_state(small_problem.N, seed=2)
    got = np.asarray(
        stencil_pallas.leapfrog_step(
            u_prev, u, small_problem, block_x=1, interpret=True
        )
    )
    assert np.all(got[:, 0, :] == 0.0)
    assert np.all(got[:, :, 0] == 0.0)


def test_choose_block_x():
    """Slab depth divides N and respects the VMEM working-set budget."""
    for n in (16, 128, 256, 512, 1024):
        bx = stencil_pallas.choose_block_x(n)
        assert n % bx == 0
        # The budget bounds any slab deeper than the bx=1 floor.
        assert (
            bx == 1
            or 2 * (3 * bx + 2) * n * n * 4 <= stencil_pallas._VMEM_BUDGET
        )
    assert stencil_pallas.choose_block_x(512) == 8
    assert stencil_pallas.choose_block_x(1024) == 1
    assert stencil_pallas.choose_block_x(128) == 8
    # The variable-c kernel has one more bx-deep slab in flight, so the
    # budget admits a shallower slab (measured cliff on v5e, see docstring).
    assert stencil_pallas.choose_block_x(512, field_itemsize=4) == 4
    # bf16 state still carries an f32 field slab - it must be counted at
    # the compute width, not the state width.
    assert (
        stencil_pallas.choose_block_x(512, itemsize=2, field_itemsize=4) == 8
    )
    full = 2 * ((3 * 2 + 4) * 8 + 2 * 2) * 512 * 512
    assert full <= stencil_pallas._VMEM_BUDGET
