"""CLI + report-writer gates: the observable output contract.

The reference's contract is positional argv, a Courant printout, and a
rank-0 report file with fixed line layout (SURVEY.md section 0); these tests
pin both the text format and the JSON sidecar.
"""

import json
import os
import re

import numpy as np
import pytest

from wavetpu import cli
from wavetpu.core.problem import Problem
from wavetpu.io import report
from wavetpu.solver import leapfrog


def test_report_filename_contract():
    assert report.report_filename(128, 1) == "output_N128_Np1_TPU.txt"
    assert (
        report.report_filename(512, 8, n_threads=4)
        == "output_N512_Np8_Nt4_TPU.txt"
    )


def test_report_format(tmp_path, small_problem):
    res = leapfrog.solve(small_problem)
    path = report.write_report(
        res,
        out_dir=str(tmp_path),
        exchange_seconds=0.5,
        loop_seconds=1.5,
    )
    text = open(path).read()
    lines = text.splitlines()
    assert re.fullmatch(r"grids initialized in \d+ms", lines[0])
    assert re.fullmatch(r"numerical solution calculated in \d+ms", lines[1])
    # One error line per layer, reference-verbatim prefix.
    layer_lines = [l for l in lines if l.startswith("max abs and rel errors")]
    assert len(layer_lines) == small_problem.timesteps + 1
    assert re.fullmatch(
        r"max abs and rel errors on layer 3: [-0-9.e+]+ [-0-9.e+]+",
        layer_lines[3],
    )
    assert "total ICI exchange time: 500ms" in lines
    assert "total loop time: 1500ms" in lines

    side = json.load(open(path.replace(".txt", ".json")))
    assert side["problem"]["N"] == small_problem.N
    assert side["max_abs_error"] == pytest.approx(res.abs_errors.max())
    assert len(side["abs_errors"]) == small_problem.timesteps + 1


def test_cli_single_device(tmp_path, capsys):
    rc = cli.main(
        [
            "16", "1", "1", "1", "1", "1", "5",
            "--backend", "single", "--out-dir", str(tmp_path),
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert out.startswith("C = ")
    assert os.path.exists(tmp_path / "output_N16_Np1_TPU.txt")


def test_cli_sharded_mesh(tmp_path, capsys):
    rc = cli.main(
        [
            "16", "1", "1", "1", "1", "1", "5",
            "--mesh", "2,2,2", "--out-dir", str(tmp_path),
        ]
    )
    assert rc == 0
    assert os.path.exists(tmp_path / "output_N16_Np8_TPU.txt")
    side = json.load(open(tmp_path / "output_N16_Np8_TPU.json"))
    assert np.isfinite(side["max_abs_error"])


def test_cli_pi_literal_and_defaults(tmp_path, capsys):
    rc = cli.main(
        ["16", "2", "pi", "1", "pi", "--backend", "single",
         "--out-dir", str(tmp_path)]
    )
    assert rc == 0
    side = json.load(open(tmp_path / "output_N16_Np1_TPU.json"))
    assert side["problem"]["Lx"] == pytest.approx(np.pi)
    assert side["problem"]["T"] == 1.0
    assert side["problem"]["timesteps"] == 20


def test_cli_profile_trace(tmp_path, capsys):
    """--profile captures a jax.profiler trace of the solve."""
    import glob

    trace_dir = str(tmp_path / "trace")
    rc = cli.main(
        ["16", "1", "1", "1", "1", "1", "5", "--backend", "single",
         "--profile", trace_dir, "--out-dir", str(tmp_path)]
    )
    assert rc == 0
    assert "profile trace:" in capsys.readouterr().out
    assert glob.glob(trace_dir + "/**/*", recursive=True)


def test_cli_bad_args(capsys):
    assert cli.main(["16"]) == 2
    assert "usage" in capsys.readouterr().err


def test_cli_bad_flags(capsys):
    base = ["16", "1", "1", "1", "1", "1", "5"]
    assert cli.main(base + ["--out_dir", "/tmp"]) == 2       # typo'd flag
    assert cli.main(base + ["--backend"]) == 2               # missing value
    assert cli.main(base + ["--dtype", "f16"]) == 2          # bad dtype
    assert cli.main(
        base + ["--backend", "single", "--mesh", "2,2,2"]
    ) == 2                                                   # contradiction
    capsys.readouterr()


def test_cli_preemption_workflow(tmp_path, capsys):
    """stop-step + save-state then resume == uninterrupted run (bitwise on
    the report's error tail)."""
    base = ["16", "1", "1", "1", "1", "1", "10", "--backend", "single"]
    full_dir, part_dir, res_dir = (
        str(tmp_path / d) for d in ("full", "part", "res")
    )
    ck = str(tmp_path / "ck.npz")
    assert cli.main(base + ["--out-dir", full_dir]) == 0
    assert (
        cli.main(
            base
            + ["--out-dir", part_dir, "--stop-step", "6", "--save-state", ck]
        )
        == 0
    )
    assert cli.main(["--resume", ck, "--out-dir", res_dir]) == 0
    capsys.readouterr()
    full = json.load(open(os.path.join(full_dir, "output_N16_Np1_TPU.json")))
    res = json.load(open(os.path.join(res_dir, "output_N16_Np1_TPU.json")))
    assert res["abs_errors"][7:] == full["abs_errors"][7:]


def test_cli_fuse_steps(tmp_path, capsys):
    """--fuse-steps selects the k-fused pallas path; report errors match
    the 1-step run's (bitwise-identical layers, solver/kfused.py)."""
    base = ["16", "1", "1", "1", "1", "1", "9"]
    one_dir, k_dir = str(tmp_path / "one"), str(tmp_path / "k")
    assert cli.main(
        base + ["--backend", "single", "--kernel", "pallas",
                "--out-dir", one_dir]
    ) == 0
    assert cli.main(
        base + ["--backend", "single", "--fuse-steps", "4",
                "--out-dir", k_dir]
    ) == 0
    out = capsys.readouterr().out
    assert "fuse-steps: 4" in out
    one = json.load(open(os.path.join(one_dir, "output_N16_Np1_TPU.json")))
    kf = json.load(open(os.path.join(k_dir, "output_N16_Np1_TPU.json")))
    # identical layers; the two error-oracle formulations differ only in
    # f32 multiply order (in-kernel sxct*syz vs post-hoc ((sx*sy)*sz)*ct)
    assert kf["abs_errors"] == pytest.approx(one["abs_errors"], rel=1e-5)


def test_cli_fuse_steps_validation(capsys):
    base = ["16", "1", "1", "1", "1", "1", "5"]
    assert cli.main(base + ["--fuse-steps", "4", "--kernel", "roll"]) == 2
    assert cli.main(base + ["--fuse-steps", "4", "--mesh", "2,2,2"]) == 2
    # Compensated k-fusion requires k | N (the velocity-form onion has no
    # pad-and-mask variant); the standard scheme pads instead.
    assert cli.main(["18", "1", "1", "1", "1", "1", "5", "--fuse-steps",
                     "4", "--scheme", "compensated"]) == 2
    # Uneven layouts that would leave the last shard empty are refused.
    assert cli.main(base + ["--fuse-steps", "4", "--mesh", "8,1,1"]) == 2
    # 2D meshes keep the divisibility requirement.
    assert cli.main(["18", "1", "1", "1", "1", "1", "5",
                     "--fuse-steps", "4", "--mesh", "2,3,1"]) == 2
    # --v-dtype bf16 outside the compensated k-fused mode is an error.
    assert cli.main(base + ["--v-dtype", "bf16"]) == 2
    assert cli.main(
        base + ["--fuse-steps", "4", "--v-dtype", "bf16"]
    ) == 2
    capsys.readouterr()


def test_cli_fuse_steps_uneven(tmp_path, capsys):
    """k not dividing N routes through the pad-and-mask path and matches
    the 1-step run's layers (which k-fused paths are bitwise-pinned to)."""
    base = ["15", "1", "1", "1", "1", "1", "6"]
    one_dir = str(tmp_path / "one")
    k_dir = str(tmp_path / "kf")
    assert cli.main(
        base + ["--backend", "single", "--out-dir", one_dir]
    ) == 0
    assert cli.main(
        base + ["--fuse-steps", "2", "--out-dir", k_dir]
    ) == 0
    capsys.readouterr()
    one = json.load(open(os.path.join(one_dir, "output_N15_Np1_TPU.json")))
    kf = json.load(open(os.path.join(k_dir, "output_N15_Np1_TPU.json")))
    # In-kernel plane-max rows vs the post-hoc jnp oracle differ only in
    # f32 multiply order (~2e-7 absolute on ~1e-3 errors at N=15).
    assert kf["abs_errors"] == pytest.approx(one["abs_errors"], rel=1e-4)


def test_cli_compensated_kfused(tmp_path, capsys):
    """--scheme compensated --fuse-steps K (the flagship config) runs and
    reports, and the bf16 increment mode runs via --v-dtype bf16."""
    base = ["16", "1", "1", "1", "1", "1", "9"]
    assert cli.main(
        base + ["--scheme", "compensated", "--fuse-steps", "4",
                "--out-dir", str(tmp_path)]
    ) == 0
    out = capsys.readouterr().out
    assert "scheme: compensated" in out and "fuse-steps: 4" in out
    side = json.load(open(tmp_path / "output_N16_Np1_TPU.json"))
    assert side["run_config"]["scheme"] == "compensated"
    assert side["run_config"]["fuse_steps"] == 4
    assert cli.main(
        base + ["--scheme", "compensated", "--fuse-steps", "4",
                "--v-dtype", "bf16", "--out-dir", str(tmp_path)]
    ) == 0
    capsys.readouterr()
    side = json.load(open(tmp_path / "output_N16_Np1_TPU.json"))
    assert side["run_config"]["v_dtype"] == "bf16"


@pytest.mark.heavy
def test_cli_compensated_kfused_sharded(tmp_path, capsys):
    """--scheme compensated --fuse-steps K --mesh MX,1,1 runs the
    distributed velocity-form flagship, checkpoints per shard, and
    resumes on the stored mesh."""
    base = ["16", "1", "1", "1", "1", "1", "9"]
    ck = str(tmp_path / "ck")
    assert cli.main(
        base + ["--scheme", "compensated", "--fuse-steps", "4",
                "--mesh", "2,1,1", "--stop-step", "5",
                "--save-state", ck, "--out-dir", str(tmp_path)]
    ) == 0
    out = capsys.readouterr().out
    assert "scheme: compensated" in out and "fuse-steps: 4" in out
    res_dir = str(tmp_path / "res")
    assert cli.main(
        ["--resume", ck, "--fuse-steps", "4", "--out-dir", res_dir]
    ) == 0
    capsys.readouterr()
    side = json.load(open(os.path.join(res_dir, "output_N16_Np2_TPU.json")))
    assert side["run_config"]["scheme"] == "compensated"
    assert side["run_config"]["mesh"] == [2, 1, 1]
    # 2D meshes run the xy velocity-form kernel (round-5).
    assert cli.main(
        base + ["--scheme", "compensated", "--fuse-steps", "4",
                "--mesh", "2,2,1", "--out-dir", str(tmp_path / "xy")]
    ) == 0
    capsys.readouterr()
    side = json.load(
        open(os.path.join(str(tmp_path / "xy"), "output_N16_Np4_TPU.json"))
    )
    assert side["run_config"]["mesh"] == [2, 2, 1]


@pytest.mark.heavy
def test_cli_compensated_kfused_resume(tmp_path, capsys):
    """A compensated checkpoint resumes onto the k-fused path; stopping on
    a block-aligned layer keeps the remaining march's op sequence equal,
    so the final error matches the uninterrupted run's."""
    base = ["16", "1", "1", "1", "1", "1", "9"]
    full_dir = str(tmp_path / "full")
    assert cli.main(
        base + ["--scheme", "compensated", "--fuse-steps", "4",
                "--out-dir", full_dir]
    ) == 0
    ck = str(tmp_path / "comp.npz")
    assert cli.main(
        base + ["--scheme", "compensated", "--fuse-steps", "4",
                "--stop-step", "5", "--save-state", ck,
                "--out-dir", str(tmp_path)]
    ) == 0
    res_dir = str(tmp_path / "res")
    assert cli.main(
        ["--resume", ck, "--fuse-steps", "4", "--out-dir", res_dir]
    ) == 0
    capsys.readouterr()
    full = json.load(open(os.path.join(full_dir, "output_N16_Np1_TPU.json")))
    res = json.load(open(os.path.join(res_dir, "output_N16_Np1_TPU.json")))
    assert res["abs_errors"][-1] == pytest.approx(
        full["abs_errors"][-1], rel=1e-6
    )


def test_cli_fuse_steps_phase_timing(tmp_path, capsys):
    """--phase-timing probes the k-fused program (k-blocks, scaled to the
    layers they cover) and lands in the report like the 1-step probe."""
    rc = cli.main(
        ["16", "1", "1", "1", "1", "1", "8", "--fuse-steps", "4",
         "--mesh", "2,1,1", "--phase-timing", "--out-dir", str(tmp_path)]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "total loop time:" in out and "total ICI exchange time:" in out
    text = open(tmp_path / "output_N16_Np2_TPU.txt").read()
    assert "total loop time:" in text


def test_cli_fuse_steps_resume_guards(tmp_path, capsys):
    """--fuse-steps must not silently bypass resume semantics: a sharded
    checkpoint on a non-x-only mesh is rejected.  (A single-device
    compensated checkpoint + --fuse-steps is now the flagship resume
    path, test_cli_compensated_kfused_resume.)"""
    base = ["16", "1", "1", "1", "1", "1", "8"]
    shard_ck = str(tmp_path / "shard_ck")
    assert cli.main(
        base + ["--mesh", "1,1,2", "--stop-step", "3",
                "--save-state", shard_ck, "--out-dir", str(tmp_path)]
    ) == 0
    assert cli.main(["--resume", shard_ck, "--fuse-steps", "4"]) == 2
    err = capsys.readouterr().err
    assert "(MX,MY,1)" in err


@pytest.mark.heavy
def test_cli_fuse_steps_sharded(tmp_path, capsys):
    """--fuse-steps + --mesh MX,MY,1 runs the sharded k-fused solver and
    matches the single-device k-fused report; z-sharded meshes are
    rejected."""
    base = ["16", "1", "1", "1", "1", "1", "9"]
    one_dir, sh_dir, xy_dir = (
        str(tmp_path / d) for d in ("one", "sh", "xy")
    )
    assert cli.main(
        base + ["--fuse-steps", "4", "--out-dir", one_dir,
                "--backend", "single"]
    ) == 0
    assert cli.main(
        base + ["--fuse-steps", "4", "--mesh", "2,1,1",
                "--out-dir", sh_dir]
    ) == 0
    assert cli.main(
        base + ["--fuse-steps", "4", "--mesh", "2,2,1",
                "--out-dir", xy_dir]
    ) == 0
    assert cli.main(base + ["--fuse-steps", "4", "--mesh", "2,1,2"]) == 2
    capsys.readouterr()
    one = json.load(open(os.path.join(one_dir, "output_N16_Np1_TPU.json")))
    sh = json.load(open(os.path.join(sh_dir, "output_N16_Np2_TPU.json")))
    xy = json.load(open(os.path.join(xy_dir, "output_N16_Np4_TPU.json")))
    assert sh["abs_errors"] == pytest.approx(one["abs_errors"], rel=1e-5)
    assert xy["abs_errors"] == pytest.approx(one["abs_errors"], rel=1e-5)


@pytest.mark.heavy
def test_cli_fuse_steps_sharded_resume(tmp_path, capsys):
    """An x-only sharded checkpoint resumes under --fuse-steps with the
    same error tail as the uninterrupted sharded k-fused run."""
    base = ["16", "1", "1", "1", "1", "1", "10", "--mesh", "2,1,1",
            "--fuse-steps", "4"]
    full_dir, res_dir = str(tmp_path / "full"), str(tmp_path / "res")
    ck = str(tmp_path / "ck")
    assert cli.main(base + ["--out-dir", full_dir]) == 0
    assert cli.main(
        base + ["--out-dir", str(tmp_path), "--stop-step", "6",
                "--save-state", ck]
    ) == 0
    assert cli.main(
        ["--resume", ck, "--fuse-steps", "4", "--out-dir", res_dir]
    ) == 0
    capsys.readouterr()
    full = json.load(open(os.path.join(full_dir, "output_N16_Np2_TPU.json")))
    res = json.load(open(os.path.join(res_dir, "output_N16_Np2_TPU.json")))
    assert res["abs_errors"][7:] == pytest.approx(
        full["abs_errors"][7:], rel=1e-6
    )
    assert all(e == 0 for e in res["abs_errors"][:7])


def test_cli_fuse_steps_resume_continues(tmp_path, capsys):
    """A single-device standard checkpoint resumes through resume_kfused:
    the error tail matches the uninterrupted run (not a silent restart)."""
    base = ["16", "1", "1", "1", "1", "1", "10", "--backend", "single",
            "--kernel", "pallas"]
    full_dir, res_dir = str(tmp_path / "full"), str(tmp_path / "res")
    ck = str(tmp_path / "ck.npz")
    assert cli.main(base + ["--out-dir", full_dir]) == 0
    assert cli.main(
        base + ["--out-dir", str(tmp_path), "--stop-step", "6",
                "--save-state", ck]
    ) == 0
    assert cli.main(
        ["--resume", ck, "--fuse-steps", "4", "--out-dir", res_dir]
    ) == 0
    capsys.readouterr()
    full = json.load(open(os.path.join(full_dir, "output_N16_Np1_TPU.json")))
    res = json.load(open(os.path.join(res_dir, "output_N16_Np1_TPU.json")))
    assert res["abs_errors"][7:] == full["abs_errors"][7:]
    assert all(e == 0 for e in res["abs_errors"][:7])


def test_cli_fuse_steps_bad_mesh_values(capsys):
    base = ["16", "1", "1", "1", "1", "1", "5", "--fuse-steps", "4"]
    assert cli.main(base + ["--mesh", "0,1,1"]) == 2
    assert cli.main(base + ["--mesh", "-2,1,1"]) == 2
    capsys.readouterr()


def test_cli_fuse_steps_auto_stays_single(tmp_path, capsys):
    """Bare --fuse-steps (no --mesh/--backend) runs single-device even on a
    multi-device host: sharding is explicit opt-in (N=20 would not divide
    the 8-device test mesh, which is exactly the point)."""
    rc = cli.main(["20", "1", "1", "1", "1", "1", "5", "--fuse-steps", "4",
                   "--out-dir", str(tmp_path)])
    assert rc == 0
    assert os.path.exists(tmp_path / "output_N20_Np1_TPU.txt")
    capsys.readouterr()


def test_cli_c2_field(tmp_path, capsys):
    """--c2-field reaches the variable-c kernels end-to-end: presets and
    .npy files run on single and sharded backends, the analytic oracle is
    disabled with a notice, and misuse is rejected before compute."""
    base = ["12", "1", "1", "1", "1", "1", "5"]
    assert cli.main(
        base + ["--c2-field", "gaussian-lens", "--backend", "single",
                "--out-dir", str(tmp_path)]
    ) == 0
    out = capsys.readouterr().out
    assert "errors: disabled" in out
    side = json.load(open(tmp_path / "output_N12_Np1_TPU.json"))
    assert side["run_config"]["c2_field"] == "gaussian-lens"
    assert side["errors_computed"] is False

    # .npy file of c^2 values; the constant field must reproduce the
    # constant-speed run's layers exactly (library collapse contract).
    import numpy as np

    from wavetpu.core.problem import Problem as _P

    p = _P.from_argv(base)
    npy = str(tmp_path / "c2.npy")
    np.save(npy, np.full((12, 12, 12), p.a2))
    assert cli.main(
        base + ["--c2-field", npy, "--backend", "single",
                "--out-dir", str(tmp_path / "npy")]
    ) == 0
    # Sharded backend composes with the field.
    assert cli.main(
        base + ["--c2-field", "two-layer", "--mesh", "2,2,1",
                "--out-dir", str(tmp_path / "sh")]
    ) == 0
    capsys.readouterr()
    assert os.path.exists(tmp_path / "sh" / "output_N12_Np4_TPU.txt")

    # Misuse rejected before compute: 1-step compensated has no field
    # kernel (the velocity-form onion takes it; --fuse-steps required),
    # and malformed fields fail fast.
    assert cli.main(base + ["--c2-field", "nope-not-a-preset"]) == 2
    assert cli.main(
        base + ["--c2-field", "constant", "--scheme", "compensated"]
    ) == 2
    np.save(str(tmp_path / "bad.npy"), np.zeros((3, 3, 3)))
    assert cli.main(
        base + ["--c2-field", str(tmp_path / "bad.npy")]
    ) == 2
    capsys.readouterr()


def test_cli_c2_field_kfused(tmp_path, capsys):
    """--c2-field composes with --fuse-steps (round 6): the standard
    onion, the sharded onion, and the velocity-form compensated onion
    (incl. --v-dtype bf16) all run end-to-end with the oracle disabled,
    and a variable-c k-fused checkpoint resumes under the re-passed
    field with the same final state."""
    base = ["12", "1", "1", "1", "1", "1", "6"]
    assert cli.main(
        base + ["--c2-field", "two-layer", "--fuse-steps", "2",
                "--backend", "single", "--out-dir", str(tmp_path)]
    ) == 0
    out = capsys.readouterr().out
    assert "errors: disabled" in out and "fuse-steps: 2" in out
    side = json.load(open(tmp_path / "output_N12_Np1_TPU.json"))
    assert side["run_config"]["c2_field"] == "two-layer"
    assert side["run_config"]["fuse_steps"] == 2
    # Sharded (2D mesh) composition.
    assert cli.main(
        base + ["--c2-field", "two-layer", "--fuse-steps", "2",
                "--mesh", "2,2,1", "--out-dir", str(tmp_path / "sh")]
    ) == 0
    # Velocity-form compensated onion with the field, incl. bf16-v.
    assert cli.main(
        base + ["--c2-field", "two-layer", "--scheme", "compensated",
                "--fuse-steps", "2", "--out-dir", str(tmp_path / "c")]
    ) == 0
    assert cli.main(
        base + ["--c2-field", "two-layer", "--scheme", "compensated",
                "--fuse-steps", "2", "--v-dtype", "bf16",
                "--out-dir", str(tmp_path / "cb")]
    ) == 0
    capsys.readouterr()
    # Checkpoint/resume under the field: the resumed run re-passes
    # --c2-field and must land on the uninterrupted run's state (the
    # sidecar only records state, never the field).
    full_dir = str(tmp_path / "full")
    ck = str(tmp_path / "ck.npz")
    args = base + ["--c2-field", "two-layer", "--fuse-steps", "2",
                   "--backend", "single"]
    assert cli.main(args + ["--out-dir", full_dir]) == 0
    assert cli.main(
        args + ["--stop-step", "3", "--save-state", ck,
                "--out-dir", str(tmp_path / "part")]
    ) == 0
    res_dir = str(tmp_path / "res")
    assert cli.main(
        ["--resume", ck, "--c2-field", "two-layer", "--fuse-steps", "2",
         "--out-dir", res_dir]
    ) == 0
    capsys.readouterr()
    full = json.load(open(os.path.join(full_dir, "output_N12_Np1_TPU.json")))
    rs = json.load(open(os.path.join(res_dir, "output_N12_Np1_TPU.json")))
    assert rs["run_config"]["resumed"] is True
    assert rs["run_config"]["c2_field"] == "two-layer"
    # Errors are off for variable c, so compare the recorded config and
    # that both runs completed to the same final step.
    assert full["run_config"]["fuse_steps"] == rs["run_config"]["fuse_steps"]


def test_cli_compensated_kfused_phase_timing(tmp_path, capsys):
    """--phase-timing now covers the velocity-form onion (round 6): a
    compensated k-fused sharded run reports the loop/exchange split;
    the 1-step compensated scheme still has no probe and is refused."""
    rc = cli.main(
        ["16", "1", "1", "1", "1", "1", "8", "--scheme", "compensated",
         "--fuse-steps", "4", "--mesh", "2,1,1", "--phase-timing",
         "--out-dir", str(tmp_path)]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "total loop time:" in out and "total ICI exchange time:" in out
    assert cli.main(
        ["16", "1", "1", "1", "1", "1", "8", "--scheme", "compensated",
         "--phase-timing", "--out-dir", str(tmp_path)]
    ) == 2
    err = capsys.readouterr().err
    assert "1-step scheme has none" in err


def test_cli_debug_nans_flag(tmp_path):
    """--debug-nans enables jax's NaN trap for the solve (SURVEY section 5
    sanitizer row) and a stable run completes without a false trap."""
    import jax

    try:
        rc = cli.main(["16", "1", "1", "1", "1", "1", "5",
                       "--backend", "single", "--debug-nans",
                       "--out-dir", str(tmp_path)])
        assert rc == 0
        assert jax.config.jax_debug_nans
    finally:
        jax.config.update("jax_debug_nans", False)


@pytest.mark.heavy
def test_cli_resumed_kfused_phase_timing_uses_checkpoint_mesh(
    tmp_path, capsys
):
    """A resumed sharded k-fused run probes the CHECKPOINT's mesh, not the
    host's device count (N=16 on 8 devices would not even divide)."""
    base = ["16", "1", "1", "1", "1", "1", "8", "--mesh", "2,1,1",
            "--fuse-steps", "4"]
    ck = str(tmp_path / "ck")
    assert cli.main(
        base + ["--stop-step", "4", "--save-state", ck,
                "--out-dir", str(tmp_path)]
    ) == 0
    rc = cli.main(
        ["--resume", ck, "--fuse-steps", "4", "--phase-timing",
         "--out-dir", str(tmp_path / "res")]
    )
    assert rc == 0
    assert "total loop time:" in capsys.readouterr().out


@pytest.mark.heavy
def test_cli_resumed_xy_kfused_phase_timing(tmp_path, capsys):
    """--phase-timing now covers 2D-mesh k-fused runs (round-5): a
    resumed (2,2,1) checkpoint probes the xy program and reports the
    split."""
    ck = str(tmp_path / "ck")
    assert cli.main(
        ["16", "1", "1", "1", "1", "1", "8", "--fuse-steps", "4",
         "--mesh", "2,2,1", "--stop-step", "4", "--save-state", ck,
         "--out-dir", str(tmp_path)]
    ) == 0
    assert cli.main(
        ["--resume", ck, "--fuse-steps", "4", "--phase-timing",
         "--out-dir", str(tmp_path / "res")]
    ) == 0
    out = capsys.readouterr().out
    assert "total loop time:" in out and "total ICI exchange time:" in out


def test_cli_json_run_config(tmp_path, capsys):
    """The JSON sidecar records how the run was produced (backend, kernel,
    scheme, fuse_steps, mesh, dtype) - the runtime equivalent of the
    reference encoding its configuration in which binary ran."""
    assert cli.main(
        ["16", "1", "1", "1", "1", "1", "5", "--fuse-steps", "4",
         "--mesh", "2,2,1", "--dtype", "bf16", "--out-dir", str(tmp_path)]
    ) == 0
    capsys.readouterr()
    side = json.load(open(tmp_path / "output_N16_Np4_TPU.json"))
    cfg = side["run_config"]
    assert cfg == {
        "backend": "sharded",
        "kernel": "pallas",
        "scheme": "standard",
        "fuse_steps": 4,
        "mesh": [2, 2, 1],
        "dtype": "bfloat16",
        "v_dtype": None,
        "c2_field": None,
        "distributed": False,
        "resumed": False,
        "supervised": False,
        "ckpt_every": None,
        "supervisor_status": None,
    }
