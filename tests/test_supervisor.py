"""Supervised solves (run/supervisor.py): chunked march == uninterrupted
march BITWISE on every wrapped path, checkpoint rotation with `latest`
pointer + keep-2 GC, real-signal preemption + resume, watchdog halt on
injected NaN with the last-good checkpoint preserved, and bounded
auto-retry - driven by the fault harness (run/faults.py), never by
timing races."""

import os

import numpy as np
import pytest

from wavetpu.core.problem import Problem
from wavetpu.io import checkpoint
from wavetpu.run import faults, health
from wavetpu.run import supervisor as sup
from wavetpu.solver import kfused, kfused_comp, leapfrog


def _opts(tmp_path, every=3, **kw):
    return sup.SupervisorOptions(
        ckpt_every=every, ckpt_dir=str(tmp_path / "rot"), **kw
    )


def _eq(a, b):
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_chunk_length_snaps_to_block():
    assert sup.chunk_length(5, 1) == 5
    assert sup.chunk_length(5, 4) == 4    # snapped down to one block
    assert sup.chunk_length(8, 4) == 8
    assert sup.chunk_length(1, 4) == 4    # at least one block
    with pytest.raises(ValueError):
        sup.chunk_length(0, 1)


def test_supervised_standard_bitwise_and_rotation(small_problem, tmp_path):
    full = leapfrog.solve(small_problem)
    r = sup.supervise(small_problem, sup.PathSpec(), _opts(tmp_path))
    assert r.status == "complete" and r.exit_code == sup.EXIT_COMPLETE
    _eq(r.result.u_cur, full.u_cur)
    _eq(r.result.u_prev, full.u_prev)
    np.testing.assert_array_equal(r.result.abs_errors, full.abs_errors)
    np.testing.assert_array_equal(r.result.rel_errors, full.rel_errors)
    # Rotation layout: fresh step entries, keep-last-2 GC, atomic pointer.
    root = tmp_path / "rot"
    entries = sorted(
        e for e in os.listdir(root) if e.startswith("step-")
    )
    assert r.checkpoints_written == 3          # boundaries 4, 7, 10
    assert len(entries) == 2                   # GC kept the newest two
    assert entries[-1] == "step-00000010.npz"
    assert open(root / "latest").read().strip() == entries[-1]
    assert sup.resolve_latest(str(root)) == str(root / entries[-1])
    assert sup.looks_like_rotation_root(str(root))


def test_resolve_latest_survives_lost_pointer(small_problem, tmp_path):
    sup.supervise(small_problem, sup.PathSpec(), _opts(tmp_path))
    root = tmp_path / "rot"
    os.remove(root / "latest")
    # Pointer lost to a crash: fall back to the newest step entry.
    assert sup.resolve_latest(str(root)).endswith("step-00000010.npz")
    # A per-shard checkpoint directory itself is NOT a rotation root.
    os.makedirs(tmp_path / "shardck")
    np.savez(tmp_path / "shardck" / "meta.npz", step=1)
    assert not sup.looks_like_rotation_root(str(tmp_path / "shardck"))


def test_supervised_kfused_preempt_resume_bitwise(tmp_path):
    """SIGTERM mid-solve (a REAL signal, delivered by the fault harness)
    followed by --resume of `latest` == uninterrupted run, bitwise, on
    the standard k-fused path - including the 1-step remainder tail."""
    p = Problem(N=12, timesteps=10)
    full = kfused.solve_kfused(p, k=2, interpret=True)
    spec = sup.PathSpec(fuse_steps=2, kernel="pallas", interpret=True)
    r = sup.supervise(
        p, spec,
        _opts(tmp_path, every=4, chunk_hook=faults.preempt_at_step(5)),
    )
    assert r.status == "preempted" and r.exit_code == sup.EXIT_PREEMPTED
    assert r.checkpoint_path is not None
    _, u_prev, u_cur, step = checkpoint.load_checkpoint(r.checkpoint_path)
    assert step == r.final_step < p.timesteps
    r2 = sup.supervise(
        p, spec, _opts(tmp_path, every=4),
        state=(u_prev, u_cur), start_step=step,
    )
    assert r2.status == "complete"
    _eq(r2.result.u_cur, full.u_cur)
    _eq(r2.result.u_prev, full.u_prev)
    np.testing.assert_array_equal(
        r2.result.abs_errors[step + 1:], full.abs_errors[step + 1:]
    )


def test_supervised_kfused_comp_preempt_resume_bitwise(tmp_path):
    """The same SIGTERM + resume drill on the compensated k-fused
    (velocity-form onion) path: supervision must preserve its exact
    trajectory, carry included."""
    p = Problem(N=12, timesteps=9)
    full = kfused_comp.solve_kfused_comp(p, k=2, interpret=True)
    spec = sup.PathSpec(
        scheme="compensated", fuse_steps=2, kernel="pallas",
        interpret=True,
    )
    r = sup.supervise(
        p, spec,
        _opts(tmp_path, every=4, chunk_hook=faults.preempt_at_step(5)),
    )
    assert r.status == "preempted"
    latest = sup.resolve_latest(str(tmp_path / "rot"))
    _, _, u_cur, step = checkpoint.load_checkpoint(latest)
    v, carry = checkpoint.load_checkpoint_aux(latest)
    r2 = sup.supervise(
        p, spec, _opts(tmp_path, every=4),
        state=(u_cur, v, carry), start_step=step,
    )
    assert r2.status == "complete"
    _eq(r2.result.u_cur, full.u_cur)
    _eq(r2.result.comp_v, full.comp_v)
    _eq(r2.result.comp_carry, full.comp_carry)


def test_supervised_compensated_1step_bitwise(small_problem, tmp_path):
    full = leapfrog.solve_compensated(small_problem)
    spec = sup.PathSpec(scheme="compensated")
    r = sup.supervise(small_problem, spec, _opts(tmp_path, every=4))
    assert r.status == "complete"
    _eq(r.result.u_cur, full.u_cur)
    _eq(r.result.comp_v, full.comp_v)
    _eq(r.result.comp_carry, full.comp_carry)


def test_supervised_variable_c_bitwise(tmp_path):
    from wavetpu.kernels import stencil_ref

    p = Problem(N=12, timesteps=8)
    field = stencil_ref.make_c2tau2_field(
        p, lambda x, y, z: np.where(z < 0.5, p.a2, 0.5 * p.a2)
        + 0.0 * x + 0.0 * y,
    )
    full = leapfrog.solve(
        p, step_fn=stencil_ref.make_variable_c_step(field),
        compute_errors=False,
    )
    spec = sup.PathSpec(c2tau2_field=field, compute_errors=False)
    r = sup.supervise(p, spec, _opts(tmp_path))
    assert r.status == "complete"
    _eq(r.result.u_cur, full.u_cur)


def test_watchdog_halts_with_last_good(small_problem, tmp_path):
    """An injected NaN never reaches a completed-looking result: the run
    halts with exit code 4, the LAST-GOOD state, and its checkpoint."""
    full = leapfrog.solve(small_problem)
    r = sup.supervise(
        small_problem, sup.PathSpec(),
        _opts(tmp_path, chunk_hook=faults.nan_at_step(7)),
    )
    assert r.status == "watchdog" and r.exit_code == sup.EXIT_WATCHDOG
    assert r.amax_last == float("inf")
    assert r.final_step == 4                     # boundary before the trip
    good = leapfrog.solve(small_problem, stop_step=4)
    _eq(r.result.u_cur, good.u_cur)
    # Errors beyond the last-good step are zeroed, not garbage.
    np.testing.assert_array_equal(
        r.result.abs_errors[:5], full.abs_errors[:5]
    )
    assert np.all(r.result.abs_errors[5:] == 0.0)
    # The preserved checkpoint resumes to the uninterrupted result.
    _, u_prev, u_cur, step = checkpoint.load_checkpoint(r.checkpoint_path)
    assert step == 4
    res = leapfrog.resume(small_problem, u_prev, u_cur, start_step=step)
    _eq(res.u_cur, full.u_cur)


def test_watchdog_retry_recovers_bitwise(small_problem, tmp_path):
    """--retries N: a transient injected fault is absorbed by reloading
    the last-good checkpoint, and the final state is still bitwise-equal
    to the uninterrupted run."""
    full = leapfrog.solve(small_problem)
    r = sup.supervise(
        small_problem, sup.PathSpec(),
        _opts(tmp_path, retries=1, chunk_hook=faults.nan_at_step(7)),
    )
    assert r.status == "complete" and r.retries_used == 1
    _eq(r.result.u_cur, full.u_cur)
    np.testing.assert_array_equal(r.result.abs_errors, full.abs_errors)


def test_resume_into_fresh_rotation_seeds_last_good(small_problem,
                                                    tmp_path):
    """Resuming an external checkpoint into an EMPTY rotation root seeds
    it with the injected state, so a trip in the first post-resume chunk
    retries from the resume point - never a silent restart from layer 0
    (and a halt still reports the injected step, not step 0)."""
    full = leapfrog.solve(small_problem)
    half = leapfrog.solve(small_problem, stop_step=5)
    ck = checkpoint.save_checkpoint(str(tmp_path / "ext.npz"), half)
    _, u_prev, u_cur, step = checkpoint.load_checkpoint(ck)
    r = sup.supervise(
        small_problem, sup.PathSpec(),
        _opts(tmp_path, retries=1, chunk_hook=faults.nan_at_step(6)),
        state=(u_prev, u_cur), start_step=step,
    )
    assert r.status == "complete" and r.retries_used == 1
    _eq(r.result.u_cur, full.u_cur)
    # steps marched = (10 - 5) + the retried chunk, never the full 10+.
    assert r.result.steps_computed <= 2 * (small_problem.timesteps - 5)
    # The halt flavor: no retries -> last good IS the injected step.
    r2 = sup.supervise(
        small_problem, sup.PathSpec(),
        sup.SupervisorOptions(
            ckpt_every=3, ckpt_dir=str(tmp_path / "rot2"),
            chunk_hook=faults.nan_at_step(6),
        ),
        state=(u_prev, u_cur), start_step=step,
    )
    assert r2.status == "watchdog" and r2.final_step == 5
    _eq(r2.result.u_cur, half.u_cur)
    assert r2.checkpoint_path is not None


def test_watchdog_amplitude_bound(small_problem, tmp_path):
    """A finite-but-blown-up amplitude trips the bound (not just NaN)."""
    r = sup.supervise(
        small_problem, sup.PathSpec(),
        _opts(tmp_path, max_amp=1e-4),
    )
    assert r.status == "watchdog"
    assert np.isfinite(r.amax_last) and r.amax_last > 1e-4


def test_health_guard_semantics():
    import jax.numpy as jnp

    assert health.guarded_amax(jnp.asarray([1.0, -3.0])) == 3.0
    assert health.guarded_amax(
        jnp.asarray([1.0, float("nan")])
    ) == float("inf")
    assert health.guarded_amax(
        jnp.asarray([1.0, float("inf")])
    ) == float("inf")
    assert health.healthy(0.5) and not health.healthy(float("inf"))
    assert not health.healthy(float("nan"))


def test_supervised_sharded_standard_bitwise(small_problem, tmp_path):
    """Sharded (dryrun-mesh) supervision: chunked shard_map march ==
    uninterrupted sharded solve, bitwise, and the rotation holds
    per-shard checkpoint DIRECTORIES."""
    from wavetpu.solver import sharded

    full = sharded.solve_sharded(
        small_problem, mesh_shape=(2, 1, 1), kernel="roll"
    )
    spec = sup.PathSpec(
        backend="sharded", kernel="roll", mesh_shape=(2, 1, 1)
    )
    r = sup.supervise(small_problem, spec, _opts(tmp_path, every=4))
    assert r.status == "complete"
    _eq(r.result.u_cur, full.u_cur)
    np.testing.assert_array_equal(r.result.abs_errors, full.abs_errors)
    assert os.path.isdir(r.checkpoint_path)
    assert os.path.exists(os.path.join(r.checkpoint_path, "meta.npz"))


@pytest.mark.heavy
def test_supervised_sharded_kfused_preempt_resume(tmp_path):
    """The dryrun-mesh k-fused drill: preempt a sharded k-fused
    supervised run with a real SIGTERM, resume the per-shard `latest`
    checkpoint, land bitwise on the uninterrupted run."""
    from wavetpu.solver import sharded_kfused

    p = Problem(N=12, timesteps=9)
    full = sharded_kfused.solve_sharded_kfused(
        p, mesh_shape=(2, 1, 1), k=2, interpret=True
    )
    spec = sup.PathSpec(
        backend="sharded", fuse_steps=2, kernel="pallas",
        mesh_shape=(2, 1, 1), interpret=True,
    )
    r = sup.supervise(
        p, spec,
        _opts(tmp_path, every=4, chunk_hook=faults.preempt_at_step(5)),
    )
    assert r.status == "preempted"
    (_, u_prev, u_cur, step, mesh_shape, _, _) = (
        checkpoint.load_sharded_checkpoint(r.checkpoint_path)
    )
    assert mesh_shape == (2, 1, 1)
    r2 = sup.supervise(
        p, spec, _opts(tmp_path, every=4),
        state=(u_prev, u_cur), start_step=step,
    )
    assert r2.status == "complete"
    _eq(r2.result.u_cur, full.u_cur)


@pytest.mark.heavy
def test_supervised_sharded_kfused_comp_bitwise(tmp_path):
    """Supervised distributed velocity-form flagship (dryrun mesh)."""
    p = Problem(N=12, timesteps=9)
    full = kfused_comp.solve_kfused_comp_sharded(
        p, mesh_shape=(2, 1, 1), k=2, interpret=True
    )
    spec = sup.PathSpec(
        backend="sharded", scheme="compensated", fuse_steps=2,
        kernel="pallas", mesh_shape=(2, 1, 1), interpret=True,
    )
    r = sup.supervise(p, spec, _opts(tmp_path, every=4))
    assert r.status == "complete"
    _eq(r.result.u_cur, full.u_cur)
    _eq(r.result.comp_v, full.comp_v)


@pytest.mark.heavy
def test_supervised_uneven_kfused_bitwise(tmp_path):
    """The pad-and-mask route (k does not divide N) under supervision."""
    from wavetpu.solver import sharded_kfused

    p = Problem(N=15, timesteps=8)
    full = sharded_kfused.solve_sharded_kfused(
        p, n_shards=1, k=2, interpret=True
    )
    spec = sup.PathSpec(fuse_steps=2, kernel="pallas", interpret=True)
    r = sup.supervise(p, spec, _opts(tmp_path))
    assert r.status == "complete"
    _eq(r.result.u_cur, full.u_cur)
    np.testing.assert_array_equal(r.result.abs_errors, full.abs_errors)
