"""Serve-layer contracts: engine cache, dynamic batcher, HTTP front end.

The acceptance-level smoke test drives CONCURRENT HTTP requests at a live
ThreadingHTTPServer and asserts they were coalesced into one batched
solve (batch occupancy > 1 observed via /metrics) with each request
receiving its own reference-format report - the end-to-end claim of
`wavetpu serve`.  The watchdog test pins per-lane blast-radius: a
Courant-unstable lane 422s while its batchmate's 200 stands.
"""

import json
import threading
import time
import types
import urllib.error
import urllib.request

import numpy as np
import pytest

from wavetpu.core.problem import Problem
from wavetpu.ensemble import batched as eb
from wavetpu.serve.api import _c2_preset, build_server, parse_solve_request
from wavetpu.serve.engine import ProgramKey, ServeEngine
from wavetpu.serve.scheduler import (
    DynamicBatcher,
    ServeMetrics,
    SolveRequest,
)


def _bitwise(a, b):
    return np.array_equal(np.asarray(a), np.asarray(b))


# ---- engine ----

class TestEngine:
    def test_bucket_for(self):
        eng = ServeEngine(bucket_sizes=(1, 2, 4, 8), interpret=True)
        assert eng.bucket_for(1) == 1
        assert eng.bucket_for(3) == 4
        assert eng.bucket_for(8) == 8
        with pytest.raises(ValueError, match="exceed"):
            eng.bucket_for(9)

    def test_program_cache_hits_misses_eviction(self):
        eng = ServeEngine(
            bucket_sizes=(1, 2), max_programs=1, interpret=True
        )
        p1 = Problem(N=8, timesteps=3)
        p2 = Problem(N=8, timesteps=4)
        a = eng.program(p1, "standard", "roll", 1, "f32", False, 2)
        assert a is not None and eng.misses == 1 and eng.hits == 0
        b = eng.program(p1, "standard", "roll", 1, "f32", False, 2)
        assert b is a and eng.hits == 1
        c = eng.program(p2, "standard", "roll", 1, "f32", False, 2)
        assert c is not a
        assert eng.evictions == 1
        stats = eng.cache_stats()
        assert stats["programs"] == 1
        assert stats["misses"] == 2

    def test_solve_pads_to_bucket(self):
        eng = ServeEngine(bucket_sizes=(1, 2, 4), interpret=True)
        p = Problem(N=8, timesteps=3)
        lanes = [eb.LaneSpec(), eb.LaneSpec(phase=1.0), eb.LaneSpec()]
        res, health = eng.solve(p, lanes, path="roll")
        assert res.batch_size == 4
        assert res.n_lanes == 3
        assert health == [None, None, None]
        assert res.batched

    def test_warmup_precompiles(self):
        eng = ServeEngine(bucket_sizes=(1, 2), interpret=True)
        p = Problem(N=8, timesteps=3)
        warmed = eng.warmup(p, path="roll")
        assert warmed == [1, 2]
        assert eng.misses == 2
        eng.solve(p, [eb.LaneSpec()], path="roll")
        assert eng.hits == 1  # served from the warmed program

    def test_compensated_scheme_falls_back_recorded(self):
        eng = ServeEngine(bucket_sizes=(1, 2), interpret=True)
        p = Problem(N=8, timesteps=3)
        res, health = eng.solve(p, [eb.LaneSpec()], scheme="compensated")
        assert res.batched is False
        assert "compensated" in res.fallback_reason
        assert any(
            k.startswith("scheme:") for k in eng.cache_stats()["fallbacks"]
        )

    def test_watchdog_isolates_poisoned_lane(self):
        # C = 0.55: stable under constant c^2 = a^2, but the two-layer
        # preset DOUBLES c^2 in half the domain (c * sqrt2 -> C = 0.78,
        # past the leapfrog bound) - that lane blows up while its
        # batchmate stays bounded.
        p = Problem(N=8, T=26.0, timesteps=60)
        eng = ServeEngine(bucket_sizes=(1, 2), interpret=True)
        lanes = [
            eb.LaneSpec(c2tau2_field=_c2_preset(p, "constant")),
            eb.LaneSpec(c2tau2_field=_c2_preset(p, "two-layer")),
        ]
        res, health = eng.solve(p, lanes, path="roll")
        assert health[0] is None
        assert health[1] is not None and "amax" in health[1]
        amax0 = float(np.abs(np.asarray(res.results[0].u_cur)).max())
        assert amax0 < 10.0  # the healthy lane is untouched

    def test_guarded_amax_per_lane_semantics(self):
        from wavetpu.run import health

        batch = np.stack([
            np.ones((4, 4, 4)),
            np.full((4, 4, 4), np.nan),
            np.full((4, 4, 4), 7.0),
        ])
        out = health.guarded_amax_per_lane(batch)
        assert out.shape == (3,)
        assert out[0] == 1.0
        assert np.isinf(out[1])  # NaN anywhere -> +inf, as guarded_amax
        assert out[2] == 7.0
        # agrees with the solo guard lane by lane
        for i in range(3):
            assert out[i] == health.guarded_amax(batch[i])

    def test_watchdog_can_be_disabled(self):
        p = Problem(N=8, T=26.0, timesteps=60)
        eng = ServeEngine(
            bucket_sizes=(1,), interpret=True, watchdog=False,
        )
        _, health = eng.solve(
            p, [eb.LaneSpec(c2tau2_field=_c2_preset(p, "two-layer"))],
            path="roll",
        )
        assert health == [None]


# ---- scheduler (fake engine: batching logic only) ----

class _FakeEngine:
    """Engine stub recording batch compositions."""

    max_batch = 4

    def __init__(self, fail=False):
        self.batches = []
        self.fail = fail

    def solve(self, problem, lanes, scheme, path, k, dtype_name):
        if self.fail:
            raise RuntimeError("engine exploded")
        self.batches.append(len(lanes))
        results = [
            types.SimpleNamespace(steps_computed=problem.timesteps)
            for _ in lanes
        ]
        res = types.SimpleNamespace(
            results=results, n_lanes=len(lanes), batch_size=len(lanes),
            batched=True, fallback_reason=None, path=path,
            solve_seconds=0.01, aggregate_gcells_per_second=1.0,
        )
        return res, [None] * len(lanes)


def _req(problem, **kw):
    return SolveRequest(problem=problem, lane=eb.LaneSpec(**kw))


class TestBatcher:
    def test_concurrent_same_key_requests_coalesce(self):
        eng = _FakeEngine()
        metrics = ServeMetrics()
        b = DynamicBatcher(eng, metrics=metrics, max_wait=0.5)
        p = Problem(N=8, timesteps=3)
        futs = [b.submit(_req(p, phase=1.0 + i)) for i in range(3)]
        out = [f.result(10) for f in futs]
        b.close()
        assert eng.batches == [3]
        assert all(o[2]["occupancy"] == 3 for o in out)
        snap = metrics.snapshot()
        assert snap["batches_total"] == 1
        assert snap["batch_occupancy_max"] == 3

    def test_different_keys_never_share_a_batch(self):
        eng = _FakeEngine()
        b = DynamicBatcher(eng, max_wait=0.3)
        pa = Problem(N=8, timesteps=3)
        pb = Problem(N=8, timesteps=4)
        fa = b.submit(_req(pa))
        fb = b.submit(_req(pb))
        fa.result(10)
        fb.result(10)
        b.close()
        assert sorted(eng.batches) == [1, 1]

    def test_max_batch_closes_the_batch_early(self):
        eng = _FakeEngine()
        b = DynamicBatcher(eng, max_wait=30.0, max_batch=2)
        p = Problem(N=8, timesteps=3)
        t0 = time.monotonic()
        futs = [b.submit(_req(p, phase=1.0 + i)) for i in range(2)]
        for f in futs:
            f.result(10)
        took = time.monotonic() - t0
        b.close()
        assert eng.batches == [2]
        assert took < 5.0  # did not sit out the 30 s max_wait

    def test_engine_failure_propagates_to_every_future(self):
        b = DynamicBatcher(_FakeEngine(fail=True), max_wait=0.2)
        p = Problem(N=8, timesteps=3)
        futs = [b.submit(_req(p, phase=1.0 + i)) for i in range(2)]
        for f in futs:
            with pytest.raises(RuntimeError, match="engine exploded"):
                f.result(10)
        b.close()

    def test_bucket_key_separates_program_identities(self):
        p = Problem(N=8, timesteps=3)
        base = _req(p)
        assert base.bucket_key() == _req(p, phase=2.0).bucket_key()
        other = SolveRequest(problem=p, lane=eb.LaneSpec(), dtype_name="f64")
        assert base.bucket_key() != other.bucket_key()
        kf = SolveRequest(problem=p, lane=eb.LaneSpec(), path="kfused", k=2)
        assert base.bucket_key() != kf.bucket_key()


# ---- request parsing ----

class TestParse:
    def test_minimal_request(self):
        req = parse_solve_request({"N": 8}, default_kernel="roll")
        assert req.problem.N == 8
        assert req.path == "roll"
        assert req.k == 1

    def test_fuse_steps_selects_kfused(self):
        req = parse_solve_request(
            {"N": 8, "fuse_steps": 2, "kernel": "pallas"},
            default_kernel="roll",
        )
        assert req.path == "kfused" and req.k == 2

    def test_fuse_steps_rejects_roll(self):
        with pytest.raises(ValueError, match="pallas"):
            parse_solve_request(
                {"N": 8, "fuse_steps": 2, "kernel": "roll"},
                default_kernel="roll",
            )

    def test_pi_lengths_and_preset_fields(self):
        req = parse_solve_request(
            {"N": 8, "Lx": "pi", "c2_field": "gaussian-lens"},
            default_kernel="roll",
        )
        assert req.problem.Lx == pytest.approx(np.pi)
        assert req.lane.c2tau2_field is not None

    def test_bad_fields_rejected(self):
        for body, msg in [
            ({}, "missing required field N"),
            ({"N": 8, "scheme": "x"}, "scheme"),
            ({"N": 8, "dtype": "f16"}, "dtype"),
            ({"N": 8, "c2_field": "nope"}, "c2_field"),
            ({"N": 8, "steps": 99}, "stop_step"),
            ({"N": 8, "scheme": "compensated", "phase": 1.0},
             "reference phase"),
            ({"N": 8, "scheme": "compensated", "c2_field": "constant"},
             "c2_field"),
            ({"N": 8, "phase": 1.0, "c2_field": "constant"},
             "analytic layer-1"),
        ]:
            with pytest.raises(ValueError, match=msg):
                parse_solve_request(body, default_kernel="roll")


# ---- HTTP end to end ----

@pytest.fixture()
def server():
    httpd, state = build_server(
        port=0, max_wait=0.5, default_kernel="roll", interpret=True
    )
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    yield base, state
    httpd.shutdown()
    state.batcher.close()
    httpd.server_close()


def _post(base, body, timeout=120):
    req = urllib.request.Request(
        base + "/solve", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=30) as r:
        return r.status, json.loads(r.read())


class TestHTTP:
    def test_concurrent_requests_coalesce_with_own_reports(self, server):
        base, state = server
        results = [None] * 4
        phases = [6.283, 1.0, 0.5, 0.25]

        def worker(i):
            results[i] = _post(
                base, {"N": 8, "timesteps": 4, "phase": phases[i]}
            )

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        errs = set()
        for code, body in results:
            assert code == 200
            assert body["status"] == "ok"
            assert body["batch"]["occupancy"] > 1
            assert body["report"]["final_step"] == 4
            assert len(body["report"]["abs_errors"]) == 5
            assert "grids initialized in" in body["report_text"]
            errs.add(body["report"]["max_abs_error"])
        # four distinct phases -> four distinct per-request reports
        assert len(errs) == 4
        code, metrics = _get(base, "/metrics")
        assert code == 200
        assert metrics["batch_occupancy_max"] > 1
        assert metrics["requests_total"] == 4
        assert metrics["responses_ok"] == 4
        assert metrics["aggregate_gcells_per_s"] is not None
        assert metrics["latency_p50_ms"] is not None
        assert metrics["program_cache"]["programs"] >= 1

    def test_healthz(self, server):
        base, _ = server
        code, body = _get(base, "/healthz")
        assert code == 200
        assert body["status"] == "ok"

    def test_bad_request_400(self, server):
        base, _ = server
        code, body = _post(base, {"timesteps": 4})
        assert code == 400
        assert "N" in body["error"]

    def test_unknown_route_404(self, server):
        base, _ = server
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/nope", timeout=30)
        assert ei.value.code == 404

    def test_watchdog_poisoned_request_422_batchmate_ok(self, server):
        base, _ = server
        results = [None] * 2
        bodies = [
            {"N": 8, "T": 26.0, "timesteps": 60, "c2_field": "constant"},
            {"N": 8, "T": 26.0, "timesteps": 60, "c2_field": "two-layer"},
        ]

        def worker(i):
            results[i] = _post(base, bodies[i], timeout=300)

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        codes = sorted(r[0] for r in results)
        assert codes == [200, 422]
        bad = next(b for c, b in results if c == 422)
        assert "amax" in bad["error"]
        ok = next(b for c, b in results if c == 200)
        # a field request serves without the analytic oracle
        assert ok["report"]["errors_computed"] is False
        assert ok["report"]["max_abs_error"] is None


# ---- CLI entry points ----

class TestCLI:
    def test_wavetpu_version(self, capsys):
        from wavetpu import __version__
        from wavetpu.cli import main

        assert main(["--version"]) == 0
        assert __version__ in capsys.readouterr().out

    def test_wavetpu_serve_version(self, capsys):
        from wavetpu import __version__
        from wavetpu.cli import main

        assert main(["serve", "--version"]) == 0
        out = capsys.readouterr().out
        assert "wavetpu-serve" in out and __version__ in out

    def test_serve_rejects_unknown_flag(self, capsys):
        from wavetpu.cli import main

        assert main(["serve", "--frobnicate", "1"]) == 2

    def test_program_key_shape(self):
        p = Problem(N=8, timesteps=3)
        key = ProgramKey.for_batch(
            p, "standard", "roll", 4, "f32", False, True, 2
        )
        assert key.k == 1  # non-kfused paths normalize k
        assert key.batch == 2
