"""Serve-layer contracts: engine cache, dynamic batcher, HTTP front end.

The acceptance-level smoke test drives CONCURRENT HTTP requests at a live
ThreadingHTTPServer and asserts they were coalesced into one batched
solve (batch occupancy > 1 observed via /metrics) with each request
receiving its own reference-format report - the end-to-end claim of
`wavetpu serve`.  The watchdog test pins per-lane blast-radius: a
Courant-unstable lane 422s while its batchmate's 200 stands.
"""

import json
import threading
import time
import types
import urllib.error
import urllib.request

import numpy as np
import pytest

from wavetpu.core.problem import Problem
from wavetpu.ensemble import batched as eb
from wavetpu.run import faults
from wavetpu.serve.api import _c2_preset, build_server, parse_solve_request
from wavetpu.serve.engine import ProgramKey, ServeEngine
from wavetpu.serve.preempt import SolveStateStore
from wavetpu.serve.resilience import (
    DeadlineExceededError,
    InvalidStateTokenError,
    PreemptedError,
)
from wavetpu.serve.scheduler import (
    DynamicBatcher,
    QueueFullError,
    ServeMetrics,
    SolveRequest,
)
from tests.test_obs import parse_prometheus


def _bitwise(a, b):
    return np.array_equal(np.asarray(a), np.asarray(b))


# ---- engine ----

class TestEngine:
    def test_bucket_for(self):
        eng = ServeEngine(bucket_sizes=(1, 2, 4, 8), interpret=True)
        assert eng.bucket_for(1) == 1
        assert eng.bucket_for(3) == 4
        assert eng.bucket_for(8) == 8
        with pytest.raises(ValueError, match="exceed"):
            eng.bucket_for(9)

    def test_program_cache_hits_misses_eviction(self):
        eng = ServeEngine(
            bucket_sizes=(1, 2), max_programs=1, interpret=True
        )
        p1 = Problem(N=8, timesteps=3)
        p2 = Problem(N=8, timesteps=4)
        a = eng.program(p1, "standard", "roll", 1, "f32", False, 2)
        assert a is not None and eng.misses == 1 and eng.hits == 0
        b = eng.program(p1, "standard", "roll", 1, "f32", False, 2)
        assert b is a and eng.hits == 1
        c = eng.program(p2, "standard", "roll", 1, "f32", False, 2)
        assert c is not a
        assert eng.evictions == 1
        stats = eng.cache_stats()
        assert stats["programs"] == 1
        assert stats["misses"] == 2

    def test_solve_pads_to_bucket(self):
        eng = ServeEngine(bucket_sizes=(1, 2, 4), interpret=True)
        p = Problem(N=8, timesteps=3)
        lanes = [eb.LaneSpec(), eb.LaneSpec(phase=1.0), eb.LaneSpec()]
        res, health = eng.solve(p, lanes, path="roll")
        assert res.batch_size == 4
        assert res.n_lanes == 3
        assert health == [None, None, None]
        assert res.batched

    def test_warmup_precompiles(self):
        eng = ServeEngine(bucket_sizes=(1, 2), interpret=True)
        p = Problem(N=8, timesteps=3)
        warmed = eng.warmup(p, path="roll")
        assert warmed == [1, 2]
        assert eng.misses == 2
        eng.solve(p, [eb.LaneSpec()], path="roll")
        assert eng.hits == 1  # served from the warmed program

    def test_compensated_scheme_batches_through_the_engine(self):
        # The flagship scheme now rides the vmapped core: padded to the
        # bucket, no fallback, each lane bitwise its solo solve.
        eng = ServeEngine(bucket_sizes=(1, 2, 4), interpret=True)
        p = Problem(N=8, timesteps=5)
        lanes = [eb.LaneSpec(), eb.LaneSpec(phase=1.0)]
        res, health = eng.solve(
            p, lanes, scheme="compensated", path="kfused", k=2
        )
        assert res.batched is True
        assert res.fallback_reason is None
        assert res.batch_size == 2 and health == [None, None]
        from wavetpu.solver import kfused_comp

        solo = kfused_comp.solve_kfused_comp(
            p, k=2, interpret=True, phase=1.0
        )
        assert _bitwise(res.results[1].u_cur, solo.u_cur)

    def test_vmap_probes_surface_in_cache_stats(self):
        eng = ServeEngine(bucket_sizes=(1,), interpret=True)
        p = Problem(N=8, timesteps=3)
        eng.solve(p, [eb.LaneSpec()], scheme="compensated", path="roll")
        probes = eng.cache_stats()["vmap_probes"]
        assert any(
            pr.get("scheme") == "compensated" and pr["path"] == "roll"
            and pr["ok"] for pr in probes
        )
        # every probe row names its backend and carries an ok/reason pair
        for pr in probes:
            assert "backend" in pr and "ok" in pr and "reason" in pr

    def test_sharded_batched_program_cached_per_mesh_bucket(self):
        eng = ServeEngine(bucket_sizes=(1, 2), interpret=True)
        p = Problem(N=8, timesteps=4)
        warmed = eng.warmup(p, path="roll", mesh=(2, 2, 1))
        assert warmed == [1, 2]
        res, health = eng.solve(
            p, [eb.LaneSpec(), eb.LaneSpec(phase=1.0)], path="roll",
            mesh=(2, 2, 1),
        )
        assert res.batched and res.fallback_reason is None
        assert health == [None, None]
        assert eng.hits == 1  # served from the warmed (mesh, bucket=2)
        keys = eng.cache_stats()["keys"]
        assert any(tuple(k[-1] or ()) == (2, 2, 1) for k in keys)
        # parity of one lane vs the solo sharded solve
        from wavetpu.solver import sharded

        solo = sharded.solve_sharded(
            p, mesh_shape=(2, 2, 1), kernel="roll", phase=1.0
        )
        assert _bitwise(res.results[1].u_cur, solo.u_cur)

    def test_watchdog_isolates_poisoned_lane(self):
        # C = 0.55: stable under constant c^2 = a^2, but the two-layer
        # preset DOUBLES c^2 in half the domain (c * sqrt2 -> C = 0.78,
        # past the leapfrog bound) - that lane blows up while its
        # batchmate stays bounded.
        p = Problem(N=8, T=26.0, timesteps=60)
        eng = ServeEngine(bucket_sizes=(1, 2), interpret=True)
        lanes = [
            eb.LaneSpec(c2tau2_field=_c2_preset(p, "constant")),
            eb.LaneSpec(c2tau2_field=_c2_preset(p, "two-layer")),
        ]
        res, health = eng.solve(p, lanes, path="roll")
        assert health[0] is None
        assert health[1] is not None and "amax" in health[1]
        amax0 = float(np.abs(np.asarray(res.results[0].u_cur)).max())
        assert amax0 < 10.0  # the healthy lane is untouched

    def test_guarded_amax_per_lane_semantics(self):
        from wavetpu.run import health

        batch = np.stack([
            np.ones((4, 4, 4)),
            np.full((4, 4, 4), np.nan),
            np.full((4, 4, 4), 7.0),
        ])
        out = health.guarded_amax_per_lane(batch)
        assert out.shape == (3,)
        assert out[0] == 1.0
        assert np.isinf(out[1])  # NaN anywhere -> +inf, as guarded_amax
        assert out[2] == 7.0
        # agrees with the solo guard lane by lane
        for i in range(3):
            assert out[i] == health.guarded_amax(batch[i])

    def test_mesh_with_compensated_scheme_refused_loudly(self):
        # Silently serving a compensated request with the standard
        # scheme would be a wrong-result bug, not a fallback.
        eng = ServeEngine(bucket_sizes=(1,), interpret=True)
        p = Problem(N=8, timesteps=3)
        with pytest.raises(ValueError, match="standard scheme only"):
            eng.solve(
                p, [eb.LaneSpec()], scheme="compensated", path="roll",
                mesh=(2, 1, 1),
            )

    def test_watchdog_can_be_disabled(self):
        p = Problem(N=8, T=26.0, timesteps=60)
        eng = ServeEngine(
            bucket_sizes=(1,), interpret=True, watchdog=False,
        )
        _, health = eng.solve(
            p, [eb.LaneSpec(c2tau2_field=_c2_preset(p, "two-layer"))],
            path="roll",
        )
        assert health == [None]


# ---- scheduler (fake engine: batching logic only) ----

class _FakeEngine:
    """Engine stub recording batch compositions."""

    max_batch = 4

    def __init__(self, fail=False):
        self.batches = []
        self.fail = fail

    def solve(self, problem, lanes, scheme, path, k, dtype_name,
              mesh=None, timing=None):
        if self.fail:
            raise RuntimeError("engine exploded")
        if timing is not None:
            timing["compile_seconds"] = 0.0
            timing["warm"] = "true"
        self.batches.append(len(lanes))
        results = [
            types.SimpleNamespace(steps_computed=problem.timesteps)
            for _ in lanes
        ]
        res = types.SimpleNamespace(
            results=results, n_lanes=len(lanes), batch_size=len(lanes),
            batched=True, fallback_reason=None, path=path,
            solve_seconds=0.01, aggregate_gcells_per_second=1.0,
        )
        return res, [None] * len(lanes)


def _req(problem, **kw):
    return SolveRequest(problem=problem, lane=eb.LaneSpec(**kw))


class TestBatcher:
    def test_concurrent_same_key_requests_coalesce(self):
        eng = _FakeEngine()
        metrics = ServeMetrics()
        b = DynamicBatcher(eng, metrics=metrics, max_wait=0.5)
        p = Problem(N=8, timesteps=3)
        futs = [b.submit(_req(p, phase=1.0 + i)) for i in range(3)]
        out = [f.result(10) for f in futs]
        b.close()
        assert eng.batches == [3]
        assert all(o[2]["occupancy"] == 3 for o in out)
        snap = metrics.snapshot()
        assert snap["batches_total"] == 1
        assert snap["batch_occupancy_max"] == 3

    def test_different_keys_never_share_a_batch(self):
        eng = _FakeEngine()
        b = DynamicBatcher(eng, max_wait=0.3)
        pa = Problem(N=8, timesteps=3)
        pb = Problem(N=8, timesteps=4)
        fa = b.submit(_req(pa))
        fb = b.submit(_req(pb))
        fa.result(10)
        fb.result(10)
        b.close()
        assert sorted(eng.batches) == [1, 1]

    def test_max_batch_closes_the_batch_early(self):
        eng = _FakeEngine()
        b = DynamicBatcher(eng, max_wait=30.0, max_batch=2)
        p = Problem(N=8, timesteps=3)
        t0 = time.monotonic()
        futs = [b.submit(_req(p, phase=1.0 + i)) for i in range(2)]
        for f in futs:
            f.result(10)
        took = time.monotonic() - t0
        b.close()
        assert eng.batches == [2]
        assert took < 5.0  # did not sit out the 30 s max_wait

    def test_engine_failure_propagates_to_every_future(self):
        b = DynamicBatcher(_FakeEngine(fail=True), max_wait=0.2)
        p = Problem(N=8, timesteps=3)
        futs = [b.submit(_req(p, phase=1.0 + i)) for i in range(2)]
        for f in futs:
            with pytest.raises(RuntimeError, match="engine exploded"):
                f.result(10)
        b.close()

    def test_bucket_key_separates_program_identities(self):
        p = Problem(N=8, timesteps=3)
        base = _req(p)
        assert base.bucket_key() == _req(p, phase=2.0).bucket_key()
        other = SolveRequest(problem=p, lane=eb.LaneSpec(), dtype_name="f64")
        assert base.bucket_key() != other.bucket_key()
        kf = SolveRequest(problem=p, lane=eb.LaneSpec(), path="kfused", k=2)
        assert base.bucket_key() != kf.bucket_key()
        meshy = SolveRequest(
            problem=p, lane=eb.LaneSpec(), mesh_shape=(2, 2, 1)
        )
        assert base.bucket_key() != meshy.bucket_key()


class TestLengthBuckets:
    """Length-bucketed scheduling: lanes with diverging stop_steps are
    sorted into step-length buckets (k-block-granular) before batching,
    so a short request never marches a long batch's masked tail."""

    def _kreq(self, p, stop, k=2):
        return SolveRequest(
            problem=p, lane=eb.LaneSpec(stop_step=stop), path="kfused",
            k=k,
        )

    def test_bucket_assignment_and_quantum(self):
        p = Problem(N=8, timesteps=40)
        b = DynamicBatcher(
            _FakeEngine(), max_wait=0.01, length_bucket_steps=10
        )
        try:
            # 1-step path: quantum 10, bucket = (stop-1)//10
            assert b.length_bucket(_req(p)) == 3  # stop=40
            r5 = SolveRequest(problem=p, lane=eb.LaneSpec(stop_step=5))
            r11 = SolveRequest(problem=p, lane=eb.LaneSpec(stop_step=11))
            assert b.length_bucket(r5) == 0
            assert b.length_bucket(r11) == 1
        finally:
            b.close()

    def test_quantum_rounds_up_to_k_block_grid(self):
        # quantum 10 with k=4 aligns to 12: every bucket boundary sits
        # on the onion's k-block grid ((stop-1) % k == 0 freeze points).
        p = Problem(N=8, timesteps=40)
        b = DynamicBatcher(
            _FakeEngine(), max_wait=0.01, length_bucket_steps=10
        )
        try:
            assert b.length_bucket(self._kreq(p, 13, k=4)) == 1  # 12//12
            assert b.length_bucket(self._kreq(p, 12 + 1, k=4)) == 1
            assert b.length_bucket(self._kreq(p, 9, k=4)) == 0
            assert b.length_bucket(self._kreq(p, 25, k=4)) == 2
        finally:
            b.close()

    def test_disabled_by_default_everything_one_bucket(self):
        p = Problem(N=8, timesteps=40)
        b = DynamicBatcher(_FakeEngine(), max_wait=0.01)
        try:
            r5 = SolveRequest(problem=p, lane=eb.LaneSpec(stop_step=5))
            assert b.length_bucket(r5) == 0
            assert b.length_bucket(_req(p)) == 0
        finally:
            b.close()

    def test_different_length_buckets_never_share_a_batch(self):
        eng = _FakeEngine()
        b = DynamicBatcher(eng, max_wait=0.3, length_bucket_steps=10)
        p = Problem(N=8, timesteps=40)
        fs = b.submit(SolveRequest(problem=p, lane=eb.LaneSpec(stop_step=5)))
        fl = b.submit(_req(p, phase=1.0))
        fs2 = b.submit(SolveRequest(problem=p, lane=eb.LaneSpec(stop_step=7)))
        out = [f.result(10) for f in (fs, fl, fs2)]
        b.close()
        # the two short requests coalesce; the long one runs alone
        assert sorted(eng.batches) == [1, 2]
        assert out[1][2]["occupancy"] == 1

    def test_starvation_bound_stashed_request_served_next_round(self):
        # A non-matching stashed request becomes the NEXT batch's leader
        # (arrival order), so it waits at most one batch - the bound the
        # occupancy/latency tradeoff rests on.
        eng = _FakeEngine()
        b = DynamicBatcher(eng, max_wait=0.2, length_bucket_steps=10)
        p = Problem(N=8, timesteps=40)
        f1 = b.submit(SolveRequest(problem=p, lane=eb.LaneSpec(stop_step=5)))
        f2 = b.submit(_req(p, phase=1.0))  # different bucket: stashed
        t0 = time.monotonic()
        f1.result(10)
        f2.result(10)
        took = time.monotonic() - t0
        b.close()
        assert eng.batches == [1, 1]
        assert took < 5.0


class TestDrain:
    def test_drain_resolves_queued_futures_with_results(self):
        eng = _FakeEngine()
        # max_wait far longer than the test: drain must flush
        # immediately, not sit out the window.
        b = DynamicBatcher(eng, max_wait=30.0, max_batch=2)
        p = Problem(N=8, timesteps=3)
        futs = [b.submit(_req(p, phase=1.0 + i)) for i in range(3)]
        t0 = time.monotonic()
        b.close(timeout=60.0, drain=True)
        took = time.monotonic() - t0
        for f in futs:
            res, health, info = f.result(0)  # already resolved
            assert health is None
        assert took < 10.0
        assert sum(eng.batches) == 3

    def test_drain_refuses_new_submits(self):
        b = DynamicBatcher(_FakeEngine(), max_wait=0.01)
        b.close(drain=True)
        p = Problem(N=8, timesteps=3)
        with pytest.raises(RuntimeError, match="closed"):
            b.submit(_req(p))

    def test_drain_timeout_fails_unserved_futures_without_stranding(self):
        # A drain that outlives its timeout must stop draining, and
        # close() must fail whatever the worker could not finish -
        # blocked handlers get an error, never the 600 s request
        # timeout.  The slow engine makes each batch outlast the drain
        # timeout deterministically.
        class _SlowEngine(_FakeEngine):
            def solve(self, *a, **k):
                time.sleep(1.0)
                return super().solve(*a, **k)

        eng = _SlowEngine()
        b = DynamicBatcher(eng, max_wait=30.0, max_batch=1)
        p = Problem(N=8, timesteps=3)
        futs = [b.submit(_req(p, phase=1.0 + i)) for i in range(4)]
        b.close(timeout=0.2, drain=True)
        resolved = errored = 0
        for f in futs:
            try:
                f.result(10)  # in-flight batches may still land
                resolved += 1
            except RuntimeError:
                errored += 1
        assert resolved + errored == 4
        assert errored >= 1  # the tail was failed, not stranded

    def test_drain_vs_submit_race_never_hangs(self):
        """A request submitted CONCURRENTLY with close(drain=True) must
        resolve - with a result or a fast shutdown error - never hang
        to the client timeout.  Hammer the race: a spammer thread
        submits as fast as it can while the main thread drains; every
        future it got back must be done shortly after close returns."""
        eng = _FakeEngine()
        b = DynamicBatcher(eng, max_wait=0.01, max_batch=4)
        p = Problem(N=8, timesteps=3)
        futs = []
        started = threading.Event()

        def spam():
            i = 0
            while True:
                try:
                    futs.append(b.submit(_req(p, phase=1.0 + i)))
                except RuntimeError:
                    return  # batcher closed: the race window is over
                i += 1
                started.set()

        th = threading.Thread(target=spam, daemon=True)
        th.start()
        assert started.wait(5)
        b.close(timeout=30.0, drain=True)
        th.join(10)
        assert not th.is_alive()
        assert futs  # the race actually happened
        deadline = time.monotonic() + 10.0
        resolved = errored = 0
        for f in futs:
            try:
                f.result(max(0.0, deadline - time.monotonic()))
                resolved += 1
            except RuntimeError:
                errored += 1
        # every single future resolved fast - results for what the
        # drain flushed, an immediate error for what raced past it
        assert resolved + errored == len(futs)
        assert resolved >= 1

    def test_close_without_drain_still_errors_stashed_leftovers(self):
        # The non-drain path keeps its contract: the in-flight batch
        # resolves, but a stashed different-key request fails fast
        # instead of hanging to the request timeout.
        eng = _FakeEngine()
        b = DynamicBatcher(eng, max_wait=30.0, max_batch=8)
        pa = Problem(N=8, timesteps=3)
        pb = Problem(N=8, timesteps=4)
        f1 = b.submit(_req(pa))
        f2 = b.submit(SolveRequest(problem=pb, lane=eb.LaneSpec()))
        b.close(timeout=10.0)
        res, health, info = f1.result(10)  # the batch in flight finishes
        assert health is None
        with pytest.raises(RuntimeError, match="shutting down"):
            f2.result(0)


class TestBoundedQueue:
    """Bounded request queue with 429 backpressure (ROADMAP serving-
    hardening item): submit() raises QueueFullError once max_queue
    requests are submitted-but-not-executing; depth and rejections are
    exposed via the registry and /metrics."""

    def test_submit_rejects_when_full(self):
        class _StuckEngine(_FakeEngine):
            def __init__(self):
                super().__init__()
                self.release = threading.Event()

            def solve(self, *a, **k):
                self.release.wait(30)
                return super().solve(*a, **k)

        eng = _StuckEngine()
        metrics = ServeMetrics()
        b = DynamicBatcher(eng, metrics=metrics, max_wait=30.0,
                           max_batch=1, max_queue=2)
        p = Problem(N=8, timesteps=3)
        try:
            # First fills the (max_batch=1) in-flight batch; the worker
            # takes it off the queue, so keep stuffing until depth
            # sticks at the bound, then the next submit must 429.
            futs = [b.submit(_req(p, phase=1.0 + i)) for i in range(2)]
            with pytest.raises(QueueFullError, match="queue full"):
                for i in range(8):
                    futs.append(b.submit(_req(p, phase=10.0 + i)))
            snap = metrics.snapshot()
            assert snap["rejected_total"] >= 1
            assert snap["queue_depth"] >= 1
        finally:
            eng.release.set()
            b.close(timeout=10.0, drain=True)

    def test_zero_max_queue_rejects_everything(self):
        b = DynamicBatcher(_FakeEngine(), max_wait=0.01, max_queue=0)
        p = Problem(N=8, timesteps=3)
        try:
            with pytest.raises(QueueFullError):
                b.submit(_req(p))
        finally:
            b.close()

    def test_unbounded_by_default(self):
        b = DynamicBatcher(_FakeEngine(), max_wait=0.2)
        assert b.max_queue is None
        p = Problem(N=8, timesteps=3)
        futs = [b.submit(_req(p, phase=1.0 + i)) for i in range(16)]
        for f in futs:
            f.result(10)
        b.close()

    def test_depth_returns_to_zero_after_service(self):
        metrics = ServeMetrics()
        b = DynamicBatcher(_FakeEngine(), metrics=metrics, max_wait=0.05)
        p = Problem(N=8, timesteps=3)
        b.submit(_req(p)).result(10)
        b.close()
        assert metrics.snapshot()["queue_depth"] == 0


class TestPreemptible:
    """The preemption drill (docs/robustness.md "Preemptible solves"):
    long solves march CHUNKED through the batcher, interrupted by each
    of {deadline, worker crash, drain} they resume - via resume token
    or in-memory progress - and the final state is BITWISE identical to
    the same solve run unpreempted.  Corrupt tokens 422 cleanly and the
    circuit breaker never hears about any of it."""

    THRESHOLD = 8
    CHUNK = 4

    @pytest.fixture(scope="class")
    def eng(self):
        # one real CPU engine for the whole class: the chunk programs
        # compile once, every test after the first runs warm
        return ServeEngine(bucket_sizes=(1,), interpret=True)

    def _batcher(self, eng, store=None, plan=None, max_wait=0.02):
        return DynamicBatcher(
            eng, max_wait=max_wait, fault_plan=plan,
            chunk_threshold=self.THRESHOLD, chunk_steps=self.CHUNK,
            state_store=store,
        )

    def _long(self, timesteps=17):
        return Problem(N=8, timesteps=timesteps)

    def _control(self, eng, p):
        """The unpreempted chunked march (the drill's parity baseline)."""
        b = self._batcher(eng)
        try:
            return b.submit(_req(p)).result(120)
        finally:
            b.close()

    def test_long_solve_marches_chunked_matching_monolithic(self, eng):
        p = self._long()
        res, health, info = self._control(eng, p)
        assert health is None
        assert info["chunked"] is True
        assert info["chunks"] == 4          # ceil(16 / 4)
        assert info["chunk_len"] == self.CHUNK
        assert info["resumed_from"] is None
        assert info["occupancy"] == 1 and info["batched"] is True
        assert res.final_step == p.timesteps
        # parity with the monolithic (vmapped, batch-of-1) serve path:
        # the chunked march is a latency/preemption trade, never an
        # accuracy one
        mono, mono_health = eng.solve(p, [eb.LaneSpec()], path="roll")
        assert mono_health == [None]
        assert _bitwise(res.u_cur, mono.results[0].u_cur)
        assert _bitwise(res.u_prev, mono.results[0].u_prev)
        assert _bitwise(res.abs_errors, mono.results[0].abs_errors)

    def test_short_requests_stay_on_the_batched_path(self, eng):
        b = self._batcher(eng)
        try:
            res, health, info = b.submit(
                _req(Problem(N=8, timesteps=4))
            ).result(120)
            assert health is None
            assert not info.get("chunked")
        finally:
            b.close()

    def test_deadline_preempts_with_token_resume_is_bitwise(
        self, eng, tmp_path
    ):
        p = self._long()
        control = self._control(eng, p)[0]
        store = SolveStateStore(str(tmp_path / "state"))
        # the per-chunk slow injection stretches the march so the
        # budget expires mid-flight, deterministically
        plan = faults.parse_serve_spec(
            f"serve-slow-batch:seconds=0.25,timesteps={p.timesteps}"
        )
        b = self._batcher(eng, store=store, plan=plan)
        try:
            fut = b.submit(_req(p), deadline=time.monotonic() + 0.4)
            with pytest.raises(DeadlineExceededError) as ei:
                fut.result(120)
            token = ei.value.resume_token
            assert SolveStateStore.valid_token(token)
            snap = b.metrics.snapshot()
            assert snap["preempted_total"] == 1
        finally:
            b.close()
        # resume on a FRESH batcher (same store), no budget this time
        b2 = self._batcher(eng, store=store)
        try:
            req = SolveRequest(
                problem=p, lane=eb.LaneSpec(), resume_token=token
            )
            res, health, info = b2.submit(req).result(120)
            assert health is None
            assert info["resumed_from"] >= 1
            assert b2.metrics.snapshot()["resumed_total"] == 1
        finally:
            b2.close()
        assert _bitwise(res.u_cur, control.u_cur)
        assert _bitwise(res.u_prev, control.u_prev)
        assert _bitwise(res.abs_errors, control.abs_errors)

    def test_worker_crash_resumes_march_zero_client_errors(self, eng):
        p = self._long()
        control = self._control(eng, p)[0]
        plan = faults.parse_serve_spec(
            f"serve-chunk-crash:timesteps={p.timesteps},count=1"
        )
        b = self._batcher(eng, plan=plan)
        try:
            # the crash escapes the worker mid-march; the supervisor
            # restarts it and the item resumes from its in-memory
            # progress - the CLIENT never sees an error
            res, health, info = b.submit(_req(p)).result(120)
            assert health is None
            assert res.final_step == p.timesteps
            snap = b.metrics.snapshot()
            assert snap["worker_restarts_total"] == 1
            assert snap["resumed_total"] == 1
        finally:
            b.close()
        assert _bitwise(res.u_cur, control.u_cur)
        assert _bitwise(res.abs_errors, control.abs_errors)

    def test_drain_checkpoints_and_successor_resumes_bitwise(
        self, eng, tmp_path
    ):
        p = self._long()
        control = self._control(eng, p)[0]
        state_dir = str(tmp_path / "state")
        store = SolveStateStore(state_dir)
        plan = faults.parse_serve_spec(
            f"serve-slow-batch:seconds=0.4,timesteps={p.timesteps}"
        )
        b = self._batcher(eng, store=store, plan=plan)
        fut = b.submit(_req(p))
        # wait until the march is genuinely in flight, then drain
        deadline = time.monotonic() + 60.0
        while (b.metrics.snapshot()["chunks_total"] == 0
               and time.monotonic() < deadline):
            time.sleep(0.005)
        assert b.metrics.snapshot()["chunks_total"] >= 1
        b.close(timeout=60.0, drain=True)
        with pytest.raises(PreemptedError) as ei:
            fut.result(0)
        token = ei.value.resume_token
        assert SolveStateStore.valid_token(token)
        # the "successor replica": a DIFFERENT engine sharing only the
        # state dir (the cross-replica handoff surface)
        eng2 = ServeEngine(bucket_sizes=(1,), interpret=True)
        b2 = self._batcher(eng2, store=SolveStateStore(state_dir))
        try:
            req = SolveRequest(
                problem=p, lane=eb.LaneSpec(), resume_token=token
            )
            res, health, info = b2.submit(req).result(120)
            assert health is None
            assert info["resumed_from"] >= 1
        finally:
            b2.close()
        assert _bitwise(res.u_cur, control.u_cur)
        assert _bitwise(res.u_prev, control.u_prev)
        assert _bitwise(res.abs_errors, control.abs_errors)

    def test_corrupt_token_422s_cleanly_breaker_never_hears(
        self, eng, tmp_path
    ):
        p = self._long()
        store = SolveStateStore(str(tmp_path / "state"))
        # mint a genuine token, then corrupt its bytes on disk
        # (serve-handoff-corrupt truncates it at load time)
        plan = faults.parse_serve_spec(
            f"serve-slow-batch:seconds=0.25,timesteps={p.timesteps}"
        )
        b = self._batcher(eng, store=store, plan=plan)
        try:
            fut = b.submit(_req(p), deadline=time.monotonic() + 0.4)
            with pytest.raises(DeadlineExceededError) as ei:
                fut.result(120)
            token = ei.value.resume_token
        finally:
            b.close()
        corrupt = faults.parse_serve_spec("serve-handoff-corrupt:count=1")
        b2 = self._batcher(eng, store=store, plan=corrupt)
        try:
            req = SolveRequest(
                problem=p, lane=eb.LaneSpec(), resume_token=token
            )
            with pytest.raises(InvalidStateTokenError,
                               match="content verification"):
                b2.submit(req).result(120)
            # an unknown (never-minted) token is the same clean 422
            req2 = SolveRequest(
                problem=p, lane=eb.LaneSpec(), resume_token="0" * 64
            )
            with pytest.raises(InvalidStateTokenError, match="not found"):
                b2.submit(req2).result(120)
        finally:
            b2.close()
        # neither rejection fed the engine's circuit breaker
        assert eng.breaker_stats()["open"] == 0

    def test_token_identity_mismatch_is_rejected(self, eng, tmp_path):
        store = SolveStateStore(str(tmp_path / "state"))
        p = self._long()
        plan = faults.parse_serve_spec(
            f"serve-slow-batch:seconds=0.25,timesteps={p.timesteps}"
        )
        b = self._batcher(eng, store=store, plan=plan)
        try:
            fut = b.submit(_req(p), deadline=time.monotonic() + 0.4)
            with pytest.raises(DeadlineExceededError) as ei:
                fut.result(120)
            token = ei.value.resume_token
        finally:
            b.close()
        # replaying the token against a DIFFERENT solve is a clean 422
        other = Problem(N=8, timesteps=13)
        b2 = self._batcher(eng, store=store)
        try:
            req = SolveRequest(
                problem=other, lane=eb.LaneSpec(), resume_token=token
            )
            with pytest.raises(InvalidStateTokenError,
                               match="does not match"):
                b2.submit(req).result(120)
        finally:
            b2.close()


class TestMetricsRegistryIntegration:
    """ServeMetrics writes through the registry: the JSON snapshot keeps
    its historical fields while the same cut renders as Prometheus text,
    and snapshot() holds ONE lock across everything it reads."""

    def test_snapshot_fields_preserved_and_extended(self):
        m = ServeMetrics()
        m.observe_request()
        m.observe_response(True)
        m.observe_batch(occupancy=3, batched=True, cells=1e9,
                        solve_seconds=0.5, batch_size=4)
        m.observe_latency(0.1)
        snap = m.snapshot()
        # historical fields, exact names and derivations
        assert snap["requests_total"] == 1
        assert snap["responses_ok"] == 1
        assert snap["responses_error"] == 0
        assert snap["batches_total"] == 1
        assert snap["batch_occupancy_mean"] == 3.0
        assert snap["batch_occupancy_max"] == 3
        assert snap["fallback_batches"] == 0
        assert snap["latency_p50_ms"] == 100.0
        assert snap["aggregate_gcells_per_s"] == 2.0
        # new observability fields
        assert snap["queue_depth"] == 0
        assert snap["rejected_total"] == 0
        assert snap["padding_lanes_total"] == 1
        assert snap["last_batch_age_seconds"] is not None

    def test_last_batch_age_none_only_before_any_batch(self):
        """The /healthz discriminator: age is None IFF no batch was
        ever executed.  Keyed on the batches counter, not the timestamp
        gauge, so a gauge sitting at its 0.0 default ("idle since t=0")
        can never read as "never executed"."""
        m = ServeMetrics()
        assert m.last_batch_age() is None
        m.observe_batch(occupancy=1, batched=True, cells=1.0,
                        solve_seconds=0.1)
        assert m.last_batch_age() is not None
        # even a zero timestamp is "has executed", not "never"
        m._last_batch_ts.set(0.0)
        assert m.last_batch_age() is not None

    def test_json_and_text_views_agree(self):
        m = ServeMetrics()
        for _ in range(3):
            m.observe_request()
        m.observe_response(True)
        m.observe_response(False)
        m.observe_batch(occupancy=2, batched=False, cells=2e9,
                        solve_seconds=1.0, batch_size=2)
        m.observe_latency(0.2)
        snap = m.snapshot()
        samples, types = parse_prometheus(m.registry.render_prometheus())
        assert types["wavetpu_serve_requests_total"] == "counter"
        assert samples["wavetpu_serve_requests_total"] == \
            snap["requests_total"] == 3
        assert samples['wavetpu_serve_responses_total{status="ok"}'] == \
            snap["responses_ok"] == 1
        assert samples['wavetpu_serve_responses_total{status="error"}'] \
            == snap["responses_error"] == 1
        assert samples["wavetpu_serve_batches_total"] == \
            snap["batches_total"] == 1
        assert samples["wavetpu_serve_fallback_batches_total"] == \
            snap["fallback_batches"] == 1
        # histogram triplet for the latency distribution
        assert samples["wavetpu_serve_request_seconds_count"] == 1
        assert samples["wavetpu_serve_request_seconds_sum"] == \
            pytest.approx(0.2)
        assert samples['wavetpu_serve_request_seconds_bucket{le="+Inf"}'] \
            == 1


# ---- request parsing ----

class TestParse:
    def test_minimal_request(self):
        req = parse_solve_request({"N": 8}, default_kernel="roll")
        assert req.problem.N == 8
        assert req.path == "roll"
        assert req.k == 1

    def test_fuse_steps_selects_kfused(self):
        req = parse_solve_request(
            {"N": 8, "fuse_steps": 2, "kernel": "pallas"},
            default_kernel="roll",
        )
        assert req.path == "kfused" and req.k == 2

    def test_fuse_steps_rejects_roll(self):
        with pytest.raises(ValueError, match="pallas"):
            parse_solve_request(
                {"N": 8, "fuse_steps": 2, "kernel": "roll"},
                default_kernel="roll",
            )

    def test_pi_lengths_and_preset_fields(self):
        req = parse_solve_request(
            {"N": 8, "Lx": "pi", "c2_field": "gaussian-lens"},
            default_kernel="roll",
        )
        assert req.problem.Lx == pytest.approx(np.pi)
        assert req.lane.c2tau2_field is not None

    def test_bad_fields_rejected(self):
        for body, msg in [
            ({}, "missing required field N"),
            ({"N": 8, "scheme": "x"}, "scheme"),
            ({"N": 8, "dtype": "f16"}, "dtype"),
            ({"N": 8, "c2_field": "nope"}, "c2_field"),
            ({"N": 8, "steps": 99}, "stop_step"),
            ({"N": 8, "scheme": "compensated", "c2_field": "constant"},
             "c2_field"),
            ({"N": 8, "phase": 1.0, "c2_field": "constant"},
             "analytic layer-1"),
            ({"N": 8, "mesh": [2, 2]}, "mesh"),
            ({"N": 8, "mesh": [99, 99, 99]}, "devices"),
            ({"N": 8, "mesh": [2, 1, 1], "scheme": "compensated"},
             "standard scheme"),
            ({"N": 8, "mesh": [2, 1, 1], "fuse_steps": 2,
              "kernel": "pallas"}, "fuse_steps"),
            ({"N": 8, "mesh": [2, 1, 1], "c2_field": "constant"},
             "c2_field"),
        ]:
            with pytest.raises(ValueError, match=msg):
                parse_solve_request(body, default_kernel="roll")

    def test_compensated_bf16_rejected_at_parse(self):
        with pytest.raises(ValueError, match="f32/f64"):
            parse_solve_request(
                {"N": 8, "scheme": "compensated", "dtype": "bf16"},
                default_kernel="roll",
            )

    def test_compensated_shifted_phase_now_parses(self):
        # The vmapped compensated core serves shifted phases; the old
        # parse-time refusal is gone.
        req = parse_solve_request(
            {"N": 8, "scheme": "compensated", "phase": 1.0},
            default_kernel="roll",
        )
        assert req.scheme == "compensated"
        assert req.lane.phase == 1.0

    def test_mesh_request_parses(self):
        req = parse_solve_request(
            {"N": 8, "mesh": [2, 2, 1], "phase": 1.0},
            default_kernel="roll",
        )
        assert req.mesh_shape == (2, 2, 1)
        assert req.path == "roll"


# ---- HTTP end to end ----

@pytest.fixture()
def server():
    httpd, state = build_server(
        port=0, max_wait=0.5, default_kernel="roll", interpret=True
    )
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    yield base, state
    httpd.shutdown()
    state.batcher.close()
    httpd.server_close()


def _post(base, body, timeout=120):
    code, payload, _headers = _post_full(base, body, timeout=timeout)
    return code, payload


def _post_full(base, body, timeout=120, headers=None):
    req = urllib.request.Request(
        base + "/solve", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=30) as r:
        return r.status, json.loads(r.read())


class TestHTTP:
    def test_concurrent_requests_coalesce_with_own_reports(self, server):
        base, state = server
        results = [None] * 4
        phases = [6.283, 1.0, 0.5, 0.25]

        def worker(i):
            results[i] = _post(
                base, {"N": 8, "timesteps": 4, "phase": phases[i]}
            )

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        errs = set()
        for code, body in results:
            assert code == 200
            assert body["status"] == "ok"
            assert body["batch"]["occupancy"] > 1
            assert body["report"]["final_step"] == 4
            assert len(body["report"]["abs_errors"]) == 5
            assert "grids initialized in" in body["report_text"]
            errs.add(body["report"]["max_abs_error"])
        # four distinct phases -> four distinct per-request reports
        assert len(errs) == 4
        code, metrics = _get(base, "/metrics")
        assert code == 200
        assert metrics["batch_occupancy_max"] > 1
        assert metrics["requests_total"] == 4
        assert metrics["responses_ok"] == 4
        assert metrics["aggregate_gcells_per_s"] is not None
        assert metrics["latency_p50_ms"] is not None
        assert metrics["program_cache"]["programs"] >= 1

    def test_healthz(self, server):
        base, _ = server
        code, body = _get(base, "/healthz")
        assert code == 200
        assert body["status"] == "ok"

    def test_healthz_memory_fields(self, server):
        """Device-memory visibility: both fields present and unit-pinned
        in the name (`_bytes`); None exactly when the backend has no
        memory_stats() (the CPU backend CI runs on), else non-negative
        ints."""
        base, _ = server
        code, body = _get(base, "/healthz")
        assert code == 200
        assert "memory_bytes_in_use" in body
        assert "memory_peak_bytes" in body
        for field in ("memory_bytes_in_use", "memory_peak_bytes"):
            v = body[field]
            assert v is None or (isinstance(v, int) and v >= 0)
        # Both sides of the contract agree: None iff the probe says
        # unsupported.
        from wavetpu.obs import perf

        snap = perf.memory_snapshot()
        assert (body["memory_bytes_in_use"] is None) == (snap is None)

    def test_healthz_liveness_vs_readiness(self, server):
        """The readiness split: `status: ok` = the process serves HTTP;
        `ready` = route traffic here - false while the warmup compile
        runs or once draining is set, so a load balancer pulls the
        replica BEFORE drain starts failing requests.  The loadgen
        preflight refuses a not-ready target the same way."""
        from wavetpu.loadgen import runner as lg_runner

        base, state = server
        code, body = _get(base, "/healthz")
        assert code == 200
        assert body["ready"] is True and body["warming"] is False
        state.warming = True
        try:
            code, body = _get(base, "/healthz")
            assert body["status"] == "ok"  # alive...
            assert body["ready"] is False  # ...but do not route yet
            with pytest.raises(lg_runner.PreflightError,
                               match="not ready"):
                lg_runner.preflight(base)
        finally:
            state.warming = False
        state.draining = True
        try:
            code, body = _get(base, "/healthz")
            assert body["ready"] is False and body["draining"] is True
        finally:
            state.draining = False
        assert _get(base, "/healthz")[1]["ready"] is True

    def test_429_and_503_carry_retry_after(self):
        httpd, state = build_server(
            port=0, max_wait=0.1, default_kernel="roll",
            interpret=True, max_queue=0,
        )
        thread = threading.Thread(target=httpd.serve_forever, daemon=True)
        thread.start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            code, body, headers = _post_full(
                base, {"N": 8, "timesteps": 4}
            )
            assert code == 429
            assert headers.get("Retry-After") is not None
            assert body["retriable"] is True
            state.draining = True
            code, body, headers = _post_full(
                base, {"N": 8, "timesteps": 4}
            )
            assert code == 503
            assert headers.get("Retry-After") is not None
            assert body["retriable"] is True
        finally:
            state.draining = False
            httpd.shutdown()
            state.batcher.close()
            httpd.server_close()

    def test_metrics_json_carries_breaker_block(self, server):
        base, _ = server
        code, snap = _get(base, "/metrics")
        assert code == 200
        assert snap["breaker"]["enabled"] is True
        assert snap["breaker"]["open"] == 0

    def test_healthz_idle_vs_wedged_fields(self, server):
        # The load-balancer discriminator fields: uptime, draining, and
        # last-batch age (null while idle, a number after traffic).
        base, state = server
        code, body = _get(base, "/healthz")
        assert code == 200
        assert body["uptime_seconds"] >= 0
        assert body["draining"] is False
        assert body["last_batch_age_seconds"] is None
        _post(base, {"N": 8, "timesteps": 4})
        code, body = _get(base, "/healthz")
        assert body["last_batch_age_seconds"] is not None
        assert body["last_batch_age_seconds"] >= 0
        state.draining = True
        try:
            code, body = _get(base, "/healthz")
            assert body["draining"] is True
        finally:
            state.draining = False

    def test_metrics_prometheus_text_negotiated(self, server):
        base, state = server
        _post(base, {"N": 8, "timesteps": 4})
        req = urllib.request.Request(
            base + "/metrics", headers={"Accept": "text/plain"}
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/plain")
            text = r.read().decode()
        samples, types = parse_prometheus(text)
        assert samples["wavetpu_serve_requests_total"] >= 1
        assert types["wavetpu_serve_request_seconds"] == "histogram"
        assert samples["wavetpu_serve_request_seconds_count"] >= 1
        # engine metrics share the server registry (build_server wiring)
        assert samples['wavetpu_program_cache_events_total{event="miss"}'] \
            >= 1
        # the same cut agrees with the JSON view
        code, snap = _get(base, "/metrics")
        assert code == 200
        assert snap["requests_total"] == \
            samples["wavetpu_serve_requests_total"]
        # default Accept still gets the historical JSON shape
        assert "program_cache" in snap

    def test_request_and_batch_spans_join_on_request_id(
        self, server, tmp_path
    ):
        from wavetpu.obs import report as obs_report
        from wavetpu.obs import tracing

        base, _ = server
        path = str(tmp_path / "trace.jsonl")
        tracing.configure(path)
        try:
            code, body = _post(base, {"N": 8, "timesteps": 4})
            assert code == 200
        finally:
            tracing.disable()
        recs = [json.loads(line) for line in open(path)]
        reqs = [r for r in recs if r["kind"] == "serve.request"]
        batches = [r for r in recs if r["kind"] == "serve.batch"]
        assert len(reqs) == 1 and len(batches) == 1
        rid = reqs[0]["attrs"]["request_id"]
        assert rid in batches[0]["attrs"]["request_ids"]
        assert reqs[0]["attrs"]["status"] == 200
        assert batches[0]["attrs"]["padding_lanes"] == 0
        # execute (and on first contact compile) spans nest under batch
        execs = [r for r in recs if r["kind"] == "serve.execute"]
        assert execs and execs[0]["parent_id"] == batches[0]["span_id"]
        # trace-report stitches the critical path from the id
        view = obs_report.request_view(recs, rid)
        kinds = {r["kind"] for r in view}
        assert {"serve.request", "serve.batch", "serve.execute"} <= kinds

    def test_server_timing_components_sum_to_total(self, server):
        """Acceptance: every /solve response carries Server-Timing whose
        additive components (queue + compile + execute) sum to within
        10% of the server-measured wall (`total`), and the per-request
        timing rides the JSON batch context too."""
        from wavetpu.loadgen.runner import parse_server_timing

        base, _ = server
        for i in range(2):  # first contact (cold compile) AND warm
            t0 = time.monotonic()
            code, body, headers = _post_full(
                base, {"N": 8, "timesteps": 4, "phase": 1.0 + i}
            )
            client_wall = time.monotonic() - t0
            assert code == 200
            timing = parse_server_timing(headers.get("Server-Timing"))
            assert set(timing) == {
                "queue", "compile", "execute", "padding", "total"
            }
            additive = timing["queue"] + timing["compile"] + \
                timing["execute"]
            # components ~= the server-measured wall (parse/serialize
            # overhead is the slack; 10% + a tiny absolute epsilon for
            # the CI-scale solves where total is single-digit ms)
            assert abs(additive - timing["total"]) <= \
                0.1 * timing["total"] + 0.010
            # server total never exceeds what the client measured
            assert timing["total"] <= client_wall + 0.010
            # padding is a subset-of-execute attribution
            assert timing["padding"] <= timing["execute"] + 1e-9
            # and the same attribution is in the JSON batch context
            jt = body["batch"]["timing"]
            assert jt["compile_s"] == pytest.approx(
                timing["compile"], abs=1e-4
            )
        # the cold/warm split is visible: first request compiled,
        # second hit the cache
        assert body["batch"]["warm"] == "true"

    def test_request_id_echoed_and_client_id_wins(self, server):
        base, _ = server
        # client-minted id is echoed verbatim
        code, _body, headers = _post_full(
            base, {"N": 8, "timesteps": 4},
            headers={"X-Request-Id": "lg-abc-7"},
        )
        assert code == 200
        assert headers.get("X-Request-Id") == "lg-abc-7"
        # junk ids (bad chars / over-long) are dropped, not reflected
        junk = 'evil"id with spaces' + "x" * 80
        code, _body, headers = _post_full(
            base, {"N": 8, "timesteps": 4},
            headers={"X-Request-Id": junk},
        )
        assert code == 200
        assert headers.get("X-Request-Id") != junk

    def test_client_request_id_tags_server_spans(self, server, tmp_path):
        """The loadgen join contract: a client-supplied X-Request-Id is
        THE request_id on the server's trace spans, so a report outlier
        resolves via `wavetpu trace-report --request ID`."""
        from wavetpu.obs import report as obs_report
        from wavetpu.obs import tracing

        base, _ = server
        path = str(tmp_path / "trace.jsonl")
        tracing.configure(path)
        try:
            code, _, headers = _post_full(
                base, {"N": 8, "timesteps": 4},
                headers={"X-Request-Id": "lg-join-1"},
            )
            assert code == 200
        finally:
            tracing.disable()
        recs = [json.loads(line) for line in open(path)]
        view = obs_report.request_view(recs, "lg-join-1")
        kinds = {r["kind"] for r in view}
        assert {"serve.request", "serve.batch", "serve.execute"} <= kinds

    def test_metrics_openmetrics_exemplars_negotiated(self, server):
        base, _ = server
        _post_full(base, {"N": 8, "timesteps": 4},
                   headers={"X-Request-Id": "lg-ex-1"})
        req = urllib.request.Request(
            base + "/metrics",
            headers={"Accept": "application/openmetrics-text"},
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.status == 200
            assert r.headers["Content-Type"].startswith(
                "application/openmetrics-text"
            )
            text = r.read().decode()
        samples, _types, exemplars = parse_prometheus(
            text, with_exemplars=True
        )
        assert text.rstrip().endswith("# EOF")
        # the latency histogram carries the request id as an exemplar
        latency_ex = [
            ex for name, ex in exemplars.items()
            if name.startswith("wavetpu_serve_request_seconds_bucket")
        ]
        assert any(
            ex["labels"].get("request_id") == "lg-ex-1"
            for ex in latency_ex
        )
        # plain text/plain stays exemplar-free (0.0.4 parsers)
        req = urllib.request.Request(
            base + "/metrics", headers={"Accept": "text/plain"}
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            plain = r.read().decode()
        assert " # " not in plain and "# EOF" not in plain

    def test_malformed_content_length_gets_400(self, server):
        """A junk Content-Length header must produce a 400 JSON error,
        not an unhandled handler exception (dropped connection)."""
        import socket

        base, _ = server
        host, port = base.replace("http://", "").split(":")
        with socket.create_connection((host, int(port)), timeout=30) as s:
            s.sendall(
                b"POST /solve HTTP/1.1\r\nHost: x\r\n"
                b"Content-Length: abc\r\n\r\n"
            )
            data = s.recv(65536)
        status_line = data.split(b"\r\n", 1)[0]
        assert b" 400 " in status_line + b" "
        assert b"Content-Length" in data
        # A NEGATIVE length must 400 too - rfile.read(-1) would block
        # to EOF and pin the handler thread forever (thread-exhaustion
        # DoS), so it is the same malformed-header case.
        with socket.create_connection((host, int(port)), timeout=30) as s:
            s.sendall(
                b"POST /solve HTTP/1.1\r\nHost: x\r\n"
                b"Content-Length: -1\r\n\r\n"
            )
            data = s.recv(65536)
        assert b" 400 " in data.split(b"\r\n", 1)[0] + b" "

    def test_max_body_bytes_413(self):
        httpd, state = build_server(
            port=0, max_wait=0.1, default_kernel="roll",
            interpret=True, max_body_bytes=64,
        )
        thread = threading.Thread(target=httpd.serve_forever, daemon=True)
        thread.start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            big = {"N": 8, "timesteps": 4, "pad": "x" * 500}
            code, body, _ = _post_full(base, big)
            assert code == 413
            assert "max-body-bytes" in body["error"]
            code, snap = _get(base, "/metrics")
            assert snap["limit_rejected_total"] == 1
            # and in the Prometheus view, labeled by limit
            samples, _ = parse_prometheus(
                state.metrics.registry.render_prometheus()
            )
            assert samples[
                'wavetpu_serve_limit_rejected_total{limit="body_bytes"}'
            ] == 1
            # a small request still serves
            code, _, _ = _post_full(base, {"N": 8, "timesteps": 4})
            assert code == 200
        finally:
            httpd.shutdown()
            state.batcher.close()
            httpd.server_close()

    def test_max_lane_cells_422_before_scheduling(self):
        httpd, state = build_server(
            port=0, max_wait=0.1, default_kernel="roll",
            interpret=True, max_lane_cells=1000,  # (N+1)^3 <= 1000
        )
        thread = threading.Thread(target=httpd.serve_forever, daemon=True)
        thread.start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            code, body, _ = _post_full(base, {"N": 16, "timesteps": 4})
            assert code == 422
            assert "max-lane-cells" in body["error"]
            code, snap = _get(base, "/metrics")
            assert snap["limit_rejected_total"] == 1
            # nothing reached the scheduler
            assert snap["batches_total"] == 0
            code, _, _ = _post_full(base, {"N": 8, "timesteps": 4})
            assert code == 200  # 9^3 = 729 <= 1000
        finally:
            httpd.shutdown()
            state.batcher.close()
            httpd.server_close()

    def test_queue_full_returns_429(self):
        httpd, state = build_server(
            port=0, max_wait=0.1, default_kernel="roll",
            interpret=True, max_queue=0,
        )
        thread = threading.Thread(target=httpd.serve_forever, daemon=True)
        thread.start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            code, body = _post(base, {"N": 8, "timesteps": 4})
            assert code == 429
            assert "queue full" in body["error"]
            code, snap = _get(base, "/metrics")
            assert snap["rejected_total"] == 1
            assert snap["responses_error"] == 1
        finally:
            httpd.shutdown()
            state.batcher.close()
            httpd.server_close()

    def test_draining_returns_503(self, server):
        base, state = server
        state.draining = True
        try:
            code, body = _post(base, {"N": 8, "timesteps": 4})
            assert code == 503
            assert "draining" in body["error"]
        finally:
            state.draining = False

    def test_metrics_exposes_vmap_probes(self, server):
        base, _ = server
        _post(base, {"N": 8, "timesteps": 4})
        code, metrics = _get(base, "/metrics")
        assert code == 200
        probes = metrics["program_cache"]["vmap_probes"]
        assert any(p.get("path") == "roll" and p["ok"] for p in probes)

    def test_mesh_request_serves_sharded_batched(self, server):
        base, _ = server
        code, body = _post(
            base, {"N": 8, "timesteps": 4, "mesh": [2, 2, 1],
                   "phase": 1.0}, timeout=300,
        )
        assert code == 200
        assert body["batch"]["batched"] is True
        assert "sharded(2, 2, 1)" in body["batch"]["path"]
        assert body["report"]["final_step"] == 4

    def test_bad_request_400(self, server):
        base, _ = server
        code, body = _post(base, {"timesteps": 4})
        assert code == 400
        assert "N" in body["error"]

    def test_unknown_route_404(self, server):
        base, _ = server
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/nope", timeout=30)
        assert ei.value.code == 404

    def test_watchdog_poisoned_request_422_batchmate_ok(self, server):
        base, _ = server
        results = [None] * 2
        bodies = [
            {"N": 8, "T": 26.0, "timesteps": 60, "c2_field": "constant"},
            {"N": 8, "T": 26.0, "timesteps": 60, "c2_field": "two-layer"},
        ]

        def worker(i):
            results[i] = _post(base, bodies[i], timeout=300)

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        codes = sorted(r[0] for r in results)
        assert codes == [200, 422]
        bad = next(b for c, b in results if c == 422)
        assert "amax" in bad["error"]
        ok = next(b for c, b in results if c == 200)
        # a field request serves without the analytic oracle
        assert ok["report"]["errors_computed"] is False
        assert ok["report"]["max_abs_error"] is None


# ---- CLI entry points ----

class TestPreemptibleHTTP:
    """The HTTP face of the preemption drill: 504-with-token, token
    resume with full error-history parity, token hygiene (400/422),
    and the tenant label riding serve-side metrics."""

    def _server(self, tmp_path, **kw):
        kw.setdefault("max_wait", 0.05)
        kw.setdefault("default_kernel", "roll")
        kw.setdefault("interpret", True)
        kw.setdefault("chunk_threshold", 64)
        kw.setdefault("chunk_steps", 1)
        kw.setdefault("solve_state_dir", str(tmp_path / "state"))
        httpd, state = build_server(port=0, **kw)
        threading.Thread(
            target=httpd.serve_forever, daemon=True
        ).start()
        return httpd, state, f"http://127.0.0.1:{httpd.server_address[1]}"

    def test_deadline_504_with_token_then_resume_matches(
        self, tmp_path
    ):
        httpd, state, base = self._server(tmp_path)
        body = {"N": 8, "timesteps": 193}
        try:
            # control march (also warms every chunk program, so the
            # deadline below expires mid-MARCH, not mid-compile)
            code, control = _post(base, body)
            assert code == 200
            assert control["batch"]["chunked"] is True
            # a budget far smaller than the march: 504 whose body
            # carries the resumable state token
            code, payload = _post(base, dict(body, deadline_ms=20))
            assert code == 504, payload
            token = payload.get("resume_token")
            assert SolveStateStore.valid_token(token), payload
            # resubmit with the token, no budget: the march finishes
            # and the FULL per-layer error history matches the
            # uninterrupted control exactly
            code, resumed = _post(base, dict(body, resume_token=token))
            assert code == 200, resumed
            assert resumed["report"]["final_step"] == 193
            assert resumed["batch"]["resumed_from"] >= 1
            assert (resumed["report"]["abs_errors"]
                    == control["report"]["abs_errors"])
            assert (resumed["report"]["rel_errors"]
                    == control["report"]["rel_errors"])
            _, metrics = _get(base, "/metrics")
            assert metrics["chunks_total"] > 0
            assert metrics["preempted_total"] >= 1
            assert metrics["resumed_total"] >= 1
        finally:
            httpd.shutdown()
            state.batcher.close()
            httpd.server_close()

    def test_token_hygiene_400_and_422(self, tmp_path):
        httpd, state, base = self._server(tmp_path)
        body = {"N": 8, "timesteps": 193}
        try:
            # not even token-shaped: rejected at parse (400)
            code, payload = _post(base, dict(body, resume_token="zz"))
            assert code == 400
            # well-formed but never minted: clean 422, never retriable
            code, payload = _post(
                base, dict(body, resume_token="0" * 64)
            )
            assert code == 422
            assert "not found" in payload["error"]
        finally:
            httpd.shutdown()
            state.batcher.close()
            httpd.server_close()

    def test_tenant_header_lands_in_metrics(self, tmp_path):
        httpd, state, base = self._server(tmp_path)
        try:
            code, _, _ = _post_full(
                base, {"N": 8, "timesteps": 3},
                headers={"X-Wavetpu-Tenant": "acme"},
            )
            assert code == 200
            req = urllib.request.Request(
                base + "/metrics", headers={"Accept": "text/plain"}
            )
            with urllib.request.urlopen(req, timeout=30) as r:
                text = r.read().decode()
            samples, _types = parse_prometheus(text)
            assert samples[
                'wavetpu_serve_tenant_requests_total{tenant="acme"}'
            ] == 1.0
        finally:
            httpd.shutdown()
            state.batcher.close()
            httpd.server_close()


class TestDistributedTracingServe:
    """The replica's half of the fleet trace contract
    (docs/observability.md "Distributed tracing"): traceparent echoed
    on every /solve answer, inbound context adopted as the remote
    parent of serve.request, the in-flight chunk-march gauge, and the
    originating trace context riding the resume checkpoint so a
    preempted march resumed under a NEW trace links back to its first
    request."""

    @staticmethod
    def _lower(headers):
        return {k.lower(): v for k, v in headers.items()}

    def test_untraced_replica_reflects_inbound_verbatim(self, server):
        base, _state = server
        tp = "00-" + "ab" * 16 + "-" + "12" * 8 + "-01"
        code, _body, hdrs = _post_full(
            base, {"N": 8, "timesteps": 3}, headers={"traceparent": tp}
        )
        assert code == 200
        # untraced tier: the join handle still answers - the inbound
        # header comes back untouched
        assert self._lower(hdrs).get("traceparent") == tp

    def test_untraced_replica_without_inbound_sends_no_header(
        self, server
    ):
        base, _state = server
        code, _body, hdrs = _post_full(base, {"N": 8, "timesteps": 3})
        assert code == 200
        assert "traceparent" not in self._lower(hdrs)

    def test_untraced_replica_drops_malformed_inbound(self, server):
        base, _state = server
        code, _body, hdrs = _post_full(
            base, {"N": 8, "timesteps": 3},
            headers={"traceparent": "00-nothex-11-01"},
        )
        assert code == 200
        assert "traceparent" not in self._lower(hdrs)

    def test_traced_replica_adopts_inbound_and_echoes_own_context(
        self, tmp_path
    ):
        from wavetpu.obs import tracing
        trace_path = str(tmp_path / "trace.jsonl")
        tracing.configure(trace_path)
        httpd, state = build_server(
            port=0, max_wait=0.05, default_kernel="roll", interpret=True
        )
        threading.Thread(
            target=httpd.serve_forever, daemon=True
        ).start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        tid, wire = "ab" * 16, "12" * 8
        try:
            code, _body, hdrs = _post_full(
                base, {"N": 8, "timesteps": 3},
                headers={"traceparent": f"00-{tid}-{wire}-01"},
            )
            assert code == 200
            echoed = tracing.parse_traceparent(
                self._lower(hdrs)["traceparent"]
            )
            # traced tier overwrites the echo with its OWN context:
            # same fleet trace id, fresh wire span id
            assert echoed is not None
            assert echoed[0] == tid
            assert echoed[1] != wire
            # no inbound context: a fresh trace id is minted
            code, _body, hdrs2 = _post_full(
                base, {"N": 8, "timesteps": 3}
            )
            assert code == 200
            fresh = tracing.parse_traceparent(
                self._lower(hdrs2)["traceparent"]
            )
            assert fresh is not None and fresh[0] != tid
        finally:
            httpd.shutdown()
            state.batcher.close()
            httpd.server_close()
            tracing.disable()
        recs = [json.loads(l) for l in open(trace_path)]
        adopted = [
            r for r in recs
            if r.get("kind") == "serve.request"
            and r.get("trace_id") == tid
        ]
        assert len(adopted) == 1
        # the inbound wire id IS the remote parent, and the span
        # advertises the echoed wire id for the cross-process joiner
        assert adopted[0]["parent_id"] == wire
        assert adopted[0]["attrs"]["w3c_id"] == echoed[1]

    def test_inflight_gauge_and_origin_trace_ride_checkpoint(
        self, tmp_path
    ):
        from wavetpu.obs import tracing
        eng = ServeEngine(bucket_sizes=(1,), interpret=True)
        p = Problem(N=8, timesteps=17)
        store_dir = str(tmp_path / "state")
        plan = faults.parse_serve_spec(
            f"serve-slow-batch:seconds=0.25,timesteps={p.timesteps}"
        )
        origin = ("ab" * 16, "cd" * 8)
        b = DynamicBatcher(
            eng, max_wait=0.02, fault_plan=plan, chunk_threshold=8,
            chunk_steps=4, state_store=SolveStateStore(store_dir),
        )
        gauge = b.metrics._inflight_chunks
        try:
            fut = b.submit(
                _req(p), deadline=time.monotonic() + 0.4,
                trace_context=origin,
            )
            # the gauge rises while the march is genuinely in flight...
            seen, deadline = 0.0, time.monotonic() + 60.0
            while time.monotonic() < deadline and not fut.done():
                seen = max(seen, gauge.value())
                time.sleep(0.005)
            with pytest.raises(DeadlineExceededError) as ei:
                fut.result(120)
            token = ei.value.resume_token
        finally:
            b.close()
        assert seen == 1.0
        # ...and falls back to zero however the march ends (here:
        # deadline preemption)
        assert gauge.value() == 0.0
        # resume on a traced successor under a DIFFERENT client trace:
        # the checkpoint's origin_trace turns into span links, so the
        # whole march is still one joinable story
        trace_path = str(tmp_path / "trace.jsonl")
        tracing.configure(trace_path)
        b2 = DynamicBatcher(
            eng, max_wait=0.02, chunk_threshold=8, chunk_steps=4,
            state_store=SolveStateStore(store_dir),
        )
        fresh = ("12" * 16, "34" * 8)
        try:
            req = SolveRequest(
                problem=p, lane=eb.LaneSpec(), resume_token=token
            )
            res, health, info = b2.submit(
                req, trace_context=fresh
            ).result(120)
            assert health is None
            assert info["resumed_from"] >= 1
        finally:
            b2.close()
            tracing.disable()
        end = time.monotonic() + 5.0
        while (b2.metrics._inflight_chunks.value() != 0.0
               and time.monotonic() < end):
            time.sleep(0.005)
        assert b2.metrics._inflight_chunks.value() == 0.0
        recs = [json.loads(l) for l in open(trace_path)]
        chunks = [r for r in recs if r.get("kind") == "serve.chunk"]
        assert chunks
        for r in chunks:
            assert r.get("trace_id") == fresh[0]
            assert r.get("links") == [
                {"trace_id": origin[0], "span_id": origin[1]}
            ]


class TestCLI:
    def test_wavetpu_version(self, capsys):
        from wavetpu import __version__
        from wavetpu.cli import main

        assert main(["--version"]) == 0
        assert __version__ in capsys.readouterr().out

    def test_wavetpu_serve_version(self, capsys):
        from wavetpu import __version__
        from wavetpu.cli import main

        assert main(["serve", "--version"]) == 0
        out = capsys.readouterr().out
        assert "wavetpu-serve" in out and __version__ in out

    def test_serve_rejects_unknown_flag(self, capsys):
        from wavetpu.cli import main

        assert main(["serve", "--frobnicate", "1"]) == 2

    def test_serve_rejects_malformed_warmup(self, capsys):
        """Malformed --warmup values are usage errors (exit 2 with the
        usage line, like every other numeric flag), not tracebacks."""
        from wavetpu.serve.api import main

        assert main(["--warmup", "8x4"]) == 2
        assert "usage" in capsys.readouterr().err
        assert main(["--warmup", "8,4,2,9"]) == 2
        assert "--warmup wants" in capsys.readouterr().err

    def test_serve_main_crash_stops_telemetry(self, tmp_path,
                                              monkeypatch, capsys):
        """A crash after telemetry start but before/at serve (an
        accept-loop failure injected here; --warmup now compiles in the
        background and records failures instead of crashing main) must
        not leak the heartbeat daemon or leave the process tracer bound
        for an in-process caller."""
        from http.server import ThreadingHTTPServer

        from wavetpu.obs import tracing
        from wavetpu.serve.api import main

        def boom(self, *a, **kw):
            raise RuntimeError("injected accept-loop failure")

        monkeypatch.setattr(ThreadingHTTPServer, "serve_forever", boom)
        with pytest.raises(RuntimeError, match="injected"):
            main([
                "--port", "0", "--kernel", "roll",
                "--telemetry-dir", str(tmp_path / "tel"),
            ])
        assert not tracing.enabled()
        # the final heartbeat landed on the way out
        assert (tmp_path / "tel" / "heartbeat.jsonl").exists()

    def test_serve_rejects_malformed_breaker_flags(self, capsys):
        from wavetpu.serve.api import main

        assert main(["--breaker-threshold", "x"]) == 2
        assert main(["--breaker-cooldown-s", "y"]) == 2

    def test_program_key_shape(self):
        p = Problem(N=8, timesteps=3)
        key = ProgramKey.for_batch(
            p, "standard", "roll", 4, "f32", False, True, 2
        )
        assert key.k == 1  # non-kfused paths normalize k
        assert key.batch == 2
        assert key.mesh is None  # single-device default
        sharded_key = ProgramKey.for_batch(
            p, "standard", "roll", 4, "f32", False, True, 2, (2, 2, 1)
        )
        assert sharded_key.mesh == (2, 2, 1)
        assert sharded_key != key
