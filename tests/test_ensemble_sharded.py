"""Sharded x batched contracts (wavetpu/ensemble/sharded.py).

The load-bearing invariant mirrors tests/test_ensemble.py's at mesh
scale: every lane of a batched SHARDED solve - the shard_map-of-vmap
composition of the ensemble axis with the device mesh - is BITWISE
identical to the same problem solved solo through
`sharded.solve_sharded` on the same mesh, including per-lane phases,
per-lane stop layers, and padded batches.  Runs on the suite's 8
virtual CPU devices; the headline mesh is (2, 2, 1).
"""

import numpy as np
import pytest

from wavetpu.core.problem import Problem
from wavetpu.ensemble import batched as eb
from wavetpu.ensemble import sharded as es
from wavetpu.solver import sharded


def _bitwise(a, b):
    return np.array_equal(np.asarray(a), np.asarray(b))


MESH = (2, 2, 1)


@pytest.fixture(scope="module")
def problem():
    return Problem(N=16, timesteps=9)


@pytest.fixture(scope="module")
def lanes():
    # default phase, shifted phase, shifted phase + early stop
    return [
        eb.LaneSpec(),
        eb.LaneSpec(phase=1.0),
        eb.LaneSpec(phase=0.5, stop_step=5),
    ]


def _assert_lane_parity(res, solos):
    assert res.batched, res.fallback_reason
    assert res.fallback_reason is None
    for got, solo in zip(res.results, solos):
        assert _bitwise(got.u_cur, solo.u_cur)
        assert _bitwise(got.u_prev, solo.u_prev)
        assert got.final_step == solo.final_step
        assert np.array_equal(got.abs_errors, solo.abs_errors)
        assert np.array_equal(got.rel_errors, solo.rel_errors)


class TestShardedLaneParity:
    def test_roll_on_221_mesh(self, problem, lanes):
        res = es.solve_ensemble_sharded(
            problem, lanes, mesh_shape=MESH, kernel="roll"
        )
        solos = [
            sharded.solve_sharded(
                problem, mesh_shape=MESH, kernel="roll",
                phase=lane.phase, stop_step=lane.stop(problem),
            )
            for lane in lanes
        ]
        _assert_lane_parity(res, solos)

    def test_pallas_on_221_mesh(self, problem, lanes):
        ok, why = es.vmap_capability(MESH, kernel="pallas",
                                     interpret=True)
        if not ok:
            pytest.skip(f"pallas sharded batching unavailable: {why}")
        res = es.solve_ensemble_sharded(
            problem, lanes, mesh_shape=MESH, kernel="pallas",
            interpret=True,
        )
        solos = [
            sharded.solve_sharded(
                problem, mesh_shape=MESH, kernel="pallas",
                interpret=True, phase=lane.phase,
                stop_step=lane.stop(problem),
            )
            for lane in lanes
        ]
        _assert_lane_parity(res, solos)

    def test_x_only_mesh(self, problem, lanes):
        res = es.solve_ensemble_sharded(
            problem, lanes, mesh_shape=(4, 1, 1), kernel="roll"
        )
        solos = [
            sharded.solve_sharded(
                problem, mesh_shape=(4, 1, 1), kernel="roll",
                phase=lane.phase, stop_step=lane.stop(problem),
            )
            for lane in lanes
        ]
        _assert_lane_parity(res, solos)

    def test_padded_batch_leaves_real_lanes_bitwise_unchanged(
        self, problem, lanes
    ):
        plain = es.solve_ensemble_sharded(
            problem, lanes, mesh_shape=MESH, kernel="roll"
        )
        padded = es.solve_ensemble_sharded(
            problem, lanes, mesh_shape=MESH, kernel="roll", pad_to=4
        )
        assert padded.batch_size == 4 and padded.n_lanes == 3
        for a, b in zip(padded.results, plain.results):
            assert _bitwise(a.u_cur, b.u_cur)
            assert _bitwise(a.u_prev, b.u_prev)
            assert np.array_equal(a.abs_errors, b.abs_errors)


class TestSoloShardedPhase:
    def test_default_phase_is_the_reference_program(self, problem):
        a = sharded.solve_sharded(problem, mesh_shape=MESH, kernel="roll")
        b = sharded.solve_sharded(
            problem, mesh_shape=MESH, kernel="roll", phase=2.0 * np.pi
        )
        assert _bitwise(a.u_cur, b.u_cur)
        assert np.array_equal(a.abs_errors, b.abs_errors)

    def test_shifted_phase_errors_stay_discretization_small(self):
        p = Problem(N=16, timesteps=9)
        ref = sharded.solve_sharded(
            p, mesh_shape=MESH, kernel="roll"
        ).abs_errors.max()
        e = sharded.solve_sharded(
            p, mesh_shape=MESH, kernel="roll", phase=1.0
        ).abs_errors.max()
        # without the analytic layer-1 bootstrap this is O(1)
        assert e < 10 * ref, f"{e} vs ref {ref}"

    def test_sharded_phase_matches_single_device(self, problem):
        # The (1,1,1) sharded roll program and the single-device roll
        # solver integrate the same shifted-phase IVP to the same class.
        s = sharded.solve_sharded(
            problem, mesh_shape=(1, 1, 1), kernel="roll", phase=1.0
        )
        from wavetpu.solver import leapfrog

        solo = leapfrog.solve(problem, phase=1.0)
        assert s.abs_errors.max() == pytest.approx(
            solo.abs_errors.max(), rel=1e-3
        )

    def test_compensated_rejects_shifted_phase(self, problem):
        with pytest.raises(ValueError, match="reference phase"):
            sharded.solve_sharded(
                problem, mesh_shape=MESH, kernel="roll",
                scheme="compensated", phase=1.0,
            )


class TestShardedFallback:
    def test_probe_failure_falls_back_with_reason(
        self, problem, lanes, monkeypatch
    ):
        monkeypatch.setattr(
            es, "vmap_capability",
            lambda *a, **k: (False, "forced-by-test"),
        )
        res = es.solve_ensemble_sharded(
            problem, lanes, mesh_shape=MESH, kernel="roll"
        )
        assert res.batched is False
        assert "forced-by-test" in res.fallback_reason
        solo = sharded.solve_sharded(
            problem, mesh_shape=MESH, kernel="roll", phase=1.0
        )
        assert _bitwise(res.results[1].u_cur, solo.u_cur)

    def test_probe_verdict_cached_and_surfaced(self):
        es._PROBE_CACHE.clear()
        try:
            ok, why = es.vmap_capability((2, 1, 1), kernel="roll",
                                         interpret=True)
            assert ok, why
            assert len(es._PROBE_CACHE) == 1
            es.vmap_capability((2, 1, 1), kernel="roll", interpret=True)
            assert len(es._PROBE_CACHE) == 1
            rows = es.probe_results()
            assert rows[0]["mesh"] == [2, 1, 1]
            assert rows[0]["ok"] is True
        finally:
            es._PROBE_CACHE.clear()


class TestShardedValidation:
    def test_field_lanes_rejected(self, problem):
        field = np.full((problem.N,) * 3, problem.a2tau2)
        with pytest.raises(ValueError, match="field"):
            es.solve_ensemble_sharded(
                problem, [eb.LaneSpec(c2tau2_field=field)],
                mesh_shape=MESH, compute_errors=False,
            )

    def test_empty_batch_rejected(self, problem):
        with pytest.raises(ValueError, match="at least one lane"):
            es.solve_ensemble_sharded(problem, [], mesh_shape=MESH)

    def test_bad_kernel_rejected(self, problem):
        with pytest.raises(ValueError, match="kernel"):
            es.solve_ensemble_sharded(
                problem, [eb.LaneSpec()], mesh_shape=MESH, kernel="cuda"
            )

    def test_stop_out_of_range(self, problem):
        with pytest.raises(ValueError, match="stop_step"):
            es.solve_ensemble_sharded(
                problem, [eb.LaneSpec(stop_step=99)], mesh_shape=MESH
            )
