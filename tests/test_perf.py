"""Roofline attribution + device-memory watermarks (wavetpu/obs/perf.py).

Pins: the shared cost model reproduces the BENCH-documented per-row
traffic figures and agrees with `choose_kstep_block`'s block choice;
the roofline fraction is reported for every instrumented solver path
(roll / pallas 1-step / k-fused / comp / sharded) plus the serve
execute span; memory sampling keeps the None-on-unsupported contract
and the watermark/warn machinery works against a fake stats provider.
"""

import json
import os

import pytest

from wavetpu.core.problem import Problem
from wavetpu.obs import perf, telemetry, tracing
from wavetpu.obs.registry import MetricsRegistry, get_registry


class TestCostModel:
    @pytest.mark.parametrize("kw,want", [
        # The bench-documented N=512 models, now this function's outputs
        # (bench.py quotes these numbers in its row comments).
        (dict(path="kfused", k=4, n=512), 8.0),
        (dict(path="kfused", k=2, n=512), 10.0),
        (dict(path="kfused", k=4, n=512, itemsize=2), 3.0),
        (dict(path="kfused", k=4, n=512, with_field=True, block_x=4),
         11.0),
        (dict(path="kfused", k=2, n=512, with_field=True), 16.0),
        (dict(path="kfused_comp", k=4, n=512), 9.0),
        (dict(path="kfused_comp", k=2, n=512), 14.0),
        (dict(path="kfused_comp", k=4, n=512, v_itemsize=2,
              carry=False), 6.0),
        (dict(path="kfused_comp", k=2, n=512, v_itemsize=2, carry=False,
              with_field=True), 13.0),
        (dict(path="pallas"), 12.0),
        (dict(path="roll"), 12.0),
        (dict(path="leapfrog"), 12.0),
        (dict(path="pallas", with_field=True), 16.0),
        (dict(path="pallas", itemsize=2), 6.0),
        (dict(path="compensated"), 24.0),
        (dict(path="sharded"), 12.0),
        (dict(path="sharded", scheme="compensated"), 24.0),
        (dict(path="sharded_kfused", k=4, n=512), 8.0),
        (dict(path="kfused_comp_sharded", k=2, n=512), 14.0),
    ])
    def test_bench_documented_models(self, kw, want):
        assert perf.model_bytes_per_cell(**kw) == want

    def test_onion_model_reads_the_choosers_block(self):
        """Modeled-bytes agreement with choose_kstep_block's accounting:
        the onion model's bx IS the chooser's verdict, so model and
        kernel pipeline can never drift."""
        from wavetpu.kernels.stencil_pallas import (
            choose_kstep_block,
            choose_kstep_comp_block,
        )

        for n, k, itemsize in ((512, 4, 4), (512, 2, 4), (512, 4, 2),
                               (64, 2, 4)):
            bx = choose_kstep_block(n, k, itemsize)
            assert perf.model_bytes_per_cell(
                "kfused", k=k, n=n, itemsize=itemsize
            ) == itemsize * (4 * bx + 4 * k) / (k * bx)
        bx = choose_kstep_comp_block(512, 4, 4, 4, 4)
        assert perf.model_bytes_per_cell(
            "kfused_comp", k=4, n=512
        ) == ((2 * bx + 2 * 4) * 4 * 2 + 2 * bx * 2) / (4 * bx)
        # Sharded variants: the model takes the SAME depth/ghosts
        # arguments the sharded kernels pass their chooser, so a
        # ghost-shrunk block feeds the model too.
        bx = choose_kstep_block(512, 2, 4, depth=64, ghosts=True)
        assert perf.model_bytes_per_cell(
            "sharded_kfused", k=2, n=512, depth=64, ghosts=True
        ) == 4 * (4 * bx + 4 * 2) / (2 * bx)

    def test_no_model_when_onion_does_not_fit(self):
        # k=8 comp onion with field at N=512 f32 is over the ceiling at
        # every admissible bx: the honest answer is None, not a guess.
        assert perf.model_bytes_per_cell(
            "kfused_comp", k=8, n=512, with_field=True
        ) is None
        assert perf.solve_perf(10.0, "kfused_comp", k=8, n=512,
                               with_field=True) is None

    def test_solve_perf_fields(self, monkeypatch):
        monkeypatch.setenv("WAVETPU_PEAK_GBPS", "250")
        rf = perf.solve_perf(40.0, "kfused", k=4, n=512)
        assert rf["model_bytes_per_cell"] == 8.0
        assert rf["model_gbps"] == 320.0
        assert rf["peak_gbps"] == 250.0
        assert rf["roofline_fraction"] == round(320.0 / 250.0, 4)
        assert rf["arithmetic_intensity"] == round(15.0 / 8.0, 4)
        assert perf.solve_perf(0.0, "kfused", k=4, n=512) is None


class TestRooflineRecording:
    def test_all_instrumented_paths_report_a_fraction(self):
        """Acceptance pin: after one solve per family (roll, pallas
        1-step, k-fused, comp, sharded), the process registry holds a
        positive roofline fraction for every path label."""
        from wavetpu.kernels import stencil_pallas
        from wavetpu.solver import kfused, kfused_comp, leapfrog, sharded

        p = Problem(N=8, timesteps=3)
        leapfrog.solve(p)  # roll
        leapfrog.solve(
            p, step_fn=stencil_pallas.make_step_fn(interpret=True)
        )  # pallas 1-step (same "leapfrog" label, same 1-step model)
        leapfrog.solve_compensated(p)
        kfused.solve_kfused(p, k=2, interpret=True)
        kfused_comp.solve_kfused_comp(p, k=2, interpret=True)
        sharded.solve_sharded(p, mesh_shape=(1, 1, 1))
        g = get_registry().gauge(
            "wavetpu_solve_roofline_fraction", "", ("path",)
        )
        # 1-step variable-c: the ParamStep kernel must model the extra
        # field stream (16 B/cell, not 12) - gauge ratio pins it.
        from wavetpu.kernels import stencil_ref

        field = stencil_ref.make_preset_c2tau2_field(p, "constant")
        leapfrog.solve(
            p, step_fn=stencil_ref.make_variable_c_step(field),
            compute_errors=False,
        )
        reg = get_registry()
        bpc = reg.gauge(
            "wavetpu_solve_model_gbps", "", ("path",)
        ).value(path="leapfrog") / reg.gauge(
            "wavetpu_last_solve_gcells_per_s", "", ("path",)
        ).value(path="leapfrog")
        # 0.5 slack: the gauge stores model_gbps rounded to 3 decimals,
        # which is coarse at CPU-scale throughput.
        assert abs(bpc - 16.0) < 0.5, bpc
        h = get_registry().histogram(
            "wavetpu_solve_gbps", "", ("path",),
            buckets=perf._GBPS_BUCKETS,
        )
        for path in ("leapfrog", "compensated", "kfused", "kfused_comp",
                     "sharded"):
            assert g.value(path=path) > 0.0, path
            assert h.count(path=path) >= 1, path

    def test_serve_execute_span_carries_roofline_attrs(self, tmp_path):
        from wavetpu.ensemble.batched import LaneSpec
        from wavetpu.serve.engine import ServeEngine

        d = str(tmp_path / "tel")
        tel = telemetry.start(d, interval=60.0)
        try:
            problem = Problem(N=8, timesteps=3)
            eng = ServeEngine(bucket_sizes=(1,), interpret=True)
            eng.solve(problem, [LaneSpec(phase=1.0)], path="roll")
        finally:
            tel.stop()
        spans = [
            json.loads(line)
            for line in open(os.path.join(d, "trace.jsonl"))
        ]
        ex = [s for s in spans if s.get("kind") == "serve.execute"]
        assert ex, "no serve.execute span"
        attrs = ex[-1]["attrs"]
        assert attrs["model_bytes_per_cell"] == 12.0
        assert attrs["model_gbps"] > 0.0
        assert 0.0 < attrs["roofline_fraction"]
        # and the server registry carries the same gauges
        assert eng.registry.gauge(
            "wavetpu_solve_roofline_fraction", "", ("path",)
        ).value(path="roll") > 0.0


class TestDeviceMemory:
    def teardown_method(self):
        perf.set_memory_stats_provider(None)
        perf.configure_memory_warn(None)

    def test_cpu_backend_is_none_and_cached(self):
        # jaxlib's CPU device answers memory_stats() with None -> the
        # whole memory surface reports None and later calls short-
        # circuit on the cached verdict.
        perf.set_memory_stats_provider(None)
        import jax  # noqa: F401  (memory_snapshot consults sys.modules)

        snap = perf.memory_snapshot()
        if snap is not None:  # a backend WITH memory_stats: ints
            assert snap["bytes_in_use"] >= 0
            return
        assert perf.record_memory(MetricsRegistry()) is None

    def test_gauges_watermark_and_warn(self, tmp_path):
        stats = {"bytes_in_use": 1000, "peak_bytes_in_use": 1500}
        perf.set_memory_stats_provider(lambda: dict(stats))
        perf.configure_memory_warn(1200)
        reg = MetricsRegistry()
        tracer_path = str(tmp_path / "trace.jsonl")
        tracing.configure(tracer_path)
        try:
            snap = perf.record_memory(reg, context="solve")
            assert snap == {"bytes_in_use": 1000, "peak_bytes": 1500}
            assert reg.gauge(
                "wavetpu_device_bytes_in_use", "", ("context",)
            ).value(context="solve") == 1000
            assert reg.gauge(
                "wavetpu_device_memory_watermark_bytes", ""
            ).value() == 1000
            raises = reg.counter(
                "wavetpu_device_memory_watermark_raises_total", ""
            )
            assert raises.value() == 1
            # a lower sample never lowers the watermark
            stats["bytes_in_use"] = 800
            perf.record_memory(reg, context="supervisor")
            assert reg.gauge(
                "wavetpu_device_memory_watermark_bytes", ""
            ).value() == 1000
            assert raises.value() == 1
            assert reg.counter(
                "wavetpu_device_memory_warn_total", ""
            ).value() == 0
            # crossing the warn threshold: counter + trace event
            stats["bytes_in_use"] = 2000
            perf.record_memory(reg, context="serve")
            assert reg.counter(
                "wavetpu_device_memory_warn_total", ""
            ).value() == 1
            assert reg.gauge(
                "wavetpu_device_memory_watermark_bytes", ""
            ).value() == 2000
            assert raises.value() == 2
        finally:
            tracing.disable()
        events = [
            json.loads(line) for line in open(tracer_path)
        ]
        warn = [e for e in events if e.get("kind") == "memory.warn"]
        assert len(warn) == 1
        assert warn[0]["attrs"]["bytes_in_use"] == 2000
        assert warn[0]["attrs"]["warn_bytes"] == 1200

    def test_transient_read_failure_does_not_latch_unsupported(self):
        """One failed memory_stats() read (backend bring-up race) must
        NOT permanently disable memory observability: no verdict is
        cached, and the next successful read reports normally."""
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("transient")
            return {"bytes_in_use": 7, "peak_bytes_in_use": 9}

        perf.set_memory_stats_provider(flaky)
        assert perf.memory_snapshot() is None  # the transient failure
        assert perf.memory_snapshot() == {
            "bytes_in_use": 7, "peak_bytes": 9,
        }
        assert calls["n"] == 2  # second call really re-probed

    def test_env_warn_threshold(self, monkeypatch):
        monkeypatch.setenv("WAVETPU_MEM_WARN_BYTES", "4096")
        assert perf.memory_warn_bytes() == 4096
        monkeypatch.setenv("WAVETPU_MEM_WARN_BYTES", "junk")
        assert perf.memory_warn_bytes() is None


class TestProfileSubcommand:
    def test_profile_brackets_a_solve(self, tmp_path, capsys):
        """`wavetpu profile` runs the inner command under jax.profiler,
        injects a telemetry dir so spans annotate the device trace, and
        prints the post-capture summary."""
        from wavetpu import cli

        out = str(tmp_path / "prof")
        rc = cli.main([
            "profile", "--out", out,
            "8", "1", "1", "1", "1", "1", "3",
            "--out-dir", str(tmp_path),
        ])
        assert rc == 0
        printed = capsys.readouterr().out
        assert "profile capture:" in printed
        assert "cli.solve" in printed  # span summary made it
        # the device trace landed
        assert any(
            f.endswith(".xplane.pb")
            for _, _, files in os.walk(out) for f in files
        )
        # and the injected telemetry dir holds the span trace + ledger;
        # the cli.solve span carries the gauge-read roofline attrs
        trace_path = os.path.join(out, "telemetry", "trace.jsonl")
        spans = [json.loads(line) for line in open(trace_path)]
        cs = [s for s in spans if s.get("kind") == "cli.solve"]
        assert cs and cs[-1]["attrs"]["model_gbps"] > 0
        assert cs[-1]["attrs"]["roofline_fraction"] > 0
        assert os.path.exists(
            os.path.join(out, "telemetry", "compile_ledger.jsonl")
        )

    def test_profile_usage_errors(self, capsys):
        from wavetpu.obs import perf as obs_perf

        assert obs_perf.profile_main([]) == 2
        assert obs_perf.profile_main(["--out", "/tmp/x"]) == 2
        assert obs_perf.profile_main(
            ["--out", "/tmp/x", "8", "--profile", "d"]
        ) == 2
        capsys.readouterr()
