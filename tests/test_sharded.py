"""M1 gates: the sharded solver equals the single-device solver.

Runs on the 8-virtual-CPU-device mesh (conftest.py) - the "fake backend" the
reference lacks (SURVEY.md section 4): multi-chip semantics without a pod.
Parity target: `solver.leapfrog` (itself pinned layer-by-layer to the
independent (N+1)^3 seam formulation in tests/reference_impl.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from wavetpu.core.grid import Topology, choose_mesh_shape
from wavetpu.core.problem import Problem
from wavetpu.solver import leapfrog, sharded

MESHES = [(1, 1, 1), (2, 2, 2), (1, 2, 4), (8, 1, 1), (1, 1, 8), (2, 1, 2)]


def _parity(problem, mesh_shape, dtype=jnp.float64, atol=1e-12):
    single = leapfrog.solve(problem, dtype=dtype)
    multi = sharded.solve_sharded(problem, mesh_shape=mesh_shape, dtype=dtype)
    uS = np.asarray(single.u_cur)
    uM = sharded.gather_fundamental(multi.u_cur, problem)
    np.testing.assert_allclose(uM, uS, atol=atol, rtol=0.0)
    uSp = np.asarray(single.u_prev)
    uMp = sharded.gather_fundamental(multi.u_prev, problem)
    np.testing.assert_allclose(uMp, uSp, atol=atol, rtol=0.0)
    np.testing.assert_allclose(
        multi.abs_errors, single.abs_errors, atol=atol, rtol=0.0
    )
    np.testing.assert_allclose(
        multi.rel_errors, single.rel_errors, atol=1e-9, rtol=1e-9
    )
    return single, multi


@pytest.mark.parametrize("mesh_shape", MESHES)
def test_sharded_matches_single_device(small_problem, mesh_shape):
    """Sharded == single-device across mesh shapes, including the periodic
    x seam crossing shard boundaries (8,1,1) and every-axis-cyclic cases."""
    _parity(small_problem, mesh_shape)


def test_sharded_uneven_grid():
    """N not divisible by the mesh dims: pad cells are masked out and the
    seam index arithmetic (comm/halo.py) keeps the wrap exact - the analog
    of the reference's remainder-rank folding (mpi_sol.cpp:417-421)."""
    p = Problem(N=17, timesteps=8)
    _parity(p, (2, 2, 2))
    _parity(p, (4, 1, 2))


def test_sharded_uneven_last_shard_single_plane():
    """Last shard owns exactly one real plane (r_last == 1)."""
    p = Problem(N=13, timesteps=6)
    # block = ceil(13/4) = 4, last shard owns 13 - 3*4 = 1 plane.
    _parity(p, (4, 1, 1))
    _parity(p, (1, 4, 1))


def test_sharded_pad_cells_stay_zero():
    res = sharded.solve_sharded(
        Problem(N=15, timesteps=6), mesh_shape=(2, 2, 2), dtype=jnp.float64
    )
    u = np.asarray(res.u_cur)
    assert u.shape == (16, 16, 16)
    assert np.all(u[15:] == 0.0)
    assert np.all(u[:, 15:] == 0.0)
    assert np.all(u[:, :, 15:] == 0.0)


def test_sharded_f32(small_problem):
    """The production dtype path agrees with single-device f32 bitwise-ish
    (same op order per cell; halo vs roll may differ in fusion, so allow
    tiny tolerance)."""
    _parity(small_problem, (2, 2, 2), dtype=jnp.float32, atol=1e-6)


def test_sharded_errors_bounded(medium_problem):
    res = sharded.solve_sharded(
        medium_problem, mesh_shape=(2, 2, 2), dtype=jnp.float64
    )
    assert res.abs_errors[0] == 0.0
    assert res.abs_errors.max() < 1e-2
    assert np.isfinite(res.abs_errors).all()


def test_topology_validation():
    with pytest.raises(ValueError):
        Topology(N=10, mesh_shape=(8, 1, 1))  # last shard would own <1 plane
    t = Topology(N=17, mesh_shape=(2, 2, 2))
    assert t.block == (9, 9, 9)
    assert t.padded == (18, 18, 18)
    assert t.r_last == (8, 8, 8)


def test_choose_mesh_shape():
    assert choose_mesh_shape(8) == (2, 2, 2)
    assert choose_mesh_shape(64) == (4, 4, 4)
    assert sorted(choose_mesh_shape(4), reverse=True) == [2, 2, 1]
    assert choose_mesh_shape(1) == (1, 1, 1)
    mx, my, mz = choose_mesh_shape(12)
    assert mx * my * mz == 12
