"""Compensated (velocity-form) k-fused solver: tolerance parity + resume.

Unlike the standard k-fused path (bitwise-pinned to the 1-step kernel,
tests/test_kfused.py), the velocity-form onion explicitly abandons
bitwise parity (no per-substep storage round-trip; halo-cone carries
seed to zero).  Its contract is therefore pinned here the way the
round-4 verdict prescribed: TOLERANCE parity against f64, plus
self-consistency with the 1-step compensated scheme, plus exact resume
on block-aligned boundaries.

On-chip reference numbers (v5e, N=512/1000, errors fused): 33.98 Gcell/s
at L-inf 5.72e-6 (k=4 f32) and 44.19 Gcell/s at 6.39e-4 (k=4 bf16
increment form) - recorded in BENCH_r05.json.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from wavetpu.core.problem import Problem
from wavetpu.solver import kfused_comp, leapfrog


@pytest.fixture(scope="module")
def problem():
    return Problem(N=32, Np=1, Lx=1.0, Ly=1.0, Lz=1.0, T=1.0, timesteps=21)


@pytest.fixture(scope="module")
def ref64(problem):
    return np.asarray(
        leapfrog.solve(problem, dtype=jnp.float64).u_cur, np.float64
    )


@pytest.fixture(scope="module")
def comp1(problem):
    return leapfrog.solve_compensated(problem)


@pytest.fixture(scope="module")
def ck4(problem):
    return kfused_comp.solve_kfused_comp(problem, k=4, interpret=True)


def test_f64_tolerance_parity(ck4, ref64):
    # Measured 2.2e-7 (the 1-step compensated path sits at 2.0e-7): the
    # onion must stay at the compensated class, far below the standard
    # f32 path's accumulation.
    diff = np.abs(np.asarray(ck4.u_cur, np.float64) - ref64).max()
    assert diff < 1e-6, diff


def test_matches_one_step_compensated(ck4, comp1):
    diff = np.abs(
        np.asarray(ck4.u_cur, np.float64)
        - np.asarray(comp1.u_cur, np.float64)
    ).max()
    assert diff < 1e-6, diff
    # u_prev reconstruction (u - v) must agree the same way.
    dprev = np.abs(
        np.asarray(ck4.u_prev, np.float64)
        - np.asarray(comp1.u_prev, np.float64)
    ).max()
    assert dprev < 1e-6, dprev


def test_per_layer_errors_match_compensated(ck4, comp1):
    # The in-kernel separable-oracle error rows must reproduce the jnp
    # error path of the 1-step compensated scheme to rounding (measured
    # 6e-8); layer 0 exactly 0 by the assignment contract.
    assert ck4.abs_errors[0] == 0.0
    assert ck4.abs_errors.shape == comp1.abs_errors.shape
    assert np.abs(ck4.abs_errors - comp1.abs_errors).max() < 1e-6


def test_rel_errors_guarded_and_sane(ck4, comp1):
    # This path's rel metric excludes representation-level zeros of sx
    # (the sin(pi) plane) - see solver/kfused_comp._make_march.  The jnp
    # metric (comp1) is dominated by that plane's noise/noise ratio
    # (~0.22 at N=32), so the guarded rel must be (a) far BELOW it and
    # (b) in the class of the true relative error (~abs/|u| ~ 1e-4).
    assert ck4.rel_errors.max() < 1e-2, ck4.rel_errors.max()
    assert ck4.rel_errors[2:].max() > 0.0
    assert comp1.rel_errors.max() > 0.1  # the unguarded metric's noise


@pytest.mark.heavy
def test_block_aligned_resume_bitwise(problem, ck4):
    # stop=13 is block-aligned from start=1 (blocks [2-5][6-9][10-13]);
    # the resumed march emits the identical remaining block sequence.
    st = kfused_comp.solve_kfused_comp(
        problem, k=4, stop_step=13, interpret=True
    )
    assert st.comp_v is not None and st.comp_carry is not None
    rs = kfused_comp.resume_kfused_comp(
        problem, st.u_cur, st.comp_v, st.comp_carry, 13, k=4,
        interpret=True,
    )
    assert np.array_equal(np.asarray(rs.u_cur), np.asarray(ck4.u_cur))
    assert np.array_equal(np.asarray(rs.comp_v), np.asarray(ck4.comp_v))
    # Error arrays: head zeros, tail equal.
    assert np.array_equal(rs.abs_errors[14:], ck4.abs_errors[14:])
    assert np.all(rs.abs_errors[:14] == 0.0)


@pytest.mark.heavy
def test_misaligned_resume_tolerance(problem, ck4, ref64):
    # stop=14 shifts the block grid (resume marches [15-18] + 3-layer
    # k=1 tail vs the full run's [14-17][18-21]): different op order, so
    # only tolerance equality - but accuracy vs f64 must stay in class.
    st = kfused_comp.solve_kfused_comp(
        problem, k=4, stop_step=14, interpret=True
    )
    rs = kfused_comp.resume_kfused_comp(
        problem, st.u_cur, st.comp_v, st.comp_carry, 14, k=4,
        interpret=True,
    )
    diff = np.abs(
        np.asarray(rs.u_cur, np.float64)
        - np.asarray(ck4.u_cur, np.float64)
    ).max()
    assert 0 < diff < 1e-6, diff
    assert np.abs(np.asarray(rs.u_cur, np.float64) - ref64).max() < 1e-6


def test_cross_path_resume_from_one_step(problem, ck4):
    # A checkpoint written by the 1-step compensated scheme resumes on
    # the k-fused path (the state contract is shared: u, v, carry).
    st = leapfrog.solve_compensated(problem, stop_step=13)
    rs = kfused_comp.resume_kfused_comp(
        problem, st.u_cur, st.comp_v, st.comp_carry, 13, k=4,
        interpret=True,
    )
    diff = np.abs(
        np.asarray(rs.u_cur, np.float64)
        - np.asarray(ck4.u_cur, np.float64)
    ).max()
    assert diff < 1e-6, diff


def test_bf16_increment_form(problem, ref64):
    res = kfused_comp.solve_kfused_comp(
        problem, k=4, v_dtype=jnp.bfloat16, carry=False, interpret=True
    )
    assert res.u_cur.dtype == jnp.float32
    assert res.comp_v.dtype == jnp.bfloat16
    assert res.comp_carry is None
    # Measured 5.3e-4: the bf16 quantization of the increment stream is
    # bounded (~|v| * 2^-8 per step), unlike a bf16 carrier whose
    # trajectory is garbage (0.66 at the flagship config, BENCH_r04).
    diff = np.abs(np.asarray(res.u_cur, np.float64) - ref64).max()
    assert diff < 5e-3, diff


@pytest.mark.heavy
def test_bf16_increment_resume(problem):
    st = kfused_comp.solve_kfused_comp(
        problem, k=4, stop_step=13, v_dtype=jnp.bfloat16, carry=False,
        interpret=True,
    )
    full = kfused_comp.solve_kfused_comp(
        problem, k=4, v_dtype=jnp.bfloat16, carry=False, interpret=True
    )
    rs = kfused_comp.resume_kfused_comp(
        problem, st.u_cur, st.comp_v, None, 13, k=4,
        v_dtype=jnp.bfloat16, interpret=True,
    )
    assert np.array_equal(np.asarray(rs.u_cur), np.asarray(full.u_cur))


def test_f64_state_marches_in_f64(problem):
    # Regression pin (r5 review): the kernel must compute in the state's
    # compute dtype, and u_prev reconstruction must not round through f32.
    r64 = kfused_comp.solve_kfused_comp(
        problem, dtype=jnp.float64, k=4, interpret=True
    )
    c64 = leapfrog.solve_compensated(problem, dtype=jnp.float64)
    d = np.abs(np.asarray(r64.u_cur) - np.asarray(c64.u_cur)).max()
    assert d < 1e-12, d
    dprev = np.abs(np.asarray(r64.u_prev) - np.asarray(c64.u_prev)).max()
    assert dprev < 1e-12, dprev


@pytest.mark.heavy
def test_bf16_carry_default_and_legacy_resume(problem, ck4, ref64):
    # f32 runs default to a bf16 carry (the +6% HBM win; error class
    # unchanged - ck4 above already ran with it), f64 runs keep f64.
    assert ck4.comp_carry.dtype == jnp.bfloat16
    r64 = kfused_comp.solve_kfused_comp(
        problem, dtype=jnp.float64, k=4, stop_step=5, interpret=True
    )
    assert r64.comp_carry.dtype == jnp.float64
    # An explicit f32 carry (legacy checkpoints) still resumes, with its
    # dtype preserved through the march.
    st = kfused_comp.solve_kfused_comp(
        problem, k=4, stop_step=13, carry_dtype=jnp.float32,
        interpret=True,
    )
    assert st.comp_carry.dtype == jnp.float32
    rs = kfused_comp.resume_kfused_comp(
        problem, st.u_cur, st.comp_v, st.comp_carry, 13, k=4,
        interpret=True,
    )
    assert rs.comp_carry.dtype == jnp.float32
    diff = np.abs(np.asarray(rs.u_cur, np.float64) - ref64).max()
    assert diff < 1e-6, diff


def test_errors_off(problem):
    res = kfused_comp.solve_kfused_comp(
        problem, k=4, compute_errors=False, interpret=True
    )
    assert np.all(res.abs_errors == 0.0)


# ---------------------------------------------------------------------------
# Sharded velocity-form k-fusion (the distributed flagship, x-only).
# Cross-mesh agreement is ulp-level, not bitwise: sub-f32-ulp noise at the
# representation-zero sx plane can flip rounding ties even with identical
# per-plane op sequences (see stencil_pallas._kstep_comp_sharded_kernel).
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n_shards", [1, 2, 4])
@pytest.mark.heavy
def test_sharded_matches_single_device(problem, ck4, n_shards):
    got = kfused_comp.solve_kfused_comp_sharded(
        problem, n_shards=n_shards, k=4, block_x=4, interpret=True
    )
    single = kfused_comp.solve_kfused_comp(
        problem, k=4, block_x=4, interpret=True
    )
    diff = np.abs(
        np.asarray(got.u_cur, np.float64)
        - np.asarray(single.u_cur, np.float64)
    ).max()
    assert diff < 1e-6, diff
    # The per-layer error rows assemble identically (measured: exact).
    np.testing.assert_allclose(
        got.abs_errors, single.abs_errors, rtol=1e-6, atol=1e-9
    )
    # And the accuracy stays at the compensated class vs the default-path
    # result of the same scheme.
    d2 = np.abs(
        np.asarray(got.u_cur, np.float64) - np.asarray(ck4.u_cur, np.float64)
    ).max()
    assert d2 < 1e-6, d2


@pytest.mark.heavy
def test_sharded_checkpoint_roundtrip(problem, tmp_path):
    from wavetpu.io import checkpoint as ckpt

    full = kfused_comp.solve_kfused_comp_sharded(
        problem, n_shards=2, k=4, block_x=4, interpret=True
    )
    part = kfused_comp.solve_kfused_comp_sharded(
        problem, n_shards=2, k=4, block_x=4, stop_step=13, interpret=True
    )
    path = str(tmp_path / "ck")
    ckpt.save_sharded_checkpoint(path, part)
    p2, u_prev, u_cur, step, mesh_shape, scheme, aux = (
        ckpt.load_sharded_checkpoint(path)
    )
    assert scheme == "compensated" and step == 13
    assert mesh_shape == (2, 1, 1)
    v, c = aux
    res = kfused_comp.resume_kfused_comp_sharded(
        p2, np.asarray(u_cur), np.asarray(v), np.asarray(c), step,
        n_shards=2, k=4, block_x=4, interpret=True,
    )
    # Block-aligned resume on the same mesh: identical op sequence.
    np.testing.assert_array_equal(
        np.asarray(res.u_cur), np.asarray(full.u_cur)
    )


def test_sharded_bf16_increment(problem, ref64):
    got = kfused_comp.solve_kfused_comp_sharded(
        problem, n_shards=4, k=4, v_dtype=jnp.bfloat16, carry=False,
        interpret=True,
    )
    assert got.comp_v.dtype == jnp.bfloat16 and got.comp_carry is None
    diff = np.abs(np.asarray(got.u_cur, np.float64) - ref64).max()
    assert diff < 5e-3, diff


@pytest.mark.parametrize("mesh", [(2, 2, 1), (1, 2, 1), (2, 4, 1)])
@pytest.mark.heavy
def test_sharded_xy_matches_single_device(problem, mesh):
    """2D-mesh velocity-form k-fusion (y-extended blocks, wrapped-global-y
    increment mask, corners via sequenced exchange) agrees with the
    single-device solve at ulp level; y-sharding is what lifts the VMEM
    bound on k (Mosaic-validated on chip at N=512 k=4 nl_y=64)."""
    single = kfused_comp.solve_kfused_comp(
        problem, k=4, block_x=4, interpret=True
    )
    got = kfused_comp.solve_kfused_comp_sharded(
        problem, mesh_shape=mesh, k=4, block_x=4, interpret=True
    )
    diff = np.abs(
        np.asarray(got.u_cur, np.float64)
        - np.asarray(single.u_cur, np.float64)
    ).max()
    assert diff < 1e-6, diff
    # Error rows are maxima over slightly (ulp-level) different fields:
    # a few e-7 absolute play at the 1e-3 error scale is expected.
    np.testing.assert_allclose(
        got.abs_errors, single.abs_errors, rtol=1e-3, atol=1e-7
    )


@pytest.mark.heavy
def test_sharded_xy_checkpoint_roundtrip(problem, tmp_path):
    from wavetpu.io import checkpoint as ckpt

    full = kfused_comp.solve_kfused_comp_sharded(
        problem, mesh_shape=(2, 2, 1), k=4, block_x=4, interpret=True
    )
    part = kfused_comp.solve_kfused_comp_sharded(
        problem, mesh_shape=(2, 2, 1), k=4, block_x=4, stop_step=13,
        interpret=True,
    )
    path = str(tmp_path / "ck")
    ckpt.save_sharded_checkpoint(path, part)
    p2, u_prev, u_cur, step, mesh_shape, scheme, aux = (
        ckpt.load_sharded_checkpoint(path)
    )
    assert scheme == "compensated" and mesh_shape == (2, 2, 1)
    v, c = aux
    res = kfused_comp.resume_kfused_comp_sharded(
        p2, np.asarray(u_cur), np.asarray(v), np.asarray(c), step,
        mesh_shape=(2, 2, 1), k=4, block_x=4, interpret=True,
    )
    np.testing.assert_array_equal(
        np.asarray(res.u_cur), np.asarray(full.u_cur)
    )


def test_sharded_validation(problem):
    with pytest.raises(ValueError, match="N % shards"):
        kfused_comp.solve_kfused_comp_sharded(
            problem, n_shards=3, k=4, interpret=True
        )
    with pytest.raises(ValueError, match="shard depth"):
        kfused_comp.solve_kfused_comp_sharded(
            problem, n_shards=8, k=8, interpret=True
        )
    with pytest.raises(ValueError, match="y shard depth"):
        # nl_y = 2 < k = 4 (validation precedes mesh construction).
        kfused_comp.solve_kfused_comp_sharded(
            problem, mesh_shape=(1, 16, 1), k=4, interpret=True
        )
    with pytest.raises(ValueError, match=r"\(MX, MY, 1\)"):
        kfused_comp.solve_kfused_comp_sharded(
            problem, mesh_shape=(2, 1, 2), k=4, interpret=True
        )


def test_validation(problem):
    with pytest.raises(ValueError, match="carrier"):
        kfused_comp.solve_kfused_comp(
            problem, dtype=jnp.bfloat16, k=4, interpret=True
        )
    with pytest.raises(ValueError, match="carry=False"):
        kfused_comp.solve_kfused_comp(
            problem, k=4, v_dtype=jnp.bfloat16, interpret=True
        )
    with pytest.raises(ValueError, match="divide"):
        kfused_comp.solve_kfused_comp(problem, k=5, interpret=True)
    with pytest.raises(ValueError, match="k must be >= 2"):
        kfused_comp.solve_kfused_comp(problem, k=1, interpret=True)
