"""Unified-telemetry contracts: registry, tracing, trace-report, and the
acceptance drill - a supervised multi-chunk run whose trace's chunk
boundaries match the checkpoint rotation steps on disk.

The Prometheus exposition is validated with `parse_prometheus`, a
minimal line parser shared with tests/test_serve.py (which checks the
HTTP surface); here it pins the renderer itself: sample names, label
escaping, histogram triplets, and text/JSON agreement on shared state.
"""

import json
import os
import threading

import pytest

from wavetpu.obs import report as obs_report
from wavetpu.obs import telemetry, tracing
from wavetpu.obs.registry import MetricsRegistry, get_registry


def parse_prometheus(text, with_exemplars=False):
    """Minimal exposition-format parser: {sample_name_with_labels: float}
    plus {family: type}.  Raises on malformed lines, so using it IS the
    validity assertion.  `with_exemplars=True` additionally validates +
    returns the OpenMetrics exemplar suffixes (`name # {labels} value
    ts`) and the trailing `# EOF` marker as a third mapping
    {sample_name: {"labels": {...}, "value": float, "ts": float}}."""
    samples, types, exemplars = {}, {}, {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            continue
        if line == "# EOF":
            assert with_exemplars, "EOF marker outside openmetrics mode"
            continue
        if line.startswith("# TYPE "):
            _, _, family, kind = line.split(" ", 3)
            assert kind in ("counter", "gauge", "histogram"), line
            types[family] = kind
            continue
        assert not line.startswith("#"), f"unknown comment {line!r}"
        if " # " in line:
            assert with_exemplars, f"exemplar in plain exposition: {line!r}"
            line, ex = line.split(" # ", 1)
            assert ex.startswith("{"), f"malformed exemplar {ex!r}"
            labelpart, _, rest = ex[1:].partition("} ")
            ev, _, ets = rest.partition(" ")
            ex_labels = {}
            if labelpart:
                for pair in labelpart.split('",'):
                    k, _, v = pair.partition('="')
                    ex_labels[k] = v.rstrip('"')
            name_for_ex = line.rpartition(" ")[0]
            exemplars[name_for_ex] = {
                "labels": ex_labels,
                "value": float(ev),
                "ts": float(ets),
            }
        name, _, value = line.rpartition(" ")
        assert name, f"malformed sample line {line!r}"
        samples[name] = float(value.replace("+Inf", "inf"))
    if with_exemplars:
        return samples, types, exemplars
    return samples, types


# ---- registry ----


class TestRegistry:
    def test_counter_gauge_basics(self):
        r = MetricsRegistry()
        c = r.counter("wavetpu_t_total", "things")
        c.inc()
        c.inc(2.5)
        assert c.value() == 3.5
        with pytest.raises(ValueError, match="decrease"):
            c.inc(-1)
        g = r.gauge("wavetpu_t_gauge", "level")
        g.set(7)
        g.dec(2)
        assert g.value() == 5

    def test_labels_and_reregistration(self):
        r = MetricsRegistry()
        c = r.counter("wavetpu_l_total", "labeled", ("path",))
        c.inc(path="roll")
        c.inc(3, path="kfused")
        assert c.value(path="roll") == 1
        assert c.value(path="kfused") == 3
        # idempotent re-registration returns the same child
        assert r.counter("wavetpu_l_total", "labeled", ("path",)) is c
        # type or labelname mismatch is a loud error, not a silent fork
        with pytest.raises(ValueError, match="already registered"):
            r.gauge("wavetpu_l_total", "labeled", ("path",))
        with pytest.raises(ValueError, match="already registered"):
            r.counter("wavetpu_l_total", "labeled", ("other",))
        # wrong labels at call time
        with pytest.raises(ValueError, match="wants labels"):
            c.inc(nope="x")

    def test_histogram_buckets_cumulative(self):
        r = MetricsRegistry()
        h = r.histogram("wavetpu_h_seconds", "lat", buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 5.0):
            h.observe(v)
        samples, types = parse_prometheus(r.render_prometheus())
        assert types["wavetpu_h_seconds"] == "histogram"
        assert samples['wavetpu_h_seconds_bucket{le="0.1"}'] == 1
        assert samples['wavetpu_h_seconds_bucket{le="1"}'] == 2
        assert samples['wavetpu_h_seconds_bucket{le="+Inf"}'] == 3
        assert samples["wavetpu_h_seconds_count"] == 3
        assert samples["wavetpu_h_seconds_sum"] == pytest.approx(5.55)

    def test_label_escaping(self):
        r = MetricsRegistry()
        c = r.counter("wavetpu_esc_total", "esc", ("src",))
        c.inc(src='a"b\\c\nd')
        text = r.render_prometheus()
        assert 'wavetpu_esc_total{src="a\\"b\\\\c\\nd"} 1' in text
        # the escaped line round-trips through the parser
        samples, _ = parse_prometheus(text)
        assert samples['wavetpu_esc_total{src="a\\"b\\\\c\\nd"}'] == 1

    def test_snapshot_and_text_agree(self):
        r = MetricsRegistry()
        r.counter("wavetpu_a_total", "a").inc(4)
        r.gauge("wavetpu_b", "b").set(2.5)
        snap = r.snapshot()
        samples, _ = parse_prometheus(r.render_prometheus())
        assert snap["wavetpu_a_total"] == samples["wavetpu_a_total"] == 4
        assert snap["wavetpu_b"] == samples["wavetpu_b"] == 2.5

    def test_histogram_exemplars_openmetrics_only(self):
        """Exemplars pin a request id to the bucket an observation
        landed in, render ONLY in the openmetrics view (`# {labels} v
        ts` + `# EOF`), and the classic 0.0.4 text stays byte-stable
        for parsers that do not speak the suffix."""
        r = MetricsRegistry()
        h = r.histogram("wavetpu_ex_seconds", "lat", buckets=(0.1, 1.0))
        h.observe(0.05, exemplar={"request_id": "lg-1"})
        h.observe(0.5)  # no exemplar for this bucket
        h.observe(7.0, exemplar={"request_id": "lg-3"})
        plain = r.render_prometheus()
        assert " # " not in plain and "# EOF" not in plain
        parse_prometheus(plain)  # still valid 0.0.4
        om = r.render_prometheus(openmetrics=True)
        samples, types, exemplars = parse_prometheus(
            om, with_exemplars=True
        )
        assert om.rstrip().endswith("# EOF")
        assert types["wavetpu_ex_seconds"] == "histogram"
        # the 0.05 observation landed in the le=0.1 bucket...
        ex = exemplars['wavetpu_ex_seconds_bucket{le="0.1"}']
        assert ex["labels"] == {"request_id": "lg-1"}
        assert ex["value"] == pytest.approx(0.05)
        assert ex["ts"] > 0
        # ...the 7.0 one overflowed to +Inf...
        assert exemplars['wavetpu_ex_seconds_bucket{le="+Inf"}'][
            "labels"
        ] == {"request_id": "lg-3"}
        # ...and the exemplar-less bucket has none.
        assert 'wavetpu_ex_seconds_bucket{le="1"}' not in exemplars
        # counts are untouched by exemplar bookkeeping
        assert samples["wavetpu_ex_seconds_count"] == 3

    def test_openmetrics_counter_family_drops_total_suffix(self):
        """OpenMetrics names a counter FAMILY without the _total suffix
        (samples keep it); the 0.0.4 view keeps the historical
        full-name TYPE line so existing scrapes are untouched."""
        r = MetricsRegistry()
        r.counter("wavetpu_om_total", "c").inc()
        om = r.render_prometheus(openmetrics=True)
        assert "# TYPE wavetpu_om counter" in om
        assert "\nwavetpu_om_total 1" in om
        plain = r.render_prometheus()
        assert "# TYPE wavetpu_om_total counter" in plain

    def test_exemplar_latest_wins_per_bucket(self):
        r = MetricsRegistry()
        h = r.histogram("wavetpu_ex2_seconds", "lat", buckets=(1.0,))
        h.observe(0.1, exemplar={"request_id": "a"})
        h.observe(0.2, exemplar={"request_id": "b"})
        _, _, exemplars = parse_prometheus(
            r.render_prometheus(openmetrics=True), with_exemplars=True
        )
        assert exemplars['wavetpu_ex2_seconds_bucket{le="1"}'][
            "labels"
        ] == {"request_id": "b"}

    def test_snapshot_is_one_consistent_cut(self):
        # A writer bumps two counters under the registry lock; no
        # snapshot may ever observe them out of step.
        r = MetricsRegistry()
        a = r.counter("wavetpu_pair_a_total", "a")
        b = r.counter("wavetpu_pair_b_total", "b")
        stop = threading.Event()

        def writer():
            while not stop.is_set():
                with r.lock:
                    a.inc()
                    b.inc()

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        try:
            for _ in range(200):
                snap = r.snapshot()
                assert snap["wavetpu_pair_a_total"] == \
                    snap["wavetpu_pair_b_total"]
        finally:
            stop.set()
            t.join()


# ---- tracing ----


class TestTracing:
    def test_disabled_tracer_is_noop(self):
        tracing.disable()
        assert tracing.begin_span("x") is None
        tracing.end_span(None)
        tracing.event("x", a=1)  # no crash, nothing written
        with tracing.span("x", a=1) as attrs:
            attrs["b"] = 2  # throwaway dict

    def test_spans_nest_and_link(self, tmp_path):
        path = str(tmp_path / "trace.jsonl")
        tracing.configure(path)
        try:
            with tracing.span("outer", who="parent"):
                with tracing.span("inner") as attrs:
                    attrs["found"] = 42
                tracing.event("ping", n=1)
        finally:
            tracing.disable()
        recs = [json.loads(line) for line in open(path)]
        by_kind = {r["kind"]: r for r in recs}
        # inner closes first (JSONL is emission-ordered)
        assert [r["kind"] for r in recs] == ["inner", "ping", "outer"]
        assert by_kind["inner"]["parent_id"] == by_kind["outer"]["span_id"]
        assert by_kind["ping"]["parent_id"] == by_kind["outer"]["span_id"]
        assert by_kind["inner"]["attrs"]["found"] == 42
        assert by_kind["outer"]["attrs"]["who"] == "parent"
        assert by_kind["outer"]["dur_s"] >= by_kind["inner"]["dur_s"]
        assert by_kind["ping"]["type"] == "event"

    def test_parenthood_is_thread_local(self, tmp_path):
        path = str(tmp_path / "trace.jsonl")
        tracing.configure(path)
        try:
            with tracing.span("main-span"):
                done = threading.Event()

                def other():
                    with tracing.span("other-thread"):
                        pass
                    done.set()

                threading.Thread(target=other).start()
                assert done.wait(10)
        finally:
            tracing.disable()
        recs = {r["kind"]: r for r in
                (json.loads(line) for line in open(path))}
        assert recs["other-thread"]["parent_id"] is None

    def test_attr_named_kind_allowed(self, tmp_path):
        path = str(tmp_path / "trace.jsonl")
        tracing.configure(path)
        try:
            tracing.event("checkpoint.save", kind="single", step=3)
        finally:
            tracing.disable()
        (rec,) = [json.loads(line) for line in open(path)]
        assert rec["kind"] == "checkpoint.save"
        assert rec["attrs"]["kind"] == "single"

    def test_end_span_idempotent(self, tmp_path):
        """A crash-path end_span can race the normal end on the same
        handle (supervisor's except handler after a chunk span already
        closed); the second end must be a silent no-op - one record, no
        KeyError masking the original exception, clean parent stack."""
        path = str(tmp_path / "trace.jsonl")
        tracing.configure(path)
        try:
            h = tracing.begin_span("x", a=1)
            tracing.end_span(h, ok=True)
            tracing.end_span(h, error="boom")  # must not raise or emit
            assert tracing.get_tracer().current_span_id() is None
        finally:
            tracing.disable()
        (rec,) = [json.loads(line) for line in open(path)]
        assert rec["attrs"] == {"a": 1, "ok": True}


class TestTraceContext:
    """W3C trace-context plumbing (docs/observability.md "Distributed
    tracing"): traceparent parse/format, remote-parent adoption, trace
    id inheritance and stamping, and cross-trace links."""

    def test_mint_and_roundtrip(self):
        tid, sid = tracing.mint_trace_id(), tracing.mint_span_id()
        assert len(tid) == 32 and len(sid) == 16
        int(tid, 16), int(sid, 16)
        header = tracing.format_traceparent(tid, sid)
        assert tracing.parse_traceparent(header) == (tid, sid)

    def test_parse_rejects_garbage(self):
        bad = [
            None, "", "garbage", "00-abc-def-01",
            "00-" + "g" * 32 + "-" + "1" * 16 + "-01",   # non-hex
            "00-" + "0" * 32 + "-" + "1" * 16 + "-01",   # zero trace
            "00-" + "1" * 32 + "-" + "0" * 16 + "-01",   # zero parent
            "ff-" + "1" * 32 + "-" + "2" * 16 + "-01",   # reserved ver
            "00-" + "1" * 31 + "-" + "2" * 16 + "-01",   # short trace
            "00-" + "1" * 32 + "-" + "2" * 16 + "-01-x",  # 5 fields
        ]
        for header in bad:
            assert tracing.parse_traceparent(header) is None, header

    def test_remote_adoption_and_inheritance(self, tmp_path):
        """A span opened with remote=(tid, wire_parent) records that
        exact parentage, and SAME-THREAD children inherit the trace id
        through the stack."""
        path = str(tmp_path / "trace.jsonl")
        tracing.configure(path)
        tid = tracing.mint_trace_id()
        wire = tracing.mint_span_id()
        try:
            with tracing.span("rx", remote=(tid, wire)):
                with tracing.span("child"):
                    tracing.event("tick")
        finally:
            tracing.disable()
        recs = {r["kind"]: r for r in
                (json.loads(line) for line in open(path))}
        assert recs["rx"]["parent_id"] == wire
        assert recs["rx"]["trace_id"] == tid
        assert recs["child"]["trace_id"] == tid
        assert recs["child"]["parent_id"] == recs["rx"]["span_id"]
        assert recs["tick"]["trace_id"] == tid

    def test_trace_id_stamp_without_parenthood(self, tmp_path):
        """trace_id= alone (the scheduler-thread serve.chunk case)
        stamps the record but leaves it a tree root."""
        path = str(tmp_path / "trace.jsonl")
        tracing.configure(path)
        tid = tracing.mint_trace_id()
        try:
            with tracing.span("chunk", trace_id=tid):
                pass
        finally:
            tracing.disable()
        (rec,) = [json.loads(line) for line in open(path)]
        assert rec["trace_id"] == tid and rec["parent_id"] is None

    def test_links_recorded(self, tmp_path):
        path = str(tmp_path / "trace.jsonl")
        tracing.configure(path)
        link = {"trace_id": "ab" * 16, "span_id": "cd" * 8}
        try:
            with tracing.span("resumed", links=[link]):
                pass
            with tracing.span("plain"):
                pass
        finally:
            tracing.disable()
        recs = {r["kind"]: r for r in
                (json.loads(line) for line in open(path))}
        assert recs["resumed"]["links"] == [link]
        assert "links" not in recs["plain"]

    def test_untraced_records_carry_no_trace_id(self, tmp_path):
        path = str(tmp_path / "trace.jsonl")
        tracing.configure(path)
        try:
            with tracing.span("solo"):
                pass
        finally:
            tracing.disable()
        (rec,) = [json.loads(line) for line in open(path)]
        assert "trace_id" not in rec


class TestTraceJoiner:
    """The cross-process joiner: wire-id resolution, multi-source
    merge, link-following trace closure, and the --dir CLI."""

    @staticmethod
    def _two_tier_trace(tmp_path):
        """A router + replica trace pair for one request 'r-1', plus an
        unrelated request on the replica."""
        tid = tracing.mint_trace_id()
        att_w3c = tracing.mint_span_id()
        router = tmp_path / "router"
        replica = tmp_path / "replica"
        tr = tracing.Tracer(str(router / "trace.jsonl"))
        h = tr.begin("router.request",
                     {"request_id": "r-1", "w3c_id": "aa" * 8},
                     remote=(tid, None))
        ha = tr.begin("router.attempt",
                      {"request_id": "r-1", "w3c_id": att_w3c})
        tr.end(ha, status=200)
        tr.end(h, status=200)
        tr.close()
        t2 = tracing.Tracer(str(replica / "trace.jsonl"))
        t2._prefix = "fffe"  # simulate a second process
        h2 = t2.begin("serve.request",
                      {"request_id": "r-1", "w3c_id": "bb" * 8},
                      remote=(tid, att_w3c))
        t2.end(h2, status=200)
        h3 = t2.begin("serve.request", {"request_id": "r-2"},
                      remote=(tracing.mint_trace_id(), None))
        t2.end(h3, status=200)
        t2.close()
        return str(router), str(replica), tid

    def test_join_resolves_wire_parent(self, tmp_path):
        router, replica, tid = self._two_tier_trace(tmp_path)
        records = obs_report.load_traces([
            os.path.join(router, "trace.jsonl"),
            os.path.join(replica, "trace.jsonl"),
        ])
        joined = obs_report.join_processes(records)
        by_kind = {r["kind"]: r for r in joined
                   if r["attrs"].get("request_id") == "r-1"}
        assert (by_kind["serve.request"]["parent_id"]
                == by_kind["router.attempt"]["span_id"])
        view = obs_report.request_view(records, "r-1")
        kinds = [r["kind"] for r in view]
        assert kinds == ["router.request", "router.attempt",
                         "serve.request"]
        assert {r["trace_id"] for r in view} == {tid}
        text = obs_report.format_request_view(view, "r-1")
        assert "joined across 2 processes" in text
        assert "<-hop" in text

    def test_unresolvable_wire_parent_roots_cleanly(self, tmp_path):
        """A replica-only view (upstream dir not passed) must render the
        serve.request as a root, not dangle under an unknown parent."""
        _, replica, _ = self._two_tier_trace(tmp_path)
        records = obs_report.load_trace(
            os.path.join(replica, "trace.jsonl"))
        view = obs_report.request_view(records, "r-1")
        assert [r["kind"] for r in view] == ["serve.request"]
        assert view[0]["parent_id"] is None

    def test_link_closure_joins_resume_chain_both_ways(self, tmp_path):
        """A march resumed under a FRESH trace links back to the
        originating request; querying by EITHER request id must pull in
        the whole chain."""
        t = tracing.Tracer(str(tmp_path / "trace.jsonl"))
        tid1, tid2 = tracing.mint_trace_id(), tracing.mint_trace_id()
        h = t.begin("serve.request", {"request_id": "orig"},
                    remote=(tid1, None))
        origin = [tid1, "ee" * 8]
        t.end(h, status=504)
        h2 = t.begin("serve.request", {"request_id": "resumed"},
                     remote=(tid2, None))
        t.end(h2, status=200)
        hc = t.begin(
            "serve.chunk", {"request_id": "resumed"}, trace_id=tid2,
            links=[{"trace_id": origin[0], "span_id": origin[1]}],
        )
        t.end(hc)
        t.close()
        records = obs_report.load_trace(str(tmp_path / "trace.jsonl"))
        for rid in ("orig", "resumed"):
            view = obs_report.request_view(records, rid)
            kinds = sorted(r["kind"] for r in view)
            assert kinds == ["serve.chunk", "serve.request",
                             "serve.request"], (rid, kinds)
        text = obs_report.format_request_view(
            obs_report.request_view(records, "orig"), "orig")
        assert "~>resumed-from" in text

    def test_cli_multi_dir(self, tmp_path, capsys):
        from wavetpu.cli import main

        router, replica, _ = self._two_tier_trace(tmp_path)
        assert main(["trace-report", "--dir", router, "--dir", replica,
                     "--request", "r-1"]) == 0
        out = capsys.readouterr().out
        assert "router.attempt" in out and "serve.request" in out
        # summary mode merges too
        assert main(["trace-report", "--dir", router,
                     "--dir", replica]) == 0
        out = capsys.readouterr().out
        assert "router.request" in out and "serve.request" in out
        # no sources is a usage error
        assert main(["trace-report"]) == 2

    def test_multi_source_merge_includes_rotated(self, tmp_path):
        """--dir merges each source's rotated segment set oldest-first
        (the long-lived-server case)."""
        a = tmp_path / "a"
        tracing.configure(str(a / "trace.jsonl"), max_bytes=300, keep=3)
        try:
            for i in range(12):
                tracing.event("rot.tick", n=i)
        finally:
            tracing.disable()
        b = tmp_path / "b"
        tracing.configure(str(b / "trace.jsonl"))
        try:
            tracing.event("other.tick", n=99)
        finally:
            tracing.disable()
        records = obs_report.load_traces([
            str(a / "trace.jsonl"), str(b / "trace.jsonl"),
        ])
        kinds = {r["kind"] for r in records}
        assert kinds == {"rot.tick", "other.tick"}
        ns = [r["attrs"]["n"] for r in records
              if r["kind"] == "rot.tick"]
        assert ns == sorted(ns) and ns[-1] == 11 and len(ns) > 1


class TestMetricCatalogLint:
    """Every wavetpu_* metric the code constructs must be documented in
    docs/observability.md's metric catalog - an undocumented metric is
    a tier-1 failure, not a drive-by (ISSUE: the catalog is the
    contract operators alert on)."""

    @staticmethod
    def _constructed_metrics():
        import re

        root = os.path.join(os.path.dirname(__file__), "..", "wavetpu")
        ctor = re.compile(
            r"(?:counter|gauge|histogram)\(\s*['\"]"
            r"(wavetpu_[a-z0-9_]+)['\"]"
        )
        # The router renders its own samples as text, not through the
        # registry - catch every full-name literal there too.  The
        # control-plane store and HA coordinator do the same with
        # wavetpu_store_* / wavetpu_fleet_* samples.
        literal_res = {
            "router.py": re.compile(
                r"['\"](wavetpu_router_[a-z0-9_]+)"
            ),
            "store.py": re.compile(
                r"['\"](wavetpu_store_[a-z0-9_]+)"
            ),
            "ha.py": re.compile(
                r"['\"](wavetpu_fleet_[a-z0-9_]+)"
            ),
        }
        names = set()
        for dirpath, _dirs, files in os.walk(root):
            if "__pycache__" in dirpath:
                continue
            for fn in files:
                if not fn.endswith(".py"):
                    continue
                src = open(os.path.join(dirpath, fn),
                           encoding="utf-8").read()
                names.update(ctor.findall(src))
                lit = literal_res.get(fn)
                if lit is not None:
                    names.update(
                        m for m in lit.findall(src)
                        if not m.endswith("_")
                    )
        return names

    def test_every_constructed_metric_is_documented(self):
        import re

        doc = open(
            os.path.join(os.path.dirname(__file__), "..", "docs",
                         "observability.md"),
            encoding="utf-8",
        ).read()
        documented = set(re.findall(r"wavetpu_[a-z0-9_]+", doc))
        constructed = self._constructed_metrics()
        assert constructed, "lint found no metrics - pattern broke?"
        missing = sorted(constructed - documented)
        assert not missing, (
            f"metrics constructed in wavetpu/ but absent from "
            f"docs/observability.md's catalog: {missing}"
        )


class TestTraceRotation:
    """Size-based telemetry rotation: a long-lived server must not grow
    trace.jsonl / heartbeat.jsonl forever (keep-last-K segments, atomic
    os.replace shifts), and trace-report reads the whole rotated set."""

    def test_tracer_rotates_and_keeps_k_segments(self, tmp_path):
        path = str(tmp_path / "trace.jsonl")
        # ~120 B records against a 400 B cap: every few events rotate.
        tracing.configure(path, max_bytes=400, keep=3)
        try:
            for i in range(40):
                tracing.event("rot.tick", n=i)
        finally:
            tracing.disable()
        segs = [p.name for p in sorted(tmp_path.iterdir())]
        assert "trace.jsonl" in segs
        assert "trace.jsonl.1" in segs and "trace.jsonl.2" in segs
        assert "trace.jsonl.3" not in segs  # keep=3 total segments
        for p in tmp_path.iterdir():
            assert p.stat().st_size <= 400 + 200  # cap + one record slack

    def test_load_trace_reads_rotated_set_oldest_first(self, tmp_path):
        path = str(tmp_path / "trace.jsonl")
        tracing.configure(path, max_bytes=400, keep=4)
        try:
            for i in range(30):
                tracing.event("rot.tick", n=i)
        finally:
            tracing.disable()
        records = obs_report.load_trace(path)
        ns = [r["attrs"]["n"] for r in records]
        # the retained window is contiguous, ordered, and ends at the
        # newest record; older-than-window records were GCed
        assert ns == list(range(ns[0], 30))
        # include_rotated=False reads only the live segment
        live = obs_report.load_trace(path, include_rotated=False)
        assert len(live) < len(records)
        # segments enumerate oldest -> newest, live file last
        segs = obs_report.trace_segments(path)
        assert segs[-1] == path and len(segs) >= 2

    def test_heartbeat_rotation(self, tmp_path):
        reg = MetricsRegistry()
        reg.counter("wavetpu_beats_total", "x").inc()
        tel = telemetry.start(str(tmp_path), registry=reg,
                              interval=60.0, max_bytes=300, keep=2)
        try:
            for _ in range(20):
                tel.beat()
        finally:
            tel.stop()
        assert (tmp_path / "heartbeat.jsonl").exists()
        assert (tmp_path / "heartbeat.jsonl.1").exists()
        assert not (tmp_path / "heartbeat.jsonl.2").exists()
        # every retained line is whole JSON (atomic rotation, no tears)
        for name in ("heartbeat.jsonl", "heartbeat.jsonl.1"):
            for line in open(tmp_path / name):
                assert "metrics" in json.loads(line)

    def test_rotation_disabled_by_default_for_direct_configure(
        self, tmp_path
    ):
        path = str(tmp_path / "trace.jsonl")
        tracing.configure(path)
        try:
            for i in range(50):
                tracing.event("rot.tick", n=i)
        finally:
            tracing.disable()
        assert not (tmp_path / "trace.jsonl.1").exists()
        assert len(obs_report.load_trace(path)) == 50


# ---- trace-report ----


def _synthetic_trace(tmp_path):
    recs = [
        {"type": "span", "kind": "serve.request", "span_id": "p-1",
         "parent_id": None, "t_start": 10.0, "dur_s": 0.50,
         "attrs": {"request_id": "p-9", "status": 200}},
        {"type": "span", "kind": "serve.execute", "span_id": "p-3",
         "parent_id": "p-2", "t_start": 10.1, "dur_s": 0.30,
         "attrs": {"warm": True}},
        {"type": "span", "kind": "serve.batch", "span_id": "p-2",
         "parent_id": None, "t_start": 10.05, "dur_s": 0.40,
         "attrs": {"request_ids": ["p-9"], "occupancy": 2}},
        {"type": "span", "kind": "serve.request", "span_id": "p-4",
         "parent_id": None, "t_start": 11.0, "dur_s": 0.10,
         "attrs": {"request_id": "p-8", "status": 400}},
        {"type": "event", "kind": "supervisor.retry", "span_id": "p-5",
         "parent_id": None, "t_start": 12.0, "attrs": {"step": 4}},
    ]
    path = tmp_path / "t.jsonl"
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
        f.write("not json\n")  # mid-write tail must not be fatal
    return str(path)


class TestTraceReport:
    def test_summarize(self, tmp_path):
        records = obs_report.load_trace(_synthetic_trace(tmp_path))
        s = obs_report.summarize(records)
        assert s["spans"]["serve.request"]["count"] == 2
        assert s["spans"]["serve.request"]["total_s"] == pytest.approx(0.6)
        assert s["spans"]["serve.request"]["p95_ms"] == pytest.approx(500.0)
        assert s["events"] == {"supervisor.retry": 1}
        text = obs_report.format_summary(s)
        assert "serve.request" in text and "p95_ms" in text

    def test_request_view_joins_batch_and_descendants(self, tmp_path):
        records = obs_report.load_trace(_synthetic_trace(tmp_path))
        view = obs_report.request_view(records, "p-9")
        kinds = [r["kind"] for r in view]
        # the request span, the batch tagged with its id, AND the
        # batch's untagged execute child - the other request excluded
        assert kinds == ["serve.request", "serve.batch", "serve.execute"]
        text = obs_report.format_request_view(view, "p-9")
        assert "serve.execute" in text

    def test_cli_subcommand(self, tmp_path, capsys):
        from wavetpu.cli import main

        path = _synthetic_trace(tmp_path)
        assert main(["trace-report", path]) == 0
        out = capsys.readouterr().out
        assert "serve.batch" in out
        assert main(["trace-report", path, "--request", "p-9"]) == 0
        assert "critical path of request p-9" in capsys.readouterr().out
        assert main(["trace-report"]) == 2
        assert main(["trace-report", str(tmp_path / "missing.jsonl")]) == 2


# ---- telemetry dir ----


class TestTelemetry:
    def test_heartbeat_and_prom_files(self, tmp_path):
        reg = MetricsRegistry()
        reg.counter("wavetpu_beats_total", "x").inc(5)
        tel = telemetry.start(str(tmp_path), registry=reg, interval=60.0)
        try:
            tracing.event("hello", n=1)
        finally:
            tel.stop()
        beats = [json.loads(line)
                 for line in open(tmp_path / "heartbeat.jsonl")]
        assert beats  # stop() always writes a final beat
        assert beats[-1]["metrics"]["wavetpu_beats_total"] == 5
        samples, _ = parse_prometheus(open(tmp_path / "metrics.prom").read())
        assert samples["wavetpu_beats_total"] == 5
        recs = [json.loads(line) for line in open(tmp_path / "trace.jsonl")]
        assert recs[0]["kind"] == "hello"
        # tracer is torn down with the handle
        assert not tracing.enabled()


# ---- solver counters ----


class TestSolveCounters:
    def test_leapfrog_solve_increments_registry(self, small_problem):
        from wavetpu.solver import leapfrog

        reg = get_registry()
        c = reg.counter("wavetpu_solves_total",
                        "completed solve entry points", ("path",))
        before = c.value(path="leapfrog")
        cells = reg.counter(
            "wavetpu_solve_cells_total",
            "cell updates marched ((N+1)^3 per layer)", ("path",),
        )
        cells_before = cells.value(path="leapfrog")
        leapfrog.solve(small_problem)
        assert c.value(path="leapfrog") == before + 1
        expected = (
            small_problem.cells_per_step * small_problem.timesteps
        )
        assert cells.value(path="leapfrog") - cells_before == \
            pytest.approx(expected)


# ---- acceptance: supervised multi-chunk run under --telemetry-dir ----


class TestSupervisedTelemetry:
    def test_chunk_spans_match_checkpoint_rotation(self, tmp_path):
        """The ISSUE's acceptance drill: a supervised multi-chunk run
        with telemetry on emits chunk spans whose boundaries equal the
        checkpoint steps (spans AND rotation entries on disk), and
        trace-report summarizes them."""
        from wavetpu.cli import main
        from wavetpu.run.supervisor import _entry_step

        tel = tmp_path / "tel"
        ckpt = tmp_path / "ckpt"
        rc = main([
            "16", "1", "1", "1", "1", "1", "12", "--backend", "single",
            "--ckpt-every", "4", "--ckpt-dir", str(ckpt),
            "--telemetry-dir", str(tel), "--out-dir", str(tmp_path),
        ])
        assert rc == 0
        recs = [json.loads(line) for line in open(tel / "trace.jsonl")]
        chunk_ends = sorted(
            r["attrs"]["end"] for r in recs
            if r["kind"] == "supervisor.chunk"
        )
        ckpt_steps = sorted(
            r["attrs"]["step"] for r in recs
            if r["kind"] == "supervisor.checkpoint"
        )
        # ckpt_every=4 over 12 layers: first chunk marches 1+4, then 4+3
        assert chunk_ends == [5, 9, 12]
        assert ckpt_steps == chunk_ends
        # ...and the spans agree with the rotation on disk (keep-2 GC
        # leaves the newest two entries).
        disk_steps = sorted(
            s for e in os.listdir(ckpt)
            if (s := _entry_step(e)) is not None
        )
        assert disk_steps == ckpt_steps[-2:]
        # every chunk span nests under the one supervisor.march span
        march = [r for r in recs if r["kind"] == "supervisor.march"]
        assert len(march) == 1
        assert march[0]["attrs"]["status"] == "complete"
        for r in recs:
            if r["kind"] == "supervisor.chunk":
                assert r["parent_id"] == march[0]["span_id"]
        # io-layer events carry byte counts
        saves = [r for r in recs if r["kind"] == "checkpoint.save"]
        assert saves and all(r["attrs"]["bytes"] > 0 for r in saves)
        # heartbeat carries the supervisor counters
        beats = [json.loads(line) for line in open(tel / "heartbeat.jsonl")]
        assert beats[-1]["metrics"]["wavetpu_supervisor_checkpoints_total"] \
            >= 3
        # and trace-report summarizes the trace
        s = obs_report.summarize(recs)
        assert s["spans"]["supervisor.chunk"]["count"] == 3
        assert "supervisor.checkpoint" in s["spans"]
        assert not tracing.enabled()  # CLI tore telemetry down

    def test_seed_checkpoint_counted(self, small_problem, tmp_path):
        """An injected-state resume into an empty rotation root seeds
        the rotation with the caller's checkpoint; the registry counter
        must count that entry like SupervisedResult.checkpoints_written
        (else the counters-vs-rotation audit reports a false mismatch)."""
        from wavetpu.io import checkpoint
        from wavetpu.run import supervisor as sup

        c = get_registry().counter(
            "wavetpu_supervisor_checkpoints_total",
            "rotation entries written",
        )
        r = sup.supervise(
            small_problem, sup.PathSpec(),
            sup.SupervisorOptions(ckpt_every=3,
                                  ckpt_dir=str(tmp_path / "rot")),
        )
        _, u_prev, u_cur, step = checkpoint.load_checkpoint(
            r.checkpoint_path
        )
        before = c.value()
        r2 = sup.supervise(
            small_problem, sup.PathSpec(),
            sup.SupervisorOptions(ckpt_every=3,
                                  ckpt_dir=str(tmp_path / "rot2")),
            state=(u_prev, u_cur), start_step=step,
        )
        assert r2.checkpoints_written >= 2  # the seed + the final save
        assert c.value() - before == r2.checkpoints_written

    def test_crash_mid_dispatch_stops_telemetry(self, tmp_path,
                                                monkeypatch):
        """An exception inside the solve dispatch must still emit the
        open cli.solve span, stop the heartbeat daemon, and unbind the
        process tracer - in-process callers (this test) never reach the
        atexit net, and a later run must not inherit a stale tracer."""
        from wavetpu.cli import main
        from wavetpu.solver import leapfrog

        def boom(*a, **kw):
            raise RuntimeError("injected mid-dispatch failure")

        monkeypatch.setattr(leapfrog, "solve", boom)
        tel = tmp_path / "tel"
        with pytest.raises(RuntimeError, match="injected"):
            main([
                "16", "1", "1", "1", "1", "1", "10", "--backend",
                "single", "--kernel", "roll", "--telemetry-dir",
                str(tel), "--out-dir", str(tmp_path),
            ])
        assert not tracing.enabled()
        recs = [json.loads(line) for line in open(tel / "trace.jsonl")]
        (span,) = [r for r in recs if r["kind"] == "cli.solve"]
        assert span["attrs"]["aborted"] is True
        # the final heartbeat landed too
        assert (tel / "heartbeat.jsonl").exists()
