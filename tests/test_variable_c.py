"""Variable wave speed c(x,y,z) and bf16-state/fp32-accum mode.

BASELINE.md stretch config 5.  The variable-c update is a capability
extension over the reference (its a^2 is a hardcoded __constant__,
openmp_sol.cpp:207, cuda_sol_kernels.cu:3); the constant-field case must
collapse to the scalar path exactly, which pins the new code to the tested
one.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from wavetpu.kernels import stencil_pallas, stencil_ref
from wavetpu.solver import leapfrog


def _c2_bump(problem):
    """A smooth positive speed-squared field, max value a2 (so the constant-
    speed Courant bound still guarantees stability)."""

    def fn(x, y, z):
        return problem.a2 * (
            0.5 + 0.5 * np.sin(2 * np.pi * x) * np.sin(np.pi * y) ** 2
        ) / 1.0

    return stencil_ref.make_c2tau2_field(problem, fn)


def test_constant_field_matches_scalar_path(small_problem):
    """c^2(x,y,z) == a^2 everywhere must reproduce the scalar solver."""
    field = stencil_ref.make_c2tau2_field(
        small_problem, lambda x, y, z: small_problem.a2
    )
    assert field == pytest.approx(small_problem.a2tau2)
    ref = leapfrog.solve(small_problem)
    var = leapfrog.solve(
        small_problem, step_fn=stencil_ref.make_variable_c_step(field)
    )
    np.testing.assert_allclose(
        np.asarray(var.u_cur), np.asarray(ref.u_cur), atol=1e-7, rtol=0.0
    )


def test_variable_c_stays_finite(small_problem):
    field = _c2_bump(small_problem)
    res = leapfrog.solve(
        small_problem,
        step_fn=stencil_ref.make_variable_c_step(field),
        compute_errors=False,
    )
    u = np.asarray(res.u_cur)
    assert np.isfinite(u).all()
    # The field genuinely varies, and the solution differs from constant-c.
    ref = leapfrog.solve(small_problem, compute_errors=False)
    assert np.max(np.abs(u - np.asarray(ref.u_cur))) > 1e-6
    # Dirichlet invariant survives the variable-c update.
    assert np.all(u[:, 0, :] == 0.0)
    assert np.all(u[:, :, 0] == 0.0)


def test_variable_c_bootstrap_uses_field(small_problem):
    """Layer 1 must be u0 + (tau^2 c^2(x)/2) lap(u0) with the FIELD, not the
    constant a^2 (make_solver derives it from the step function)."""
    from wavetpu.core.problem import Problem

    field = _c2_bump(small_problem)
    p1 = Problem(
        N=small_problem.N, timesteps=1, T=small_problem.T / small_problem.timesteps
    )  # same tau; scan range empty, so u_cur == layer 1
    field1 = _c2_bump(p1)
    res = leapfrog.solve(
        p1,
        step_fn=stencil_ref.make_variable_c_step(field1),
        compute_errors=False,
    )
    u0 = leapfrog.initial_layer0(p1)
    lap = stencil_ref.laplacian(u0, p1.inv_h2)
    want = stencil_ref.apply_dirichlet(
        u0 + 0.5 * jnp.asarray(field1, u0.dtype) * lap
    )
    np.testing.assert_allclose(
        np.asarray(res.u_cur), np.asarray(want), atol=1e-7, rtol=0.0
    )


def test_pallas_variable_c_matches_ref(small_problem):
    field = _c2_bump(small_problem)
    rng = np.random.default_rng(3)
    n = small_problem.N
    u_prev = stencil_ref.apply_dirichlet(
        jnp.asarray(rng.standard_normal((n, n, n)), jnp.float32)
    )
    u = stencil_ref.apply_dirichlet(
        jnp.asarray(rng.standard_normal((n, n, n)), jnp.float32)
    )
    want = stencil_ref.make_variable_c_step(field)(u_prev, u, small_problem)
    got = stencil_pallas.make_step_fn(
        block_x=2, interpret=True, c2tau2_field=field
    )(u_prev, u, small_problem)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-6, rtol=1e-6
    )


def test_bf16_state_f32_accum(small_problem):
    """bf16 state stays stable and lands within bf16-resolution of f32."""
    res16 = leapfrog.solve(small_problem, dtype=jnp.bfloat16)
    res32 = leapfrog.solve(small_problem, dtype=jnp.float32)
    assert res16.u_cur.dtype == jnp.bfloat16
    u16 = np.asarray(res16.u_cur, dtype=np.float32)
    u32 = np.asarray(res32.u_cur)
    assert np.isfinite(u16).all()
    # bf16 has ~3 decimal digits; the trajectory should track f32 loosely.
    assert np.max(np.abs(u16 - u32)) < 0.05
    # Error oracle evaluates in f32 (not quantized to bf16).
    assert res16.abs_errors.dtype == np.float64
    assert res16.abs_errors.max() < 0.05


def test_bf16_pallas_step_matches_ref_step(small_problem):
    rng = np.random.default_rng(4)
    n = small_problem.N
    u_prev = stencil_ref.apply_dirichlet(
        jnp.asarray(rng.standard_normal((n, n, n)), jnp.bfloat16)
    )
    u = stencil_ref.apply_dirichlet(
        jnp.asarray(rng.standard_normal((n, n, n)), jnp.bfloat16)
    )
    want = stencil_ref.leapfrog_step(u_prev, u, small_problem)
    got = stencil_pallas.leapfrog_step(
        u_prev, u, small_problem, block_x=2, interpret=True
    )
    assert got.dtype == jnp.bfloat16
    # Both compute in f32 and round once to bf16: results should agree to
    # 1 bf16 ulp.
    np.testing.assert_allclose(
        np.asarray(got, dtype=np.float32),
        np.asarray(want, dtype=np.float32),
        atol=0.01,
        rtol=0.01,
    )

def test_variable_c_f64_self_convergence():
    """The variable-c DYNAMICS are second-order accurate: an f64
    grid-refinement chain (h -> h/2 with tau proportional to h, so both
    error terms scale together) must contract by ~4x per refinement.

    This is the convergence evidence the round-4 verdict asked for -
    constant-field collapse and one-step kernel parity pin the
    implementation, this pins the discretization of the spatially
    varying coefficient itself (the generalization of the reference's
    hardcoded __constant__ a2, cuda_sol_kernels.cu:3).  Coarse grid
    points coincide with every second fine point on the fundamental
    domain, so restriction is a plain stride-2 slice.  Measured ratios
    at these sizes: 3.993 (8->16->32), 3.894 (16->32->64).
    """

    def c2_fn(x, y, z):
        return 1.0 - 0.4 * np.exp(
            -((x - 0.5) ** 2 + (y - 0.5) ** 2 + (z - 0.5) ** 2) / 0.08
        )

    def run(n, steps):
        from wavetpu.core.problem import Problem

        p = Problem(
            N=n, Np=1, Lx=1.0, Ly=1.0, Lz=1.0, T=0.25, timesteps=steps
        )
        field = stencil_ref.make_c2tau2_field(p, c2_fn)
        res = leapfrog.solve(
            p,
            dtype=jnp.float64,
            step_fn=stencil_ref.make_variable_c_step(field),
            compute_errors=False,
        )
        return np.asarray(res.u_cur)

    u8 = run(8, 6)
    u16 = run(16, 12)
    u32 = run(32, 24)
    e1 = np.abs(u16[::2, ::2, ::2] - u8).max()
    e2 = np.abs(u32[::2, ::2, ::2] - u16).max()
    assert e1 > e2 > 0
    ratio = e1 / e2
    assert 3.5 < ratio < 4.5, ratio
