"""Accuracy observatory contracts (obs/accuracy.py, serve/shadow.py,
`wavetpu plan-report`).

The acceptance drill: a warmed server replaying a two-tier trace
(bf16-increment onion vs compensated f32) at --shadow-sample-rate 1.0
must yield a plan_table.json whose MEASURED frontier orders the two
plans correctly on BOTH axes - the bf16 plan faster, the compensated
plan >= 3 decades more accurate - with zero primary-path errors, zero
breaker events, and every shadow accounted for.  Around it: the
accuracy ledger's durability/foreign-line discipline (same contract as
obs/ledger.py), the shadow sampler's full eligibility/busy/chaos
matrix (a crashed shadow is a counter tick and nothing else), the
never-feeds-the-breaker pin at the scheduler seam, and the plan-table
join reproducing a known Pareto frontier from a fabricated ledger.
"""

import json
import os
import threading
import time
import types
import urllib.request

import numpy as np
import pytest

from wavetpu.core.problem import Problem
from wavetpu.ensemble import batched as eb
from wavetpu.obs import accuracy, telemetry, tracing
from wavetpu.obs import ledger as compile_ledger
from wavetpu.obs.registry import MetricsRegistry
from wavetpu.run import faults
from wavetpu.serve.scheduler import DynamicBatcher, SolveRequest
from wavetpu.serve.shadow import ShadowSampler


# ---- plan identity ----

class TestPlanIdentity:
    def test_n_bucket_rounds_up_to_power_of_two(self):
        assert accuracy.n_bucket(1) == 1
        assert accuracy.n_bucket(2) == 2
        assert accuracy.n_bucket(3) == 4
        assert accuracy.n_bucket(100) == 128
        assert accuracy.n_bucket(120) == 128  # shares 100's bucket
        assert accuracy.n_bucket(512) == 512

    def test_make_plan_forces_k_1_off_the_onion(self):
        assert accuracy.make_plan("standard", "roll", 4, "f32")["k"] == 1
        assert accuracy.make_plan(
            "compensated", "kfused", 4, "f32"
        )["k"] == 4

    def test_normalize_plan_rejects_unknown_and_missing(self):
        plan = accuracy.make_plan("standard", "roll", 1, "f32")
        with pytest.raises(ValueError, match="unknown plan field"):
            accuracy.normalize_plan(dict(plan, bogus=1))
        with pytest.raises(ValueError, match="missing plan field"):
            accuracy.normalize_plan({"scheme": "standard"})

    def test_dtype_name_mapping(self):
        assert accuracy.dtype_name("float32") == "f32"
        assert accuracy.dtype_name("bfloat16") == "bf16"
        assert accuracy.dtype_name(np.dtype(np.float64)) == "f64"
        # a foreign dtype passes through instead of crashing the seam
        assert accuracy.dtype_name("int8") == "int8"


def _plan(**over):
    base = dict(scheme="standard", path="kfused", k=4, dtype="bf16",
                with_field=False)
    base.update(over)
    return base


# ---- ledger durability ----

class TestAccuracyLedgerDurability:
    def test_round_trip_across_two_process_lifetimes(self, tmp_path):
        p = str(tmp_path / accuracy.ACCURACY_FILENAME)
        led = accuracy.AccuracyLedger(p)
        led.record(_plan(), 512, 1000, 0.66, 2.19, 1.35e11,
                   ts=1.0, pid=111)
        led.close()
        led2 = accuracy.AccuracyLedger(p)  # "restart": appends
        led2.record(_plan(scheme="compensated", dtype="f32"),
                    100, 50, 5.7e-6, 8.0, 5.2e7,
                    source="shadow", ts=2.0, pid=222)
        led2.close()
        recs = accuracy.load_accuracy_ledger(p)
        assert len(recs) == 2
        assert recs[0]["plan"] == accuracy.normalize_plan(_plan())
        assert recs[0]["max_abs_err"] == 0.66
        assert recs[0]["n_bucket"] == 512
        assert recs[0]["source"] == "oracle"
        assert recs[1]["n_bucket"] == 128  # N=100 rounds up
        assert recs[1]["source"] == "shadow"
        assert [r["pid"] for r in recs] == [111, 222]

    def test_foreign_and_malformed_lines_skipped(self, tmp_path, capsys):
        """Junk in the append-only file - non-JSON, a foreign record
        type, a plan a future wavetpu wrote, a non-numeric error - is
        skipped and counted, never a crash."""
        p = str(tmp_path / accuracy.ACCURACY_FILENAME)
        led = accuracy.AccuracyLedger(p)
        led.record(_plan(), 64, 48, 0.5, 1.0, 1e7, ts=1.0, pid=1)
        led.close()
        with open(p, "a") as f:
            f.write("not json\n")
            f.write(json.dumps({"type": "compile", "key": {}}) + "\n")
            f.write(json.dumps({
                "type": "accuracy", "plan": dict(_plan(), novel="x"),
                "n": 64, "max_abs_err": 1.0,
            }) + "\n")
            f.write(json.dumps({
                "type": "accuracy", "plan": _plan(), "n": 64,
                "max_abs_err": "NaNish",
            }) + "\n")
        recs = accuracy.load_accuracy_ledger(p)
        assert len(recs) == 1
        assert "skipped 4 malformed" in capsys.readouterr().err
        # the report CLI survives the same file
        assert accuracy.main([p]) == 0
        capsys.readouterr()

    def test_unconfigured_record_is_zero_file_io(self, tmp_path,
                                                 monkeypatch):
        monkeypatch.chdir(tmp_path)
        accuracy.disable()
        assert not accuracy.enabled()
        accuracy.record_accuracy(_plan(), 64, 48, 0.5, 1.0, 1e7)
        assert list(tmp_path.iterdir()) == []

    def test_telemetry_configures_and_stops_ledger(self, tmp_path):
        d = str(tmp_path / "tel")
        tel = telemetry.start(d, interval=60.0)
        try:
            assert accuracy.enabled()
            assert accuracy.get_ledger().path == os.path.join(
                d, accuracy.ACCURACY_FILENAME
            )
        finally:
            tel.stop()
        assert not accuracy.enabled()

    def test_exempt_from_telemetry_rotation(self, tmp_path):
        """Same durability clause as the compile ledger: a tiny
        max_bytes rotates trace.jsonl while accuracy_ledger.jsonl
        keeps every entry in one un-rotated file."""
        d = str(tmp_path / "tel")
        tel = telemetry.start(d, interval=60.0, max_bytes=512, keep=2)
        try:
            for i in range(40):
                tracing.event("spam", i=i, pad="x" * 64)
                accuracy.record_accuracy(
                    _plan(), 64, i + 1, 0.5, 1.0, 1e7
                )
        finally:
            tel.stop()
        assert os.path.exists(os.path.join(d, "trace.jsonl.1"))
        lp = os.path.join(d, accuracy.ACCURACY_FILENAME)
        assert not os.path.exists(lp + ".1")
        recs = accuracy.load_accuracy_ledger(lp)
        assert len(recs) == 40
        assert [r["timesteps"] for r in recs] == list(range(1, 41))


# ---- metric stamps ----

class TestErrorMetrics:
    def test_oracle_and_shadow_signals_never_collide(self):
        reg = MetricsRegistry()
        plan = _plan(scheme="compensated", path="kfused", dtype="f32")
        accuracy.record_error_metrics(reg, plan, 1e-5)
        accuracy.record_error_metrics(reg, plan, 3e-3, shadow=True)
        labels = dict(path="kfused", scheme="compensated", dtype="f32")
        assert reg.gauge(
            "wavetpu_solve_max_abs_err", "", ("path", "scheme", "dtype")
        ).value(**labels) == 1e-5
        assert reg.gauge(
            "wavetpu_shadow_divergence", "", ("path", "scheme", "dtype")
        ).value(**labels) == 3e-3

    def test_solver_entry_point_records_measured_error(self, tmp_path):
        """The instrumented-solver seam end to end: a tiny solve with
        telemetry live appends one oracle line whose max_abs_err is
        exactly the result's measured maximum."""
        from wavetpu.solver import leapfrog

        d = str(tmp_path / "tel")
        problem = Problem(N=8, timesteps=4)
        tel = telemetry.start(d, interval=60.0)
        try:
            res = leapfrog.solve(problem)
        finally:
            tel.stop()
        recs = accuracy.load_accuracy_ledger(
            os.path.join(d, accuracy.ACCURACY_FILENAME)
        )
        mine = [r for r in recs if r["n"] == 8]
        assert len(mine) == 1
        assert mine[0]["max_abs_err"] == float(res.abs_errors.max())
        assert mine[0]["timesteps"] == 4
        assert mine[0]["source"] == "oracle"

    def test_oracle_skipped_means_nothing_recorded(self, tmp_path):
        from wavetpu.solver import leapfrog

        d = str(tmp_path / "tel")
        tel = telemetry.start(d, interval=60.0)
        try:
            leapfrog.solve(Problem(N=8, timesteps=4),
                           compute_errors=False)
        finally:
            tel.stop()
        lp = os.path.join(d, accuracy.ACCURACY_FILENAME)
        assert (not os.path.exists(lp)
                or accuracy.load_accuracy_ledger(lp) == [])


# ---- shadow sampler (unit: fabricated batcher) ----

class _StubFuture:
    def __init__(self, fn):
        self._fn = fn

    def result(self, timeout=None):
        return self._fn()


class _StubBatcher:
    """Deterministic twin: returns a fixed reference array (or an
    error), optionally blocking until released - enough surface for
    every ShadowSampler path without a real engine."""

    def __init__(self, ref, error=None, release=None):
        self.ref = ref
        self.error = error
        self.release = release
        self.submits = []

    def submit(self, req, request_id=None, deadline=None,
               trace_context=None):
        self.submits.append(req)

        def run():
            if self.release is not None:
                assert self.release.wait(30.0)
            if self.error is not None:
                return None, self.error, {}
            return (
                types.SimpleNamespace(u_cur=self.ref),
                None,
                {},
            )

        return _StubFuture(run)


def _shadow_req(problem=None, **over):
    kw = dict(scheme="standard", path="kfused", k=2, dtype_name="f32")
    kw.update(over)
    return SolveRequest(
        problem=problem or Problem(N=8, timesteps=4),
        lane=kw.pop("lane", eb.LaneSpec()), **kw
    )


def _lane_result(u, solve_seconds=0.02):
    return types.SimpleNamespace(
        u_cur=u, solve_seconds=solve_seconds, steps_computed=None
    )


class TestShadowSampler:
    def test_rate_bounds_validated(self):
        reg = MetricsRegistry()
        for bad in (-0.1, 1.5):
            with pytest.raises(ValueError, match="shadow-sample-rate"):
                ShadowSampler(_StubBatcher(None), reg, bad)

    def test_eligibility_matrix(self):
        s = ShadowSampler(_StubBatcher(None), MetricsRegistry(), 1.0)
        assert s.ineligible_reason(
            _shadow_req(resume_token="tok")
        ) == "resume"
        assert s.ineligible_reason(
            _shadow_req(mesh_shape=(2, 1, 1))
        ) == "mesh"
        assert s.ineligible_reason(_shadow_req(
            scheme="compensated", path="roll", k=1
        )) == "reference-plan"
        # the onion keeps its k, so compensated kfused is NOT reference
        assert s.ineligible_reason(_shadow_req(
            scheme="compensated", path="kfused", k=4
        )) is None
        assert s.ineligible_reason(_shadow_req()) is None

    def test_reference_request_shape(self):
        s = ShadowSampler(_StubBatcher(None), MetricsRegistry(), 1.0)
        req = _shadow_req(dtype_name="bf16", priority="interactive")
        ref = s.reference_request(req)
        assert (ref.scheme, ref.path, ref.k, ref.dtype_name) == (
            "compensated", "roll", 1, "f32"
        )
        assert ref.priority == "best_effort"
        assert ref.shadow is True
        assert ref.problem is req.problem
        # a c2-field lane keeps the standard scheme (no compensated
        # field variant) - still the f32 roll reference
        field_req = _shadow_req(
            lane=eb.LaneSpec(c2tau2_field=np.ones((9, 9, 9)))
        )
        assert s.reference_request(field_req).scheme == "standard"

    def test_rate_zero_skips_unsampled(self):
        reg = MetricsRegistry()
        s = ShadowSampler(_StubBatcher(None), reg, 0.0)
        assert s.offer(_shadow_req(), _lane_result(np.zeros(3)),
                       "r1") is False
        assert s.snapshot()["skipped"] == {"unsampled": 1.0}

    def test_divergence_measured_and_ledgered(self, tmp_path):
        """The divergence math pinned: served differs from the twin by
        exactly 0.5 in one cell -> L-inf divergence 0.5, recorded under
        the SERVED plan with source=shadow."""
        d = str(tmp_path / "tel")
        ref = np.zeros((4, 4, 4), dtype=np.float32)
        served = ref.copy()
        served[1, 2, 3] = 0.5
        reg = MetricsRegistry()
        batcher = _StubBatcher(ref)
        s = ShadowSampler(batcher, reg, 1.0, deadline_s=30.0)
        tel = telemetry.start(d, interval=60.0)
        try:
            assert s.offer(_shadow_req(), _lane_result(served),
                           "req-1") is True
            assert s.wait_idle(30.0)
        finally:
            tel.stop()
        snap = s.snapshot()
        assert snap["solves"] == 1.0 and snap["failures"] == 0.0
        assert reg.gauge(
            "wavetpu_shadow_divergence", "", ("path", "scheme", "dtype")
        ).value(path="kfused", scheme="standard", dtype="f32") == 0.5
        recs = accuracy.load_accuracy_ledger(
            os.path.join(d, accuracy.ACCURACY_FILENAME)
        )
        shadows = [r for r in recs if r["source"] == "shadow"]
        assert len(shadows) == 1
        assert shadows[0]["max_abs_err"] == 0.5
        # the SERVED plan, not the reference twin's
        assert shadows[0]["plan"]["path"] == "kfused"
        assert shadows[0]["plan"]["k"] == 2
        # the twin request the batcher saw was the reference plan
        assert batcher.submits[0].scheme == "compensated"
        assert batcher.submits[0].shadow is True

    def test_one_in_flight_second_offer_skipped_busy(self):
        release = threading.Event()
        ref = np.zeros(3, dtype=np.float32)
        reg = MetricsRegistry()
        s = ShadowSampler(_StubBatcher(ref, release=release), reg, 1.0)
        try:
            assert s.offer(_shadow_req(), _lane_result(ref), "a") is True
            assert s.offer(_shadow_req(), _lane_result(ref), "b") is False
            assert s.snapshot()["skipped"] == {"busy": 1.0}
        finally:
            release.set()
        assert s.wait_idle(30.0)
        assert s.snapshot()["solves"] == 1.0

    def test_shadow_fail_chaos_is_counter_only(self, tmp_path):
        """`WAVETPU_FAULT=serve-shadow-fail` kills the shadow worker
        BEFORE the twin is submitted: failure counted, no twin solve,
        no ledger line, and the next shadow (fault exhausted) runs
        clean."""
        d = str(tmp_path / "tel")
        ref = np.zeros(3, dtype=np.float32)
        batcher = _StubBatcher(ref)
        reg = MetricsRegistry()
        plan = faults.parse_serve_spec("serve-shadow-fail:count=1")
        s = ShadowSampler(batcher, reg, 1.0, fault_plan=plan)
        tel = telemetry.start(d, interval=60.0)
        try:
            assert s.offer(_shadow_req(), _lane_result(ref), "a") is True
            assert s.wait_idle(30.0)
            snap = s.snapshot()
            assert snap["failures"] == 1.0 and snap["solves"] == 0.0
            assert batcher.submits == []  # died before the twin
            # fault exhausted: the next sample succeeds
            assert s.offer(_shadow_req(), _lane_result(ref), "b") is True
            assert s.wait_idle(30.0)
        finally:
            tel.stop()
        assert s.snapshot()["solves"] == 1.0
        recs = accuracy.load_accuracy_ledger(
            os.path.join(d, accuracy.ACCURACY_FILENAME)
        )
        assert len([r for r in recs if r["source"] == "shadow"]) == 1

    def test_unhealthy_twin_is_a_failure_not_a_crash(self):
        reg = MetricsRegistry()
        ref = np.zeros(3, dtype=np.float32)
        s = ShadowSampler(_StubBatcher(ref, error="lane blew up"),
                          reg, 1.0)
        assert s.offer(_shadow_req(), _lane_result(ref), "a") is True
        assert s.wait_idle(30.0)
        snap = s.snapshot()
        assert snap["failures"] == 1.0 and snap["solves"] == 0.0


class _BreakerProbeEngine:
    """Records exactly what the scheduler passed for feed_breaker:
    'absent' = the production calling convention (stand-ins with the
    plain signature keep working), False = the shadow-only bypass."""

    max_batch = 4

    def __init__(self):
        self.feed_breaker_seen = []

    def solve(self, problem, lanes, scheme, path, k, dtype_name,
              mesh=None, timing=None, **kw):
        self.feed_breaker_seen.append(kw.get("feed_breaker", "absent"))
        if timing is not None:
            timing["compile_seconds"] = 0.0
            timing["warm"] = "true"
        results = [
            types.SimpleNamespace(steps_computed=problem.timesteps)
            for _ in lanes
        ]
        res = types.SimpleNamespace(
            results=results, n_lanes=len(lanes), batch_size=len(lanes),
            batched=True, fallback_reason=None, path=path,
            solve_seconds=0.01, aggregate_gcells_per_second=1.0,
        )
        return res, [None] * len(lanes)


class TestShadowNeverFeedsBreaker:
    def test_scheduler_bypasses_breaker_for_shadow_only_batches(self):
        eng = _BreakerProbeEngine()
        b = DynamicBatcher(eng, max_wait=0.01)
        p = Problem(N=8, timesteps=4)
        try:
            b.submit(SolveRequest(problem=p, lane=eb.LaneSpec())).result(30)
            b.submit(SolveRequest(
                problem=p, lane=eb.LaneSpec(), shadow=True,
                priority="best_effort",
            )).result(30)
        finally:
            b.close()
        assert eng.feed_breaker_seen == ["absent", False]


# ---- plan table / plan-report ----

def _acc_rec(plan, err, wall, cells, n=64, source="oracle"):
    return {
        "type": "accuracy", "ts": 1.0, "pid": 1,
        "plan": accuracy.normalize_plan(plan), "n": n,
        "n_bucket": accuracy.n_bucket(n), "timesteps": 48,
        "max_abs_err": err, "wall_s": wall, "cells": cells,
        "source": source,
    }


class TestPlanTable:
    def _two_plan_ledger(self):
        """A fabricated frontier with a KNOWN shape: the bf16 onion is
        fast/inaccurate, compensated f32 is slow/accurate (both
        non-dominated), and a third plan slower AND less accurate than
        compensated is Pareto-dominated."""
        fast = _plan()  # standard:kfused k=4 bf16
        slow = _plan(scheme="compensated", path="roll", k=1,
                     dtype="f32")
        dead = _plan(scheme="standard", path="roll", k=1, dtype="f32")
        recs = []
        for w in (0.5, 0.6, 0.7):
            recs.append(_acc_rec(fast, 0.6 + w / 10, w, 1e9))
        for w in (2.0, 2.2, 2.4):
            recs.append(_acc_rec(slow, 1e-5, w, 1e9))
        recs.append(_acc_rec(dead, 1e-3, 4.0, 1e9))
        return recs, fast, slow, dead

    def test_known_pareto_frontier_reproduced(self):
        recs, fast, slow, dead = self._two_plan_ledger()
        table = accuracy.build_plan_table(recs)
        assert table[accuracy.PLAN_TABLE_FLAG] is True
        assert table["entries"] == 7
        rows = {accuracy.canonical_plan(r["plan"]): r
                for r in table["rows"]}
        frow = rows[accuracy.canonical_plan(fast)]
        srow = rows[accuracy.canonical_plan(slow)]
        drow = rows[accuracy.canonical_plan(dead)]
        # the two real plans span the frontier; the third is dominated
        assert frow["pareto_dominated"] is False
        assert srow["pareto_dominated"] is False
        assert drow["pareto_dominated"] is True
        # measured medians, exactly
        assert frow["wall_s_per_request"] == 0.6
        assert srow["wall_s_per_request"] == 2.2
        assert frow["gcells_per_s"] == round(1e9 / 0.6 / 1e9, 6)
        assert srow["err_p50"] == 1e-5
        assert frow["err_max"] == pytest.approx(0.67)
        assert frow["requests"] == 3 and frow["oracle_requests"] == 3

    def test_buckets_isolate_dominance(self):
        """Dominance is judged within an N-bucket only: a plan beaten
        at N=64 still stands alone in its own bucket."""
        fast = _plan()
        recs = [
            _acc_rec(fast, 0.6, 0.5, 1e9, n=64),
            _acc_rec(_plan(dtype="f32"), 1e-3, 0.4, 1e9, n=64),
            _acc_rec(fast, 0.6, 4.0, 1e9, n=300),  # alone in 512
        ]
        table = accuracy.build_plan_table(recs)
        by_bucket = {(accuracy.canonical_plan(r["plan"]), r["n_bucket"]):
                     r["pareto_dominated"] for r in table["rows"]}
        assert by_bucket[(accuracy.canonical_plan(fast), 64)] is True
        assert by_bucket[(accuracy.canonical_plan(fast), 512)] is False

    def test_shadow_lines_counted_and_mixed_into_percentiles(self):
        plan = _plan()
        recs = [
            _acc_rec(plan, 0.1, 1.0, 1e9),
            _acc_rec(plan, 0.3, 1.0, 1e9, source="shadow"),
        ]
        row = accuracy.build_plan_table(recs)["rows"][0]
        assert row["oracle_requests"] == 1
        assert row["shadow_requests"] == 1
        assert row["err_max"] == 0.3

    def test_compile_ledger_join(self):
        plan = _plan(scheme="compensated", dtype="f32")
        key = dict(N=64, Lx=1.0, Ly=1.0, Lz=1.0, T=1.0, timesteps=48,
                   scheme="compensated", path="kfused", k=4,
                   dtype="f32", with_field=False, compute_errors=True,
                   batch=1, mesh=None)
        compiles = [
            {"type": "compile", "key": key, "compile_s": 7.5,
             "cold": True},
            {"type": "compile", "key": key, "compile_s": 2.5,
             "cold": False},
            # disk loads are cache hits, not compiles - excluded
            {"type": "compile", "key": key, "compile_s": 0.2,
             "cold": True, "source": "disk"},
        ]
        row = accuracy.build_plan_table(
            [_acc_rec(plan, 1e-5, 1.0, 1e9)], compiles
        )["rows"][0]
        assert row["compiles"] == 2
        assert row["compile_s"] == 10.0

    def test_report_cli_text_json_and_emitted_table(self, tmp_path,
                                                    capsys):
        recs, fast, slow, dead = self._two_plan_ledger()
        d = str(tmp_path)
        with open(os.path.join(d, accuracy.ACCURACY_FILENAME),
                  "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")
        assert accuracy.main([d]) == 0
        out = capsys.readouterr().out
        assert "7 measured solve(s)" in out
        assert "3 (plan, N-bucket) frontier row(s)" in out
        assert "fleet/quota.py" in out  # the quota pricing pointer
        assert accuracy.main([d, "--json"]) == 0
        table = json.loads(capsys.readouterr().out)
        assert table[accuracy.PLAN_TABLE_FLAG] is True
        tpath = str(tmp_path / "plan_table.json")
        assert accuracy.main([d, "--emit-plan-table", tpath]) == 0
        capsys.readouterr()
        with open(tpath) as f:
            emitted = json.load(f)
        assert emitted[accuracy.PLAN_TABLE_FLAG] is True
        assert len(emitted["rows"]) == 3

    def test_report_cli_usage_errors(self, tmp_path, capsys):
        assert accuracy.main([]) == 2
        assert accuracy.main(["--bogus"]) == 2
        assert accuracy.main([str(tmp_path / "missing.jsonl")]) == 2
        capsys.readouterr()


# ---- loadgen error-budget loop ----

class TestLoadgenErrorBudget:
    def _report(self, errs_by_tier, budgets=None):
        from wavetpu.loadgen import report as lg_report
        from wavetpu.loadgen.runner import ReplayResult, RequestOutcome

        outs = []
        for tier, errs in errs_by_tier.items():
            for i, e in enumerate(errs):
                outs.append(RequestOutcome(
                    index=len(outs), scenario=tier, request_id=f"{tier}{i}",
                    status=200, latency_s=0.1, t_sent=0.0,
                    max_abs_error=e,
                ))
        result = ReplayResult(
            outcomes=outs, warmup_outcomes=[], metrics_before={},
            metrics_after={}, wall_seconds=1.0, mode="sequential",
            concurrency=1, speed=1.0,
        )
        return lg_report.build_report(result, error_budgets=budgets)

    def test_tier_rows_carry_measured_error_and_budget(self):
        rep = self._report(
            {"comp": [1e-6, 5e-6], "blind": [None, None]},
            budgets={"comp": 1e-5},
        )
        tiers = rep["tiers"]
        assert tiers["comp"]["max_abs_err"] == 5e-6
        assert tiers["comp"]["measured_requests"] == 2
        assert tiers["comp"]["error_budget"] == 1e-5
        # an oracle-less tier keeps the baseline row shape
        assert "max_abs_err" not in tiers["blind"]

    def test_error_slo_gate_passes_and_fails(self):
        from wavetpu.loadgen import report as lg_report

        rep = self._report({"comp": [1e-6, 5e-6], "blind": [None]})
        ok = lg_report.gate(rep, slo={"error_slos": {"comp": 1e-5}})
        assert ok == []
        bad = lg_report.gate(rep, slo={"error_slos": {"comp": 1e-9}})
        assert [v["slo"] for v in bad] == ["err:comp"]
        # a tier with no measured errors cannot claim to meet a budget
        blind = lg_report.gate(rep, slo={"error_slos": {"blind": 1e-3}})
        assert [v["slo"] for v in blind] == ["err:blind"]
        missing = lg_report.gate(rep, slo={"error_slos": {"nope": 1.0}})
        assert [v["slo"] for v in missing] == ["err:nope"]

    def test_error_slo_flag_parsing(self):
        from wavetpu.loadgen.cli import _parse_error_slos

        assert _parse_error_slos(["a=1e-3", "b=0.5"]) == {
            "a": 1e-3, "b": 0.5
        }
        with pytest.raises(ValueError, match="TIER=BUDGET"):
            _parse_error_slos(["nobudget"])


# ---- HTTP end to end ----

def _post(base, body, timeout=300):
    req = urllib.request.Request(
        base + "/solve", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=30) as r:
        return r.status, json.loads(r.read())


def _wait_shadow(state, n, timeout=300.0):
    """The offer fires AFTER the primary bytes are on the wire, so the
    client can observe its 200 before the shadow thread exists - poll
    until n shadows have resolved (solved or failed), then join."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        snap = state.shadow.snapshot()
        if snap["solves"] + snap["failures"] >= n:
            assert state.shadow.wait_idle(timeout)
            return snap
        time.sleep(0.05)
    raise AssertionError(
        f"shadow never resolved {n} sample(s): {state.shadow.snapshot()}"
    )


def _serve(tmp_path, **kw):
    from wavetpu.serve.api import build_server

    kw.setdefault("port", 0)
    kw.setdefault("max_wait", 0.1)
    kw.setdefault("default_kernel", "roll")
    kw.setdefault("interpret", True)
    httpd, state = build_server(**kw)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    return httpd, state, base


class TestServeShadowHTTP:
    def test_sampled_request_shadowed_and_ledgered(self, tmp_path):
        d = str(tmp_path / "tel")
        tel = telemetry.start(d, interval=60.0)
        httpd, state, base = _serve(tmp_path, shadow_sample_rate=1.0)
        try:
            code, body = _post(base, {"N": 8, "timesteps": 4})
            assert code == 200 and body["status"] == "ok"
            _wait_shadow(state, 1)
            _, metrics = _get(base, "/metrics")
            assert metrics["shadow"]["rate"] == 1.0
            assert metrics["shadow"]["solves"] == 1
            assert metrics["shadow"]["failures"] == 0
        finally:
            httpd.shutdown()
            state.batcher.close()
            httpd.server_close()
            tel.stop()
        recs = accuracy.load_accuracy_ledger(
            os.path.join(d, accuracy.ACCURACY_FILENAME)
        )
        shadows = [r for r in recs if r["source"] == "shadow"]
        assert len(shadows) == 1
        # divergence of the served standard plan vs the compensated
        # twin: two different f32 rounding paths, so tiny but bounded
        assert 0.0 <= shadows[0]["max_abs_err"] < 1e-3
        assert shadows[0]["plan"]["scheme"] == "standard"
        # oracle lines landed too: the primary lane AND the twin lane
        oracles = [r for r in recs if r["source"] == "oracle"]
        assert len(oracles) >= 2

    def test_shadow_crash_invisible_to_primary_and_breaker(self,
                                                           tmp_path):
        """The chaos drill: with serve-shadow-fail armed, the primary
        answer is numerically identical to the clean run's, the
        breaker records nothing, and the failure is one counter tick."""
        plan = faults.parse_serve_spec("serve-shadow-fail:count=1")
        httpd, state, base = _serve(
            tmp_path, shadow_sample_rate=1.0, fault_plan=plan,
        )
        try:
            body = {"N": 8, "timesteps": 4}
            code1, p1 = _post(base, body)
            assert code1 == 200
            _wait_shadow(state, 1)
            _, m1 = _get(base, "/metrics")
            assert m1["shadow"]["failures"] == 1
            assert m1["shadow"]["solves"] == 0
            assert m1["breaker"]["enabled"] is True
            assert m1["breaker"]["open"] == 0
            assert m1["breaker"]["keys"] == []
            # fault exhausted: same request again, clean shadow
            code2, p2 = _post(base, body)
            assert code2 == 200
            _wait_shadow(state, 2)
            # primary answers are numerically identical - the crashed
            # shadow touched nothing
            assert p1["report"]["abs_errors"] == p2["report"]["abs_errors"]
            assert (p1["report"]["max_abs_error"]
                    == p2["report"]["max_abs_error"])
            _, m2 = _get(base, "/metrics")
            assert m2["shadow"]["solves"] == 1
            assert m2["responses_error"] == 0
        finally:
            httpd.shutdown()
            state.batcher.close()
            httpd.server_close()

    def test_ineligible_reference_plan_not_shadowed(self, tmp_path):
        httpd, state, base = _serve(tmp_path, shadow_sample_rate=1.0)
        try:
            code, _ = _post(
                base, {"N": 8, "timesteps": 4, "scheme": "compensated"}
            )
            assert code == 200
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                if state.shadow.snapshot()["skipped"]:
                    break
                time.sleep(0.05)
            _, metrics = _get(base, "/metrics")
            assert metrics["shadow"]["solves"] == 0
            assert metrics["shadow"]["skipped"] == {
                "reference-plan": 1
            }
        finally:
            httpd.shutdown()
            state.batcher.close()
            httpd.server_close()


@pytest.mark.slow
class TestTwoTierDrill:
    def test_measured_frontier_orders_both_axes(self, tmp_path):
        """The pinned acceptance drill: a warmed server replays a
        two-tier trace (bf16-increment k=4 onion vs compensated f32
        onion, N=64/T=48 - the size where the trade is real on CPU)
        at --shadow-sample-rate 1.0.  The resulting plan_table.json
        must order the plans correctly on BOTH measured axes (bf16
        faster, compensated >= 3 decades more accurate), with zero
        primary-path errors, zero breaker events, and shadows > 0."""
        d = str(tmp_path / "tel")
        tel = telemetry.start(d, interval=60.0)
        httpd, state, base = _serve(
            tmp_path, shadow_sample_rate=1.0, default_kernel="auto",
            max_wait=0.05,
        )
        bf16 = {"N": 64, "timesteps": 48, "fuse_steps": 4,
                "dtype": "bf16", "kernel": "pallas"}
        comp = {"N": 64, "timesteps": 48, "scheme": "compensated",
                "fuse_steps": 4, "kernel": "pallas"}
        try:
            # Three rounds per tier: each plan's first request carries
            # trace/compile overhead, and with only two samples the
            # nearest-rank p50 lands on that cold wall - where the two
            # tiers tie.  Three samples put the median on a warm solve.
            for i, body in enumerate((bf16, comp) * 3):
                code, payload = _post(base, body, timeout=600)
                assert code == 200 and payload["status"] == "ok"
                # one shadow in flight at a time: join before the next
                # tier so every sampled request really shadows
                _wait_shadow(state, i + 1, timeout=600.0)
            _, metrics = _get(base, "/metrics")
            assert metrics["responses_error"] == 0
            assert metrics["shadow"]["solves"] == 6
            assert metrics["shadow"]["failures"] == 0
            assert metrics["breaker"]["enabled"] is True
            assert metrics["breaker"]["open"] == 0
        finally:
            httpd.shutdown()
            state.batcher.close()
            httpd.server_close()
            tel.stop()
        tpath = str(tmp_path / "plan_table.json")
        assert accuracy.main([d, "--emit-plan-table", tpath]) == 0
        with open(tpath) as f:
            table = json.load(f)
        assert table[accuracy.PLAN_TABLE_FLAG] is True
        rows = {
            (r["plan"]["scheme"], r["plan"]["dtype"]): r
            for r in table["rows"]
            if r["plan"]["path"] == "kfused" and r["n_bucket"] == 64
        }
        brow = rows[("standard", "bf16")]
        crow = rows[("compensated", "f32")]
        # each tier measured three times by the oracle + thrice by shadow
        assert brow["requests"] >= 3 and crow["requests"] >= 3
        # axis 1: the bf16 onion is measurably faster
        assert brow["gcells_per_s"] > crow["gcells_per_s"]
        assert brow["wall_s_per_request"] < crow["wall_s_per_request"]
        # axis 2: compensated f32 is >= 3 decades more accurate
        assert crow["err_p50"] * 1e3 <= brow["err_p50"]
        # the shadow reference twin (compensated roll) earns its own
        # measured row - proof the twin's oracle lines land in the table
        rrow = next(
            r for r in table["rows"]
            if r["plan"]["path"] == "roll" and r["n_bucket"] == 64
            and r["plan"]["scheme"] == "compensated"
        )
        assert rrow["requests"] >= 3
        # comp-kfused holds the strictly best measured error of the
        # three plans, so nothing can Pareto-dominate it
        assert crow["pareto_dominated"] is False
        # bf16's flag must agree with the table it sits in: dominated
        # iff some same-bucket row beats it on speed without losing on
        # error (on CPU interpret the jnp roll twin usually does)
        beats = any(
            r["gcells_per_s"] >= brow["gcells_per_s"]
            and r["err_p50"] <= brow["err_p50"]
            and (r["gcells_per_s"] > brow["gcells_per_s"]
                 or r["err_p50"] < brow["err_p50"])
            for r in table["rows"]
            if r["n_bucket"] == 64 and r is not brow
        )
        assert brow["pareto_dominated"] is beats
