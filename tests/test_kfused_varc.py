"""Variable-c(x,y,z) through the k-step onion family: oracle + parity.

The c^2tau^2 field threads through every onion (standard single-device,
x/xy-sharded, pad-and-mask uneven; velocity-form compensated single and
sharded).  Contracts pinned here:

 * INDEPENDENT ORACLE: `tests/reference_impl.solve_reference_variable_c`
   is a numpy f64 implementation of the scheme in the reference's own
   (N+1)^3-with-seam indexing, written from the scheme description - the
   variable-c analog of the constant-c pinning in test_single_device.py
   (closes the round-5 "variable-c has no independent oracle" weakness).
   The onion paths must be LAYER-EXACT against it at f32 rounding,
   including mid-run layers reached through stop_step.
 * OP-IDENTICAL MIXING: variable-c k-fused layers are op-identical to
   the 1-step variable-c pallas kernel's (same summation order after the
   round-6 `_var_step_kernel` unification), so checkpoints mix across
   paths.  On this jaxlib's XLA-CPU pipeline, FMA contraction differs
   between program SHAPES (a scanned onion vs an unrolled 1-step loop),
   so "bitwise" asserts here allow 1 ulp - the same caveat as the
   uneven suite in test_sharded_kfused.py; on-chip/same-program runs
   remain bit-identical.
 * The compensated onion keeps its tolerance-vs-f64 contract with a
   field coefficient, including the bf16-increment mode (BASELINE
   config 5 in its meaningful form).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tests import reference_impl
from wavetpu.core.problem import Problem
from wavetpu.kernels import stencil_pallas, stencil_ref
from wavetpu.solver import kfused, kfused_comp, leapfrog, sharded, \
    sharded_kfused


def _c2_fn(p):
    """Smooth positive c^2 field with max value a^2, so the constant-c
    Courant bound still guarantees stability."""

    def fn(x, y, z):
        return p.a2 * (
            0.6 + 0.4 * np.sin(2 * np.pi * x / p.Lx) ** 2
            * np.sin(np.pi * y / p.Ly) ** 2
        )

    return fn


@pytest.fixture(scope="module")
def problem():
    return Problem(N=12, Np=1, Lx=1.0, Ly=1.0, Lz=1.0, T=1.0, timesteps=9)


@pytest.fixture(scope="module")
def field(problem):
    return stencil_ref.make_c2tau2_field(problem, _c2_fn(problem))


@pytest.fixture(scope="module")
def ref_history(problem):
    return reference_impl.solve_reference_variable_c(
        problem, _c2_fn(problem)
    )


@pytest.fixture(scope="module")
def varc_1step(problem, field):
    return leapfrog.solve(
        problem,
        step_fn=stencil_pallas.make_step_fn(
            interpret=True, c2tau2_field=field
        ),
        compute_errors=False,
    )


@pytest.fixture(scope="module")
def varc_k4(problem, field):
    return kfused.solve_kfused(
        problem, k=4, interpret=True, compute_errors=False,
        c2tau2_field=field,
    )


def _fund(layer):
    """(N+1)^3 reference layer -> fundamental (N,N,N) domain."""
    return layer[:-1, :-1, :-1]


def test_oracle_pins_1step_pallas(problem, field, ref_history, varc_1step):
    """The 1-step variable-c pallas path is layer-exact (f32 rounding)
    against the independent numpy scheme at the final two layers."""
    np.testing.assert_allclose(
        np.asarray(varc_1step.u_cur, np.float64),
        _fund(ref_history[-1]), atol=5e-6, rtol=0,
    )
    np.testing.assert_allclose(
        np.asarray(varc_1step.u_prev, np.float64),
        _fund(ref_history[-2]), atol=5e-6, rtol=0,
    )


@pytest.mark.parametrize("k,stop", [(4, 5), (2, 9), (4, 9)])
def test_oracle_pins_kfused_layers(problem, field, ref_history, k, stop):
    """Variable-c k-fused output is layer-exact against the numpy oracle,
    including a mid-run non-block-aligned layer reached via stop_step
    (the in-VMEM intermediate layers feed it, so this pins them too)."""
    res = kfused.solve_kfused(
        problem, k=k, stop_step=stop, interpret=True,
        compute_errors=False, c2tau2_field=field,
    )
    np.testing.assert_allclose(
        np.asarray(res.u_cur, np.float64),
        _fund(ref_history[stop]), atol=5e-6, rtol=0,
    )
    np.testing.assert_allclose(
        np.asarray(res.u_prev, np.float64),
        _fund(ref_history[stop - 1]), atol=5e-6, rtol=0,
    )


def test_kfused_bitwise_vs_1step(problem, field, varc_1step, varc_k4):
    """Variable-c onion layers are op-identical to 1-step variable-c
    pallas layers: the states match BITWISE (the checkpoint-mixing
    contract of the constant-c onion, extended to the field)."""
    np.testing.assert_allclose(
        np.asarray(varc_k4.u_cur), np.asarray(varc_1step.u_cur),
        atol=3e-7, rtol=0,
    )
    np.testing.assert_allclose(
        np.asarray(varc_k4.u_prev), np.asarray(varc_1step.u_prev),
        atol=3e-7, rtol=0,
    )


def test_varc_stop_resume_bitwise(problem, field, varc_k4):
    part = kfused.solve_kfused(
        problem, k=4, stop_step=5, interpret=True, compute_errors=False,
        c2tau2_field=field,
    )
    res = kfused.resume_kfused(
        problem, part.u_prev, part.u_cur, 5, k=4, interpret=True,
        compute_errors=False, c2tau2_field=field,
    )
    np.testing.assert_array_equal(
        np.asarray(res.u_cur), np.asarray(varc_k4.u_cur)
    )


@pytest.mark.parametrize("mesh", [(2, 1, 1), (2, 2, 1)])
def test_sharded_varc_matches_single(problem, field, mesh):
    """Even-decomposition sharded variable-c k-fusion matches the
    single-device onion (the c^2 slab is sharded on the same mesh; its
    k-deep ghosts are exchanged once per solve).  k=2 keeps N=12
    divisible on both mesh axes."""
    single = kfused.solve_kfused(
        problem, k=2, interpret=True, compute_errors=False,
        c2tau2_field=field,
    )
    got = sharded_kfused.solve_sharded_kfused(
        problem, mesh_shape=mesh, k=2, interpret=True,
        compute_errors=False, c2tau2_field=field,
    )
    np.testing.assert_allclose(
        np.asarray(got.u_cur), np.asarray(single.u_cur),
        atol=3e-7, rtol=0,
    )


def test_padded_varc_matches_oracle():
    """Uneven N routes variable-c through the pad-and-mask onion (zero
    junk coefficient, hi-splice field ext); pinned against the numpy
    oracle at 1-ulp tolerance (XLA-CPU FMA contraction differs between
    program shapes on this jaxlib; see test_sharded_kfused.py)."""
    p = Problem(N=15, Np=1, Lx=1.0, Ly=1.0, Lz=1.0, T=1.0, timesteps=7)
    fn = _c2_fn(p)
    field = stencil_ref.make_c2tau2_field(p, fn)
    hist = reference_impl.solve_reference_variable_c(p, fn)
    got = sharded_kfused.solve_sharded_kfused(
        p, n_shards=2, k=2, interpret=True, compute_errors=False,
        c2tau2_field=field,
    )
    np.testing.assert_allclose(
        sharded.gather_fundamental(got.u_cur, p).astype(np.float64),
        _fund(hist[-1]), atol=5e-6, rtol=0,
    )


def test_comp_varc_beats_standard_f32(problem, field, ref_history,
                                      varc_k4):
    """The velocity-form onion keeps the compensated accuracy class under
    a field coefficient: its error vs the f64 oracle must not exceed the
    standard-f32 onion's (both are discretization-exact here; the win is
    rounding, which only shows at long horizons - this pins correctness,
    bench pins the class at N=512/1000)."""
    comp = kfused_comp.solve_kfused_comp(
        problem, k=4, interpret=True, compute_errors=False,
        c2tau2_field=field,
    )
    ref = _fund(ref_history[-1])
    e_comp = np.abs(np.asarray(comp.u_cur, np.float64) - ref).max()
    e_std = np.abs(np.asarray(varc_k4.u_cur, np.float64) - ref).max()
    assert e_comp < 5e-6, e_comp
    assert e_comp <= e_std * 1.5, (e_comp, e_std)


def test_comp_varc_bf16_increment(problem, field, ref_history):
    """bf16-increment variable-c (BASELINE config 5 in its meaningful
    form): bf16 v stream + f32 carrier + field coefficient, error bounded
    by the increment quantization (~|v| 2^-8 per step)."""
    res = kfused_comp.solve_kfused_comp(
        problem, k=4, interpret=True, compute_errors=False,
        c2tau2_field=field, v_dtype=jnp.bfloat16, carry=False,
    )
    assert res.u_cur.dtype == jnp.float32
    assert res.comp_v.dtype == jnp.bfloat16 and res.comp_carry is None
    diff = np.abs(
        np.asarray(res.u_cur, np.float64) - _fund(ref_history[-1])
    ).max()
    assert diff < 5e-3, diff


@pytest.mark.parametrize("mesh", [(2, 1, 1), (2, 2, 1)])
def test_comp_sharded_varc(problem, field, mesh):
    """Sharded velocity-form variable-c agrees with the single-device comp
    onion at ulp level (the scheme's cross-mesh contract), and resumes
    bitwise from a block-aligned stop on the same mesh."""
    single = kfused_comp.solve_kfused_comp(
        problem, k=2, block_x=2, interpret=True, compute_errors=False,
        c2tau2_field=field,
    )
    got = kfused_comp.solve_kfused_comp_sharded(
        problem, mesh_shape=mesh, k=2, block_x=2, interpret=True,
        compute_errors=False, c2tau2_field=field,
    )
    diff = np.abs(
        np.asarray(got.u_cur, np.float64)
        - np.asarray(single.u_cur, np.float64)
    ).max()
    assert diff < 1e-6, diff
    part = kfused_comp.solve_kfused_comp_sharded(
        problem, mesh_shape=mesh, k=2, block_x=2, stop_step=5,
        interpret=True, compute_errors=False, c2tau2_field=field,
    )
    res = kfused_comp.resume_kfused_comp_sharded(
        problem, np.asarray(part.u_cur), np.asarray(part.comp_v),
        np.asarray(part.comp_carry), 5, mesh_shape=mesh, k=2, block_x=2,
        interpret=True, compute_errors=False, c2tau2_field=field,
    )
    np.testing.assert_array_equal(
        np.asarray(res.u_cur), np.asarray(got.u_cur)
    )


def test_varc_requires_errors_off(problem, field):
    """No analytic oracle for variable c: every k-fused entry point
    refuses a field with compute_errors=True instead of reporting
    garbage."""
    with pytest.raises(ValueError, match="no analytic oracle"):
        kfused.solve_kfused(
            problem, k=4, interpret=True, c2tau2_field=field
        )
    with pytest.raises(ValueError, match="no analytic oracle"):
        kfused_comp.solve_kfused_comp(
            problem, k=4, interpret=True, c2tau2_field=field
        )
    with pytest.raises(ValueError, match="no analytic oracle"):
        sharded_kfused.solve_sharded_kfused(
            problem, n_shards=2, k=4, interpret=True, c2tau2_field=field
        )
    with pytest.raises(ValueError, match="no analytic oracle"):
        kfused_comp.solve_kfused_comp_sharded(
            problem, n_shards=2, k=4, interpret=True, c2tau2_field=field
        )
