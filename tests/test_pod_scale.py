"""Pod-scale mesh shapes beyond the 8-device test backend.

BASELINE.md's weak-scaling configs run on 64-256 chips ((4,4,4) and
(8,8,4) decompositions).  The in-process suite is pinned to 8 virtual CPU
devices (conftest), so these gates spawn a SUBPROCESS with a 64-device
CPU backend and compile + execute the full sharded program on the
pod-shaped meshes, parity-checked against the single-device solver -
the same trick the reference cannot play without 64 GPUs (SURVEY.md
section 4's "fake backend" gap).
"""

import os
import subprocess
import sys
import textwrap

import pytest

_SCRIPT = textwrap.dedent("""
    import os
    os.environ["XLA_FLAGS"] = (
        "--xla_force_host_platform_device_count=64 "
        + os.environ.get("XLA_FLAGS", "").replace(
            "--xla_force_host_platform_device_count=8", ""
        )
    )
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from wavetpu.core.problem import Problem
    from wavetpu.solver import leapfrog, sharded, sharded_kfused

    assert len(jax.devices()) == 64, jax.devices()
    p = Problem(N=16, timesteps=4)
    single = leapfrog.solve(p)

    # BASELINE config 3/4 shape: full 3D decomposition, 64 ranks.
    res = sharded.solve_sharded(p, mesh_shape=(4, 4, 4), kernel="pallas")
    np.testing.assert_allclose(
        sharded.gather_fundamental(res.u_cur, p),
        np.asarray(single.u_cur), atol=1e-5, rtol=0,
    )
    print("mesh (4,4,4) x 64 devices OK")

    # x-only 64-way decomposition under k-fusion (N=128 -> 2 planes/shard).
    # timesteps=40 keeps the Courant number ~0.51 < 1/sqrt(3): an unstable
    # config would amplify rounding noise exponentially and void the
    # cross-implementation comparison.
    p2 = Problem(N=128, timesteps=40)
    single2 = leapfrog.solve(p2)
    res2 = sharded_kfused.solve_sharded_kfused(
        p2, n_shards=64, k=2, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(res2.u_cur), np.asarray(single2.u_cur),
        atol=1e-5, rtol=0,
    )
    print("kfused mesh (64,1,1) OK")

    # 2D decomposition under k-fusion: the flagship pod shape family
    # ((8,8,1) factors v5e-64 without cutting the z lane dimension).
    res3 = sharded_kfused.solve_sharded_kfused(
        p2, mesh_shape=(8, 8, 1), k=2, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(res3.u_cur), np.asarray(single2.u_cur),
        atol=1e-5, rtol=0,
    )
    print("kfused mesh (8,8,1) OK")

    # BASELINE config 5 (stretch) composition, RE-SCOPED round 6 to the
    # meaningful form: sharded velocity-form k-fusion + bf16 INCREMENT
    # stream (f32 carrier u + bf16 v, carry-less) + variable c +
    # per-shard checkpoint/resume, on the pod-family (8, 8, 1) mesh over
    # 64 virtual devices.  (The old gate used a bf16 CARRIER state,
    # whose trajectory error is O(1) by design - a throughput demo, not
    # a meaningful config.)  There is no analytic oracle for variable c,
    # so the gate pins (a) the resumed state equals the uninterrupted
    # run's bitwise, and (b) the bf16-increment run tracks an f32-v run
    # of the same config to increment-quantization precision.
    import tempfile
    import jax.numpy as jnp
    from wavetpu.io import checkpoint as ckpt
    from wavetpu.kernels import stencil_ref
    from wavetpu.solver import kfused_comp

    # T/timesteps keep max(c)*tau*sqrt(3)/h well under 1 (c^2 in
    # [0.6, 1] here).  N=16 on (8, 8, 1): nl_x = nl_y = 2, k = 2.
    p3 = Problem(N=16, Np=1, Lx=1.0, Ly=1.0, Lz=1.0, T=0.25, timesteps=10)
    c2 = stencil_ref.make_c2tau2_field(
        p3, lambda x, y, z: 1.0 - 0.4 * np.exp(
            -((x - 0.5) ** 2 + (y - 0.5) ** 2 + (z - 0.5) ** 2) / 0.08
        )
    )

    def stretch(v_dtype, carry, stop=None):
        return kfused_comp.solve_kfused_comp_sharded(
            p3, mesh_shape=(8, 8, 1), k=2, dtype=jnp.float32,
            v_dtype=v_dtype, carry=carry,
            c2tau2_field=np.asarray(c2), compute_errors=False,
            stop_step=stop, interpret=True,
        )

    full = stretch(jnp.bfloat16, False)
    assert full.comp_v.dtype == jnp.bfloat16
    # stop=5 is block-aligned from start=1 (k=2 blocks [2-3][4-5]): the
    # resumed march emits the identical remaining block sequence, which
    # is what makes the bitwise pin below valid (the velocity form has
    # no misaligned-resume bitwise claim; see test_kfused_comp.py).
    part = stretch(jnp.bfloat16, False, stop=5)
    with tempfile.TemporaryDirectory() as d:
        path = ckpt.save_sharded_checkpoint(d + "/ck", part)
        p3b, u_prev, u_cur, step, mesh_shape, scheme, aux = (
            ckpt.load_sharded_checkpoint(path)
        )
        assert step == 5 and mesh_shape == (8, 8, 1)
        assert scheme == "compensated"
        v, _carry = aux
        res = kfused_comp.resume_kfused_comp_sharded(
            p3b, np.asarray(u_cur), np.asarray(v), None,
            start_step=step, mesh_shape=mesh_shape, k=2,
            v_dtype=jnp.bfloat16, c2tau2_field=np.asarray(c2),
            compute_errors=False, interpret=True,
        )
    got = np.asarray(res.u_cur)
    np.testing.assert_array_equal(got, np.asarray(full.u_cur))
    fullf32 = stretch(None, True)
    np.testing.assert_allclose(
        got, np.asarray(fullf32.u_cur), atol=0.02, rtol=0,
    )
    assert np.isfinite(got).all()
    print("stretch composition (sharded kfused-comp + bf16-inc + var-c"
          " + checkpoint, (8,8,1)) OK")
""")


@pytest.mark.slow
def test_64_device_meshes():
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    proc = subprocess.run(
        [sys.executable, "-c", _SCRIPT],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env, capture_output=True, text=True, timeout=900,
    )
    assert proc.returncode == 0, (
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr[-4000:]}"
    )
    assert "mesh (4,4,4) x 64 devices OK" in proc.stdout
    assert "kfused mesh (64,1,1) OK" in proc.stdout
    assert "kfused mesh (8,8,1) OK" in proc.stdout
    assert (
        "stretch composition (sharded kfused-comp + bf16-inc + var-c"
        " + checkpoint, (8,8,1)) OK" in proc.stdout
    )
