"""Pod-scale mesh shapes beyond the 8-device test backend.

BASELINE.md's weak-scaling configs run on 64-256 chips ((4,4,4) and
(8,8,4) decompositions).  The in-process suite is pinned to 8 virtual CPU
devices (conftest), so these gates spawn a SUBPROCESS with a 64-device
CPU backend and compile + execute the full sharded program on the
pod-shaped meshes, parity-checked against the single-device solver -
the same trick the reference cannot play without 64 GPUs (SURVEY.md
section 4's "fake backend" gap).
"""

import os
import subprocess
import sys
import textwrap

import pytest

_SCRIPT = textwrap.dedent("""
    import os
    os.environ["XLA_FLAGS"] = (
        "--xla_force_host_platform_device_count=64 "
        + os.environ.get("XLA_FLAGS", "").replace(
            "--xla_force_host_platform_device_count=8", ""
        )
    )
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from wavetpu.core.problem import Problem
    from wavetpu.solver import leapfrog, sharded, sharded_kfused

    assert len(jax.devices()) == 64, jax.devices()
    p = Problem(N=16, timesteps=4)
    single = leapfrog.solve(p)

    # BASELINE config 3/4 shape: full 3D decomposition, 64 ranks.
    res = sharded.solve_sharded(p, mesh_shape=(4, 4, 4), kernel="pallas")
    np.testing.assert_allclose(
        sharded.gather_fundamental(res.u_cur, p),
        np.asarray(single.u_cur), atol=1e-5, rtol=0,
    )
    print("mesh (4,4,4) x 64 devices OK")

    # x-only 64-way decomposition under k-fusion (N=128 -> 2 planes/shard).
    # timesteps=40 keeps the Courant number ~0.51 < 1/sqrt(3): an unstable
    # config would amplify rounding noise exponentially and void the
    # cross-implementation comparison.
    p2 = Problem(N=128, timesteps=40)
    single2 = leapfrog.solve(p2)
    res2 = sharded_kfused.solve_sharded_kfused(
        p2, n_shards=64, k=2, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(res2.u_cur), np.asarray(single2.u_cur),
        atol=1e-5, rtol=0,
    )
    print("kfused mesh (64,1,1) OK")

    # 2D decomposition under k-fusion: the flagship pod shape family
    # ((8,8,1) factors v5e-64 without cutting the z lane dimension).
    res3 = sharded_kfused.solve_sharded_kfused(
        p2, mesh_shape=(8, 8, 1), k=2, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(res3.u_cur), np.asarray(single2.u_cur),
        atol=1e-5, rtol=0,
    )
    print("kfused mesh (8,8,1) OK")
""")


@pytest.mark.slow
def test_64_device_meshes():
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    proc = subprocess.run(
        [sys.executable, "-c", _SCRIPT],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env, capture_output=True, text=True, timeout=900,
    )
    assert proc.returncode == 0, (
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr[-4000:]}"
    )
    assert "mesh (4,4,4) x 64 devices OK" in proc.stdout
    assert "kfused mesh (64,1,1) OK" in proc.stdout
    assert "kfused mesh (8,8,1) OK" in proc.stdout
