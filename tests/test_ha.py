"""Control-plane + router-HA contracts (fleet/store.py, fleet/ha.py,
the router's --control-plane-dir wiring, and the multi-endpoint
WavetpuClient):

 * the store's WAL/snapshot crash discipline - torn tails and corrupt
   snapshots are COUNTED recoverable misses, never crashes;
 * the file lease's epoch fencing - a deposed active can never renew
   its way back, and the epoch stays monotonic across orderly releases;
 * quota-bucket persistence - a restarted router resumes enforcement
   (downtime refilled, never reopened-full);
 * the client's endpoint rotation on transport failure / standby-503;
 * the router-tier WAVETPU_FAULT grammar (router-crash / store-corrupt
   / store-stale-lease) and its isolation from the run-side hook;
 * /metrics monotonicity across a ROUTER restart (frozen LEFT members
   included) - the bracketing-deltas pin;
 * two routers sharing a store admit within bounded slack fleet-wide
   (and the ~2x over-admission WITHOUT the store, pinned both ways);
 * the failover drill: active killed mid-flight with a chunked-march
   resume token outstanding -> the standby promotes within one lease
   TTL, the multi-endpoint client rotates with ZERO visible errors,
   the token completes the march, and quota levels survive the swap.

Scripted members throughout - no jax, no sockets beyond loopback.
"""

import json
import os
import threading
import time
import urllib.request

import pytest

from wavetpu.client import WavetpuClient
from wavetpu.fleet import ha as fleet_ha
from wavetpu.fleet import quota
from wavetpu.fleet.membership import LEFT, UP
from wavetpu.fleet.router import build_router
from wavetpu.fleet.store import ControlPlaneStore
from wavetpu.loadgen.runner import parse_prometheus_text
from wavetpu.run import faults

from tests.test_fleet import _ScriptedMember, _get, _post


# ---- the crash-safe store ----


class TestControlPlaneStore:
    def test_wal_replay_latest_wins_per_section(self, tmp_path):
        s = ControlPlaneStore(str(tmp_path))
        s.append("quota", {"v": 1})
        s.append("membership", {"m": "a"})
        s.append("quota", {"v": 2})
        fresh = ControlPlaneStore(str(tmp_path))
        state = fresh.load()
        assert state == {"quota": {"v": 2}, "membership": {"m": "a"}}
        assert fresh.loads_total == 1
        assert fresh.corrupt_lines_total == 0

    def test_compact_truncates_wal_and_survives_reload(self, tmp_path):
        s = ControlPlaneStore(str(tmp_path))
        s.append("quota", {"v": 1})
        s.compact({"quota": {"v": 1}})
        assert os.path.getsize(s.wal_path) == 0
        s.append("quota", {"v": 2})
        fresh = ControlPlaneStore(str(tmp_path))
        assert fresh.load() == {"quota": {"v": 2}}
        # seq continues past the snapshot: appends after a reload can
        # never collide with pre-compaction history
        assert fresh.append("quota", {"v": 3}) > 2

    def test_torn_wal_tail_is_counted_skip_not_crash(self, tmp_path):
        s = ControlPlaneStore(str(tmp_path))
        s.append("a", {"v": 1})
        s.append("b", {"v": 2})
        s.append("a", {"v": 3})
        # a killed writer tears the last record mid-line
        with open(s.wal_path, "r+b") as f:
            f.truncate(os.path.getsize(s.wal_path) - 7)
        fresh = ControlPlaneStore(str(tmp_path))
        state = fresh.load()
        assert state == {"a": {"v": 1}, "b": {"v": 2}}
        assert fresh.corrupt_lines_total == 1

    def test_corrupt_snapshot_counted_wal_still_replays(self, tmp_path):
        s = ControlPlaneStore(str(tmp_path))
        s.compact({"a": {"v": 1}})
        s.append("b", {"v": 2})
        with open(s.snapshot_path, "r+b") as f:
            size = os.path.getsize(s.snapshot_path)
            f.seek(size // 2)
            byte = f.read(1)
            f.seek(size // 2)
            f.write(bytes([byte[0] ^ 0x01]))
        fresh = ControlPlaneStore(str(tmp_path))
        state = fresh.load()
        assert state == {"b": {"v": 2}}  # degraded to the WAL prefix
        assert fresh.corrupt_snapshots_total == 1

    def test_store_corrupt_fault_drives_real_rejection(self, tmp_path):
        plan = faults.parse_router_spec("store-corrupt:count=1")
        s = ControlPlaneStore(str(tmp_path), fault_plan=plan)
        s.append("a", {"v": 1})
        s.append("a", {"v": 2})
        state = s.load()  # the injection chops the tail first
        assert state == {"a": {"v": 1}}
        assert s.corrupt_lines_total == 1
        assert plan.snapshot()[0]["fired"] == 1
        # budget spent: the next load is clean
        assert s.load() == {"a": {"v": 1}}

    def test_prom_samples_cover_all_five_counters(self, tmp_path):
        s = ControlPlaneStore(str(tmp_path))
        assert sorted(s.prom_samples()) == [
            "wavetpu_store_appends_total",
            "wavetpu_store_compactions_total",
            "wavetpu_store_corrupt_lines_total",
            "wavetpu_store_corrupt_snapshots_total",
            "wavetpu_store_loads_total",
        ]


# ---- the lease ----


class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


class TestLease:
    def test_epoch_fences_a_deposed_active(self, tmp_path):
        clk = _Clock()
        l1 = fleet_ha.LeaseManager(str(tmp_path), "r1", ttl_s=2.0,
                                   clock=clk)
        l2 = fleet_ha.LeaseManager(str(tmp_path), "r2", ttl_s=2.0,
                                   clock=clk)
        assert l1.try_acquire() and l1.epoch == 1
        assert not l2.try_acquire()      # live and not ours
        assert l1.renew()
        clk.t += 5.0                     # r1 stops renewing (crashed)
        assert l2.try_acquire() and l2.epoch == 2
        # the resumed r1 discovers the loss on its next renewal and can
        # NEVER renew its way back in
        assert not l1.renew()
        assert l1.epoch == 0
        clk.t += 5.0
        assert l1.try_acquire() and l1.epoch == 3

    def test_release_hands_off_immediately_epoch_monotonic(
        self, tmp_path
    ):
        clk = _Clock()
        l1 = fleet_ha.LeaseManager(str(tmp_path), "r1", clock=clk)
        l2 = fleet_ha.LeaseManager(str(tmp_path), "r2", clock=clk)
        assert l1.try_acquire() and l1.epoch == 1
        l1.release()
        assert l1.epoch == 0
        # NO clock advance: the release itself freed the lease, and the
        # epoch kept counting (fencing survives orderly handoffs)
        assert l2.try_acquire() and l2.epoch == 2

    def test_corrupt_lease_file_reads_as_absent(self, tmp_path):
        clk = _Clock()
        l1 = fleet_ha.LeaseManager(str(tmp_path), "r1", clock=clk)
        assert l1.try_acquire()
        with open(l1.path, "w", encoding="utf-8") as f:
            f.write("{torn")
        l2 = fleet_ha.LeaseManager(str(tmp_path), "r2", clock=clk)
        assert l2.holder() is None
        assert l2.try_acquire()          # a torn write only delays

    def test_stale_lease_fault_forces_demotion_path(self, tmp_path):
        plan = faults.parse_router_spec("store-stale-lease:count=1")
        clk = _Clock()
        lease = fleet_ha.LeaseManager(str(tmp_path), "r1", clock=clk,
                                      fault_plan=plan)
        assert lease.try_acquire()
        assert not lease.renew()         # chaos: observed stale
        assert lease.epoch == 0
        assert lease.renew_failures_total == 1
        clk.t += 5.0
        assert lease.try_acquire()       # clean re-election after


# ---- quota persistence ----


class TestQuotaPersistence:
    def test_bucket_restore_refills_for_downtime_only(self):
        b = quota.TokenBucket(rate=10.0, burst=10.0)
        for _ in range(8):
            assert b.try_take(1.0)[0]
        exported = b.export_state()
        # pretend the router was down for 0.5s: 5 tokens refill, the
        # other 3 stay SPENT
        exported = dict(exported, unix=exported["unix"] - 0.5)
        restored = quota.TokenBucket.restore(exported)
        assert 6.5 <= restored.tokens() <= 7.6
        # a long outage refills to burst, never past it
        stale = dict(exported, unix=exported["unix"] - 3600.0)
        assert quota.TokenBucket.restore(stale).tokens() == 10.0

    def test_manager_restore_skips_malformed_per_bucket(self):
        qm = quota.QuotaManager(default_rps=5.0)
        adopted = qm.restore_state({
            "rps": {
                "good": {"rate": 5.0, "burst": 5.0, "tokens": 1.0,
                         "unix": time.time()},
                "bad": {"rate": "junk"},
            },
            "rejected_per_tenant": {"good": 3, "junk": "x"},
        })
        assert adopted == 1
        assert 0.9 <= qm.levels()["good"]["rps_tokens"] <= 1.5
        assert qm.rejected_per_tenant == {"good": 3}

    def test_roundtrip_preserves_levels(self):
        qm = quota.QuotaManager()
        cfg = quota.TenantConfig(tenant="t", rps=4.0, burst=4.0)
        assert qm.admit(cfg, 0.0)[0]
        assert qm.admit(cfg, 0.0)[0]
        qm2 = quota.QuotaManager()
        qm2.restore_state(qm.export_state())
        assert qm2.levels()["t"]["rps_tokens"] <= 2.5


# ---- the multi-endpoint client ----


class TestClientMultiEndpoint:
    def _standby(self):
        m = _ScriptedMember()
        m.solve_script = [(503, {
            "status": "error",
            "error": "standby router (not the lease holder)",
            "retriable": True, "standby": True,
        }, {"Retry-After": "1"})] * 50
        return m

    def test_rotates_past_dead_and_standby_to_active(self):
        import socket

        dead = socket.socket()
        dead.bind(("127.0.0.1", 0))
        dead_url = f"http://127.0.0.1:{dead.getsockname()[1]}"
        dead.close()  # nothing listens here now
        standby, live = self._standby(), _ScriptedMember()
        try:
            c = WavetpuClient(
                [dead_url, standby.url, live.url], retries=4,
                sleep=lambda s: None,
            )
            out = c.solve({"N": 8, "timesteps": 4})
            assert out.ok and out.attempts == 3
            assert c.endpoint_failovers == 2
            assert c.base_url == live.url
            # the cursor is sticky: the next request goes straight to
            # the live endpoint, no rediscovery
            assert c.solve({"N": 8, "timesteps": 4}).attempts == 1
            assert c.endpoint_failovers == 2
        finally:
            standby.close()
            live.close()

    def test_retry_budget_and_request_id_semantics_unchanged(self):
        standby, live = self._standby(), _ScriptedMember()
        try:
            c = WavetpuClient([standby.url, live.url], retries=3,
                              sleep=lambda s: None)
            out = c.solve({"N": 8, "timesteps": 4}, request_id="rid-1")
            assert out.ok and out.request_id == "rid-1"
            # every attempt carried the SAME id and traceparent
            seen = standby.seen_headers + live.seen_headers
            assert {h.get("X-Request-Id") for h in seen} == {"rid-1"}
            assert len({h.get("traceparent") for h in seen}) == 1
        finally:
            standby.close()
            live.close()

    def test_single_endpoint_never_rotates(self):
        import socket

        dead = socket.socket()
        dead.bind(("127.0.0.1", 0))
        url = f"http://127.0.0.1:{dead.getsockname()[1]}"
        dead.close()
        c = WavetpuClient(url, retries=1, sleep=lambda s: None)
        out = c.solve({"N": 8})
        assert out.status == 0
        assert c.endpoint_failovers == 0

    def test_empty_endpoint_list_rejected(self):
        with pytest.raises(ValueError):
            WavetpuClient([])


# ---- router-tier fault grammar ----


class TestRouterFaultSpecs:
    def test_parse_kinds_and_budgets(self):
        plan = faults.parse_router_spec(
            "router-crash:after=2,count=1;store-corrupt"
        )
        snaps = plan.snapshot()
        assert [s["kind"] for s in snaps] == [
            "router-crash", "store-corrupt"
        ]
        assert snaps[0]["after"] == 2 and snaps[0]["remaining"] == 1
        # after= skips the first K eligible events
        assert plan.fire("router-crash") is None
        assert plan.fire("router-crash") is None
        assert plan.fire("router-crash") is not None
        assert plan.fire("router-crash") is None  # count budget spent

    def test_unknown_param_and_kind_rejected(self):
        with pytest.raises(ValueError):
            faults.parse_router_spec("router-crash:seconds=3")
        with pytest.raises(ValueError):
            faults.parse_router_spec("router-explode")

    def test_plan_from_env_ignores_run_and_serve_specs(self):
        env = {"WAVETPU_FAULT": "nan:3;serve-crash:count=1"}
        assert faults.router_plan_from_env(env) is None
        env = {"WAVETPU_FAULT": "nan:3;store-corrupt:count=2"}
        plan = faults.router_plan_from_env(env)
        assert [s["kind"] for s in plan.snapshot()] == ["store-corrupt"]

    def test_router_wires_env_plan_and_exposes_firings(
        self, tmp_path, monkeypatch
    ):
        """build_router adopts the WAVETPU_FAULT router plan and
        renders per-kind firing counts - `after=` keeps the SIGKILL
        seam armed-but-unfired here (firing it would kill pytest; the
        nightly HA smoke fires it for real in a subprocess router)."""
        monkeypatch.setenv("WAVETPU_FAULT",
                           "router-crash:after=9999;store-corrupt")
        m = _ScriptedMember()
        h, s, b = _start([m.url],
                         control_plane_dir=str(tmp_path / "cp"))
        try:
            assert s.fault_plan is not None
            assert s.store.fault_plan is s.fault_plan  # ONE budget
            code, _, _ = _post(b, "/solve", {"N": 8, "timesteps": 4})
            assert code == 200  # after= swallowed the eligible event
            samples = _scrape(b)
            assert samples[
                'wavetpu_router_fault_injections_total'
                '{kind="router-crash"}'
            ] == 0.0
            # store-corrupt fired on the boot load (count unlimited)
            assert samples[
                'wavetpu_router_fault_injections_total'
                '{kind="store-corrupt"}'
            ] >= 1.0
        finally:
            _stop(h, s)
            m.close()

    def test_run_hook_ignores_router_specs(self):
        # a router chaos env leaking into `wavetpu run` must not crash
        env = {"WAVETPU_FAULT":
               "router-crash:after=1;store-stale-lease"}
        assert faults.hook_from_env(env) is None
        env = {"WAVETPU_FAULT": "store-corrupt;nan:3"}
        hook = faults.hook_from_env(env)
        assert hook is not None  # the run-side half still parses


# ---- router restart: state + /metrics monotonicity (satellite) ----


def _start(member_urls, **kw):
    kw.setdefault("poll_interval_s", 60.0)
    httpd, state = build_router(member_urls, **kw)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, state, f"http://127.0.0.1:{httpd.server_address[1]}"


def _stop(httpd, state, release=True):
    if state.ha is not None:
        state.ha.stop(release=release)
    state.stop_poller()
    httpd.shutdown()
    httpd.server_close()


def _scrape(base):
    _, text = _get(base, "/metrics", accept="text/plain")
    return parse_prometheus_text(text)


class TestRouterRestartResumesState:
    BODY = {"N": 8, "timesteps": 4}

    def test_restart_restores_counters_quota_and_frozen_members(
        self, tmp_path
    ):
        """The bracketing-deltas pin: scrape r1, restart into r2 over
        the same --control-plane-dir, scrape r2 - every counter sample
        present in both cuts must be monotonic, INCLUDING a LEFT
        member's frozen fleet counters (the member is off the network
        and absent from r2's --member list; only the store remembers
        it)."""
        cp = str(tmp_path / "cp")
        gone = _ScriptedMember(prom="wavetpu_y_total 5\n")
        stays = _ScriptedMember(prom="wavetpu_y_total 2\n")
        keys = {"k": quota.TenantConfig(tenant="t", rps=0.5,
                                        burst=6.0)}
        h1, s1, b1 = _start(
            [gone.url, stays.url], control_plane_dir=cp,
            api_keys=keys, store_flush_interval_s=0.05,
        )
        try:
            assert s1.role == fleet_ha.ACTIVE  # lone router boots active
            for _ in range(3):
                code, _, _ = _post(b1, "/solve", self.BODY,
                                   headers={"X-Api-Key": "k"})
                assert code == 200
            # retire `gone` (a completed roll): counters freeze
            s1.table.leave(gone.url)
            s1.table.retire(gone.url)
            gone.close()
            gone = None
            before = _scrape(b1)
            assert before["wavetpu_y_total"] == 7.0
            assert before["wavetpu_router_requests_total"] == 3.0
            levels_before = s1.quotas.levels()["t"]["rps_tokens"]
            assert levels_before <= 3.5    # 6 - 3 spent (+tiny refill)
        finally:
            _stop(h1, s1)
            if gone is not None:
                gone.close()
        # r2: same dir, but `gone` is NOT in the member list - only the
        # restored membership section can carry its frozen 5.0
        h2, s2, b2 = _start(
            [stays.url], control_plane_dir=cp, api_keys=keys,
            store_flush_interval_s=0.05,
        )
        try:
            assert s2.role == fleet_ha.ACTIVE
            after = _scrape(b2)
            for name, v in before.items():
                # wavetpu_store_*/wavetpu_fleet_ha_* describe THIS
                # process's store/lease activity (like a process start
                # time) - they are the one family that legitimately
                # resets with the process.
                if name.startswith(("wavetpu_store_",
                                    "wavetpu_fleet_ha_")):
                    continue
                if name.endswith("_total") and name in after:
                    assert after[name] >= v, (
                        f"{name} went backwards across the restart: "
                        f"{v} -> {after[name]}"
                    )
            assert after["wavetpu_y_total"] >= 7.0
            assert after["wavetpu_router_requests_total"] >= 3.0
            # the frozen member is back in the table, frozen
            left = [
                row for row in s2.snapshot()["members"]
                if row["state"] == LEFT
            ]
            assert left, "restored LEFT member missing from the table"
            up = [
                row for row in s2.snapshot()["members"]
                if row["state"] == UP
            ]
            assert [row["url"] for row in up] == [stays.url]
            # quota enforcement RESUMED: the bucket is not full again
            levels_after = s2.quotas.levels()["t"]["rps_tokens"]
            assert levels_after <= levels_before + 1.5
            # and the store's own counters are exposed
            assert after["wavetpu_store_loads_total"] >= 1.0
            assert after["wavetpu_fleet_ha_active"] == 1.0
        finally:
            _stop(h2, s2)
            stays.close()


# ---- two-router coordination (satellite: bounded fleet admission) ----


class TestTwoRouterCoordination:
    BODY = {"N": 8, "timesteps": 4}
    LIMIT = 20.0  # burst: the configured per-tenant admission budget

    def _keys(self):
        return {"k": quota.TenantConfig(tenant="t", rps=2.0,
                                        burst=self.LIMIT)}

    def _flood(self, bases, n=60):
        """n requests round-robined across `bases` from 8 threads;
        returns (admitted_200s, standby_503s)."""
        counts = {"ok": 0, "standby": 0}
        lock = threading.Lock()
        nxt = {"i": 0}

        def worker():
            while True:
                with lock:
                    i = nxt["i"]
                    if i >= n:
                        return
                    nxt["i"] = i + 1
                code, payload, _ = _post(
                    bases[i % len(bases)], "/solve", self.BODY,
                    headers={"X-Api-Key": "k"},
                )
                with lock:
                    if code == 200:
                        counts["ok"] += 1
                    elif code == 503 and payload.get("standby"):
                        counts["standby"] += 1

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        return counts["ok"], counts["standby"]

    def test_shared_store_bounds_fleet_admission(self, tmp_path):
        """Two routers over ONE control-plane dir: the single-writer
        lease means only the active admits, so the fleet-wide admitted
        count stays within limit + refill slack - not N x limit."""
        cp = str(tmp_path / "cp")
        m = _ScriptedMember()
        ha, sa, ba = _start([m.url], control_plane_dir=cp,
                            api_keys=self._keys(), lease_ttl_s=5.0)
        hb, sb, bb = _start([m.url], control_plane_dir=cp,
                            api_keys=self._keys(), lease_ttl_s=5.0)
        try:
            assert sa.role == fleet_ha.ACTIVE
            assert sb.role == fleet_ha.STANDBY
            ok, standby = self._flood([ba, bb])
            assert ok <= self.LIMIT * 1.25, (
                f"fleet admitted {ok} > 1.25x the configured "
                f"{self.LIMIT}"
            )
            assert standby > 0  # B refused retriably, not silently
            assert sb.snapshot()["standby_rejected_total"] == standby
            # the standby's /healthz tells balancers not to route there
            _, text = _get(bb, "/healthz")
            health = json.loads(text)
            assert health["role"] == "standby"
            assert health["ready"] is False
            assert health["status"] == "ok"
        finally:
            _stop(ha, sa)
            _stop(hb, sb)
            m.close()

    def test_without_store_two_routers_overadmit(self):
        """The regression pin for the world this PR fixes: two
        independent routers each open the full per-tenant budget, so
        the same flood admits ~2x the configured limit."""
        m = _ScriptedMember()
        ha, sa, ba = _start([m.url], api_keys=self._keys())
        hb, sb, bb = _start([m.url], api_keys=self._keys())
        try:
            ok, _ = self._flood([ba, bb])
            assert ok >= self.LIMIT * 1.5, (
                f"expected ~2x over-admission without the store, "
                f"got {ok} (did quota coordination appear for free?)"
            )
        finally:
            _stop(ha, sa)
            _stop(hb, sb)
            m.close()


# ---- the failover drill (acceptance) ----


class TestFailoverDrill:
    BODY = {"N": 8, "timesteps": 4}
    TOKEN = "fa" * 32

    def test_kill_active_midflight_standby_resumes_the_march(
        self, tmp_path
    ):
        """The whole tentpole in one drill: a chunked long solve is
        mid-march (the member checkpointed it - 504 + resume_token)
        when the active router DIES (no flush, no release).  The
        multi-endpoint client rotates; the standby acquires the expired
        lease, restores quota/counter state, and serves the retry; the
        re-presented token completes the march.  Zero client-visible
        errors, quota levels within one refill interval of pre-kill."""
        cp = str(tmp_path / "cp")
        m = _ScriptedMember()
        keys = {"k": quota.TenantConfig(tenant="t", rps=0.2,
                                        burst=5.0)}
        ha_httpd, sa, ba = _start(
            [m.url], control_plane_dir=cp, api_keys=keys,
            lease_ttl_s=0.6, store_flush_interval_s=0.05,
        )
        hb, sb, bb = _start(
            [m.url], control_plane_dir=cp, api_keys=keys,
            lease_ttl_s=0.6, store_flush_interval_s=0.05,
        )
        killed = []

        def kill_active():
            # the crash: stop serving AND stop renewing, release
            # NOTHING - the lease must expire on its own
            ha_httpd.shutdown()
            ha_httpd.server_close()
            sa.ha.stop(release=False)
            sa.stop_poller()

        def chaos_sleep(s):
            if not killed:
                killed.append(time.monotonic())
                kill_active()
            time.sleep(min(s, 0.25))

        client = WavetpuClient([ba, bb], retries=15,
                               sleep=chaos_sleep)
        try:
            assert sa.role == fleet_ha.ACTIVE
            assert sb.role == fleet_ha.STANDBY
            # pre-kill traffic: spend quota the successor must remember
            for _ in range(2):
                out = client.solve(self.BODY,
                                   headers={"X-Api-Key": "k"})
                assert out.ok
            time.sleep(0.3)  # >= one flush interval: spends persisted
            pre_kill_level = sa.quotas.levels()["t"]["rps_tokens"]
            # NOW the chunked march: the member answers its next /solve
            # with "deadline died mid-march but CHECKPOINTED" - the
            # client's first backoff sleep is where the active dies
            with m.lock:
                m.solve_script = [(504, {
                    "status": "error",
                    "error": "deadline exceeded mid-march; "
                             "checkpointed",
                    "retriable": False, "resume_token": self.TOKEN,
                }, {})]
            out = client.solve(self.BODY, headers={"X-Api-Key": "k"})
            # ZERO client-visible errors across the failover
            assert out.ok, (out.status, out.error)
            assert killed, "the kill hook never fired"
            assert client.endpoint_failovers >= 1
            assert client.base_url == bb
            assert sb.role == fleet_ha.ACTIVE
            assert sb.ha.snapshot()["takeovers_total"] == 1
            # the successor holds a HIGHER epoch: the dead active is
            # fenced out even if it resurrects
            assert sb.ha.lease.epoch > 1
            # the resume token completed the march at the member via
            # the promoted router
            final_body = json.loads(m.seen_bodies[-1])
            assert final_body.get("resume_token") == self.TOKEN
            # quota state survived: the restored bucket is within one
            # refill interval (takeover gap ~1-3 s at 0.2/s, plus the
            # drill request itself) of the pre-kill level - NOT
            # reopened to the full burst of 5
            post_level = sb.quotas.levels()["t"]["rps_tokens"]
            assert post_level <= pre_kill_level + 1.5, (
                f"quota reopened across failover: {pre_kill_level} -> "
                f"{post_level}"
            )
            # and the standby's rejections were all retriable
            assert out.status == 200
        finally:
            _stop(hb, sb)
            if not killed:
                _stop(ha_httpd, sa)
            m.close()
            client.close()

    def test_orderly_stop_hands_off_within_one_tick(self, tmp_path):
        """The zero-downtime half: an orderly shutdown releases the
        lease, so the standby promotes on its next tick - no TTL
        wait."""
        cp = str(tmp_path / "cp")
        m = _ScriptedMember()
        ha_httpd, sa, ba = _start(
            [m.url], control_plane_dir=cp, lease_ttl_s=30.0,
            store_flush_interval_s=0.05,
        )
        hb, sb, bb = _start(
            [m.url], control_plane_dir=cp, lease_ttl_s=30.0,
            store_flush_interval_s=0.05,
        )
        try:
            assert sb.role == fleet_ha.STANDBY
            _stop(ha_httpd, sa)  # orderly: flush + release
            deadline = time.monotonic() + 5.0
            while (sb.role != fleet_ha.ACTIVE
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            # a 30s TTL would have pinned a crash-takeover here; the
            # RELEASE is what made this fast
            assert sb.role == fleet_ha.ACTIVE
        finally:
            _stop(hb, sb)
            m.close()
