"""Result-cache tier contracts: the jax-free result_key derivation
(answer-shaping fields change the key, encoding order does not), the
replica ResultCache bounds (LRU bytes / TTL / fingerprint / digest),
the HTTP pins - a cache hit is BYTE-IDENTICAL to the fresh solve and
skips the march, `Cache-Control: no-cache` bypasses, singleflight
collapses N concurrent identical requests onto ONE executed batch -
the two WAVETPU_FAULT corruption drills (counted miss, clean
recompute, zero breaker events), and the router edge tier: a repeat
answered at the router with ZERO replica I/O, surviving an HA
failover via the control-plane store.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from wavetpu import progkey
from wavetpu.fleet import ha as fleet_ha
from wavetpu.fleet.edgecache import EdgeCache
from wavetpu.fleet.router import build_router
from wavetpu.run import faults
from wavetpu.serve.api import build_server
from wavetpu.serve.resultcache import ResultCache


# ---- plumbing (mirrors test_fleet.py; raw-bytes POST is the point:
# the byte-identity pin must compare wire bytes, not re-parsed JSON) --


def _post_raw(base, path, body, timeout=60, headers=None):
    req = urllib.request.Request(
        base + path, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


def _metrics_json(base, timeout=30):
    req = urllib.request.Request(base + "/metrics")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _start_replica(**kw):
    kw.setdefault("max_wait", 0.02)
    kw.setdefault("default_kernel", "roll")
    kw.setdefault("interpret", True)
    httpd, state = build_server(port=0, **kw)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, state, f"http://127.0.0.1:{httpd.server_address[1]}"


def _stop_replica(httpd, state):
    try:
        httpd.shutdown()
    except Exception:
        pass
    state.batcher.close(timeout=30.0, drain=False)
    httpd.server_close()


def _start_router(member_urls, **kw):
    import random

    kw.setdefault("poll_interval_s", 60.0)  # tests poll explicitly
    kw.setdefault("rng", random.Random(0))
    httpd, state = build_router(member_urls, **kw)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, state, f"http://127.0.0.1:{httpd.server_address[1]}"


def _stop_router(httpd, state, release=True):
    if getattr(state, "ha", None) is not None:
        state.ha.stop(release=release)
    state.stop_poller()
    httpd.shutdown()
    httpd.server_close()


# ---- the shared result-key derivation ----


class TestResultKey:
    def test_answer_shaping_fields_change_the_key(self):
        base = progkey.result_key({"N": 8, "timesteps": 4})
        # phase/steps/c2_field change the ANSWER (not the compiled
        # program) - they MUST fork the result key even though the
        # affinity identity treats them as irrelevant.
        assert base != progkey.result_key(
            {"N": 8, "timesteps": 4, "phase": 1.0}
        )
        assert base != progkey.result_key(
            {"N": 8, "timesteps": 4, "c2_field": "gaussian-lens"}
        )
        assert base != progkey.result_key({"N": 8, "timesteps": 5})

    def test_key_is_encoding_order_invariant(self):
        a = progkey.result_key({"N": 8, "timesteps": 4, "k": 2})
        b = progkey.result_key({"k": 2, "timesteps": 4, "N": 8})
        assert a == b

    def test_rejects_what_the_server_rejects(self):
        with pytest.raises(ValueError):
            progkey.result_key({"timesteps": 4})  # missing N

    def test_eligibility_is_conservative(self):
        assert progkey.result_cache_eligible({"N": 8, "timesteps": 4})
        # a resume-token request continues recorded state - its answer
        # depends on MORE than the body, so it must never be cached
        assert not progkey.result_cache_eligible(
            {"N": 8, "timesteps": 4, "resume_token": "tok"}
        )
        assert not progkey.result_cache_eligible("not a dict")
        assert not progkey.result_cache_eligible(None)


# ---- the replica cache's bounds (unit, injected clock) ----


class TestResultCacheBounds:
    def _cache(self, **kw):
        self.now = [0.0]
        kw.setdefault("clock", lambda: self.now[0])
        return ResultCache(**kw)

    def test_lru_evicts_oldest_when_over_bytes(self):
        c = self._cache(max_bytes=100, ttl_s=60.0)
        assert c.put("a", b"x" * 40)
        assert c.put("b", b"y" * 40)
        assert c.put("c", b"z" * 40)  # over 100 -> "a" goes
        snap = c.snapshot()
        assert snap["entries"] == 2 and snap["bytes"] <= 100
        assert c.snapshot()["events"]["evict_lru"] == 1
        assert c.get("a") is None
        assert c.get("b") is not None and c.get("c") is not None

    def test_hit_refreshes_lru_order(self):
        c = self._cache(max_bytes=100, ttl_s=60.0)
        c.put("a", b"x" * 40)
        c.put("b", b"y" * 40)
        assert c.get("a") is not None  # "a" is now most-recent
        c.put("c", b"z" * 40)          # so "b" is the victim
        assert c.get("b") is None and c.get("a") is not None

    def test_oversized_payload_rejected_not_thrashed(self):
        c = self._cache(max_bytes=100, ttl_s=60.0)
        c.put("a", b"x" * 40)
        assert not c.put("big", b"z" * 200)
        # the oversized answer must not have evicted the resident set
        assert c.get("a") is not None
        assert c.snapshot()["entries"] == 1

    def test_ttl_expiry_is_a_counted_miss(self):
        c = self._cache(max_bytes=100, ttl_s=10.0)
        c.put("a", b"payload")
        self.now[0] = 11.0
        assert c.get("a") is None
        ev = c.snapshot()["events"]
        assert ev["evict_ttl"] == 1 and ev["miss"] == 1
        assert c.snapshot()["entries"] == 0

    def test_fingerprint_drift_invalidates(self):
        c = self._cache(max_bytes=100, ttl_s=60.0,
                        fingerprint={"jaxlib": "0.4.0"})
        c.put("a", b"payload")
        assert c.get("a") is not None
        c.fingerprint = {"jaxlib": "0.5.0"}  # the upgrade landed
        assert c.get("a") is None
        assert c.snapshot()["events"]["fingerprint_mismatch"] == 1

    def test_real_corruption_is_detected_and_dropped(self):
        c = self._cache(max_bytes=100, ttl_s=60.0)
        c.put("a", b"payload-bytes")
        with c._lock:  # bit-rot the resident copy behind the API
            c._entries["a"].payload = b"payload-bytEs"
        assert c.get("a") is None
        ev = c.snapshot()["events"]
        assert ev["corrupt"] == 1 and c.snapshot()["entries"] == 0


# ---- the HTTP contract: byte-identity, bypass, singleflight ----


BODY = {"N": 8, "timesteps": 4}


class TestReplicaCacheHTTP:
    def test_hit_is_byte_identical_and_skips_the_march(self):
        httpd, state, base = _start_replica(result_cache=True)
        try:
            code, fresh, h1 = _post_raw(base, "/solve", BODY)
            assert code == 200
            assert h1.get("X-Wavetpu-Cache", "").startswith("store;fp=")
            batches = _metrics_json(base)["batches_total"]

            code, cached, h2 = _post_raw(base, "/solve", BODY)
            assert code == 200
            assert h2.get("X-Wavetpu-Cache") == "hit"
            # THE pin: the hit replays the exact bytes the cold client
            # saw - not a re-serialization that happens to parse equal.
            assert cached == fresh
            assert "cache;desc=hit" in h2.get("Server-Timing", "")
            snap = _metrics_json(base)
            assert snap["batches_total"] == batches  # no march
            assert snap["result_cache"]["events"]["hit"] == 1
        finally:
            _stop_replica(httpd, state)

    def test_no_cache_header_bypasses_and_recomputes(self):
        httpd, state, base = _start_replica(result_cache=True)
        try:
            code, _, _ = _post_raw(base, "/solve", BODY)
            assert code == 200
            batches = _metrics_json(base)["batches_total"]
            code, _, h = _post_raw(
                base, "/solve", BODY,
                headers={"Cache-Control": "no-cache"},
            )
            assert code == 200
            assert h.get("X-Wavetpu-Cache") != "hit"
            snap = _metrics_json(base)
            assert snap["batches_total"] == batches + 1  # re-marched
            assert snap["result_cache"]["events"]["bypass"] == 1
        finally:
            _stop_replica(httpd, state)

    def test_cache_off_by_default(self):
        httpd, state, base = _start_replica()
        try:
            for _ in range(2):
                code, _, h = _post_raw(base, "/solve", BODY)
                assert code == 200
                assert "X-Wavetpu-Cache" not in h
            snap = _metrics_json(base)
            assert "result_cache" not in snap
        finally:
            _stop_replica(httpd, state)

    def test_singleflight_collapses_concurrent_identicals(self):
        """N identical concurrent requests -> exactly ONE executed
        march; followers fan out the primary's answer byte-identically
        and are individually counted."""
        httpd, state, base = _start_replica(
            result_cache=True, max_wait=0.3
        )
        try:
            results = []
            lock = threading.Lock()

            def worker():
                out = _post_raw(base, "/solve", BODY)
                with lock:
                    results.append(out)

            threads = [threading.Thread(target=worker)]
            threads[0].start()
            time.sleep(0.1)  # primary is parked in the batch window
            for _ in range(4):
                t = threading.Thread(target=worker)
                t.start()
                threads.append(t)
            for t in threads:
                t.join(120)
            assert len(results) == 5
            assert all(code == 200 for code, _, _ in results)
            payloads = {bytes(body) for _, body, _ in results}
            assert len(payloads) == 1  # one answer, fanned out
            tags = sorted(
                h.get("X-Wavetpu-Cache", "") for _, _, h in results
            )
            assert sum(1 for t in tags if t == "coalesced") == 4
            snap = _metrics_json(base)
            assert snap["batches_total"] == 1  # the acceptance pin
            assert snap["coalesced_total"] == 4
            # riders are still individually accounted
            assert snap["requests_total"] == 5
        finally:
            _stop_replica(httpd, state)


# ---- the chaos drills: corruption is a counted miss, never a wrong
# answer, never a breaker event ----


class TestChaosDrills:
    @pytest.mark.parametrize("kind,event", [
        ("resultcache-corrupt", "corrupt"),
        ("resultcache-stale-fingerprint", "fingerprint_mismatch"),
    ])
    def test_corruption_recomputes_cleanly(self, kind, event):
        plan = faults.parse_serve_spec(f"serve-{kind}:count=1")
        httpd, state, base = _start_replica(
            result_cache=True, fault_plan=plan
        )
        try:
            code, fresh, _ = _post_raw(base, "/solve", BODY)
            assert code == 200
            # the armed fault fires on this lookup: the entry is
            # rejected, the request falls through to a clean recompute
            code, recomputed, h = _post_raw(base, "/solve", BODY)
            assert code == 200
            assert h.get("X-Wavetpu-Cache") != "hit"
            # never a wrong answer: the recomputed ANSWER matches the
            # original (timing fields legitimately differ per march)
            def answer(raw):
                rep = json.loads(raw)["report"]
                return {k: rep[k] for k in (
                    "problem", "final_step", "max_abs_error",
                    "abs_errors", "rel_errors",
                )}
            assert answer(recomputed) == answer(fresh)
            snap = _metrics_json(base)
            ev = snap["result_cache"]["events"]
            assert ev[event] == 1 and ev["miss"] >= 1
            # a cache losing an entry says nothing about the program:
            assert snap["breaker"]["open"] == 0
            assert snap["breaker"]["keys"] == []
            # budget spent -> the re-stored answer now hits,
            # byte-identical to the recompute that refilled it
            code, again, h = _post_raw(base, "/solve", BODY)
            assert code == 200 and h.get("X-Wavetpu-Cache") == "hit"
            assert again == recomputed
        finally:
            _stop_replica(httpd, state)


# ---- the router edge tier ----


class TestEdgeCacheUnit:
    def test_export_restore_roundtrip_with_corrupt_entry_skipped(self):
        a = EdgeCache(max_bytes=1 << 20, ttl_s=600.0)
        a.put("k1", b'{"ok":1}', "application/json", "total;dur=1",
              fp="aaaa")
        a.put("k2", b'{"ok":2}', "application/json", None, fp="aaaa")
        state = a.export_state()
        for e in state["entries"]:
            if e["key"] == "k2":
                e["digest"] = "0" * 64  # WAL bit-rot
        b = EdgeCache(max_bytes=1 << 20, ttl_s=600.0)
        b.restore_state(state)
        hit = b.get("k1")
        assert hit is not None and hit[0] == b'{"ok":1}'
        assert b.get("k2") is None  # corrupt record cost ITS entry only
        assert b.corrupt_total >= 1

    def test_fingerprint_change_flushes_the_index(self):
        c = EdgeCache(max_bytes=1 << 20, ttl_s=600.0)
        c.put("k1", b'{"ok":1}', "application/json", None, fp="aaaa")
        c.put("k2", b'{"ok":2}', "application/json", None, fp="bbbb")
        # the fleet's environment moved: every pre-drift answer is gone
        assert c.get("k1") is None
        assert c.get("k2") is not None
        assert c.fingerprint_flushes_total == 1


class TestRouterEdgeCache:
    def test_edge_hit_answers_with_zero_replica_io(self):
        h, s, u = _start_replica(result_cache=True)
        router_httpd, rstate, base = _start_router(
            [u], edge_cache=True, proxy_timeout=60.0
        )
        try:
            code, fresh, h1 = _post_raw(base, "/solve", BODY)
            assert code == 200
            assert h1.get("X-Wavetpu-Cache", "").startswith("store;fp=")
            replica = _metrics_json(u)
            batches, requests = (
                replica["batches_total"], replica["requests_total"]
            )

            code, cached, h2 = _post_raw(base, "/solve", BODY)
            assert code == 200
            assert h2.get("X-Wavetpu-Cache") == "edge-hit"
            assert cached == fresh  # byte-identical at the edge too
            assert "cache;desc=edge-hit" in h2.get("Server-Timing", "")
            replica = _metrics_json(u)
            # ZERO replica I/O: not merely "no batch" - the replica
            # never even saw an HTTP request for the repeat.
            assert replica["batches_total"] == batches
            assert replica["requests_total"] == requests
            assert rstate.edge.hits_total == 1
        finally:
            _stop_router(router_httpd, rstate)
            _stop_replica(h, s)

    def test_no_cache_bypasses_the_edge(self):
        h, s, u = _start_replica(result_cache=True)
        router_httpd, rstate, base = _start_router(
            [u], edge_cache=True, proxy_timeout=60.0
        )
        try:
            assert _post_raw(base, "/solve", BODY)[0] == 200
            replica_ok = _metrics_json(u)["responses_ok"]
            code, _, hdr = _post_raw(
                base, "/solve", BODY,
                headers={"Cache-Control": "no-cache"},
            )
            assert code == 200
            assert hdr.get("X-Wavetpu-Cache") != "edge-hit"
            # the bypass went all the way to a replica (which may
            # itself answer from ITS cache - that is the replica's
            # call; the EDGE must not have short-circuited)
            assert _metrics_json(u)["responses_ok"] == replica_ok + 1
        finally:
            _stop_router(router_httpd, rstate)
            _stop_replica(h, s)

    def test_ha_failover_inherits_the_edge_index(self, tmp_path):
        """Router A stores an edge answer, hands off through the
        control-plane store; promoted router B answers the repeat from
        ITS edge - the replica never hears about the failover."""
        cp = str(tmp_path / "cp")
        h, s, u = _start_replica(result_cache=True)
        ha_httpd, sa, ba = _start_router(
            [u], edge_cache=True, proxy_timeout=60.0,
            control_plane_dir=cp, store_flush_interval_s=0.05,
        )
        try:
            assert sa.role == fleet_ha.ACTIVE
            code, fresh, h1 = _post_raw(ba, "/solve", BODY)
            assert code == 200
            assert h1.get("X-Wavetpu-Cache", "").startswith("store;fp=")
        finally:
            _stop_router(ha_httpd, sa)  # orderly: flush + release
        hb, sb, bb = _start_router(
            [u], edge_cache=True, proxy_timeout=60.0,
            control_plane_dir=cp, store_flush_interval_s=0.05,
        )
        try:
            assert sb.role == fleet_ha.ACTIVE
            replica = _metrics_json(u)
            batches, requests = (
                replica["batches_total"], replica["requests_total"]
            )
            code, cached, h2 = _post_raw(bb, "/solve", BODY)
            assert code == 200
            assert h2.get("X-Wavetpu-Cache") == "edge-hit"
            assert cached == fresh
            replica = _metrics_json(u)
            assert replica["batches_total"] == batches
            assert replica["requests_total"] == requests
        finally:
            _stop_router(hb, sb)
            _stop_replica(h, s)
