"""Sharded k-fused solver: parity with the single-device k-fused path.

The sharded k-step kernels consume ppermute'd ghosts (x planes; y rows on
2D meshes, corners via the sequenced y-then-x exchange) where the
single-device kernel wraps around - identical values through identical op
order - so the final state must match BITWISE across mesh shapes, and the
per-layer error rows must assemble to the same global errors.  Runs on
the 8-virtual-CPU mesh in interpret mode (tests/conftest.py).

Most of this module carries the `heavy` marker (round-6 suite tiering):
interpret-mode onion compiles put the full matrix at several minutes, so
the default `pytest -q` deselects it; the tier-1 gate
(`pytest -q -m 'not slow'`) and the full gate (`-m ''`) run everything.

Uneven-path note (this jaxlib): the pad-and-mask program's XLA-CPU
compilation contracts FMAs differently from the 1-step program once the
k-block scan is longer than one iteration, so "bitwise" parity holds
only to 1 ulp here (asserted at atol=3e-7 with exact shape/zero-pad
checks); on-chip and same-program comparisons remain bit-identical.
"""

import functools

import jax.numpy as jnp
import numpy as np
import pytest

from wavetpu.core.problem import Problem
from wavetpu.solver import kfused, sharded_kfused


@functools.lru_cache(maxsize=None)
def _single(problem, k, dtype=jnp.float32, errors=True):
    """Memoized single-device k-fused reference solve (Problem is frozen,
    hence a valid cache key): several parity cases share a config, and
    each solve pays an interpret-mode compile."""
    return kfused.solve_kfused(
        problem, dtype=dtype, k=k, compute_errors=errors, interpret=True
    )


@pytest.mark.parametrize("n_shards,k,timesteps", [
    (2, 2, 11),
    (2, 4, 9),
    (4, 4, 13),   # nl = 4 = k: every program is both edges
    (8, 2, 9),    # nl = 2: minimal shard depth
    (1, 4, 9),    # single-shard mesh == single-device data path
    (2, 4, 12),   # (timesteps-1) % k == 3: exercises the 1-step remainder
])
@pytest.mark.heavy
def test_state_matches_single_device_kfused(n_shards, k, timesteps):
    p = Problem(N=16, timesteps=timesteps)
    want = _single(p, k)
    got = sharded_kfused.solve_sharded_kfused(
        p, n_shards=n_shards, k=k, interpret=True
    )
    np.testing.assert_array_equal(
        np.asarray(got.u_cur), np.asarray(want.u_cur)
    )
    np.testing.assert_array_equal(
        np.asarray(got.u_prev), np.asarray(want.u_prev)
    )


@pytest.mark.parametrize("n_shards,k", [(2, 2), (4, 4)])
@pytest.mark.heavy
def test_errors_match_single_device_kfused(n_shards, k):
    p = Problem(N=16, timesteps=11)
    want = _single(p, k)
    got = sharded_kfused.solve_sharded_kfused(
        p, n_shards=n_shards, k=k, interpret=True
    )
    np.testing.assert_allclose(
        got.abs_errors, want.abs_errors, rtol=1e-5, atol=1e-7
    )
    np.testing.assert_allclose(got.rel_errors, want.rel_errors, rtol=1e-5)


@pytest.mark.heavy
def test_stop_resume_bitwise():
    p = Problem(N=16, timesteps=13)
    full = sharded_kfused.solve_sharded_kfused(
        p, n_shards=2, k=4, interpret=True
    )
    part = sharded_kfused.solve_sharded_kfused(
        p, n_shards=2, k=4, stop_step=6, interpret=True
    )
    res = sharded_kfused.resume_sharded_kfused(
        p, part.u_prev, part.u_cur, start_step=6, n_shards=2, k=4,
        interpret=True,
    )
    np.testing.assert_array_equal(
        np.asarray(res.u_cur), np.asarray(full.u_cur)
    )
    np.testing.assert_allclose(
        res.abs_errors[7:], full.abs_errors[7:], rtol=1e-6
    )
    assert (res.abs_errors[:7] == 0).all()


@pytest.mark.heavy
def test_resume_from_host_checkpoint_roundtrip(tmp_path):
    """Save via the per-shard checkpoint writer, resume k-fused: bitwise."""
    from wavetpu.io import checkpoint as ckpt

    p = Problem(N=16, timesteps=12)
    full = sharded_kfused.solve_sharded_kfused(
        p, n_shards=2, k=4, interpret=True
    )
    part = sharded_kfused.solve_sharded_kfused(
        p, n_shards=2, k=4, stop_step=5, interpret=True
    )
    path = str(tmp_path / "ck")
    ckpt.save_sharded_checkpoint(path, part)
    problem2, u_prev, u_cur, step, mesh_shape, scheme, aux = (
        ckpt.load_sharded_checkpoint(path)
    )
    assert mesh_shape == (2, 1, 1) and step == 5 and scheme == "standard"
    res = sharded_kfused.resume_sharded_kfused(
        problem2, np.asarray(u_prev), np.asarray(u_cur), start_step=step,
        n_shards=2, k=4, interpret=True,
    )
    np.testing.assert_array_equal(
        np.asarray(res.u_cur), np.asarray(full.u_cur)
    )


def test_no_errors_mode():
    p = Problem(N=16, timesteps=9)
    got = sharded_kfused.solve_sharded_kfused(
        p, n_shards=2, k=4, compute_errors=False, interpret=True
    )
    assert (got.abs_errors == 0).all()
    want = _single(p, 4, errors=False)
    np.testing.assert_array_equal(
        np.asarray(got.u_cur), np.asarray(want.u_cur)
    )


def test_bf16_state():
    p = Problem(N=16, timesteps=9)
    want = _single(p, 4, jnp.bfloat16)
    got = sharded_kfused.solve_sharded_kfused(
        p, n_shards=2, dtype=jnp.bfloat16, k=4, interpret=True
    )
    np.testing.assert_array_equal(
        np.asarray(got.u_cur.astype(jnp.float32)),
        np.asarray(want.u_cur.astype(jnp.float32)),
    )


def test_validation():
    # Uneven configs whose pad-and-mask layout would leave the last
    # shard empty are refused with guidance (not silently mis-sharded).
    with pytest.raises(ValueError, match="pad-and-mask"):
        sharded_kfused.solve_sharded_kfused(
            Problem(N=18, timesteps=8), n_shards=4, k=2, interpret=True
        )
    with pytest.raises(ValueError, match="pad-and-mask"):
        sharded_kfused.solve_sharded_kfused(
            Problem(N=16, timesteps=8), n_shards=8, k=4, interpret=True
        )
    with pytest.raises(ValueError, match="k must be >= 2"):
        sharded_kfused.solve_sharded_kfused(
            Problem(N=16, timesteps=8), n_shards=2, k=1, interpret=True
        )


# ---------------------------------------------------------------------------
# Uneven N (pad-and-mask path): the remainder-folding analog of the
# reference (mpi_sol.cpp:417-421) for the temporally blocked solver.
# Real planes must stay BITWISE equal to the single-device 1-step pallas
# path (which the even k-fused path is already pinned to).
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _single_1step(problem, dtype=jnp.float32):
    from wavetpu.kernels import stencil_pallas
    from wavetpu.solver import leapfrog

    return leapfrog.solve(
        problem, dtype=dtype,
        step_fn=stencil_pallas.make_step_fn(interpret=True),
    )


@pytest.mark.parametrize("n,n_shards,k,timesteps", [
    (15, 8, 2, 9),    # r = 1 < k: seam windows span two source shards
    (30, 8, 2, 11),   # r = 2 = k: single-source uneven
    (15, 1, 2, 9),    # single-shard uneven (k does not divide N)
    # k does not divide N/MX (the N=1000-on-8-chips shape).  33 steps
    # keep C ~ 0.29: the old 11-step config was Courant-UNSTABLE (C=0.87),
    # which a bitwise contract tolerated but the 1-ulp contract cannot
    # (FMA seeds amplify at the instability rate).
    (60, 8, 4, 33),
    (15, 2, 2, 12),   # two shards + 1-step remainder tail through kk=1
])
@pytest.mark.heavy
def test_uneven_matches_single_device_1step(n, n_shards, k, timesteps):
    from wavetpu.solver import sharded

    p = Problem(N=n, timesteps=timesteps)
    want = _single_1step(p)
    got = sharded_kfused.solve_sharded_kfused(
        p, n_shards=n_shards, k=k, interpret=True
    )
    # Results ride the standard Topology layout (padded, P(x,y,z)) like
    # every other sharded result; gather_fundamental strips the pad.
    # Ulp-accumulation tolerance: XLA-CPU FMA contraction differs between
    # the padded and 1-step program shapes on this jaxlib (module
    # docstring), and the ~1-ulp per-layer seeds accumulate linearly on a
    # stable trajectory - hence atol ~ ulp * timesteps.
    tol = 1.2e-7 * timesteps
    np.testing.assert_allclose(
        sharded.gather_fundamental(got.u_cur, p), np.asarray(want.u_cur),
        atol=tol, rtol=0,
    )
    np.testing.assert_allclose(
        sharded.gather_fundamental(got.u_prev, p),
        np.asarray(want.u_prev), atol=tol, rtol=0,
    )
    np.testing.assert_allclose(
        got.abs_errors, want.abs_errors, rtol=1e-5, atol=tol
    )


def test_uneven_layout_properties():
    p = Problem(N=15, timesteps=8)
    bx, d, r = sharded_kfused.uneven_layout(p, 2, 8)
    assert d % bx == 0 and bx % 2 == 0 and r >= 1
    assert 7 * d < 15 <= 8 * d


@pytest.mark.heavy
def test_uneven_stop_resume_bitwise():
    p = Problem(N=15, timesteps=11)
    full = sharded_kfused.solve_sharded_kfused(
        p, n_shards=4, k=2, interpret=True
    )
    part = sharded_kfused.solve_sharded_kfused(
        p, n_shards=4, k=2, stop_step=5, interpret=True
    )
    res = sharded_kfused.resume_sharded_kfused(
        p, part.u_prev, part.u_cur, start_step=5, n_shards=4, k=2,
        interpret=True,
    )
    np.testing.assert_array_equal(
        np.asarray(res.u_cur), np.asarray(full.u_cur)
    )
    assert (res.abs_errors[:6] == 0).all()


@pytest.mark.heavy
def test_uneven_checkpoint_roundtrip(tmp_path):
    """Uneven results ride the canonical Topology layout, so the
    per-shard checkpoint writer and loader consume them unchanged
    (regression: the r5 review caught a sliced result whose collapsed
    sharding made every device race-write shard_0_0_0)."""
    from wavetpu.io import checkpoint as ckpt

    p = Problem(N=15, timesteps=11)
    full = sharded_kfused.solve_sharded_kfused(
        p, n_shards=4, k=2, interpret=True
    )
    part = sharded_kfused.solve_sharded_kfused(
        p, n_shards=4, k=2, stop_step=5, interpret=True
    )
    path = str(tmp_path / "ck")
    ckpt.save_sharded_checkpoint(path, part)
    problem2, u_prev, u_cur, step, mesh_shape, scheme, aux = (
        ckpt.load_sharded_checkpoint(path)
    )
    assert mesh_shape == (4, 1, 1) and step == 5
    res = sharded_kfused.resume_sharded_kfused(
        problem2, np.asarray(u_prev), np.asarray(u_cur), start_step=step,
        n_shards=4, k=2, interpret=True,
    )
    np.testing.assert_array_equal(
        np.asarray(res.u_cur), np.asarray(full.u_cur)
    )


def test_uneven_no_errors_and_bf16():
    from wavetpu.solver import sharded

    p = Problem(N=15, timesteps=9)
    got = sharded_kfused.solve_sharded_kfused(
        p, n_shards=2, k=2, compute_errors=False, interpret=True
    )
    assert (got.abs_errors == 0).all()
    want = _single_1step(p, jnp.bfloat16)
    got16 = sharded_kfused.solve_sharded_kfused(
        p, n_shards=2, dtype=jnp.bfloat16, k=2, interpret=True
    )
    np.testing.assert_array_equal(
        sharded.gather_fundamental(
            got16.u_cur.astype(jnp.float32), p
        ),
        np.asarray(want.u_cur.astype(jnp.float32)),
    )


@pytest.mark.parametrize("mesh,k,timesteps", [
    ((2, 2, 1), 2, 11),
    ((2, 2, 1), 4, 9),
    ((1, 2, 1), 4, 9),    # y-only split: the xy kernel alone
    ((4, 2, 1), 2, 12),   # remainder tail through the xy kernel
    ((2, 4, 1), 4, 13),   # nl_y = 4 = k: ghost strip spans a full block
])
@pytest.mark.heavy
def test_xy_mesh_matches_single_device(mesh, k, timesteps):
    """The 2D-mesh kernel (y-extended blocks, wrapped-global-y mask,
    corner data via sequenced exchange) is bitwise equal to the
    single-device k-fused solve."""
    p = Problem(N=16, timesteps=timesteps)
    want = _single(p, k)
    got = sharded_kfused.solve_sharded_kfused(
        p, mesh_shape=mesh, k=k, interpret=True
    )
    np.testing.assert_array_equal(
        np.asarray(got.u_cur), np.asarray(want.u_cur)
    )
    np.testing.assert_array_equal(
        np.asarray(got.u_prev), np.asarray(want.u_prev)
    )
    np.testing.assert_allclose(
        got.abs_errors, want.abs_errors, rtol=1e-5, atol=1e-7
    )
    np.testing.assert_allclose(got.rel_errors, want.rel_errors, rtol=1e-5)


@pytest.mark.heavy
def test_xy_mesh_stop_resume_bitwise():
    p = Problem(N=16, timesteps=13)
    full = sharded_kfused.solve_sharded_kfused(
        p, mesh_shape=(2, 2, 1), k=4, interpret=True
    )
    part = sharded_kfused.solve_sharded_kfused(
        p, mesh_shape=(2, 2, 1), k=4, stop_step=6, interpret=True
    )
    res = sharded_kfused.resume_sharded_kfused(
        p, part.u_prev, part.u_cur, start_step=6, mesh_shape=(2, 2, 1),
        k=4, interpret=True,
    )
    np.testing.assert_array_equal(
        np.asarray(res.u_cur), np.asarray(full.u_cur)
    )


@pytest.mark.heavy
def test_xy_mesh_bf16():
    p = Problem(N=16, timesteps=9)
    want = _single(p, 4, jnp.bfloat16)
    got = sharded_kfused.solve_sharded_kfused(
        p, mesh_shape=(2, 2, 1), dtype=jnp.bfloat16, k=4, interpret=True
    )
    np.testing.assert_array_equal(
        np.asarray(got.u_cur.astype(jnp.float32)),
        np.asarray(want.u_cur.astype(jnp.float32)),
    )


def test_xy_mesh_validation():
    p = Problem(N=16, timesteps=8)
    with pytest.raises(ValueError, match=r"\(MX, MY, 1\)"):
        sharded_kfused.solve_sharded_kfused(
            p, mesh_shape=(2, 1, 2), k=2, interpret=True
        )
    with pytest.raises(ValueError, match="y shard depth"):
        # nl_y = 16/8 = 2 < k = 4: the ghost strip would span 2 blocks
        sharded_kfused.solve_sharded_kfused(
            p, mesh_shape=(1, 8, 1), k=4, interpret=True
        )
