"""Persistent AOT program cache contracts (wavetpu/serve/progcache.py).

The acceptance drills: a subprocess warms a cache via `wavetpu warmup
--manifest` and the parent then serves the same tiers with ZERO fresh
compiles and bitwise-identical output; corruption (truncation, stale
fingerprint - driven through the WAVETPU_FAULT chaos harness, so the
REAL rejection branches fire) and over-budget GC are counted misses
that recompile cleanly, never crashes and never circuit-breaker trips;
the ledger's measured `source: disk` accounting activates without
disturbing the old-format what-if pin.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from wavetpu.core.problem import Problem
from wavetpu.obs import ledger, telemetry
from wavetpu.run import faults
from wavetpu.serve import progcache
from wavetpu.serve.engine import ServeEngine


def _lane():
    from wavetpu.ensemble.batched import LaneSpec

    return LaneSpec()


def _tiny_problem():
    return Problem(N=8, timesteps=4)


def _solve(engine, timing=None):
    result, health = engine.solve(_tiny_problem(), [_lane()],
                                  timing=timing)
    assert health == [None]
    return np.asarray(result.results[0].u_cur)


def _key(**over):
    base = dict(
        N=8, Lx=1.0, Ly=1.0, Lz=1.0, T=1.0, timesteps=4,
        scheme="standard", path="roll", k=1, dtype="f32",
        with_field=False, compute_errors=True, batch=1, mesh=None,
    )
    base.update(over)
    return base


aot_ok = progcache.aot_capability()[0]
needs_aot = pytest.mark.skipif(
    not aot_ok, reason="jaxlib cannot serialize executables here"
)


@needs_aot
class TestDiskTier:
    def test_second_engine_adopts_from_disk_bitwise(self, tmp_path):
        """The tentpole in two instances: engine A compiles and stores;
        engine B (a 'restarted replica') adopts from disk with zero
        fresh compiles, and the solve is bitwise identical to a fresh
        twin's."""
        d = str(tmp_path / "cache")
        a = ServeEngine(bucket_sizes=(1,), interpret=True,
                        program_cache_dir=d)
        t = {}
        u_a = _solve(a, t)
        assert t["warm"] == "false"
        assert a.misses == 1 and a.disk_hits == 0
        assert a.progcache.counts.get("store") == 1

        b = ServeEngine(bucket_sizes=(1,), interpret=True,
                        program_cache_dir=d)
        t = {}
        u_b = _solve(b, t)
        assert t["warm"] == "disk"
        assert b.misses == 0 and b.disk_hits == 1
        # deserialize wall, not an XLA compile
        assert t["compile_seconds"] < 5.0

        fresh = ServeEngine(bucket_sizes=(1,), interpret=True)
        u_fresh = _solve(fresh)
        assert np.array_equal(u_a, u_b)
        assert np.array_equal(u_b, u_fresh)

    def test_memory_hit_still_wins_over_disk(self, tmp_path):
        d = str(tmp_path / "cache")
        eng = ServeEngine(bucket_sizes=(1,), interpret=True,
                          program_cache_dir=d)
        _solve(eng)
        t = {}
        _solve(eng, t)
        assert t["warm"] == "true"  # the test_serve pin's label
        assert eng.hits == 1 and eng.disk_hits == 0

    def test_cache_stats_exposes_disk_tier(self, tmp_path):
        d = str(tmp_path / "cache")
        eng = ServeEngine(bucket_sizes=(1,), interpret=True,
                          program_cache_dir=d)
        _solve(eng)
        stats = eng.cache_stats()
        assert stats["disk_hits"] == 0
        pc = stats["progcache"]
        assert pc["enabled"] is True and pc["aot"] is True
        assert pc["entries"] == 1 and pc["bytes"] > 0
        assert pc["aot_probes"][0]["probe"] == "aot_serialize_executable"
        assert pc["aot_probes"][0]["ok"] is True
        off = ServeEngine(bucket_sizes=(1,), interpret=True)
        assert off.cache_stats()["progcache"] == {"enabled": False}

    def test_disk_hit_writes_source_disk_ledger_line(self, tmp_path):
        d = str(tmp_path / "cache")
        warm = ServeEngine(bucket_sizes=(1,), interpret=True,
                           program_cache_dir=d)
        _solve(warm)
        tel_d = str(tmp_path / "tel")
        tel = telemetry.start(tel_d, interval=60.0)
        try:
            eng = ServeEngine(bucket_sizes=(1,), interpret=True,
                              program_cache_dir=d)
            _solve(eng)
        finally:
            tel.stop()
        entries = ledger.load_ledger(
            os.path.join(tel_d, ledger.LEDGER_FILENAME)
        )
        assert [e.get("source") for e in entries] == ["disk"]
        assert entries[0]["fresh_compile_s"] > 0


@needs_aot
class TestCorruptionDrills:
    def _warm_cache(self, tmp_path):
        d = str(tmp_path / "cache")
        eng = ServeEngine(bucket_sizes=(1,), interpret=True,
                          program_cache_dir=d)
        u = _solve(eng)
        return d, u

    def test_truncated_entry_is_counted_miss(self, tmp_path):
        """Direct on-disk truncation (no harness): checksum/length
        rejection -> counted corrupt -> clean fresh recompile."""
        d, u_ref = self._warm_cache(tmp_path)
        (entry,) = [
            os.path.join(d, n) for n in os.listdir(d)
            if n.endswith(progcache.ENTRY_SUFFIX)
        ]
        faults.truncate_tail(entry, drop_bytes=64)
        eng = ServeEngine(bucket_sizes=(1,), interpret=True,
                          program_cache_dir=d)
        t = {}
        u = _solve(eng, t)
        assert t["warm"] == "false"  # fresh compile, not a crash
        assert eng.misses == 1 and eng.disk_hits == 0
        assert eng.progcache.counts.get("corrupt") == 1
        # Self-healing: the corrupt entry was deleted, so the NEXT
        # replica pays a plain disk_miss, not another corrupt parse.
        # (No AOT re-store here: the recompile was served by the
        # ride-along XLA cache, and cache-served executables must
        # never be serialized - see progcache docstring.)
        assert not os.path.exists(entry)
        assert eng.progcache.counts.get("store") is None
        again = ServeEngine(bucket_sizes=(1,), interpret=True,
                            program_cache_dir=d)
        t = {}
        u2 = _solve(again, t)
        assert t["warm"] == "false"
        assert again.progcache.counts.get("disk_miss") == 1
        assert np.array_equal(u, u_ref) and np.array_equal(u2, u_ref)

    def test_fault_harness_truncate_counted_never_breaker(self, tmp_path):
        """`serve-progcache-truncate` (WAVETPU_FAULT grammar) truncates
        the REAL entry file just before the read: the genuine
        checksum branch rejects it, the request recompiles, and the
        circuit breaker never hears about it."""
        d, _ = self._warm_cache(tmp_path)
        plan = faults.parse_serve_spec("serve-progcache-truncate:count=1")
        assert plan is not None
        eng = ServeEngine(bucket_sizes=(1,), interpret=True,
                          program_cache_dir=d, fault_plan=plan)
        t = {}
        _solve(eng, t)
        assert t["warm"] == "false"
        assert eng.progcache.counts.get("corrupt") == 1
        assert eng.breaker is not None
        snap = eng.breaker.snapshot()
        assert snap["open"] == 0 and snap["keys"] == []

    def test_fault_harness_fingerprint_mismatch(self, tmp_path):
        """`serve-progcache-fingerprint` poisons the EXPECTED
        fingerprint for one load - the real cross-version rejection
        branch fires as a counted miss, then recompiles."""
        d, _ = self._warm_cache(tmp_path)
        plan = faults.parse_serve_spec(
            "serve-progcache-fingerprint:count=1"
        )
        eng = ServeEngine(bucket_sizes=(1,), interpret=True,
                          program_cache_dir=d, fault_plan=plan)
        t = {}
        _solve(eng, t)
        assert t["warm"] == "false"
        assert eng.progcache.counts.get("fingerprint_mismatch") == 1
        assert eng.breaker.snapshot()["open"] == 0
        # budget spent: the next replica adopts normally
        eng2 = ServeEngine(bucket_sizes=(1,), interpret=True,
                           program_cache_dir=d, fault_plan=plan)
        t = {}
        _solve(eng2, t)
        assert t["warm"] == "disk"

    def test_env_fingerprint_keys_the_filename(self, tmp_path):
        """A different fingerprint means a different FILENAME - a
        cross-version entry is never even opened (disk_miss, not
        corrupt)."""
        cache = progcache.ProgramCache(str(tmp_path / "c"))
        assert cache.put(_key(), {"triple": b"x" * 64}, 1.0)
        other = progcache.ProgramCache(str(tmp_path / "c"))
        other._fp_hash = "deadbeef"
        assert other.load(_key()) is None
        assert other.counts.get("disk_miss") == 1


class TestGC:
    def test_over_budget_evicts_oldest_newest_survives(self, tmp_path):
        cache = progcache.ProgramCache(str(tmp_path / "c"))
        paths = []
        for i in range(3):
            k = _key(batch=i + 1)
            assert cache.put(k, {"blob": b"x" * 4096}, 1.0)
            p = cache.entry_path(k)
            os.utime(p, (100.0 + i, 100.0 + i))  # deterministic LRU
            paths.append(p)
        sizes = [os.path.getsize(p) for p in paths]
        cache.max_bytes = sizes[1] + sizes[2]  # room for exactly two
        assert cache.gc() == 1
        assert not os.path.exists(paths[0])
        assert os.path.exists(paths[1]) and os.path.exists(paths[2])
        assert cache.counts.get("gc_evict") == 1

    def test_budget_smaller_than_one_entry_keeps_latest(self, tmp_path):
        cache = progcache.ProgramCache(str(tmp_path / "c"), max_bytes=1)
        for i in range(2):
            k = _key(batch=i + 1)
            cache.put(k, {"blob": b"x" * 4096}, 1.0)
            os.utime(cache.entry_path(k), (100.0 + i, 100.0 + i))
        cache.gc()
        remaining = [n for n in os.listdir(cache.directory)
                     if n.endswith(progcache.ENTRY_SUFFIX)]
        assert len(remaining) == 1  # keep-latest, never keep-nothing
        assert os.path.basename(
            cache.entry_path(_key(batch=2))
        ) in remaining

    def test_hit_refreshes_lru_clock(self, tmp_path):
        if not aot_ok:
            pytest.skip("load() needs AOT mode")
        cache = progcache.ProgramCache(str(tmp_path / "c"))
        for i in range(2):
            k = _key(batch=i + 1)
            cache.put(k, {"blob": b"x" * 64}, 1.0)
            os.utime(cache.entry_path(k), (100.0 + i, 100.0 + i))
        assert cache.load(_key(batch=1)) is not None  # touch oldest
        entries = sorted(cache._entries(), key=lambda e: e[2])
        assert entries[-1][0] == cache.entry_path(_key(batch=1))


@needs_aot
class TestWarmupCLI:
    def _manifest(self, tmp_path):
        lp = str(tmp_path / "compile_ledger.jsonl")
        led = ledger.CompileLedger(lp)
        led.record(_key(), 1.0, ts=1.0, pid=1)
        led.close()
        manifest = ledger.warmup_manifest(ledger.load_ledger(lp))
        mp = str(tmp_path / "warmup_manifest.json")
        with open(mp, "w") as f:
            json.dump(manifest, f)
        return mp

    def test_round_trip_second_run_all_disk_hits(self, tmp_path, capsys):
        mp = self._manifest(tmp_path)
        d = str(tmp_path / "cache")
        assert progcache.main(
            ["--manifest", mp, "--program-cache-dir", d]
        ) == 0
        out = capsys.readouterr().out
        assert "compiled" in out and "-> cached" in out
        assert progcache.main(
            ["--manifest", mp, "--program-cache-dir", d]
        ) == 0
        out = capsys.readouterr().out
        assert "disk hit" in out
        assert "1 disk hit(s), 0 compiled" in out

    def test_usage_errors(self, tmp_path, capsys):
        assert progcache.main([]) == 2  # no --manifest
        bad = tmp_path / "bad.json"
        bad.write_text("{}")
        assert progcache.main(["--manifest", str(bad)]) == 2
        assert progcache.main(
            ["--manifest", str(tmp_path / "missing.json")]
        ) == 2
        capsys.readouterr()

    def test_oversized_mesh_key_skipped_not_failed(self, tmp_path,
                                                   capsys):
        manifest = {
            ledger.MANIFEST_FLAG: True, "version": 1,
            "keys": [ledger.normalize_key(_key(mesh=[64, 64, 64]))],
        }
        mp = str(tmp_path / "m.json")
        with open(mp, "w") as f:
            json.dump(manifest, f)
        assert progcache.main(
            ["--manifest", mp,
             "--program-cache-dir", str(tmp_path / "c")]
        ) == 0  # skip, not failure
        assert "skip (mesh needs" in capsys.readouterr().out


@needs_aot
class TestCrossProcess:
    def test_subprocess_warms_parent_serves_zero_fresh(self, tmp_path):
        """The cross-process acceptance drill: process A (a real
        subprocess) pre-populates the cache from a ledger-report
        manifest; process B (here) serves the same tier with zero
        fresh compiles, a ledger of only `source: disk`, and output
        bitwise identical to a fresh twin."""
        # a ledger naming the tier, exactly as ledger-report emits it
        lp = str(tmp_path / "compile_ledger.jsonl")
        led = ledger.CompileLedger(lp)
        led.record(_key(), 1.0, ts=1.0, pid=1)
        led.close()
        mp = str(tmp_path / "warmup_manifest.json")
        assert ledger.main([lp, "--emit-warmup-manifest", mp]) == 0
        d = str(tmp_path / "cache")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, "-m", "wavetpu.cli", "warmup",
             "--manifest", mp, "--program-cache-dir", d],
            capture_output=True, text=True, env=env, timeout=300,
        )
        assert proc.returncode == 0, proc.stderr
        assert "1 compiled" in proc.stdout or "compiled" in proc.stdout
        assert any(n.endswith(progcache.ENTRY_SUFFIX)
                   for n in os.listdir(d))

        tel_d = str(tmp_path / "tel")
        tel = telemetry.start(tel_d, interval=60.0)
        try:
            eng = ServeEngine(bucket_sizes=(1,), interpret=True,
                              program_cache_dir=d)
            t = {}
            u = _solve(eng, t)
        finally:
            tel.stop()
        assert t["warm"] == "disk"
        assert eng.misses == 0 and eng.disk_hits == 1
        entries = ledger.load_ledger(
            os.path.join(tel_d, ledger.LEDGER_FILENAME)
        )
        assert {e.get("source") for e in entries} == {"disk"}
        fresh = ServeEngine(bucket_sizes=(1,), interpret=True)
        assert np.array_equal(u, _solve(fresh))


class TestMeasuredLedger:
    def test_aggregate_partitions_disk_records(self):
        """`source: disk` lines feed ONLY the measured block; the
        what-if and every fresh-compile figure aggregate over the rest
        exactly as an old-format ledger would."""
        old = [
            {"key": _key(), "compile_s": 30.0, "cold": True,
             "ts": 1.0, "pid": 1},
            {"key": _key(), "compile_s": 28.0, "cold": True,
             "ts": 10.0, "pid": 2},
        ]
        mixed = old + [
            {"key": _key(), "compile_s": 0.05, "cold": True,
             "ts": 20.0, "pid": 3, "source": "disk",
             "fresh_compile_s": 28.0},
            {"key": _key(batch=8), "compile_s": 0.02, "cold": True,
             "ts": 21.0, "pid": 3, "source": "disk"},
        ]
        base = ledger.aggregate(old)
        agg = ledger.aggregate(mixed)
        mp = agg.pop("measured_persistent_cache")
        base.pop("measured_persistent_cache")
        assert agg == base  # disk lines invisible to the old math
        assert mp["disk_hits"] == 2
        assert mp["load_s"] == pytest.approx(0.07)
        assert mp["measured_saved_s"] == pytest.approx(28.0 - 0.05)
        assert mp["unattributed_hits"] == 1  # the no-fresh_compile_s one

    def test_report_line_only_with_disk_hits(self, capsys):
        recs = [{"key": _key(), "compile_s": 30.0, "cold": True,
                 "ts": 1.0, "pid": 1}]
        out = ledger.format_report(ledger.aggregate(recs))
        assert "measured persistent cache" not in out
        recs.append({"key": _key(), "compile_s": 0.05, "cold": True,
                     "ts": 2.0, "pid": 2, "source": "disk",
                     "fresh_compile_s": 30.0})
        out = ledger.format_report(ledger.aggregate(recs))
        assert "measured persistent cache: 1 disk hit(s)" in out


class TestLoadgenGate:
    def _report(self, cold):
        return {
            "loadgen_report": True, "requests": 4, "ok": 4,
            "latency_ms": {"p99_ms": 10.0},
            "error_rate": 0.0, "reject_rate": 0.0,
            "requests_per_s": 10.0,
            "server": {"cold_compiles": cold, "disk_hits": 2,
                       "warm_hits": 7},
        }

    def test_max_cold_compiles_gate(self):
        from wavetpu.loadgen import report as lg_report

        assert lg_report.gate(
            self._report(0), slo={"max_cold_compiles": 0}
        ) == []
        (v,) = lg_report.gate(
            self._report(2), slo={"max_cold_compiles": 0}
        )
        assert v["slo"] == "max_cold_compiles" and v["observed"] == 2
        # not gated unless asked (default None)
        assert lg_report.gate(self._report(5)) == []

    def test_format_gate_prints_compile_traffic(self):
        from wavetpu.loadgen import report as lg_report

        text = lg_report.format_gate([], self._report(0))
        assert "0 fresh, 2 disk hit(s), 7 warm hit(s)" in text


class TestAotProbe:
    def test_probe_is_cached_and_recorded(self):
        v1 = progcache.aot_capability()
        v2 = progcache.aot_capability()
        assert v1 is v2  # once per process
        (row,) = progcache.probe_results()
        assert row["probe"] == "aot_serialize_executable"
        assert row["ok"] == v1[0]
