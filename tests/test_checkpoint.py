"""Checkpoint/resume: kill-and-resume reproduces the uninterrupted run bitwise."""

import numpy as np

from wavetpu.io import checkpoint
from wavetpu.solver import leapfrog


def test_resume_bitwise_equal(small_problem, tmp_path):
    full = leapfrog.solve(small_problem)

    half = leapfrog.solve(small_problem, stop_step=5)
    path = checkpoint.save_checkpoint(str(tmp_path / "ck.npz"), half)
    resumed = checkpoint.resume_solve(path)

    # Bitwise: identical op sequence -> identical floats, not just allclose.
    np.testing.assert_array_equal(
        np.asarray(resumed.u_cur), np.asarray(full.u_cur)
    )
    np.testing.assert_array_equal(
        np.asarray(resumed.u_prev), np.asarray(full.u_prev)
    )
    # Per-layer errors for the resumed tail match the uninterrupted run's.
    np.testing.assert_array_equal(resumed.abs_errors[6:], full.abs_errors[6:])
    assert np.all(resumed.abs_errors[:6] == 0.0)
    assert resumed.steps_computed == small_problem.timesteps - 5


def test_checkpoint_roundtrip(small_problem, tmp_path):
    half = leapfrog.solve(small_problem, stop_step=3)
    path = checkpoint.save_checkpoint(str(tmp_path / "state"), half)
    assert path.endswith(".npz")
    problem, u_prev, u_cur, step = checkpoint.load_checkpoint(path)
    assert problem == small_problem
    assert step == 3
    np.testing.assert_array_equal(u_cur, np.asarray(half.u_cur))
    np.testing.assert_array_equal(u_prev, np.asarray(half.u_prev))


def test_bf16_checkpoint_roundtrip_bitwise(small_problem, tmp_path):
    """bf16 state survives save/load bitwise (np.savez would otherwise store
    ml_dtypes bfloat16 as void |V2 and resume would die with a TypeError -
    the round-2/3 advisor finding)."""
    import jax.numpy as jnp

    half = leapfrog.solve(small_problem, dtype=jnp.bfloat16, stop_step=5)
    path = checkpoint.save_checkpoint(str(tmp_path / "bf16.npz"), half)
    problem, u_prev, u_cur, step = checkpoint.load_checkpoint(path)
    assert u_cur.dtype.name == "bfloat16"
    assert u_prev.dtype.name == "bfloat16"
    np.testing.assert_array_equal(
        u_cur.view(np.uint16), np.asarray(half.u_cur).view(np.uint16)
    )
    np.testing.assert_array_equal(
        u_prev.view(np.uint16), np.asarray(half.u_prev).view(np.uint16)
    )

    # And the advertised preemption workflow runs clean end to end: the
    # resumed run equals the uninterrupted bf16 run bitwise.
    full = leapfrog.solve(small_problem, dtype=jnp.bfloat16)
    resumed = checkpoint.resume_solve(path)
    assert np.asarray(resumed.u_cur).dtype.name == "bfloat16"
    np.testing.assert_array_equal(
        np.asarray(resumed.u_cur).view(np.uint16),
        np.asarray(full.u_cur).view(np.uint16),
    )


def test_resume_from_final_state_is_noop(small_problem, tmp_path):
    full = leapfrog.solve(small_problem)
    path = checkpoint.save_checkpoint(str(tmp_path / "ck.npz"), full)
    resumed = checkpoint.resume_solve(path)
    np.testing.assert_array_equal(
        np.asarray(resumed.u_cur), np.asarray(full.u_cur)
    )
    assert resumed.steps_computed == 0


def test_stop_step_is_prefix(small_problem):
    """A stopped run is the exact prefix of the full run (same tau)."""
    full = leapfrog.solve(small_problem)
    half = leapfrog.solve(small_problem, stop_step=5)
    np.testing.assert_array_equal(half.abs_errors, full.abs_errors[:6])
