"""k-fused temporal-blocking solver: parity, errors, tails, resume.

The k-fused path (solver/kfused.py driving stencil_pallas.fused_kstep)
must be bitwise identical to the 1-step pallas solve - same per-substep
ops - and its in-kernel per-layer error factorization must reproduce the
post-hoc oracle (verify/oracle.py) for every layer, including the
intermediate layers that never reach HBM.  Interpret mode on the CPU
backend (tests/conftest.py); on-chip throughput is bench.py's job.
"""

import functools

import jax.numpy as jnp
import numpy as np
import pytest

from wavetpu.core.problem import Problem
from wavetpu.kernels import stencil_pallas
from wavetpu.solver import kfused, leapfrog


@functools.lru_cache(maxsize=None)
def _pallas_solve(problem, dtype=jnp.float32, **kw):
    """Memoized 1-step pallas reference solve (Problem is frozen, hence a
    valid cache key): the parity matrix reuses the same configs, each
    paying an interpret-mode compile."""
    return leapfrog.solve(
        problem, dtype=dtype,
        step_fn=stencil_pallas.make_step_fn(interpret=True), **kw
    )


@pytest.mark.parametrize("k,timesteps", [(2, 11), (4, 9), (4, 13), (8, 9)])
def test_state_bitwise_vs_1step_pallas(k, timesteps):
    """k-fused layers are op-identical to 1-step pallas layers - the final
    state must match BITWISE (this is what makes stop/resume mixing of the
    two paths safe), for block counts with and without a remainder tail."""
    p = Problem(N=16, timesteps=timesteps)
    want = _pallas_solve(p)
    got = kfused.solve_kfused(p, k=k, interpret=True)
    np.testing.assert_array_equal(
        np.asarray(got.u_cur), np.asarray(want.u_cur)
    )
    np.testing.assert_array_equal(
        np.asarray(got.u_prev), np.asarray(want.u_prev)
    )


@pytest.mark.parametrize("k", [2, 4])
def test_per_layer_errors_match_oracle(k):
    """Every layer's abs/rel error - including in-VMEM intermediate layers -
    agrees with the separate post-hoc oracle pass of the 1-step path."""
    p = Problem(N=16, timesteps=11)
    want = _pallas_solve(p)
    got = kfused.solve_kfused(p, k=k, interpret=True)
    np.testing.assert_allclose(
        got.abs_errors, want.abs_errors, rtol=1e-5, atol=1e-7
    )
    # rel errors include near-singular analytic planes (sx ~ 1e-16) where
    # the value is huge and meaningless but must still agree relatively.
    np.testing.assert_allclose(
        got.rel_errors, want.rel_errors, rtol=1e-5
    )


def test_against_jnp_roll_reference():
    """End-to-end agreement with the semantic jnp reference to rounding."""
    p = Problem(N=16, timesteps=10)
    want = leapfrog.solve(p)
    got = kfused.solve_kfused(p, k=2, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got.u_cur), np.asarray(want.u_cur), atol=1e-6
    )
    np.testing.assert_allclose(
        got.abs_errors, want.abs_errors, rtol=1e-4, atol=1e-7
    )


@pytest.mark.heavy
def test_stop_resume_bitwise_across_paths():
    """stop at an arbitrary layer (not a k boundary), resume k-fused OR
    1-step: all three final states bitwise equal the uninterrupted run."""
    p = Problem(N=16, timesteps=13)
    full = kfused.solve_kfused(p, k=4, interpret=True)
    part = kfused.solve_kfused(p, k=4, stop_step=6, interpret=True)
    assert part.final_step == 6
    resumed_k = kfused.resume_kfused(
        p, part.u_prev, part.u_cur, start_step=6, k=4, interpret=True
    )
    resumed_1 = leapfrog.resume(
        p, part.u_prev, part.u_cur, start_step=6,
        step_fn=stencil_pallas.make_step_fn(interpret=True),
    )
    np.testing.assert_array_equal(
        np.asarray(resumed_k.u_cur), np.asarray(full.u_cur)
    )
    np.testing.assert_array_equal(
        np.asarray(resumed_1.u_cur), np.asarray(full.u_cur)
    )
    # error arrays: head zeros, tail matches the full run's tail
    np.testing.assert_allclose(
        resumed_k.abs_errors[7:], full.abs_errors[7:], rtol=1e-6
    )
    assert (resumed_k.abs_errors[:7] == 0).all()


def test_bf16_state_bitwise_vs_1step():
    """Per-substep quantization keeps bf16 k-fused bitwise equal to bf16
    1-step pallas, and the observed errors match its error pass."""
    p = Problem(N=16, timesteps=9)
    want = _pallas_solve(p, dtype=jnp.bfloat16)
    got = kfused.solve_kfused(p, dtype=jnp.bfloat16, k=4, interpret=True)
    np.testing.assert_array_equal(
        np.asarray(got.u_cur.astype(jnp.float32)),
        np.asarray(want.u_cur.astype(jnp.float32)),
    )
    np.testing.assert_allclose(
        got.abs_errors, want.abs_errors, rtol=1e-5, atol=1e-7
    )


def test_no_errors_mode():
    p = Problem(N=16, timesteps=9)
    got = kfused.solve_kfused(p, k=4, compute_errors=False, interpret=True)
    assert (got.abs_errors == 0).all() and (got.rel_errors == 0).all()
    want = _pallas_solve(p, compute_errors=False)
    np.testing.assert_array_equal(
        np.asarray(got.u_cur), np.asarray(want.u_cur)
    )


def test_validation_errors():
    p = Problem(N=16, timesteps=9)
    with pytest.raises(ValueError, match="k must be >= 2"):
        kfused.solve_kfused(p, k=1, interpret=True)
    with pytest.raises(ValueError, match="must divide N"):
        kfused.solve_kfused(Problem(N=18, timesteps=9), k=4, interpret=True)
    with pytest.raises(ValueError, match="stop_step"):
        kfused.solve_kfused(p, k=2, stop_step=99, interpret=True)


def test_choose_kstep_block():
    """bx respects divisibility (n % bx, k | bx) and the VMEM model."""
    assert stencil_pallas.choose_kstep_block(512, 2) == 8
    assert stencil_pallas.choose_kstep_block(512, 4) == 4
    assert stencil_pallas.choose_kstep_block(16, 4) == 8
    # bf16 state halves the pipeline slabs: k=4 fits at bx=8
    assert stencil_pallas.choose_kstep_block(512, 4, itemsize=2) == 8
    # absurd k at large N: nothing fits
    assert stencil_pallas.choose_kstep_block(4096, 8) is None
